#!/usr/bin/env python
"""Elastic-fabric CI smoke: a real pod on CPU breathing 1→2→1 under
offered load, with drain-before-kill, a rolled-back canary flip, and a
preemption — the PR 12 control loops exercised end to end.

    python tools/elastic_smoke.py METRICS_OUT

Asserts, against a REAL pod (replica worker processes, real HTTP):

  1. SCALE-UP: saturating offered load (a synthetic per-dispatch device
     floor via the serve.dispatch sleep failpoint makes one CPU replica
     saturable) drives mean queue fill over the threshold and the
     autoscaler grows the pod 1→2; responses stay bit-exact and any
     503s are explicit sheds (Retry-After), never unavailability.
  2. CANARY ROLLBACK: a config flip that changes pixels (`--ops`
     override on the canary replica) is caught by the FIRST shadow
     digest spot-check, auto-reverted, and leaves a `canary_rollback`
     recorder dump; after the revert the pod serves bit-exact again.
  3. SCALE-DOWN IS DRAIN-BEFORE-KILL: with the load stopped, the
     autoscaler drains one replica — the victim is observed (via its
     own heartbeats in /stats) in state `draining` before it leaves,
     and the recorded scale-down reason is `drained`, meaning the
     SIGTERM waited for the empty queue. An `autoscale` recorder dump
     exists for the actions.
  4. PREEMPTION: SIGUSR1 on the survivor produces a `preempt` recorder
     dump from the replica's own ring and an IMMEDIATE no-backoff
     replacement (mcim_fabric_replica_preemptions_total).

METRICS_OUT gets the router's final /metrics exposition (uploaded as a
CI artifact by .github/workflows/tier1.yml).
"""

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OPS = "grayscale,contrast:3.5"
BUCKETS = "48"


def main(metrics_out: str) -> int:
    tmp = tempfile.mkdtemp(prefix="elastic_smoke_")
    rec_dir = os.path.join(tmp, "recorder")
    os.environ["MCIM_RECORDER_DIR"] = rec_dir
    os.environ["MCIM_RECORDER_MIN_INTERVAL_S"] = "0"

    from mpi_cuda_imagemanipulation_tpu.fabric.canary import CanaryConfig
    from mpi_cuda_imagemanipulation_tpu.fabric.router import RouterConfig
    from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (
        Fabric,
        FabricConfig,
    )
    from mpi_cuda_imagemanipulation_tpu.io.image import (
        decode_image_bytes,
        synthetic_image,
    )
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.serve import loadgen
    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets

    cfg = FabricConfig(
        replicas=1,
        ops=OPS,
        buckets=BUCKETS,
        channels="3",
        max_batch=4,
        max_delay_ms=4.0,
        queue_depth=16,
        heartbeat_s=0.2,
        router=RouterConfig(
            buckets=parse_buckets(BUCKETS),
            stale_s=0.8,
            forward_attempts=3,
            canary=CanaryConfig(frac=0.1, shadow_every=2, min_requests=10),
        ),
        # the synthetic device floor: every dispatch sleeps 50 ms, so one
        # replica saturates near 80 img/s and the queue-fill signal is
        # real on a shared-core CI host (same move as fabric_loadgen)
        all_replica_env={"MCIM_FAILPOINTS": "serve.dispatch=sleep:50"},
        autoscale=True,
        min_replicas=1,
        max_replicas=2,
        scale_up_frac=0.5,
        scale_down_frac=0.2,
        scale_sustain_s=0.5,
        scale_cooldown_s=2.0,
        scale_tick_s=0.2,
        scale_drain_deadline_s=30.0,
    )
    pipe = Pipeline.parse(OPS)
    imgs = [
        synthetic_image(40 + 3 * i, 44 + 2 * i, channels=3, seed=70 + i)
        for i in range(4)
    ]
    blobs = [loadgen.encode_blob(im) for im in imgs]
    golden = [np.asarray(pipe.jit()(im)) for im in imgs]

    def check_bit_exact(results) -> int:
        n = 0
        for k, r in results:
            if r["code"] != 200:
                continue
            np.testing.assert_array_equal(
                decode_image_bytes(r["body"]), golden[k % len(golden)]
            )
            n += 1
        return n

    load_stop = threading.Event()
    load_recs: list[dict] = []

    def load_loop():
        while not load_stop.is_set():
            load_recs.append(
                loadgen.http_run_offered_load(
                    fab.url, blobs, 120.0, 1.0, max_workers=64,
                    timeout_s=20.0,
                )
            )

    with Fabric(cfg).start() as fab:
        replica_states: dict[str, set] = {}

        def poll_states():
            for rid, rep in fab.router.stats()["replicas"].items():
                replica_states.setdefault(rid, set()).add(rep["state"])

        # -- 1. saturate -> scale-up 1 -> 2 ---------------------------------
        loader = threading.Thread(target=load_loop, daemon=True)
        loader.start()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            poll_states()
            if len(fab.router._routable()) >= 2:
                break
            time.sleep(0.1)
        assert len(fab.router._routable()) >= 2, (
            "autoscaler never scaled to 2 under saturating load: "
            f"{fab.router.autoscaler.status()}"
        )
        up_events = [
            e for e in fab.router.autoscaler.events if e["direction"] == "up"
        ]
        assert up_events, "no scale-up event recorded"
        print(
            f"smoke: scaled 1->2 (reason {up_events[0]['reason']!r}, "
            f"queue_fill {up_events[0]['signals']['queue_fill']:.2f})"
        )

        # -- 2. canary flip that changes pixels -> shadow digest rollback ---
        status = fab.router.canary_deploy({"argv": ["--ops", "grayscale"]})
        canary_rid = status["replica"]
        print(f"smoke: canary flip live on {canary_rid} (slice 10%)")
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            st = fab.router.canary.status()
            if st["state"] in ("rolled_back", "idle"):
                break
            time.sleep(0.1)
        st = fab.router.canary.status()
        assert st["state"] in ("rolled_back", "idle"), (
            f"canary never breached: {st}"
        )
        # wait out the revert (gate returns to idle once stable serves)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if fab.router.canary.status()["state"] == "idle":
                break
            time.sleep(0.2)
        assert fab.router.canary.status()["state"] == "idle", (
            "canary revert never completed"
        )
        dumps = [
            p for p in os.listdir(rec_dir)
            if p.startswith("recorder_canary_rollback")
        ]
        assert dumps, f"no canary_rollback dump in {rec_dir}"
        with open(os.path.join(rec_dir, dumps[0])) as f:
            dump = json.load(f)
        assert dump["extra"]["shadow"]["mismatch"] >= 1, dump["extra"]
        print(
            f"smoke: canary rolled back ({dump['extra']['reason']}); "
            f"dump {dumps[0]}"
        )

        # -- stop the load; verify shed accounting + bit-exactness ----------
        load_stop.set()
        loader.join(timeout=60.0)
        total_unavailable = sum(r["unavailable"] for r in load_recs)
        total_shed = sum(r["shed"] for r in load_recs)
        assert total_unavailable == 0, (
            f"{total_unavailable} responses counted unavailable — an "
            "elastic pod sheds explicitly (503 + Retry-After), it does "
            "not go dark"
        )
        # bit-exactness: every 200 outside the canary window matches the
        # golden output (the flip window intentionally served different
        # pixels on its slice — that is what the gate bounded)
        checked = check_bit_exact(
            [kv for rec in load_recs[:2] for kv in rec["results"]]
        )
        print(
            f"smoke: load done ({len(load_recs)} windows, shed "
            f"{total_shed}, unavailable 0, {checked} pre-canary "
            "responses bit-exact)"
        )

        # -- 3. idle -> drain-before-kill scale-down 2 -> 1 -----------------
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            poll_states()
            if len(fab.supervisor.replica_ids()) == 1:
                break
            time.sleep(0.05)
        assert len(fab.supervisor.replica_ids()) == 1, (
            f"autoscaler never scaled back down: "
            f"{fab.router.autoscaler.status()}"
        )
        down_events = [
            e for e in fab.router.autoscaler.events
            if e["direction"] == "down"
        ]
        assert down_events and down_events[-1]["reason"] == "drained", (
            f"scale-down was not drain-before-kill: {down_events}"
        )
        victim = down_events[-1]["replica"]
        assert "draining" in replica_states.get(victim, set()), (
            f"victim {victim} was never observed draining via its own "
            f"heartbeats (saw {replica_states.get(victim)})"
        )
        assert any(
            p.startswith("recorder_autoscale") for p in os.listdir(rec_dir)
        ), f"no autoscale dump in {rec_dir}"
        print(
            f"smoke: scaled 2->1 by draining {victim} (queue observed "
            "empty before SIGTERM)"
        )

        # -- 4. preemption: SIGUSR1 -> preempt dump + immediate respawn -----
        survivor = fab.supervisor.replica_ids()[0]
        pid = fab.supervisor.pids()[survivor]
        old_inc = fab.router.table.get(survivor).hb.incarnation
        import signal as _signal

        t_kill = time.monotonic()
        os.kill(pid, _signal.SIGUSR1)
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            view = fab.router.table.get(survivor)
            if (
                fab.supervisor.preemptions(survivor) >= 1
                and view.hb.incarnation != old_inc
                and view.hb.state == "serving"
            ):
                break
            time.sleep(0.1)
        assert fab.supervisor.preemptions(survivor) >= 1, (
            "preemption exit was not recognized"
        )
        view = fab.router.table.get(survivor)
        assert view.hb.incarnation != old_inc and view.hb.state == "serving"
        print(
            f"smoke: {survivor} preempted and replaced in "
            f"{time.monotonic() - t_kill:.1f}s (no backoff)"
        )
        dumps = [
            p for p in os.listdir(rec_dir)
            if p.startswith("recorder_preempt")
        ]
        assert dumps, f"no preempt dump in {rec_dir}"
        print(f"smoke: preempt dump {dumps[0]}")

        # a replacement must serve bit-exact stable traffic again
        r = loadgen.http_post_image(fab.url, blobs[0])
        assert r["code"] == 200
        np.testing.assert_array_equal(
            decode_image_bytes(r["body"]), golden[0]
        )

        with open(metrics_out, "w") as f:
            f.write(fab.scrape())
    print(f"smoke: metrics exposition -> {metrics_out}")
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
