#!/usr/bin/env python
"""CI plan smoke (tier1.yml): the fusion planner acceptance, end to end.

One process proves, on a mixed chain that exercises every stage kind
(pointwise runs, consecutive stencils, a global-stat barrier, a
geometric barrier):

  1. **bit-exactness** — the fused and pointwise-absorbed plans produce
     output identical to the per-op golden chain (`--plan off`), through
     the plain executor, jit, AND the row-sharded path over fake XLA
     host devices;
  2. **structure** — the fused plan's stage halos sum to
     `chain_halo(ops)`, the modelled HBM-pass counter drops vs per-op
     execution (mcim_plan_hbm_passes_saved_total > 0), and the compiled
     sharded fused chain contains exactly ONE ppermute pair per
     halo-carrying fused stage (not one per stencil) — temporal
     blocking over the wire, in the HLO;
  3. **observability** — the mcim_plan_* families render as parseable
     Prometheus exposition with the build counters populated;
  4. **the lane** — the plan_ab bench lane runs (its own pre-timing
     bit-exactness gate must pass) and its record lands at argv[1]
     (uploaded as a CI artifact). The speedup itself is asserted by the
     committed BENCH_HISTORY record, not here — shared CI runners are
     too noisy to gate on a ratio.

Usage: python tools/plan_smoke.py /tmp/plan_ab.json
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import numpy as np  # noqa: E402

# pointwise prefix -> stencil -> global-stat barrier -> stencil pair ->
# geometric barrier -> stencil -> pointwise tail: every stage kind and
# every fusion rule fires
OPS = "grayscale,contrast:3.5,gaussian:5,equalize,sharpen,sobel,rot180,emboss:3,quantize:6"
H, W, C = 160, 96, 3


def main() -> int:
    import jax
    import jax.numpy as jnp

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
    from mpi_cuda_imagemanipulation_tpu.ops.spec import chain_halo
    from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh
    from mpi_cuda_imagemanipulation_tpu.plan import build_plan, plan_metrics
    from mpi_cuda_imagemanipulation_tpu.plan.exec import plan_callable

    pipe = Pipeline.parse(OPS)
    img = jnp.asarray(synthetic_image(H, W, channels=C, seed=11))
    golden = np.asarray(pipe.apply(img))

    # -- 1. bit-exactness across modes and entry points --------------------
    saved0 = plan_metrics.passes_saved.value()
    plans = {m: build_plan(pipe.ops, m) for m in ("off", "pointwise", "fused")}
    for mode, plan in plans.items():
        got = np.asarray(plan_callable(plan)(img))
        assert np.array_equal(got, golden), f"plan {mode} != golden"
        got = np.asarray(pipe.jit(plan=mode)(img))
        assert np.array_equal(got, golden), f"jit plan {mode} != golden"
    print(f"bit-exact: off/pointwise/fused == golden at {H}x{W}x{C}")

    # -- 2. structure: halo conservation, pass savings, HLO ppermutes ------
    assert plans["fused"].total_halo == chain_halo(pipe.ops), (
        plans["fused"].total_halo, chain_halo(pipe.ops)
    )
    assert plans["fused"].hbm_passes < plans["off"].hbm_passes, (
        "fusion saved no modelled HBM passes"
    )
    assert plan_metrics.passes_saved.value() > saved0, (
        "mcim_plan_hbm_passes_saved_total did not advance"
    )
    mesh = make_mesh(4)
    # the sharded chain splits at the geometric barrier into two
    # shard_map segments; count ppermutes per compiled plan mode
    counts = {}
    for mode in ("off", "fused"):
        fn = pipe.sharded(mesh, plan=mode)
        assert np.array_equal(np.asarray(fn(img)), golden), (
            f"sharded plan {mode} != golden"
        )
        counts[mode] = fn.lower(img).as_text().count("collective_permute")
    # fused: one ppermute PAIR per halo-carrying fused stage. The chain
    # fuses to [gray+contrast+gaussian][equalize][sharpen+sobel] then,
    # post-rot180, [emboss+quantize] -> 3 halo-carrying stages = 3 pairs.
    # off: one pair per stencil (gaussian/sharpen/sobel/emboss) = 4 pairs.
    n_stages = sum(
        1 for s in plans["fused"].stages if s.kind == "fused" and s.halo > 0
    )
    assert counts["fused"] == 2 * n_stages, (counts, n_stages)
    n_stencils = sum(1 for op in pipe.ops if getattr(op, "halo", 0) > 0)
    assert counts["off"] == 2 * n_stencils, (counts, n_stencils)
    assert counts["fused"] < counts["off"]
    print(
        f"HLO: {counts['off']} ppermutes per-op -> {counts['fused']} fused "
        f"({n_stages} halo-carrying stages)"
    )

    # -- 3. exposition ------------------------------------------------------
    text = plan_metrics.registry.render()
    fams = parse_exposition(text)
    for fam in (
        "mcim_plan_builds_total",
        "mcim_plan_stages_total",
        "mcim_plan_fused_ops_total",
        "mcim_plan_hbm_passes_saved_total",
    ):
        assert fam in fams, f"missing metric family {fam}"
    snap = plan_metrics.snapshot()
    assert snap["builds_fused"] >= 1 and snap["hbm_passes_saved"] > 0, snap
    print(f"exposition: {len(fams)} families parse; snapshot {snap}")

    # -- 4. the plan_ab lane (record -> CI artifact) ------------------------
    out = sys.argv[1] if len(sys.argv) > 1 else None
    # CI-sized shape: the lane's own gate still runs at full strength
    os.environ.setdefault("MCIM_PLAN_AB_HEIGHT", "384")
    os.environ.setdefault("MCIM_PLAN_AB_WIDTH", "512")
    from mpi_cuda_imagemanipulation_tpu.bench_suite import run_plan_ab

    rec = run_plan_ab(json_path=out, printer=lambda s: None)
    assert rec["bit_exact_gate"].startswith("passed"), rec["bit_exact_gate"]
    assert rec["hbm_passes_saved_model"] > 0
    print(
        f"plan_ab: fused {rec['speedup_fused_vs_off'] or 0:.2f}x vs off "
        f"({rec['hbm_passes_saved_model']} modelled passes saved)"
        + (f" -> {out}" if out else "")
    )
    print("plan smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
