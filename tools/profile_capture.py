#!/usr/bin/env python
"""Capture a jax.profiler trace of the headline kernel and summarize it.

VERDICT r2 missing #4: the roofline argument (BASELINE.md) rests on modeled
HBM traffic; a DMA-wait vs compute breakdown from a real trace corroborates
or kills it independently of the wide-word A/B. This script:

  1. compiles the headline pipeline (8K 5x5 Gaussian, Pallas),
  2. records `jax.profiler.trace(..., create_perfetto_trace=True)` around
     ~30 steady-state iterations,
  3. parses the Perfetto/Chrome trace JSON (stdlib gzip+json — no
     tensorboard_plugin_profile in this image) and writes
     {OUTDIR}_summary.md + .json: per-track top events by total
     duration, plus a device-time split over DMA/copy-shaped vs
     compute-shaped event names.

Usage: python tools/profile_capture.py [OUTDIR]   (default profile_r03)
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DMA_MARKERS = ("dma", "copy", "memcpy", "transfer", "infeed", "outfeed")


def _load_trace_events(out_dir: str) -> list[dict]:
    paths = sorted(
        glob.glob(os.path.join(out_dir, "**", "*.json.gz"), recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        return []
    with gzip.open(paths[-1], "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", data) if isinstance(data, dict) else data


def summarize(events: list[dict]) -> dict:
    pid_name: dict = {}
    tid_name: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e.get("pid")] = e.get("args", {}).get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_name[(e.get("pid"), e.get("tid"))] = e.get("args", {}).get(
                "name", ""
            )
    agg: dict = defaultdict(lambda: [0.0, 0])  # (proc, name) -> [us, count]
    proc_total: dict = defaultdict(float)
    for e in events:
        if e.get("ph") != "X":
            continue
        dur = float(e.get("dur", 0.0))
        proc = pid_name.get(e.get("pid"), str(e.get("pid")))
        key = (proc, e.get("name", "?"))
        agg[key][0] += dur
        agg[key][1] += 1
        proc_total[proc] += dur
    top = sorted(agg.items(), key=lambda kv: -kv[1][0])[:40]
    # device-side DMA vs compute split: XLA device tracks are the processes
    # that are not the python host thread
    device_procs = {
        p for p in proc_total if not p.lower().startswith(("python", "/host"))
    }
    dma_us = comp_us = 0.0
    for (proc, name), (us, _n) in agg.items():
        if proc not in device_procs:
            continue
        if any(m in name.lower() for m in DMA_MARKERS):
            dma_us += us
        else:
            comp_us += us
    return {
        "processes": {p: round(v, 1) for p, v in sorted(proc_total.items())},
        "device_dma_us": round(dma_us, 1),
        "device_compute_us": round(comp_us, 1),
        "top_events": [
            {
                "process": proc,
                "name": name,
                "total_us": round(us, 1),
                "count": n,
            }
            for (proc, name), (us, n) in top
        ],
    }


def main() -> int:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "profile_r03"
    summary_json = f"{out_dir}_summary.json"
    summary_md = f"{out_dir}_summary.md"
    import jax
    import jax.numpy as jnp

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.utils.timing import _sync

    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)
    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

    if not is_tpu_backend():
        print("not a TPU backend; refusing (trace would be host-only)",
              file=sys.stderr)
        return 3

    img = jnp.asarray(synthetic_image(4320, 7680, channels=1, seed=7))
    pipe = Pipeline.parse("gaussian:5")
    combined: dict = {}
    lines = [
        f"# Headline-kernel profiler trace summary ({out_dir})",
        "",
        f"8K 5x5 Gaussian, 30 iterations each on `{backend}` — u8 streaming "
        "(production headline) AND the SWAR quarter-strip variant, so the "
        "trace attributes where the wide path's time goes (DMA wait vs the "
        "in-kernel field compute), not just the u8 baseline's.",
    ]
    # one variant's failure must not cost the window the u8 trace:
    # trace variants independently, summarize whatever succeeded
    for variant in ("pallas", "swar"):
        vdir = out_dir if variant == "pallas" else f"{out_dir}_{variant}"
        try:
            fn = pipe.jit(backend=variant)
            _sync(fn(img))  # compile outside the trace
            _sync(fn(img))
            with jax.profiler.trace(vdir, create_perfetto_trace=True):
                out = None
                for _ in range(30):
                    out = fn(img)
                _sync(out)
            events = _load_trace_events(vdir)
            print(f"{variant}: trace events: {len(events)}", flush=True)
            summary = (
                summarize(events) if events else {"error": "no perfetto trace"}
            )
        except Exception as e:  # noqa: BLE001 — recorded per variant
            summary = {"error": str(e)[:300]}
        summary["iterations"] = 30
        summary["config"] = f"gaussian5_8k {variant}"
        combined[variant] = summary
        lines += [
            "",
            f"## {variant}",
            "",
            f"Raw trace: `{vdir}/` (perfetto json.gz).",
            "",
            f"Device DMA-shaped time: {summary.get('device_dma_us', 0)} us; "
            f"device compute-shaped time: "
            f"{summary.get('device_compute_us', 0)} us."
            + (f" ERROR: {summary['error']}" if "error" in summary else ""),
            "",
            "| process | event | total us | count |",
            "|---|---|---|---|",
        ]
        for t in summary.get("top_events", []):
            lines.append(
                f"| {t['process']} | {t['name'][:60]} | "
                f"{t['total_us']} | {t['count']} |"
            )
        # write after EVERY variant: a later variant wedging (and the step
        # timeout killing the process) must not lose an earlier variant's
        # completed measurement
        with open(summary_json, "w") as f:
            json.dump(combined, f, indent=1)
        with open(summary_md, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"wrote {summary_md} / {summary_json} ({variant})", flush=True)
    # the u8 headline trace is the round's required artifact; swar is
    # best-effort diagnosis
    return 0 if "error" not in combined["pallas"] else 1


if __name__ == "__main__":
    sys.exit(main())
