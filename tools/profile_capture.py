#!/usr/bin/env python
"""Capture a jax.profiler trace of the headline kernel and summarize it —
a thin capture shim over obs/profile.py (the parser/merge logic graduated
there; this file keeps the chip-window workflow and the file outputs).

Two modes:

  1. CAPTURE (default, TPU only): compile the headline pipeline (8K 5x5
     Gaussian), record `jax.profiler.trace(..., create_perfetto_trace=
     True)` around ~30 steady-state iterations for the u8 and SWAR
     variants, and write {OUTDIR}_summary.md + .json — per-track top
     events plus the device DMA-vs-compute split (the roofline
     corroboration artifact, VERDICT r2 #4).

  2. MERGE (`--merge-host-trace SPANS.json --device-trace DIR`, any
     backend): join an obs `--trace-out` host-span file with a Perfetto
     device trace onto ONE timeline — combined trace JSON for
     ui.perfetto.dev plus a single summary table interleaving host spans
     (serve.dispatch / engine.force / engine.encode ...) with device
     tracks, so host stalls vs DMA vs compute are one picture.

Usage:
  python tools/profile_capture.py [OUTDIR]            (capture; default
                                                       profile_r03)
  python tools/profile_capture.py --merge-host-trace spans.json \
      --device-trace profile_r03 [--out merged]       (merge + summarize)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_imagemanipulation_tpu.obs.profile import (  # noqa: E402
    DMA_MARKERS,  # noqa: F401  (re-export: round-3 scripts import it here)
    load_device_trace,
    merge_and_summarize,
    summarize,
    summary_table,
)


def _load_trace_events(out_dir: str) -> list[dict]:
    """Back-compat alias for the pre-graduation name."""
    return load_device_trace(out_dir)


def run_merge(args: argparse.Namespace) -> int:
    out = args.out or "merged_trace"
    merged_json = f"{out}.json"
    summary = merge_and_summarize(
        args.merge_host_trace, args.device_trace, merged_out=merged_json
    )
    lines = [
        "# Merged host-span + device-trace summary",
        "",
        f"Host spans: `{args.merge_host_trace}` "
        f"({summary['host_events']} events); device trace: "
        f"`{args.device_trace}` ({summary['device_events']} events); "
        f"combined timeline: `{merged_json}` (open in ui.perfetto.dev).",
        "",
        f"Device DMA-shaped time: {summary.get('device_dma_us', 0)} us; "
        f"device compute-shaped time: "
        f"{summary.get('device_compute_us', 0)} us.",
        "",
    ] + summary_table(summary)
    summary_md = f"{out}_summary.md"
    summary_json = f"{out}_summary.json"
    with open(summary_md, "w") as f:
        f.write("\n".join(lines) + "\n")
    with open(summary_json, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"wrote {merged_json} / {summary_md} / {summary_json}", flush=True)
    return 0


def run_capture(out_dir: str) -> int:
    summary_json = f"{out_dir}_summary.json"
    summary_md = f"{out_dir}_summary.md"
    import jax
    import jax.numpy as jnp

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.utils.timing import _sync

    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)
    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

    if not is_tpu_backend():
        print("not a TPU backend; refusing (trace would be host-only)",
              file=sys.stderr)
        return 3

    img = jnp.asarray(synthetic_image(4320, 7680, channels=1, seed=7))
    pipe = Pipeline.parse("gaussian:5")
    combined: dict = {}
    lines = [
        f"# Headline-kernel profiler trace summary ({out_dir})",
        "",
        f"8K 5x5 Gaussian, 30 iterations each on `{backend}` — u8 streaming "
        "(production headline) AND the SWAR quarter-strip variant, so the "
        "trace attributes where the wide path's time goes (DMA wait vs the "
        "in-kernel field compute), not just the u8 baseline's.",
    ]
    # one variant's failure must not cost the window the u8 trace:
    # trace variants independently, summarize whatever succeeded
    for variant in ("pallas", "swar"):
        vdir = out_dir if variant == "pallas" else f"{out_dir}_{variant}"
        try:
            fn = pipe.jit(backend=variant)
            _sync(fn(img))  # compile outside the trace
            _sync(fn(img))
            with jax.profiler.trace(vdir, create_perfetto_trace=True):
                out = None
                for _ in range(30):
                    out = fn(img)
                _sync(out)
            events = load_device_trace(vdir)
            print(f"{variant}: trace events: {len(events)}", flush=True)
            summary = (
                summarize(events) if events else {"error": "no perfetto trace"}
            )
        except Exception as e:  # noqa: BLE001 — recorded per variant
            summary = {"error": str(e)[:300]}
        summary["iterations"] = 30
        summary["config"] = f"gaussian5_8k {variant}"
        combined[variant] = summary
        lines += [
            "",
            f"## {variant}",
            "",
            f"Raw trace: `{vdir}/` (perfetto json.gz).",
            "",
            f"Device DMA-shaped time: {summary.get('device_dma_us', 0)} us; "
            f"device compute-shaped time: "
            f"{summary.get('device_compute_us', 0)} us."
            + (f" ERROR: {summary['error']}" if "error" in summary else ""),
            "",
        ] + summary_table(summary)
        # write after EVERY variant: a later variant wedging (and the step
        # timeout killing the process) must not lose an earlier variant's
        # completed measurement
        with open(summary_json, "w") as f:
            json.dump(combined, f, indent=1)
        with open(summary_md, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"wrote {summary_md} / {summary_json} ({variant})", flush=True)
    # the u8 headline trace is the round's required artifact; swar is
    # best-effort diagnosis
    return 0 if "error" not in combined["pallas"] else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="profile_capture")
    ap.add_argument("out_dir", nargs="?", default="profile_r03")
    ap.add_argument(
        "--merge-host-trace",
        default=None,
        metavar="SPANS_JSON",
        help="merge this obs --trace-out span file with --device-trace "
        "onto one timeline instead of capturing (works on any backend)",
    )
    ap.add_argument(
        "--device-trace",
        default=None,
        metavar="DIR_OR_JSON",
        help="jax.profiler output dir (newest *.json.gz inside) or a "
        "plain trace json; required with --merge-host-trace",
    )
    ap.add_argument(
        "--out",
        default=None,
        help="merge-mode output stem (default merged_trace)",
    )
    args = ap.parse_args(argv)
    if args.merge_host_trace:
        if not args.device_trace:
            ap.error("--merge-host-trace requires --device-trace")
        return run_merge(args)
    return run_capture(args.out_dir)


if __name__ == "__main__":
    sys.exit(main())
