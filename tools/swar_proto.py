#!/usr/bin/env python
"""SWAR quarter-strip prototype for the headline 5x5 Gaussian (run on TPU).

HISTORICAL NOTE (round 5): this prototype was designed against the
round-3 element-rate-cap hypothesis, which the round-5 round-robin probe
FALSIFIED (u8 copy kernels sustain ~550 GB/s; the compute kernels are
VPU-bound — BASELINE.md round-5 section). Its measurements remain the
record of why: the SWAR *compute* is 3.1x faster per element
(swar_xla_prepacked), the end-to-end production impl is 0.83x (pack and
unpack boundary costs), and the packed-u32 path is 3-4x slower (f32 lane
unpack pays the full element count plus overhead;
tools/packed_kernels._lanes_f32, demoted round 5).

The original design rationale, with two ingredients the production
packed path lacked:

1. **Quarter-strip (SoA) packing**: the row is split into 4 equal strips
   and byte k of word j is strip k's pixel j — so a horizontal tap is a
   plain word-column shift for all 4 strips simultaneously. No per-tap
   byte-granular recombination across words (the production packed
   layout interleaves adjacent pixels, forcing cross-lane byte algebra).
2. **SWAR 16-bit fields**: words are split once into two u32 arrays
   holding 2x16-bit fields each (bytes 0,2 and bytes 1,3). The whole
   separable correlation runs as u32 mul/add on those fields — 2 pixels
   per 32-bit element, half the VPU element count of f32-lane compute,
   and exact: binomial taps keep every field < 2^16
   (row max 255*16 = 4080; column max 4080*16 = 65,280), and the final
   x 2^-8 + round-half-to-even is the integer identity
   q = (s + 127 + (q0 & 1)) >> 8 with q0 = s >> 8 — asserted bit-exact
   against the golden StencilOp on every run before anything is timed.

Cases measured (each `device_throughput`, with the element-rate context):
  swar_xla_prepacked    — whole-array jnp SWAR on pre-packed input
                          (steady-state kernel bound; pack cost excluded)
  swar_pallas_prepacked — row-block streaming Pallas variant of the same
  swar_pack_cost        — the one-time quarter-strip pack + unpack round
                          trip (what a packed pipeline would amortise)
  gaussian5_8k_pallas   — the production u8 kernel, same process/chip state

Usage: python tools/swar_proto.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TAPS = (1, 4, 6, 4, 1)  # binomial_1d(5); scale 1/256 total (ops/filters.py)
H_ = 2  # halo


def build_fns():
    import jax
    import jax.numpy as jnp

    # python-int literals (not traced jnp constants: a pallas kernel body
    # must not capture tracers); & / + with a uint32 array stays uint32
    M_LO = 0x00FF00FF
    M_B = 0x00010001
    M_127 = 0x007F007F

    def pack_quarters(xpad):
        """(H+2h, W+2h) u8 reflect-padded plane -> (H+2h, Ws+2h) u32 words;
        byte k of word j = quarter-strip k's padded pixel j. Each strip's
        ext covers [k*Ws, k*Ws + Ws + 2h) of the padded row, so every
        horizontal tap is word-local."""
        Hp, Wp2 = xpad.shape
        Ws = (Wp2 - 2 * H_) // 4
        strips = [xpad[:, k * Ws : k * Ws + Ws + 2 * H_] for k in range(4)]
        stacked = jnp.stack(strips, axis=-1)  # (Hp, Ws+2h, 4) u8
        return jax.lax.bitcast_convert_type(stacked, jnp.uint32)

    def unpack_quarters(words):
        """(H, Ws) u32 -> (H, 4*Ws) u8 by reassembling the 4 quarter strips."""
        b = jax.lax.bitcast_convert_type(words, jnp.uint8)  # (H, Ws, 4)
        return jnp.concatenate([b[..., k] for k in range(4)], axis=1)

    def swar_gaussian5_words(ext):
        """(H+2h, Ws+2h) u32 ext words -> (H, Ws) u32 output words: the
        composition of the shared row/column helpers (the Pallas carry
        kernel uses the same two, so the variants cannot drift)."""
        return _col_finalize(*_row_pass_fields(ext))

    def swar_xla(ext_words):
        return swar_gaussian5_words(ext_words)

    def _row_pass_fields(ext_block):
        """(bh, Ws+2h) u32 words -> two (bh, Ws) u32 field arrays (bytes
        0,2 and 1,3 as 16-bit fields), row-correlated with the binomial
        taps. Fields <= 4080."""
        lo = ext_block & M_LO
        hi = (ext_block >> 8) & M_LO

        def row(a):
            acc = a[:, 0 : a.shape[1] - 4] * jnp.uint32(TAPS[0])
            for t in range(1, 5):
                acc = acc + a[:, t : a.shape[1] - 4 + t] * jnp.uint32(TAPS[t])
            return acc

        return row(lo), row(hi)

    def _col_finalize(lo_rows, hi_rows):
        """(bh+2h, Ws) field arrays -> (bh, Ws) u32 output words: column
        pass + x 2^-8 round-half-to-even + byte repack."""

        def col(a):
            acc = a[0 : a.shape[0] - 4, :] * jnp.uint32(TAPS[0])
            for t in range(1, 5):
                acc = acc + a[t : a.shape[0] - 4 + t, :] * jnp.uint32(TAPS[t])
            return acc

        def rnd(s):
            b = (s >> 8) & M_B
            return ((s + M_127 + b) >> 8) & M_LO

        return rnd(col(lo_rows)) | (rnd(col(hi_rows)) << 8)

    def make_swar_pallas(ext_shape, bh, *, interpret=False):
        """Streaming SWAR kernel with the production scratch-carry
        structure (ops/pallas_kernels.stencil_tile_pallas): input blocks of
        `bh` ext rows stream in non-overlapping; the row-passed fields of
        the previous block live in VMEM scratch, and output block i-1 is
        the column pass over [scratch ; first 2h rows of block i]. Any
        height (ragged tails produce garbage only at rows >= H, which the
        caller crops); needs bh >= 2h."""
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        Hp, Wsp = ext_shape  # (H+2h, Ws+2h)
        H = Hp - 2 * H_
        Ws = Wsp - 2 * H_
        assert bh >= 2 * H_, bh
        # ragged heights are fine: out rows >= H are garbage (OOB-padded
        # input blocks / duplicated tail rp) and the caller crops [:H] —
        # every REAL out row r reads ext rows [r, r+2h] which live in the
        # scratch block and the next block's first 2h rp rows by
        # construction, clamped index maps included (see the ragged
        # interpret-mode gate)
        nb = -(-H // bh)
        nb_in = -(-Hp // bh)  # last block holds the 2h-row bottom halo

        def kernel(in_ref, out_ref, lo_ref, hi_ref):
            i = pl.program_id(0)
            rlo, rhi = _row_pass_fields(in_ref[:])

            @pl.when(i >= 1)
            def _():
                lo_rows = jnp.concatenate([lo_ref[:], rlo[: 2 * H_]], axis=0)
                hi_rows = jnp.concatenate([hi_ref[:], rhi[: 2 * H_]], axis=0)
                out_ref[:] = _col_finalize(lo_rows, hi_rows)

            lo_ref[:] = rlo
            hi_ref[:] = rhi

        return pl.pallas_call(
            kernel,
            grid=(nb + 1,),
            in_specs=[
                pl.BlockSpec(
                    (bh, Wsp),
                    lambda i: (jnp.minimum(i, nb_in - 1), 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (bh, Ws),
                lambda i: (jnp.maximum(i - 1, 0), 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((nb * bh, Ws), jnp.uint32),
            scratch_shapes=[
                pltpu.VMEM((bh, Ws), jnp.uint32),
                pltpu.VMEM((bh, Ws), jnp.uint32),
            ],
            interpret=interpret,
        )

    return pack_quarters, unpack_quarters, swar_xla, make_swar_pallas


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--height", type=int, default=4320)
    ap.add_argument("--width", type=int, default=7680)
    args = ap.parse_args()
    # fixed-configuration probe: calibration must not steer the production
    # comparison case. Set inside main (not at import: tpu_validate and the
    # pytest gates import this module, and a module-level setdefault would
    # leak into their process env — review finding), restored on exit.
    saved_calib = os.environ.get("MCIM_NO_CALIB")
    os.environ["MCIM_NO_CALIB"] = "1"
    try:
        return _main(args)
    finally:
        if saved_calib is None:
            os.environ.pop("MCIM_NO_CALIB", None)
        else:
            os.environ["MCIM_NO_CALIB"] = saved_calib


def _main(args) -> int:

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        pipeline_pallas,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.ops.spec import pad2d
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

    pack_quarters, unpack_quarters, swar_xla, make_swar_pallas = build_fns()

    H, W = args.height, args.width
    assert W % 4 == 0
    Ws = W // 4
    print(f"backend: {jax.default_backend()}", flush=True)

    def emit(rec):
        print(json.dumps(rec), flush=True)

    # ---- bit-exactness gate (small image) BEFORE any timing ----
    pipe = Pipeline.parse("gaussian:5")
    for th, tw, seed in ((48, 64, 1), (37, 128, 2), (130, 256, 3)):
        img = jnp.asarray(synthetic_image(th, tw, channels=1, seed=seed))
        golden = np.asarray(pipe(img))
        xpad = pad2d(img.astype(jnp.float32), "reflect101", H_, H_, H_, H_)
        ext = pack_quarters(xpad.astype(jnp.uint8))
        outw = jax.jit(swar_xla)(ext)
        got = np.asarray(unpack_quarters(outw))
        if not np.array_equal(got, golden):
            d = np.argwhere(got != golden)
            print(
                f"SWAR MISMATCH at {th}x{tw}: {len(d)} pixels, first {d[0]} "
                f"got {got[tuple(d[0])]} want {golden[tuple(d[0])]}",
                file=sys.stderr,
            )
            return 1
    # the streaming kernel's carry structure, in interpret mode
    timg = jnp.asarray(synthetic_image(48, 64, channels=1, seed=4))
    tgold = np.asarray(pipe(timg))
    tpad = jnp.asarray(np.pad(np.asarray(timg), H_, mode="reflect"))
    text = pack_quarters(tpad)
    toutw = make_swar_pallas(text.shape, 16, interpret=True)(text)
    tgot = np.asarray(unpack_quarters(toutw[:48]))
    if not np.array_equal(tgot, tgold):
        print("SWAR pallas (carry) MISMATCH at 48x64", file=sys.stderr)
        return 1
    # ragged heights: 37 % 16 != 0 and 37 % 11... exercises the ceil-nb
    # clamped-index tail (garbage rows land at r >= H only, cropped)
    for rh, rbh in ((37, 16), (50, 24)):
        rimg = jnp.asarray(synthetic_image(rh, 64, channels=1, seed=6))
        rgold = np.asarray(pipe(rimg))
        rpad = jnp.asarray(np.pad(np.asarray(rimg), H_, mode="reflect"))
        rext = pack_quarters(rpad)
        routw = make_swar_pallas(rext.shape, rbh, interpret=True)(rext)
        rgot = np.asarray(unpack_quarters(routw[:rh]))
        if not np.array_equal(rgot, rgold):
            print(f"SWAR pallas ragged MISMATCH at {rh}x64 bh={rbh}",
                  file=sys.stderr)
            return 1
    print("bit-exactness gate: SWAR == golden on 3 shapes + carry kernel", flush=True)

    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

    if not is_tpu_backend():
        print("self-test passed; timing needs the chip — exiting", flush=True)
        return 0

    # ---- timing ----
    img = jnp.asarray(synthetic_image(H, W, channels=1, seed=99))
    xpad_u8 = jnp.asarray(
        np.pad(np.asarray(img), H_, mode="reflect")  # reflect101 == np reflect
    )
    ext = jax.jit(pack_quarters)(xpad_u8)
    ext.block_until_ready()
    mp = H * W / 1e6

    cases = [
        ("swar_xla_prepacked", jax.jit(swar_xla), [ext]),
    ]
    for bh in (120, 240, 480):
        if H % bh:
            continue
        f = jax.jit(lambda x, b=bh: make_swar_pallas(x.shape, b)(x)[:H, :])
        cases.append((f"swar_pallas_prepacked_bh{bh}", f, [ext]))
    cases += [
        (
            "swar_pack_cost",
            jax.jit(lambda x: unpack_quarters(pack_quarters(x))),
            [xpad_u8],
        ),
    ]
    # what a SINGLE-op production pipeline would pay: pad + pack, the
    # best streaming kernel, unpack — decides whether SWAR wins
    # stand-alone or only amortised across packed op chains
    cases.append(
        (
            "swar_end_to_end",
            jax.jit(
                lambda x: unpack_quarters(
                    make_swar_pallas(
                        (x.shape[0] + 2 * H_, x.shape[1] // 4 + 2 * H_),
                        240,
                    )(pack_quarters(jnp.pad(x, H_, mode="reflect")))[
                        : x.shape[0], :
                    ]
                )
            ),
            [img],
        )
    )
    cases += [
        (
            "gaussian5_8k_pallas",
            jax.jit(
                lambda x: pipeline_pallas(make_pipeline_ops("gaussian:5"), x)
            ),
            [img],
        ),
    ]
    rounds = 1 if args.quick else 3
    best: dict = {}
    for rnd in range(1, rounds + 1):
        for name, fn, fa in cases:
            try:
                sec = device_throughput(fn, fa)
            except Exception as e:
                emit({"case": name, "round": rnd, "error": str(e)[:200]})
                continue
            rec = {
                "case": name, "round": rnd, "ms": sec * 1e3,
                "mp_s": mp / sec,
            }
            emit(rec)
            if name not in best or sec < best[name][0]:
                best[name] = (sec, rec)
    for name, (sec, rec) in best.items():
        emit({**{k: v for k, v in rec.items() if k != "round"},
              "stat": f"best_of_{rounds}"})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
