#!/usr/bin/env python
"""Seeded chaos acceptance for the deadline/budget/hedge machinery
(ISSUE 18): the front door over TWO real pods (each a `fabric` CLI
subprocess with 2 replica processes), a compiled ChaosSchedule replayed
mid-traffic, then a single-pod brownout A/B proving hedged requests buy
back tail latency.

    python tools/chaos_smoke.py METRICS_OUT [SUMMARY_OUT]

Part A — chaos runs, one per fixed seed (MCIM_CHAOS_SEED overrides to a
single seed). Each run compiles `ChaosSchedule.compile(seed)` into
per-pod failpoint env (probabilistic forward/dispatch faults, dropped
replica + pod heartbeats, a sleep:MS dispatch brownout on one pod) plus
timed process faults (replica SIGKILL, SIGUSR1 preemption, one whole-pod
SIGKILL), drives >= 200 open-loop requests through the door with a
client deadline, and asserts the global invariants:

  1. every 200 is BIT-EXACT against the in-process golden — chaos may
     slow or refuse work, never corrupt it;
  2. zero late 200s: nothing lands after deadline + grace (the deadline
     chain refuses doomed work with 504 instead of finishing it late);
  3. zero unexplained failures: every response is 200, an explicit shed
     (503 + Retry-After) or a deadline verdict (504) — bare 503/599
     unavailability is a lost accepted request, which is the bug the
     whole tier exists to prevent;
  4. retry amplification is bounded at EVERY budgeted tier:
     withdrawn <= frac * deposits + reserve at the door and at the
     surviving pod's router (per-tier bound 1 + frac + reserve/N; the
     tiers compose multiplicatively in the worst case, which is why
     each tier enforces its own budget rather than trusting callers);
  5. every give-up is closed-vocabulary: reroute reasons within
     REROUTE_REASONS, deadline tiers within deadline.TIERS, hedge
     outcomes within HEDGE_OUTCOMES — straight from /metrics.

Part B — brownout A/B on one pod (2 replicas, chain lane): the
rendezvous-sticky replica for the test bucket (deterministic over
replica ids r0/r1) gets an unconditional `serve.dispatch=sleep:MS`
brownout via per-replica env; the same offered load runs with hedging
off then on (delay frac of the federated p99, cap 100%). Acceptance:
the hedged arm's p99 lands under the brownout floor the unhedged arm
cannot get below, with >= 1 hedge won. Both arms are appended to
BENCH_HISTORY.jsonl as `chaos_loadgen` records (tools/bench_regress.py
tracks goodput_rps up / e2e_p99_ms down).

METRICS_OUT gets the final chaos run's front-door exposition;
SUMMARY_OUT (optional) the whole acceptance summary as JSON.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# pods inherit this: fast beats keep staleness waits short under chaos
os.environ["MCIM_FED_HEARTBEAT_S"] = "0.25"

import numpy as np  # noqa: E402

from mpi_cuda_imagemanipulation_tpu.fabric.router import (  # noqa: E402
    RouterConfig,
    _rendezvous_score,
)
from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (  # noqa: E402
    Fabric,
    FabricConfig,
)
from mpi_cuda_imagemanipulation_tpu.federation.frontdoor import (  # noqa: E402
    REROUTE_REASONS,
    FrontDoor,
    FrontDoorConfig,
)
from mpi_cuda_imagemanipulation_tpu.graph import (  # noqa: E402
    compile_graph,
    graph_callable,
    parse_spec,
)
from mpi_cuda_imagemanipulation_tpu.io.image import (  # noqa: E402
    decode_image_bytes,
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.obs.metrics import (  # noqa: E402
    parse_exposition,
)
from mpi_cuda_imagemanipulation_tpu.resilience import (  # noqa: E402
    chaos,
    deadline as deadline_mod,
)
from mpi_cuda_imagemanipulation_tpu.serve import loadgen  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.serve.bucketing import (  # noqa: E402
    parse_buckets,
)
from mpi_cuda_imagemanipulation_tpu.utils import (  # noqa: E402
    env as env_registry,
)

OPS = "grayscale,contrast:3.5"
BUCKETS = "48,96"
STALE_S = 1.2
DEADLINE_MS = 6000.0   # client budget each chaos request carries
GRACE_MS = 2000.0      # covers one in-flight dispatch past the budget
BROWN_MS = 250         # part-B brownout floor on the sticky replica

SPEC = {
    "version": 1,
    "name": "unsharp",
    "nodes": [
        {"id": "src", "kind": "source"},
        {"id": "g", "kind": "op", "op": "grayscale", "input": "src"},
        {"id": "blur", "kind": "op", "op": "gaussian:5", "input": "g"},
        {"id": "mask", "kind": "merge", "merge": "subtract",
         "inputs": ["g", "blur"]},
    ],
    "outputs": {"image": "mask"},
}

# "already dead" shapes a chaos action may legitimately race into: a
# kill_replica scheduled after its whole pod was SIGKILLed, a preempt of
# a pid the supervisor already replaced. Swallowed by the actions (the
# fault's intent — that target is down — already holds); anything ELSE
# raising is a harness bug and must surface through ChaosRunner.errors.
_GONE = (
    ProcessLookupError, ConnectionError, TimeoutError, OSError,
    urllib.error.URLError, KeyError, TypeError, ValueError,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Pod:
    """One whole pod as a `fabric` CLI subprocess (same shape as
    tools/federation_smoke.py), plus the chaos delta: the compiled
    schedule's MCIM_FAILPOINTS spec baked into the pod's env at spawn —
    the router AND the replicas it spawns inherit it, so every armed
    site fires in the process that owns it."""

    def __init__(self, pod_id: str, frontdoor_url: str,
                 failpoints: str, seed: int):
        self.pod_id = pod_id
        self.port = _free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        env = dict(os.environ)
        if failpoints:
            env["MCIM_FAILPOINTS"] = failpoints
            env["MCIM_FAILPOINT_SEED"] = str(seed)
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "mpi_cuda_imagemanipulation_tpu",
                "fabric",
                "--replicas", "2",
                "--ops", OPS,
                "--buckets", BUCKETS,
                "--channels", "3",
                "--max-batch", "4",
                "--queue-depth", "64",
                "--host", "127.0.0.1",
                "--port", str(self.port),
                "--heartbeat-s", "0.2",
                "--stale-s", "0.8",
                "--federate", frontdoor_url,
                "--pod-id", pod_id,
            ],
            env=env,
            # its own process group: kill_pod (and teardown) can killpg
            # the supervisor AND every replica it spawned, even when the
            # pod's /stats is already unreachable mid-chaos
            start_new_session=True,
        )

    def stats(self) -> dict:
        with urllib.request.urlopen(self.url + "/stats", timeout=5) as r:
            return json.loads(r.read())

    def replica_pid(self, rid: str) -> int:
        return int(self.stats()["replicas"][rid]["pid"])

    def sigkill(self) -> None:
        """The whole pod, hard: one SIGKILL to the process group takes
        the supervisor and both replicas at once — nothing drains,
        nothing hands over, nothing leaks."""
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        self.proc.wait(timeout=10.0)

    def close(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=60.0)
            except Exception:
                pass
        # belt and braces: reap any straggler in the group (a replica
        # whose supervisor died before it could be drained)
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            self.proc.wait(timeout=10.0)
        except Exception:
            pass


def _post(url: str, path: str, data: bytes, headers=None):
    req = urllib.request.Request(
        url + path, data=data, headers=headers or {}, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _door_stats(url: str) -> dict:
    with urllib.request.urlopen(url + "/stats", timeout=10) as r:
        return json.loads(r.read())


def _get_metrics(url: str) -> str:
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        return r.read().decode()


def _label_values(exposition: str, family: str, label: str) -> set:
    fams = parse_exposition(exposition)
    out = set()
    fam = fams.get(family)
    if fam:
        for (_n, labels), _v in fam["samples"].items():
            if f'{label}="' in labels:
                out.add(labels.split(f'{label}="', 1)[1].split('"', 1)[0])
    return out


def _wait_pods(url: str, want: set, deadline_s: float = 240.0):
    t_end = time.monotonic() + deadline_s
    pods = {}
    while time.monotonic() < t_end:
        try:
            pods = _door_stats(url)["pods"]
        except Exception:
            pods = {}
        ready = {
            pid for pid, v in pods.items()
            if v["fresh"] and v["routable"] >= 2
        }
        if want <= ready:
            return
        time.sleep(0.2)
    raise TimeoutError(f"pods {sorted(want)} never joined (saw {pods.keys()})")


def _budget_bound_ok(stats: dict) -> bool:
    """The amplification invariant one tier enforces for itself:
    withdrawals never exceed frac * deposits + reserve."""
    return (
        stats["withdrawn"]
        <= stats["frac"] * stats["deposits"] + stats["reserve"] + 1e-9
    )


# --------------------------------------------------------------------------
# part A: one seeded chaos run
# --------------------------------------------------------------------------


def chaos_run(seed: int, rps: float, duration_s: float,
              metrics_out: str | None) -> dict:
    sched = chaos.ChaosSchedule.compile(
        seed,
        pods=("pod0", "pod1"),
        duration_s=duration_s,
        replicas_per_pod=2,
        brownout_ms=100,
    )
    print(f"chaos[{seed}]: schedule")
    for line in sched.trace():
        print(f"chaos[{seed}]:   {line}")
    tmp = tempfile.mkdtemp(prefix=f"chaos_smoke_{seed}_")
    door = FrontDoor(FrontDoorConfig(
        registry_path=os.path.join(tmp, "fed_registry.jsonl"),
        buckets=tuple(parse_buckets(BUCKETS)),
        stale_s=STALE_S,
        forward_timeout_s=20.0,
        forward_attempts=3,
    )).start(host="127.0.0.1", port=0)
    pods = {
        pid: _Pod(pid, door.url, sched.failpoints[pid], seed)
        for pid in sched.pods
    }

    def _kill_replica(ev):
        try:
            os.kill(
                pods[ev.pod].replica_pid(f"r{ev.detail}"), signal.SIGKILL
            )
        except _GONE:
            pass

    def _preempt_replica(ev):
        try:
            os.kill(
                pods[ev.pod].replica_pid(f"r{ev.detail}"), signal.SIGUSR1
            )
        except _GONE:
            pass

    def _kill_pod(ev):
        try:
            pods[ev.pod].sigkill()
        except _GONE:
            pass

    runner = chaos.ChaosRunner(sched, {
        "kill_replica": _kill_replica,
        "preempt_replica": _preempt_replica,
        "kill_pod": _kill_pod,
    })

    img = synthetic_image(40, 44, channels=3, seed=50)
    blob = encode_image_bytes(img)
    golden = np.asarray(
        graph_callable(compile_graph(parse_spec(SPEC)))(img)["image"]
    )
    try:
        _wait_pods(door.url, set(sched.pods))
        code, _h, out = _post(
            door.url, "/v1/tenants",
            json.dumps({"tenant": "acme", "qos": "interactive"}).encode(),
        )
        assert code == 200, (code, out[:200])
        code, _h, out = _post(
            door.url, "/v1/pipelines",
            json.dumps({"tenant": "acme", "spec": SPEC}).encode(),
        )
        assert code == 200, (code, out[:300])
        pipeline = json.loads(out)["pipeline"]
        headers = {"X-MCIM-Tenant": "acme", "X-MCIM-Pipeline": pipeline}
        # warm both pods (jit compile off the measured clock) before any
        # fault fires
        for pod in pods.values():
            code, _h, out = _post(pod.url, "/v1/process", blob, headers)
            assert code == 200, (pod.pod_id, code, out[:200])

        runner.start()
        rec = loadgen.http_run_offered_load(
            door.url, [blob], rps, duration_s,
            timeout_s=20.0, headers=headers, deadline_ms=DEADLINE_MS,
        )
        runner.stop()
        runner.join(timeout=10.0)
        results = rec.pop("results")

        # -- invariants ----------------------------------------------------
        assert not runner.errors, (
            f"chaos actions failed for their OWN reasons: {runner.errors}"
        )
        assert any(e.kind == "kill_pod" for e in runner.applied), (
            "the whole-pod SIGKILL never fired — the run proved nothing"
        )
        assert rec["submitted"] >= 200, (
            f"need >= 200 requests for the acceptance, got "
            f"{rec['submitted']} (raise MCIM_CHAOS_RPS/_DURATION_S)"
        )
        # 1. bit-exactness over every accepted-and-completed request
        for _k, r in results:
            if r["code"] == 200:
                np.testing.assert_array_equal(
                    decode_image_bytes(r["body"]), golden
                )
        # 2. zero late 200s past deadline + grace
        late = [
            r["e2e_s"] for _k, r in results
            if r["code"] == 200
            and r["e2e_s"] * 1e3 > DEADLINE_MS + GRACE_MS
        ]
        assert not late, (
            f"{len(late)} responses landed AFTER deadline+grace "
            f"(worst {max(late):.2f}s): the deadline chain finished "
            f"doomed work instead of refusing it"
        )
        # 3. no unexplained failure class
        assert rec["unavailable"] == 0, (
            f"{rec['unavailable']} bare-503/transport failures — "
            f"accepted requests were LOST, not refused "
            f"(ok={rec['ok']} shed={rec['shed']} "
            f"expired={rec['deadline_expired']})"
        )
        bad = {
            r["code"] for _k, r in results
            if r["code"] not in (200, 503, 504)
        }
        assert not bad, f"responses outside the closed contract: {bad}"
        assert rec["ok"] > 0.5 * rec["submitted"], (
            f"only {rec['ok']}/{rec['submitted']} completed — the "
            f"surviving capacity never carried the load"
        )
        # 4. per-tier amplification bounds (door + surviving pod router)
        door_budget = _door_stats(door.url)["retry_budget"]
        assert _budget_bound_ok(door_budget), door_budget
        survivor = next(
            p for p in sched.pods if p != sched.killed_pod()
        )
        pod_budget = pods[survivor].stats()["retry_budget"]
        assert _budget_bound_ok(pod_budget), pod_budget
        # 5. closed vocabularies, straight from the expositions
        door_expo = _get_metrics(door.url)
        pod_expo = _get_metrics(pods[survivor].url)
        reasons = _label_values(
            door_expo, "mcim_fed_reroutes_total", "reason"
        )
        assert reasons <= set(REROUTE_REASONS), (
            f"reroute reasons outside the vocabulary: "
            f"{reasons - set(REROUTE_REASONS)}"
        )
        for expo, where in ((door_expo, "door"), (pod_expo, survivor)):
            tiers = _label_values(
                expo, "mcim_deadline_expired_total", "tier"
            )
            assert tiers <= set(deadline_mod.TIERS), (where, tiers)
            outcomes = _label_values(
                expo, "mcim_hedge_requests_total", "outcome"
            )
            assert outcomes <= set(deadline_mod.HEDGE_OUTCOMES), (
                where, outcomes,
            )
        if metrics_out:
            with open(metrics_out, "w") as f:
                f.write(door_expo)
        print(
            f"chaos[{seed}]: {rec['submitted']} requests through "
            f"{len(runner.applied)} faults (killed {sched.killed_pod()}): "
            f"{rec['ok']} ok (100% bit-exact, 0 late), "
            f"{rec['shed']} shed, {rec['deadline_expired']} expired; "
            f"door budget {door_budget['withdrawn']:.0f}w/"
            f"{door_budget['deposits']:.0f}d, "
            f"{survivor} budget {pod_budget['withdrawn']:.0f}w/"
            f"{pod_budget['deposits']:.0f}d"
        )
        return {
            "seed": seed,
            "trace": list(sched.trace()),
            "applied": [e.kind for e in runner.applied],
            "killed_pod": sched.killed_pod(),
            "door_budget": door_budget,
            "survivor_budget": pod_budget,
            **{
                k: rec[k]
                for k in (
                    "submitted", "ok", "shed", "deadline_expired",
                    "unavailable", "ok_in_deadline", "goodput_rps",
                )
            },
        }
    finally:
        runner.stop()
        door.close()
        for pod in pods.values():
            pod.close()


# --------------------------------------------------------------------------
# part B: brownout A/B — hedging buys back the tail
# --------------------------------------------------------------------------


def brownout_ab(rps: float, duration_s: float) -> dict:
    img = synthetic_image(40, 44, channels=3, seed=60)
    blob = encode_image_bytes(img)
    # the chain lane routes rendezvous-sticky per bucket; replica ids
    # are deterministic (r0/r1), so the harness can compute which one
    # the traffic pins to and arm the brownout exactly there — the
    # other replica stays fast, which is precisely the asymmetry a
    # hedged request exploits
    sticky = max(
        ("r0", "r1"), key=lambda r: _rendezvous_score("48x48", r)
    )
    arms = {}
    digests = {}
    # delay frac 0.15, NOT larger: the trigger is a fraction of the
    # MEASURED federated p99, and the brownout inflates that p99 (queue
    # wait on the browned replica rides into the histograms). A frac
    # near 1/(1 + inflation) would push the trigger past the brownout
    # itself and hedging would silently stop — the feedback loop the
    # first cut of this harness hit at frac 0.3 under queueing.
    for arm, delay_frac in (("hedge_off", 0.0), ("hedge_on", 0.15)):
        fab = Fabric(FabricConfig(
            replicas=2,
            ops=OPS,
            buckets="48",
            channels="3",
            max_batch=4,
            queue_depth=64,
            heartbeat_s=0.2,
            router=RouterConfig(
                buckets=tuple(parse_buckets("48")),
                hedge_delay_frac=delay_frac,
                hedge_max_frac=1.0,
                # hedges WITHDRAW from the same retry budget as
                # reroutes (the shared amplification cap); the default
                # frac 0.1 would throttle this arm to ~10% hedged.
                # frac 1.0 = "every request may forward twice" — the
                # regime whose tail win this A/B measures
                retry_budget_frac=1.0,
            ),
            replica_env={sticky: {
                "MCIM_FAILPOINTS": f"serve.dispatch=sleep:{BROWN_MS}",
                "MCIM_FAILPOINT_SEED": "0",
            }},
        )).start(host="127.0.0.1", port=0)
        try:
            # off the measured clock: jit warmup on the sticky replica,
            # plus enough e2e samples that the router's federated p99
            # (the hedge trigger base) is live before the run
            for _ in range(8):
                r = loadgen.http_post_image(fab.router.url, blob)
                assert r["code"] == 200, (arm, r["code"], r["body"][:200])
            time.sleep(0.6)  # >= 2 heartbeats: fleet p99 lands
            rec = loadgen.http_run_offered_load(
                fab.router.url, [blob], rps, duration_s,
                timeout_s=15.0, deadline_ms=8000.0,
            )
            results = rec.pop("results")
            assert rec["unavailable"] == 0 and rec["shed"] == 0, rec
            assert rec["deadline_expired"] == 0, rec
            assert rec["ok"] == rec["submitted"], rec
            digests[arm] = {r["body"] for _k, r in results}
            assert len(digests[arm]) == 1, (
                f"{arm}: non-deterministic bodies across replicas"
            )
            hedge = fab.router.stats()["hedge"]
            won = fab.router._m_hedges.value(outcome="won")
            suppressed = sum(
                fab.router._m_hedges.value(outcome=o)
                for o in ("suppressed_cap", "suppressed_budget")
            )
            arms[arm] = {
                "config": "chaos_loadgen",
                "impl": arm,
                "platform": "cpu",
                "ops": OPS,
                "brownout_ms": BROWN_MS,
                "sticky_replica": sticky,
                "hedge_delay_frac": delay_frac,
                "hedges_fired": hedge["fired"],
                "hedges_won": won,
                "hedges_suppressed": suppressed,
                **{
                    k: rec[k]
                    for k in (
                        "offered_rps", "submitted", "ok",
                        "ok_in_deadline", "goodput_rps", "e2e_p50_ms",
                        "e2e_p99_ms", "wall_s",
                    )
                },
            }
        finally:
            fab.close(drain=False)
    # the two arms ran the same pipeline on the same pixels: one output
    assert digests["hedge_off"] == digests["hedge_on"], (
        "hedged responses diverged from unhedged ones bit-wise"
    )
    off, on = arms["hedge_off"], arms["hedge_on"]
    # the unhedged arm cannot get under the brownout floor (every
    # request rides the browned sticky replica)...
    assert off["e2e_p99_ms"] >= BROWN_MS, (
        f"brownout never bit: unhedged p99 {off['e2e_p99_ms']:.0f}ms "
        f"< sleep {BROWN_MS}ms"
    )
    # ...and the hedged arm must: its p99 is hedge-delay + a fast
    # dispatch, strictly inside the floor
    assert on["hedges_won"] >= 1, on
    assert on["e2e_p99_ms"] < BROWN_MS, (
        f"hedging did not buy back the tail: p99 "
        f"{on['e2e_p99_ms']:.0f}ms vs brownout {BROWN_MS}ms "
        f"({on['hedges_fired']} fired, {on['hedges_won']:.0f} won)"
    )
    print(
        f"ab: brownout sleep:{BROWN_MS} on {sticky}; p99 "
        f"{off['e2e_p99_ms']:.0f}ms unhedged -> {on['e2e_p99_ms']:.0f}ms "
        f"hedged ({on['hedges_fired']} fired, {on['hedges_won']:.0f} won, "
        f"goodput {off['goodput_rps']:.1f} -> {on['goodput_rps']:.1f} "
        f"req/s)"
    )
    return arms


def _append_history(arms: dict) -> None:
    entry = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "note": (
            f"chaos brownout A/B (ISSUE 18): chain lane, 2 replicas, "
            f"serve.dispatch=sleep:{BROWN_MS} on the rendezvous-sticky "
            f"replica; hedged requests (delay 0.15 x federated p99, cap "
            f"100%) vs hedging off — tools/chaos_smoke.py"
        ),
        "records": [arms["hedge_off"], arms["hedge_on"]],
    }
    from bench import git_head_sha

    sha = git_head_sha()
    if sha:
        entry["git_sha"] = sha
    if os.environ.get("MCIM_NO_HISTORY"):
        return
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "BENCH_HISTORY.jsonl"), "a") as f:
        f.write(json.dumps(entry) + "\n")


def main(metrics_out: str, summary_out: str | None = None) -> int:
    seed_env = env_registry.get("MCIM_CHAOS_SEED")
    seeds = [int(seed_env)] if seed_env else [11, 23]
    rps = float(env_registry.get("MCIM_CHAOS_RPS"))
    duration_s = float(env_registry.get("MCIM_CHAOS_DURATION_S"))
    runs = [
        chaos_run(
            seed, rps, duration_s,
            metrics_out if i == len(seeds) - 1 else None,
        )
        for i, seed in enumerate(seeds)
    ]
    # 8 req/s on a 2-replica pod whose sticky replica sleeps 250ms per
    # dispatch: enough load that the tail is real, little enough that
    # the browned replica's queue stays shallow (so the unhedged arm
    # measures the brownout, not an overload collapse)
    arms = brownout_ab(rps=8.0, duration_s=5.0)
    _append_history(arms)
    summary = {"chaos_runs": runs, "brownout_ab": arms}
    if summary_out:
        with open(summary_out, "w") as f:
            json.dump(summary, f, indent=2, default=str)
    print(f"chaos smoke: all invariants held -> {metrics_out}")
    return 0


if __name__ == "__main__":
    if len(sys.argv) not in (2, 3):
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1], *sys.argv[2:]))
