#!/usr/bin/env python
"""Prototype: packed-u32 streaming for u8 image kernels (A/B candidate).

Round-2 roofline question (BASELINE.md): the streaming kernels pin at
~92 GB/s effective on u8 tiles — is the cap *byte*-rate (nothing to do) or
*element*-rate (then moving 4 pixels per 32-bit lane quadruples pixel
throughput)? tools/roofline_probe.py's `pallas_copy_u32_packed` case
answers that on hardware; this prototype holds the matching compute-side
machinery so the A/B can run in the same healthy window:

  - pack/unpack helpers: (H, W) u8 <-> (H, W/4) u32 (little-endian byte 0
    = column 4j), unpack implemented with in-kernel i32 shifts/masks
    (Mosaic-lowerable; no gather, no u8 loads),
  - a packed grayscale+contrast pointwise kernel (3 packed planes in, one
    packed plane out),
  - a packed separable row-pass building the +/-h shifted columns from
    word shifts + cross-word byte rotations (gaussian:5 row pass).

Everything is validated bit-exact against the golden ops in interpret
mode / on CPU (`python tools/packed_proto.py` runs the self-test); only
the throughput question needs the chip.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_imagemanipulation_tpu.utils.platform import claim_platform

if __name__ == "__main__":
    # the selftest is CPU/interpret-only by design (docstring above) and
    # must never touch the possibly-wedged accelerator tunnel; the future
    # on-chip A/B gets its own entry point rather than an env override
    claim_platform("cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

F32 = jnp.float32
I32 = jnp.int32


def pack_u8(img: jnp.ndarray) -> jnp.ndarray:
    """(H, W) u8 -> (H, W//4) u32, byte k of word j = column 4j+k."""
    H, W = img.shape
    assert W % 4 == 0, "pad width to a multiple of 4 first"
    return jax.lax.bitcast_convert_type(
        img.reshape(H, W // 4, 4), jnp.uint32
    )


def unpack_u32(words: jnp.ndarray) -> jnp.ndarray:
    """(H, Wp) u32 -> (H, 4*Wp) u8 (inverse of pack_u8)."""
    H, Wp = words.shape
    return jax.lax.bitcast_convert_type(words, jnp.uint8).reshape(H, 4 * Wp)


def _lanes_i32(words: jnp.ndarray) -> list[jnp.ndarray]:
    """Split packed u32 words into 4 i32 byte-lane arrays (values 0..255).

    Pure shifts/masks on i32 — the ops Mosaic lowers natively (no u8
    anywhere inside the kernel body). Lane k holds columns 4j+k.
    """
    w = words.astype(I32) if words.dtype != I32 else words
    m = jnp.int32(0xFF)
    return [
        w & m,
        (w >> 8) & m,
        (w >> 16) & m,
        (w >> 24) & m,
    ]


def _pack_lanes_i32(lanes: list[jnp.ndarray]) -> jnp.ndarray:
    """Inverse of _lanes_i32: 4 i32 lane arrays (0..255) -> packed i32."""
    l0, l1, l2, l3 = lanes
    return l0 | (l1 << 8) | (l2 << 16) | (l3 << 24)


def _shift_lanes(lanes: list[jnp.ndarray], d: int):
    """Byte-lane view of the image shifted by d columns (d in [-3..3] per
    word step is enough for halo<=3 after combining with word shifts).

    Columns come from lane (k+d) mod 4 with a word shift when crossing a
    word boundary; out-of-range columns replicate the edge — the prototype
    only needs an edge-correct interior, the framework's real edge
    synthesis stays in the existing kernels.
    """
    out = []
    for k in range(4):
        src_lane = (k + d) % 4
        word_shift = (k + d) // 4  # -1, 0 or +1 for |d| <= 3
        lane = lanes[src_lane]
        if word_shift == 0:
            out.append(lane)
        elif word_shift > 0:
            shifted = jnp.concatenate(
                [lane[:, word_shift:], jnp.repeat(lane[:, -1:], word_shift, 1)],
                axis=1,
            )
            out.append(shifted)
        else:
            shifted = jnp.concatenate(
                [jnp.repeat(lane[:, :1], -word_shift, 1), lane[:, :word_shift]],
                axis=1,
            )
            out.append(shifted)
    return out


def packed_gray_contrast_kernel(r_ref, g_ref, b_ref, out_ref):
    """Reference grayscale + contrast 3.5 on packed u32 lanes, computed by
    the registry's own core functions (grayscale_core / make_contrast_core
    are the golden semantics' single source of truth and are built to run
    inside Pallas kernels on f32 values) — only the lane packing differs
    from the production kernels."""
    from mpi_cuda_imagemanipulation_tpu.ops.registry import (
        grayscale_core,
        make_contrast_core,
    )

    contrast = make_contrast_core(3.5)
    rl = _lanes_i32(r_ref[:])
    gl = _lanes_i32(g_ref[:])
    bl = _lanes_i32(b_ref[:])
    outs = []
    for k in range(4):
        gray = grayscale_core(
            rl[k].astype(F32), gl[k].astype(F32), bl[k].astype(F32)
        )
        outs.append(contrast(gray).astype(I32))
    out_ref[:] = _pack_lanes_i32(outs)


def packed_gray_contrast(r, g, b, *, interpret=False, block_h=128):
    """Row-blocked grid: the whole-image form OOMed the 16 MiB scoped-VMEM
    stack on a real v5e at 2160x960 words (~101 MiB of f32 lane temps);
    a (block_h, Wp) block keeps the temp footprint a few MiB."""
    H, Wp = r.shape
    bh = min(block_h, H)
    spec = pl.BlockSpec((bh, Wp), lambda i: (i, 0))
    call = pl.pallas_call(
        packed_gray_contrast_kernel,
        grid=(-(-H // bh),),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((H, Wp), I32),
        interpret=interpret,
    )
    return call(r.astype(I32), g.astype(I32), b.astype(I32))


def packed_row_corr_interior(words, w1d, *, halo):
    """Separable row pass on packed lanes (interior columns exact; edges
    replicate). Returns 4 f32 lane arrays."""
    lanes = _lanes_i32(words)
    wv = np.asarray(w1d, np.float32).reshape(-1)
    acc = None
    for i, wgt in enumerate(wv):
        d = i - halo
        sh = _shift_lanes(lanes, d)
        for k in range(4):
            term = sh[k].astype(F32) * np.float32(wgt)
            if acc is None:
                acc = [None] * 4
            acc[k] = term if acc[k] is None else acc[k] + term
    return acc


def _selftest() -> int:
    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline

    rgb = np.asarray(synthetic_image(64, 256, channels=3, seed=3))
    r8, g8, b8 = (jnp.asarray(rgb[..., c]) for c in range(3))

    # pack/unpack round trip
    packed = pack_u8(r8)
    assert np.array_equal(np.asarray(unpack_u32(packed)), np.asarray(r8))

    # lanes/pack round trip (i32 domain)
    lanes = _lanes_i32(packed.astype(I32))
    repacked = _pack_lanes_i32(lanes)
    assert np.array_equal(
        np.asarray(repacked.astype(jnp.uint32)), np.asarray(packed)
    )

    # packed grayscale+contrast vs the golden pipeline — block_h=24 forces
    # a multi-step grid WITH a ragged trailing block (64 = 2*24 + 16), the
    # row-blocked path the production-size TPU run takes (H=2160, bh=128
    # is also ragged); the default whole-image degenerate case (bh=min(128,
    # 64)=64, grid=1) is covered by packed_ab.py's cpu-validation path
    pipe = Pipeline.parse("grayscale,contrast:3.5")
    golden = np.asarray(pipe(jnp.asarray(rgb)))
    out_packed = packed_gray_contrast(
        pack_u8(r8), pack_u8(g8), pack_u8(b8), interpret=True, block_h=24
    )
    got = np.asarray(unpack_u32(out_packed.astype(jnp.uint32)))
    assert np.array_equal(got, golden), (
        f"packed gray+contrast mismatch: {np.abs(got.astype(int) - golden.astype(int)).max()}"
    )

    # packed gaussian:5 row pass (interior) vs direct correlation
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_gaussian

    op = make_gaussian(5)
    w1d = np.asarray(op.separable, np.float32).reshape(-1)
    acc_lanes = packed_row_corr_interior(pack_u8(r8).astype(I32), w1d, halo=2)
    acc = np.zeros((64, 256), np.float32)
    for k in range(4):
        acc[:, k::4] = np.asarray(acc_lanes[k])
    x = np.asarray(r8).astype(np.float32)
    ref = np.zeros_like(x)
    for i, wgt in enumerate(w1d):
        d = i - 2
        idx = np.clip(np.arange(256) + d, 0, 255)
        ref += x[:, idx] * wgt
    interior = slice(4, -4)
    assert np.allclose(acc[:, interior], ref[:, interior]), "row-pass mismatch"

    print("packed_proto selftest: all ok")
    return 0


if __name__ == "__main__":
    sys.exit(_selftest())
