#!/bin/bash
# Pod-level systolic execution on silicon (round 7, ISSUE 16): DAG
# stages sharded across replicas, row-band tiles streaming over ICI.
#
# Two records, both bit-exactness-gated before any timing:
#
#   systolic_ab   the bench lane — a real 2-replica systolic pod vs the
#                 pinned single-replica path on the >= 8-stage headline
#                 chain (same offered requests, byte-identical bodies
#                 required pre-timing); columns: req/s + p99 per lane,
#                 transport forwards per request (must equal stage
#                 boundaries crossed), exchange bytes/request. On TPU
#                 the question is real: does streaming tiles between
#                 stage-owning replicas over ICI beat one replica
#                 walking all stages, once per-stage VMEM residency is
#                 on the table?
#   device lane   the in-process sharded executor (parallel/systolic):
#                 the wavefront over a real stage mesh — its exchange
#                 count is proven STRUCTURALLY (collective-permute count
#                 in the lowered HLO == stage boundaries), so the lane
#                 records MP/s at n_devices=2/4 against --plan off.
#
# The smoke then proves the full pod contract on the chip: placement
# across both replicas, one transport forward per boundary, SIGKILL of
# a stage owner mid-load -> counted fallback with 100% of accepted
# requests bit-exact, mcim_systolic_* parsing federated on the router.
# Knobs: MCIM_SYSTOLIC_AB_OPS / _REQUESTS / _HEIGHT, MCIM_SYSTOLIC_AB_JSON.
# Budget: ~5-8 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/systolic_r07.out
: > "$out"
timeout 1500 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config systolic_ab \
  --json-metrics artifacts/systolic_ab_r07.json >> "$out" 2>&1 || true
MCIM_SYSTOLIC_AB_JSON=artifacts/systolic_smoke_r07.json \
timeout 900 python tools/systolic_smoke.py \
  artifacts/systolic_metrics_r07.prom >> "$out" 2>&1 || true
commit_artifacts "TPU window: pod-level systolic A/B + pod smoke (round 7)" \
  "$out" artifacts/systolic_ab_r07.json artifacts/systolic_metrics_r07.prom
exit 0
