#!/bin/bash
# On-chip block-height autotune (VERDICT r3 priority #6): sweep the
# headline pipeline's block heights and commit the calibration store, so
# the store finally holds a measured entry and 55_ records the headline
# with calibration live.
# Wall-time budget: ~8-12 min (one compile per candidate block height;
# none cached — the sweep has never run on chip).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2400 python -m mpi_cuda_imagemanipulation_tpu autotune \
  --json-metrics artifacts/autotune_r05.jsonl > artifacts/autotune_r05.out 2>&1
rc=$?
arts=(artifacts/autotune_r05.out)
[ -f artifacts/autotune_r05.jsonl ] && arts+=(artifacts/autotune_r05.jsonl)
[ -f .mcim_calibration.json ] && arts+=(.mcim_calibration.json)
commit_artifacts "TPU window: on-chip block-height autotune (round 4)" \
  "${arts[@]}"
exit $rc
