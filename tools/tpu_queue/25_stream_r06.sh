#!/bin/bash
# Streaming tile engine lane (round 6): the stream_ab bench lane on real
# hardware — serial whole-image vs streamed fixed-shape row bands over
# the SAME op chain (bit-exactness gated before any timing). Headline
# columns: e2e img/s per lane, per-lane device-idle fraction (the
# overlap proof: streamed must sit below serial), and peak resident
# bytes per lane (the constant-memory proof: the streamed lane's peak
# follows tile_rows, not image size). On TPU the tile budget is worth
# sweeping upward — HBM fits far bigger bands than the CI smoke's, and
# the MXU banded backend is eligible inside tiles (--impl mxu streams
# bit-exact; stream/tiles.py routes per stencil exactly like the
# whole-image paths).
# Also runs one gigapixel-scale demo through the CLI so the window
# leaves a measured "problem size decoupled from footprint" record:
# 100000x4096 synthetic rows through a 1024-row budget.
# Knobs: MCIM_STREAM_AB_HEIGHT / _WIDTH / _TILE_ROWS.
# Budget: ~2-4 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/stream_ab_r06.out
: > "$out"
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config stream_ab >> "$out" 2>&1
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.cli stream \
  --synthetic 100000x4096x3 --output artifacts/_stream_giga.png \
  --ops grayscale,contrast:3.5,emboss:3 --tile-rows 1024 --inflight 4 \
  --show-timing --json-metrics artifacts/stream_giga_r06.json \
  >> "$out" 2>&1
rm -f artifacts/_stream_giga.png
commit_artifacts "TPU window: streaming tile engine A/B + gigapixel record (round 6)" \
  "$out" artifacts/stream_giga_r06.json
exit 0
