#!/bin/bash
# Pod-fabric availability + scaling lane (round 6): the fabric_loadgen
# bench lane on real hardware — the SAME open-loop HTTP mix against a
# replicas=1 pod and a replicas=3 pod (each replica a full serve stack on
# its own process; on a multi-chip host give each replica its own chip
# via the supervisor env), then the churn phases: SIGKILL the hottest
# replica mid-sweep and report ok%/retried%/p99 before/during/after plus
# the supervisor respawn. Headline columns: achieved rps per lane, the
# replicas=3 / replicas=1 scaling factor (>= 2x gate), and during-kill
# ok% (100% = rerouting works; the during-phase retried% is the price).
# On TPU the synthetic per-dispatch device floor is OFF — the lane
# measures real chips (bench_suite.fabric_loadgen_params).
# Knobs: MCIM_FABRIC_RPS / MCIM_FABRIC_DURATION_S / MCIM_FABRIC_REPLICAS.
# Budget: ~4-6 min warm (3 pod stand-ups; each replica pays the serving
# grid warmup: ~10-15 min cold).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/fabric_loadgen_r06.out
: > "$out"
timeout 2400 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config fabric_loadgen >> "$out" 2>&1
commit_artifacts "TPU window: pod-fabric scaling + churn lane (round 6)" "$out"
exit 0
