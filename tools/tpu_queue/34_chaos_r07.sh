#!/bin/bash
# Seeded chaos + deadline/budget/hedge acceptance on silicon (round 7,
# ISSUE 18): the front door over two real pods under a compiled
# ChaosSchedule, then the single-pod brownout A/B for hedged requests.
#
# One script, two deliverables:
#
#   chaos runs        two fixed seeds through door -> 2 pods x 2
#                     replicas with the schedule's failpoint env baked
#                     into each pod (probabilistic forward/dispatch
#                     faults, dropped replica + pod beats, a sleep:MS
#                     dispatch brownout) and its timed process faults
#                     replayed mid-traffic (replica SIGKILL, SIGUSR1
#                     preemption, one whole-pod SIGKILL). Acceptance is
#                     absolute, not statistical: every 200 bit-exact,
#                     zero 200s past deadline+grace, zero bare-503/599
#                     losses, withdrawn <= frac*deposits + reserve at
#                     the door AND the surviving pod's router, every
#                     give-up reason inside its closed vocabulary.
#   chaos_loadgen     the brownout A/B record pair (hedge_off vs
#                     hedge_on) appended to BENCH_HISTORY.jsonl —
#                     tools/bench_regress.py tracks goodput_rps up and
#                     e2e_p99_ms down. On TPU the open question is how
#                     much tail the hedge buys when the brownout is
#                     real device contention rather than an injected
#                     sleep — the same harness answers it unchanged.
#
# Knobs: MCIM_CHAOS_SEED (pin one seed), MCIM_CHAOS_RPS /
# _DURATION_S (load per chaos run), MCIM_FED_HEARTBEAT_S.
# Budget: ~8-12 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/chaos_r07.out
: > "$out"
timeout 1500 python tools/chaos_smoke.py \
  artifacts/chaos_metrics_r07.prom \
  artifacts/chaos_smoke_r07.json >> "$out" 2>&1 || true
commit_artifacts "TPU window: seeded chaos + hedging A/B (round 7)" \
  "$out" artifacts/chaos_metrics_r07.prom artifacts/chaos_smoke_r07.json
exit 0
