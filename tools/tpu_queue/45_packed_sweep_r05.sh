#!/bin/bash
# Full-suite packed-impl sweep: packed numbers for every bench config
# (bench.py only races packed on the headline).
# Wall-time budget: ~10-15 min (one compile per config shape; several are
# cold for the packed impl). Partial .jsonl/.out commit on a wedge.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 3000 python -m mpi_cuda_imagemanipulation_tpu bench --impl packed \
  --json-metrics artifacts/bench_packed_r05.jsonl > artifacts/bench_packed_r05.out 2>&1
rc=$?
arts=(artifacts/bench_packed_r05.out)
[ -f artifacts/bench_packed_r05.jsonl ] && arts+=(artifacts/bench_packed_r05.jsonl)
commit_artifacts "TPU window: full packed-impl bench sweep (round 4)" \
  "${arts[@]}"
exit $rc
