#!/bin/bash
# Profiler trace, u8 AND swar variants (VERDICT r3 priority #5; round-2
# directive #4): the DMA-wait vs compute vs overhead breakdown that
# attributes the swar slowdown independently of more A/Bs.
# Wall-time budget: ~4-6 min warm (kernels cached after 05_/10_; tracing
# adds seconds). profile_capture.py writes summaries after every variant,
# so a later wedge cannot strand a completed trace.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2400 python tools/profile_capture.py artifacts/profile_r05 > artifacts/profile_r05.out 2>&1
rc=$?
arts=(artifacts/profile_r05.out)
[ -f artifacts/profile_r05_summary.md ] && arts+=(artifacts/profile_r05_summary.md)
[ -f artifacts/profile_r05_summary.json ] && arts+=(artifacts/profile_r05_summary.json)
commit_artifacts "TPU window: headline-kernel profiler trace (round 4)" \
  "${arts[@]}"
exit $rc
