#!/bin/bash
# SWAR quarter-strip prototype timing: the element-rate exploitation design
# that the measured-slow packed-f32-lane path lacked (see tools/swar_proto.py
# docstring). Bit-exactness gates run before any timing; 3-round per-case
# bests like the roofline probe. If swar_pallas beats the production u8
# kernel (~0.7 ms best window), promote the design into ops/ next.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2700 python tools/swar_proto.py > swar_proto_r03.out 2>&1
rc=$?
commit_artifacts "TPU window: SWAR quarter-strip prototype timings" \
  swar_proto_r03.out
exit $rc
