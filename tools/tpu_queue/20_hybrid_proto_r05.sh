#!/bin/bash
# Hybrid SWAR end-to-end candidates (BASELINE.md round-5 "where the next
# perf win actually is"): pack -> field compute -> unpack as ONE jitted
# XLA program (and an XLA-pack + Pallas-compute variant), measured against
# the production u8 kernel in the same process. The window that closed the
# SWAR-vs-u8 production decision saw hybrid_xla_nounpack at 0.422 ms vs
# pallas 0.604 ms same-process; this step captures the complete, committed
# comparison (incl. the full e2e case the first look lost to an output
# truncation). Budget: ~3-5 min warm (compute executables cached), ~8 cold.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1200 python tools/hybrid_proto.py \
  > artifacts/hybrid_proto_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: hybrid pack/compute/unpack split-design measurements" \
  artifacts/hybrid_proto_r05.out
exit $rc
