#!/bin/bash
# Profiler trace of the headline kernel, u8 AND packed variants (the packed
# trace attributes where the slow path's time goes). Artifacts commit even
# on a partial failure — profile_capture.py writes its summaries after
# every variant precisely so a later wedge cannot strand a completed trace.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 3000 python tools/profile_capture.py profile_r03 > profile_r03.out 2>&1
rc=$?
commit_artifacts "TPU window: headline-kernel profiler trace summary" \
  profile_r03.out profile_r03_summary.md profile_r03_summary.json
exit $rc
