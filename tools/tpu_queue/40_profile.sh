#!/bin/bash
# Profiler trace of the headline kernel + DMA-vs-compute summary.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1800 python tools/profile_capture.py profile_r03 > profile_r03.out 2>&1 || exit $?
commit_artifacts "TPU window: headline-kernel profiler trace summary" \
  profile_r03.out profile_r03_summary.md profile_r03_summary.json
