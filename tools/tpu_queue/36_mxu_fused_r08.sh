#!/bin/bash
# MXU inside the megakernel (round 8, ISSUE 20): the first silicon
# measurement of the in-stage dot arms. One fused halo-6 stage
# (gaussian:5 -> sharpen -> box:5) timed FIVE ways on the 8K frame:
#
#   off             the unfused baseline (`--plan off`)
#   fused_vpu       the megakernel, every op on the VPU shift walk
#   fused_mxu       the megakernel, eligible ops as in-kernel banded
#                   dot_general contractions (f32/bf16 accumulate)
#   fused_mxu_int8  same contraction, int8 operands + int32 accumulate
#                   (only arms whose exactness is proven under 2^24)
#   mxu_whole_op    the existing whole-op MXU backend (PR 23's path) —
#                   the "is fusion + MXU better than MXU alone" control
#
# All five lanes are bit-exactness-gated against `--plan off` on three
# odd shapes BEFORE any timing; a gate failure aborts the record.
# Predictions are pre-registered in BASELINE.md ("MXU-in-stage arms"):
# fused_mxu 1.15-1.6x over fused_vpu (roofline_frac 0.65-0.85),
# int8 1.0-1.25x over f32, fused_mxu >= 1.8x over mxu_whole_op.
# roofline_frac < 0.60 or int8 < f32 refutes the design — see the
# promote/hold/shelve decision procedure there. The committed CPU
# record is an interpret-mode gate anchor, NOT a perf claim (the
# banded dot does ~26x the arithmetic of the walk off-chip).
#
# Knobs: MCIM_MXU_FUSED_AB_OPS / _HEIGHT / _WIDTH (lane shape).
# Budget: ~4-6 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/mxu_fused_r08.out
: > "$out"
timeout 600 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config mxu_fused_ab \
  --json-metrics artifacts/mxu_fused_ab_r08.json >> "$out" 2>&1 || true
# promote the lane record into the history (the bench_regress input)
python - >> "$out" 2>&1 <<'EOF' || true
import datetime, json, subprocess
rec = json.load(open("artifacts/mxu_fused_ab_r08.json"))
sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                     capture_output=True, text=True).stdout.strip()
line = {"ts": datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ"),
        "records": [rec],
        "note": "mxu_fused_ab on silicon (round 8): in-stage dot arms "
                "vs the VPU walk vs whole-op MXU, scored against the "
                "BASELINE.md pre-registered targets",
        "git_sha": sha}
with open("BENCH_HISTORY.jsonl", "a") as f:
    f.write(json.dumps(line) + "\n")
EOF
# pre-merge sentinel: the fresh record vs the committed trajectory
timeout 120 python tools/bench_regress.py \
  --candidate artifacts/mxu_fused_ab_r08.json >> "$out" 2>&1 || true
commit_artifacts "TPU window: in-stage MXU fused A/B (round 8)" \
  "$out" BENCH_HISTORY.jsonl artifacts/mxu_fused_ab_r08.json
exit 0
