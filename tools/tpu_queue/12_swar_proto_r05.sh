#!/bin/bash
# SWAR quarter-strip prototype timing (VERDICT r3 priority #1, second leg):
# the element-rate exploitation design the packed-f32-lane path lacked.
# Predictions pre-registered in BASELINE.md (2-4x if element-rate-bound).
# Bit-exactness gates run before any timing; 3-round per-case bests.
# If swar_pallas beats the production u8 kernel, promote into ops/ next.
# Wall-time budget: ~6-8 min warm (carry-kernel compiles are small but
# none are cached from round 3 — this tool never got a window). The .out
# streams per-round records, so it commits even on a mid-run wedge.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2400 python tools/swar_proto.py > artifacts/swar_proto_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: SWAR quarter-strip prototype timings (round 4)" \
  artifacts/swar_proto_r05.out
exit $rc
