# Shared helper for queue steps (not a step itself: the watcher only runs
# [0-9]*.sh). commit_artifacts commits EXACTLY the listed paths (pathspec
# commit — never sweeps unrelated staged work into a watcher commit),
# tolerates nothing-to-commit (re-captured identical artifact), and treats
# a persistently failing commit as non-fatal: the measurement succeeded and
# the artifacts are on disk, so burning another serialized chip campaign to
# re-produce them would be strictly worse than picking them up in the next
# manual commit.
# Persistent XLA compilation cache, shared by every queue step: a step
# retried after a mid-compile wedge (observed: 15_quick_headline2 burned a
# whole 35-min try inside one 8K compile) reuses the executable from any
# earlier attempt or window and gets to the measurement in seconds. The
# cache keys on HLO + compile options, so it can never change results.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$(pwd)/tools/.jax_cache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
mkdir -p artifacts

commit_artifacts() {
  local msg="$1"
  shift
  for _i in 1 2 3; do
    # a failed add (e.g. the driver session holding .git/index.lock while
    # it commits its own artifacts) must retry, not fall through to the
    # nothing-staged check and masquerade as "nothing new to commit"
    if ! git add -- "$@"; then
      echo "commit_artifacts: git add failed (try $_i); retrying" >&2
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- "$@" 2>/dev/null; then
      echo "commit_artifacts: nothing new to commit for: $*"
      return 0
    fi
    git commit -m "$msg" -- "$@" && return 0
    sleep 5
  done
  echo "commit_artifacts: commit failed; artifacts left on disk: $*" >&2
  return 0
}
