#!/bin/bash
# Second quick headline capture: the first window's capture ran cold at
# 01:00Z and recorded 14,075 MP/s — 3.4x below the same kernel's same-window
# probe measurement minutes later. Re-capture early in the next window so
# the round's committed history holds a warm record (bench.py promotes the
# BEST same-round record, so a fresh healthy number supersedes the cold one).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2100 python tools/quick_headline.py > quick_headline2_r03.out 2>&1
rc=$?
commit_artifacts "TPU window: second same-round headline capture" \
  BENCH_HISTORY.jsonl quick_headline2_r03.out
exit $rc
