#!/bin/bash
# Hardware validation sweep (VERDICT r3 priority #4): registry-wide
# compiled-Mosaic correctness incl. the archived packed kernels (known
# narrow-plane miscompares recorded as xfail — see tools/packed_kernels
# docstring), mesh(1) + 2-D(1x1) sharded, guarded-mode and compiled-SWAR
# cases.
# Wall-time budget: ~15-25 min warm (dominated by per-case compiles the
# cache has never seen; re-tries after a wedge resume from the cache and
# drop to ~5 min). Longest step — deliberately behind the decisive bundle.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 3600 python tools/tpu_validate.py --out VALIDATE_r05.json \
  > artifacts/validate_r05b.out 2>&1
rc=$?
arts=(artifacts/validate_r05b.out)
[ -f VALIDATE_r05.json ] && arts+=(VALIDATE_r05.json)
commit_artifacts "TPU window: hardware validation sweep (round 5 re-run)" "${arts[@]}"
exit $rc
