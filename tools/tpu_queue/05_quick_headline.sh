#!/bin/bash
# First-window fast capture: one TPU headline record into BENCH_HISTORY.jsonl.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1200 python tools/quick_headline.py > quick_headline_r03.out 2>&1 || exit $?
commit_artifacts "TPU window: same-round headline record (quick capture)" \
  BENCH_HISTORY.jsonl quick_headline_r03.out
