#!/bin/bash
# First-window fast capture: one TPU headline record into BENCH_HISTORY.jsonl.
# The history commit runs even when the python step fails partway (a wedge
# after the first impl's measurement must not strand a committed-worthy
# same-round record on disk); the step's own success still gates .done.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2100 python tools/quick_headline.py > quick_headline_r03.out 2>&1
rc=$?
commit_artifacts "TPU window: same-round headline record (quick capture)" \
  BENCH_HISTORY.jsonl quick_headline_r03.out
exit $rc
