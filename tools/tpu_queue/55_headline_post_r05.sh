#!/bin/bash
# Post-autotune headline capture: records the headline with the committed
# calibration live. bench.py promotes the best same-round TPU record, so
# this only moves the artifact of record if calibration actually wins.
# Wall-time budget: ~1-3 min warm (+ one compile if the calibrated block
# height differs from the heuristic's — that compile IS the point).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1800 python tools/quick_headline.py > artifacts/quick_headline_post_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: post-autotune headline capture (round 4)" \
  BENCH_HISTORY.jsonl artifacts/quick_headline_post_r05.out
exit $rc
