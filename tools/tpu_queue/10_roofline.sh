#!/bin/bash
# Roofline ceilings probe: XLA copy / Pallas u8 / f32 / packed-u32 / lagged.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2400 python tools/roofline_probe.py > roofline_r03.out 2>&1 || exit $?
commit_artifacts "TPU window: roofline probe results (round 3)" roofline_r03.out
