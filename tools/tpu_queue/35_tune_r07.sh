#!/bin/bash
# Continuous autotuning on silicon (round 7, ISSUE 19): the closed
# control loop — online dispatch observations -> UCB candidate ranking
# -> canary-gated actuation — measured where the arms are real
# (fused-pallas is a candidate only on TPU; is_tpu_backend gates it).
#
# One script, two deliverables:
#
#   tune_convergence  the bench lane appended to BENCH_HISTORY.jsonl:
#                     wall time + dispatch count from "pinned to the
#                     slow plan, empty store" until the controller has
#                     explored the fast arm through the canary gate
#                     (real shadow comparisons) and promoted it, plus
#                     the tuned-vs-pinned MP/s payoff. On TPU the open
#                     question is whether the loop finds fused-pallas
#                     (the megakernel's win is real on chip, interpret
#                     elsewhere) — set MCIM_TUNE_ARMS to widen the arm
#                     set once 31_burndown's plan records exist.
#                     tools/bench_regress.py tracks converge_s down and
#                     tuned_mp_per_s_per_chip up.
#   tune smoke        the multi-process proof against a REAL pod:
#                     2 replicas pinned slow converge under offered
#                     load with zero unavailable responses, a poisoned
#                     candidate (tune.candidate failpoint) is caught by
#                     the FIRST shadow digest and quarantined, and the
#                     federated mcim_tune_* exposition parses.
#
# Knobs: MCIM_TUNE_CONV_OPS / _HEIGHT / _WIDTH (lane shape),
# MCIM_TUNE_ARMS (candidate set), MCIM_TUNE_MIN_GAIN.
# Budget: ~6-10 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/tune_r07.out
: > "$out"
timeout 900 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config tune_convergence \
  --json-metrics artifacts/tune_convergence_r07.json >> "$out" 2>&1 || true
# promote the lane record into the history (the bench_regress input)
python - >> "$out" 2>&1 <<'EOF' || true
import datetime, json, subprocess
rec = json.load(open("artifacts/tune_convergence_r07.json"))
sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                     capture_output=True, text=True).stdout.strip()
line = {"ts": datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ"),
        "records": [rec],
        "note": "tune_convergence on silicon (round 7): the control "
                "loop converging on real chip timings",
        "git_sha": sha}
with open("BENCH_HISTORY.jsonl", "a") as f:
    f.write(json.dumps(line) + "\n")
EOF
timeout 900 python tools/tune_smoke.py \
  artifacts/tune_metrics_r07.prom \
  artifacts/tune_smoke_r07.json >> "$out" 2>&1 || true
commit_artifacts "TPU window: autotune convergence + tune smoke (round 7)" \
  "$out" BENCH_HISTORY.jsonl artifacts/tune_convergence_r07.json \
  artifacts/tune_metrics_r07.prom artifacts/tune_smoke_r07.json
exit 0
