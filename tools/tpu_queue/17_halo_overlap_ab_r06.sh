#!/bin/bash
# Sharded serial-vs-overlap halo A/B (round 6): per mesh size, how much of
# the ring-ppermute ghost-exchange latency does the interior-first
# overlapped execution (parallel/api.py halo_mode=overlap) hide behind
# interior compute? Two records per mesh size:
#   1. the serial lane with MCIM_HALO_AB=1 — carries serial_ms/overlap_ms,
#      the per-group comms/compute breakdown and comms_hidden_frac
#      (bench_suite._halo_ab) alongside MP/s;
#   2. the overlap lane as its own first-class MP/s record (A/B re-timing
#      suppressed — the pair above already has both numbers).
# Single-chip (shards=1) rides along as the zero-comms control: ghost
# strips are zeros there, so serial==overlap within noise bounds the
# measurement floor. Budget: ~4-8 min warm per mesh size (sharded 8K
# executables cached from 16_sharded_r05; the overlap executables are new
# compiles on the first window).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/halo_overlap_ab_r06.out
: > "$out"
ndev=$(timeout 120 python -c 'import jax; print(len(jax.devices()))' 2>/dev/null || echo 1)
for shards in 1 2 4 8; do
  [ "$shards" -gt "$ndev" ] && break
  echo "=== mesh size $shards ===" >> "$out"
  MCIM_HALO_AB=1 timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
    --config gaussian5_8k_sharded --impl pallas --shards "$shards" \
    >> "$out" 2>&1
  MCIM_HALO_AB=0 timeout 900 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
    --config gaussian5_8k_sharded_overlap --impl pallas --shards "$shards" \
    >> "$out" 2>&1
done
commit_artifacts "TPU window: sharded serial-vs-overlap halo A/B (round 6)" "$out"
exit 0
