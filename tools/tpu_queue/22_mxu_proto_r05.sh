#!/bin/bash
# MXU banded-matmul prototype (BASELINE.md round 5): the roofline RR probe
# proved the u8 headline kernel is VPU-compute-bound (91 GB/s effective vs
# ~550 GB/s streaming), so the idle MXU is the remaining order-of-magnitude
# resource. tools/mxu_proto.py times the blocked-banded bf16/f32 einsum
# formulation of the 8K gaussian:5 (both column-pass variants) against the
# production u8 kernel, same process — bit-exactness gated before timing.
# Budget: ~3-5 min warm, ~8-10 min cold (two fresh 8K einsum compiles).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1200 python tools/mxu_proto.py \
  > artifacts/mxu_proto_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: MXU banded-matmul gaussian prototype measurements" \
  artifacts/mxu_proto_r05.out
exit $rc
