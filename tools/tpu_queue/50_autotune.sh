#!/bin/bash
# On-chip block-height autotune: sweep the headline pipeline's block heights
# on the real chip and commit the calibration store. Production paths
# (bench.py, quick_headline, cli run) pick the calibrated height up
# automatically via _pick_block_h's min rule, so a follow-up headline
# capture (55_) records whatever the sweep buys.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2400 python -m mpi_cuda_imagemanipulation_tpu autotune \
  --json-metrics autotune_r03.jsonl > autotune_r03.out 2>&1
rc=$?
# a mid-sweep wedge may leave only the .out on disk; git commit -- <pathspec>
# aborts wholesale on a never-existed path, so list only what materialised
arts=(autotune_r03.out)
[ -f autotune_r03.jsonl ] && arts+=(autotune_r03.jsonl)
[ -f .mcim_calibration.json ] && arts+=(.mcim_calibration.json)
commit_artifacts "TPU window: on-chip block-height autotune -> committed calibration" \
  "${arts[@]}"
exit $rc
