#!/bin/bash
# Round-4 insurance capture: the cheapest measurement that makes this
# round's artifact of record a hardware number — headline config, pallas
# then packed, history appended + committed per impl. Runs FIRST so even a
# window too short for the decisive bundle (10_/12_/14_) leaves a
# same-round TPU headline for bench.py promotion.
# Wall-time budget (VERDICT r3 #8): ~1-3 min warm (8K gaussian pallas +
# packed executables are in tools/.jax_cache from the round-3 window;
# measurement itself is ~10 s/impl). Cold compile over the tunnel: up to
# ~10 min — the 1800s timeout covers a cold window without burning the
# watcher's whole pass on a wedge.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1800 python tools/quick_headline.py --impls pallas,packed \
  > artifacts/quick_headline_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: round-4 headline insurance capture" \
  BENCH_HISTORY.jsonl artifacts/quick_headline_r05.out
exit $rc
