#!/bin/bash
# Full-suite packed-impl sweep: packed numbers for every bench config
# (bench.py only races packed on the headline).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 3600 python -m mpi_cuda_imagemanipulation_tpu bench --impl packed \
  --json-metrics bench_packed_r03.jsonl > bench_packed_r03.out 2>&1 || exit $?
commit_artifacts "TPU window: full packed-impl bench sweep (round 3)" \
  bench_packed_r03.jsonl bench_packed_r03.out
