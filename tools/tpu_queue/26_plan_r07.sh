#!/bin/bash
# Fusion-planner lane (round 7): the plan_ab bench lane on real hardware
# — the SAME pointwise-heavy chain four ways (bit-exactness gated before
# any timing): `--plan off` (per-op golden, one jit), per-op DISPATCHES
# (the reference's sequential launches), pointwise absorption, and full
# temporal blocking. Headline columns: ms/iter + MP/s/chip per lane, the
# fused speedup vs --plan off, and the per-stage breakdown of the fused
# plan — the measured side of the modelled hbm_passes_saved. On TPU the
# HBM round trips the planner removes are the real cost (the CPU smoke
# only proves structure), so this record is what decides whether 'auto'
# should default further than the calibration table already steers it.
# Then the plan autotune dimension records the measured winner per
# (device kind, pipeline fingerprint) so every `--plan auto` entry point
# (jit/batched/sharded/serving/stream) routes through it, and a sharded
# A/B shows the one-ppermute-pair-per-stage effect end to end.
# Knobs: MCIM_PLAN_AB_OPS / _HEIGHT / _WIDTH.
# Budget: ~3-5 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/plan_ab_r07.out
: > "$out"
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config plan_ab >> "$out" 2>&1
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.cli autotune \
  --dimension plan --ops grayscale,contrast:3.5,gaussian:5,quantize:6 \
  --height 4320 --width 7680 \
  --json-metrics artifacts/plan_autotune_r07.json >> "$out" 2>&1
# sharded structure A/B: per-op ghost exchange vs one ppermute pair per
# fused stage, all visible devices (bit-identical output either way)
python - <<'EOF'
from mpi_cuda_imagemanipulation_tpu.io.image import save_image, synthetic_image
save_image("artifacts/_plan_8k.ppm", synthetic_image(4320, 7680, channels=3, seed=7))
EOF
for plan in off fused; do
  timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.cli run \
    --input artifacts/_plan_8k.ppm --output artifacts/_plan_8k_out.ppm \
    --ops grayscale,contrast:3.5,gaussian:5,quantize:6 --impl xla \
    --shards 4 --plan "$plan" --show-timing \
    --json-metrics "artifacts/plan_sharded_${plan}_r07.json" \
    >> "$out" 2>&1 || true
done
rm -f artifacts/_plan_8k.ppm artifacts/_plan_8k_out.ppm
commit_artifacts "TPU window: fusion-planner A/B + plan autotune (round 7)" \
  "$out" artifacts/plan_autotune_r07.json artifacts/plan_sharded_off_r07.json artifacts/plan_sharded_fused_r07.json
exit 0
