#!/bin/bash
# Targeted compiled-validation lane (round 6): the wide-backend sweep —
# SWAR quarter-strip AND the promoted MXU banded-matmul backend — as a
# SHORT step at the front of the window, before the long full sweep
# (30_*). Closes the compiled-validation hole the round-5 window exposed:
# the compiled-only miscompare class (the one that demoted the packed
# backend) must be caught by the queue, not discovered on silicon by
# accident after a long sweep wedges mid-run. Covers: sharded SWAR ghost
# kernels, the SWAR proto carry kernel, the full swar_prod matrix, the
# MXU backend in both modes (banded + hybrid) and both column-pass
# variants across ragged shapes, sharded MXU on mesh(1), and the serving
# bucket-padded executor with the MXU contraction at a dynamic true
# shape.
# Budget: ~3-6 min warm, ~10-15 min cold.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1800 python tools/tpu_validate.py --lane mxu_swar \
  --out VALIDATE_MXU_r06.json > artifacts/validate_mxu_r06.out 2>&1
rc=$?
arts=(artifacts/validate_mxu_r06.out)
[ -f VALIDATE_MXU_r06.json ] && arts+=(VALIDATE_MXU_r06.json)
commit_artifacts "TPU window: compiled wide-backend validation lane (round 6)" \
  "${arts[@]}"
exit $rc
