#!/bin/bash
# Packed-u32 vs u8 production A/B (element-rate vs byte-rate question).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1800 python tools/packed_ab.py > packed_ab_r03.out 2>&1 || exit $?
commit_artifacts "TPU window: packed-u32 A/B results (round 3)" packed_ab_r03.out
