#!/bin/bash
# Production MXU banded-matmul A/B (round 6): the promoted backend
# (ops/mxu_kernels.py — the graduation of tools/mxu_proto.py +
# tools/hybrid_proto.py, which this step supersedes) measured three ways
# on the headline 8K gaussian:5: vpu (the round-5 u8 Pallas streaming
# headline, VPU-compute-bound at ~11% of roofline), mxu (both separable
# passes as bf16 banded matmuls with the 64a+b column split), and hybrid
# (row pass on the VPU, column pass on the MXU, one fused launch). Each
# lane reports MP/s/chip and roofline_frac — the direct answer to the
# round-5 judge's "what keeps this from sign-off". Bit-exactness is
# gated in-process before any timing (the proto discipline).
# Afterwards: the backend autotune dimension records the per-family
# VPU-vs-MXU winner in the calibration store, which is what lets
# impl=auto cash the win in production routing.
# Budget: ~4-6 min warm, ~10 min cold (three fresh 8K compiles).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/mxu_prod_r06.out
: > "$out"
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config mxu_ab >> "$out" 2>&1
rc=$?
echo "=== autotune --dimension backend ===" >> "$out"
timeout 900 python -m mpi_cuda_imagemanipulation_tpu autotune \
  --dimension backend --ops "gaussian:5,emboss:5,sobel" \
  --json-metrics artifacts/mxu_autotune_r06.json >> "$out" 2>&1 || true
arts=(artifacts/mxu_prod_r06.out)
[ -f artifacts/mxu_autotune_r06.json ] && arts+=(artifacts/mxu_autotune_r06.json)
[ -f .mcim_calibration.json ] && arts+=(.mcim_calibration.json)
commit_artifacts "TPU window: MXU banded-matmul production A/B + backend autotune (round 6)" \
  "${arts[@]}"
exit $rc
