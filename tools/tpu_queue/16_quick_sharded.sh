#!/bin/bash
# Minimal VERDICT-r2-directive-#2 artifact: ONE on-chip record of the
# fused-ghost sharded config (gaussian5_8k_sharded, pallas first), captured
# the quick_headline way so a short window suffices; xla second for the
# same-window contrast. Per-impl incremental history appends + immediate
# commit, same crash posture as step 15. The sharded config qualifies as a
# headline (bench_suite.headline_record), but promotion is best-by-value,
# so this record only becomes the round headline if it actually wins.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2100 python tools/quick_headline.py \
  --config gaussian5_8k_sharded --impls pallas,xla \
  > quick_sharded_r03.out 2>&1
rc=$?
commit_artifacts "TPU window: sharded-config quick capture (fused-ghost on-chip record)" \
  BENCH_HISTORY.jsonl quick_sharded_r03.out
exit $rc
