#!/bin/bash
# Pipeline-service lane (round 7): pipelines-as-data (graph/) on real
# hardware. The graph_loadgen lane drives ONE serving stack's two doors
# with the same linear chain — the baked-in --ops path vs the chain
# registered as a degenerate-DAG spec and served by pipeline id — gated
# byte-identical BEFORE timing, so the dag column prices what the
# pipeline service costs over the chain path on a real chip (per-request
# jitted graph executor vs the micro-batched bucket cache). The
# multi-tenant mix (interactive/standard/batch QoS) rides the same
# offered load; on TPU the interesting columns are the batch tenant's
# shed% under saturation (the admission ladder doing its job) and the
# dag lane's p99 vs chain (dispatch-path overhead at real device
# latencies). The graph smoke then proves the full pod contract —
# broadcast registration, affinity forwarding, quota sheds counted as
# sheds — against a real 2-replica pod on the chip.
# Budget: ~5-8 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/graph_r07.out
: > "$out"
timeout 1800 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config graph_loadgen --tenants 3 \
  --json-metrics artifacts/graph_loadgen_r07.json >> "$out" 2>&1
timeout 900 python tools/graph_smoke.py \
  artifacts/graph_metrics_r07.prom >> "$out" 2>&1
commit_artifacts "TPU window: pipeline service — graph_loadgen + pod smoke (round 7)" \
  "$out" artifacts/graph_loadgen_r07.json artifacts/graph_metrics_r07.prom
exit 0
