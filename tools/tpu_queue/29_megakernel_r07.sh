#!/bin/bash
# Megakernel lane (round 7): fused-XLA vs fused-pallas on real hardware,
# PLUS the still-pending 26_plan_r07 items folded in (one window slot
# covers the whole plan axis — run 26 separately only if this step gets
# cut short).
#
# megakernel_ab times the SAME two-stencil chain three ways (bit-
# exactness gated before any timing): `--plan off` (per-op golden),
# `--plan fused` (the PR-10 fused-XLA stage walker — incumbent), and
# `--plan fused-pallas` (each eligible stage as ONE VMEM-resident
# pallas_call: one u8 read + one u8 write per stage, intermediates never
# touching HBM — plan/pallas_exec.py). This is the record that decides
# the roofline_frac claim: the fused-XLA plan measured ~11% of the ~550
# GB/s streaming bound (BENCH_HISTORY plan_ab); the megakernel's whole
# point is work-per-HBM-byte, so the MP/s/chip delta here IS the thesis.
# Then `autotune --dimension plan` sweeps all four modes (fused-pallas
# joins on real TPU) and records the measured winner per (device kind,
# pipeline fingerprint) — the ONLY way `--plan auto` ever routes to the
# megakernel — and a sharded off/fused-pallas CLI A/B shows the
# ghost-mode megakernel behind one ppermute pair per stage end to end.
# Knobs: MCIM_MEGAKERNEL_AB_OPS / _HEIGHT / _WIDTH, MCIM_PLAN_AB_*.
# Budget: ~5-8 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/megakernel_ab_r07.out
: > "$out"
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config megakernel_ab >> "$out" 2>&1
# folded-in 26_plan_r07: the plan_ab lane (off / per-op dispatch /
# pointwise / fused) — still unrecorded on silicon
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config plan_ab >> "$out" 2>&1
# plan autotune over all modes incl. fused-pallas (TPU => compiled
# kernels, no interpret hazard); the recorded winner steers --plan auto
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.cli autotune \
  --dimension plan \
  --ops grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6 \
  --height 4320 --width 7680 \
  --json-metrics artifacts/megakernel_autotune_r07.json >> "$out" 2>&1
# sharded structure A/B: fused-XLA walker vs ghost-mode megakernel, both
# behind one ppermute pair per stage (bit-identical output)
python - <<'EOF'
from mpi_cuda_imagemanipulation_tpu.io.image import save_image, synthetic_image
save_image("artifacts/_mega_8k.ppm", synthetic_image(4320, 7680, channels=3, seed=7))
EOF
for plan in off fused fused-pallas; do
  timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.cli run \
    --input artifacts/_mega_8k.ppm --output artifacts/_mega_8k_out.ppm \
    --ops grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6 --impl xla \
    --shards 4 --plan "$plan" --show-timing \
    --json-metrics "artifacts/megakernel_sharded_${plan}_r07.json" \
    >> "$out" 2>&1 || true
done
rm -f artifacts/_mega_8k.ppm artifacts/_mega_8k_out.ppm
commit_artifacts "TPU window: megakernel A/B + plan autotune incl. fused-pallas (round 7)" \
  "$out" artifacts/megakernel_autotune_r07.json \
  artifacts/megakernel_sharded_off_r07.json \
  artifacts/megakernel_sharded_fused_r07.json \
  artifacts/megakernel_sharded_fused-pallas_r07.json
exit 0
