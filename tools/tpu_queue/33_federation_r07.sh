#!/bin/bash
# Multi-pod federation on silicon (round 7, ISSUE 17): the front door
# over two real pods, whole-pod loss under load.
#
# Two records, both bit-exactness-gated before any timing:
#
#   federation_loadgen   the bench lane — the open-loop HTTP mix through
#                        the federation front door over 2 pods x 3
#                        replicas, then a WHOLE POD SIGKILLed mid-sweep
#                        (supervisor + replicas, no restart; the pod is
#                        gone, not degraded). Acceptance: during the pod
#                        loss every ACCEPTED request completes 200 and
#                        bit-exact (unavailable == 0), and the front
#                        door books the loss only under the closed
#                        REROUTE_REASONS vocabulary. Columns: achieved
#                        req/s + ok%/shed%/p99 per phase. On TPU the
#                        question is the failover cliff: how much of
#                        2-pod achieved throughput survives on one pod,
#                        and how long the affinity slice takes to
#                        re-home once beats go silent.
#   federation smoke     the full federation contract on the chip: one
#                        registration served from both pods, the global
#                        quota budget held while a tenant drives both
#                        pods at once (integral leases, sheds FINAL),
#                        whole-pod SIGKILL with zero lost accepted
#                        requests, mcim_fed_* parsing, and a front-door
#                        restart rehydrating the fsync'd registry with
#                        zero client re-registration.
#
# Knobs: MCIM_FABRIC_RPS / _DURATION_S / _REPLICAS (the fabric lane's
# knobs apply one tier up), MCIM_FED_HEARTBEAT_S / _STALE_S.
# Budget: ~5-8 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/federation_r07.out
: > "$out"
timeout 1500 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config federation_loadgen \
  --json-metrics artifacts/federation_loadgen_r07.json >> "$out" 2>&1 || true
timeout 900 python tools/federation_smoke.py \
  artifacts/federation_metrics_r07.prom >> "$out" 2>&1 || true
commit_artifacts "TPU window: multi-pod federation loadgen + smoke (round 7)" \
  "$out" artifacts/federation_loadgen_r07.json \
  artifacts/federation_metrics_r07.prom
exit 0
