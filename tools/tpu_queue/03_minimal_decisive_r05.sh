#!/bin/bash
# Round-5 MINIMAL DECISIVE SUBSET (VERDICT r4 next-round #1): the two
# measurements that close the round's stated objective, sized to fit a
# ~5-minute window — (a) the insurance headline (pallas) so the round's
# artifact of record is a hardware number, and (b) the production SWAR
# headline, whose ratio against (a) IS the SWAR-vs-u8 decision
# (pre-registered prediction: 2-4x if the element-rate ceiling is real;
# ~1x shelves SWAR — BASELINE.md round-3 pre-registration).
# quick_headline.py appends each impl's record to BENCH_HISTORY.jsonl
# IMMEDIATELY after its measurement, so a window that dies between the
# two still leaves the pallas insurance record committed.
# Budget: ~2-4 min warm (both executables cached from round-3 windows /
# the shared compile cache), ~10 min cold. The 900s timeout keeps this
# step from eating a short window that the full bundle (05-14) needs.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 900 python tools/quick_headline.py --impls pallas,swar \
  > artifacts/minimal_decisive_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: round-5 minimal decisive capture (pallas + swar headline)" \
  BENCH_HISTORY.jsonl artifacts/minimal_decisive_r05.out
exit $rc
