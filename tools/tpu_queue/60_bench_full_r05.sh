#!/bin/bash
# Full bench.py campaign: the exact program the driver runs at round end,
# executed mid-round so BENCH_HISTORY holds a complete same-round suite
# table even if the round-end window is wedged.
# Wall-time budget: ~6-10 min warm (headline pallas/swar/xla + sharded;
# all cached after 05_/10_/16_).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 3600 python bench.py > artifacts/bench_r05_manual.out 2>&1
rc=$?
commit_artifacts "TPU window: full bench campaign (round 5)" \
  BENCH_HISTORY.jsonl artifacts/bench_r05_manual.out
exit $rc
