#!/bin/bash
# Post-autotune headline capture: records the headline with the committed
# calibration live. bench.py promotes the BEST same-round TPU record, so
# this only moves the artifact of record if the calibrated block actually
# beats the heuristic's.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2100 python tools/quick_headline.py > quick_headline3_r03.out 2>&1
rc=$?
commit_artifacts "TPU window: post-autotune headline capture" \
  BENCH_HISTORY.jsonl quick_headline3_r03.out
exit $rc
