#!/bin/bash
# Re-run the roofline probe in round-robin mode (3 rounds, per-case bests):
# the first window's single-shot run showed 4.7x cross-case drift from
# other-tenant load, which is exactly the axis the probe exists to compare.
# Artifacts commit even on a timeout/wedge partway through — the streamed
# per-round records already on disk are a window's worth of evidence.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 3600 python tools/roofline_probe.py --rounds 3 > roofline_rr_r03.out 2>&1
rc=$?
commit_artifacts "TPU window: round-robin roofline probe (per-case bests)" \
  roofline_rr_r03.out
exit $rc
