#!/bin/bash
# Hardware validation sweep (compiled Mosaic) incl. sharded + guarded cases.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 5400 python tools/tpu_validate.py --out VALIDATE_r03.json > validate_r03.out 2>&1 || exit $?
commit_artifacts "TPU window: hardware validation sweep (round 3)" \
  VALIDATE_r03.json validate_r03.out
