#!/bin/bash
# Async execution-engine A/B (round 6): serial vs overlapped end-to-end
# pipeline on real hardware (bench_suite --config engine_ab). The lane
# runs the same compiled reference pipeline over a synthetic slow-decode
# corpus two ways — decode→dispatch→force→encode serially, then through
# the engine (inflight dispatches outstanding, in-order completion drain,
# encode worker pool) — and reports e2e images/sec per lane, the speedup,
# and each lane's device-idle fraction. On TPU the decisive question the
# CPU smoke cannot answer: how much of the host decode/transfer/encode
# path the async dispatch + donated-buffer steady state actually hides
# behind real device compute (and whether inflight=2 suffices or deeper
# helps — the sweep below covers 1/2/4).
# Knobs: MCIM_ENGINE_AB_IMAGES/_DECODE_MS/_ENCODE_MS size the corpus
# (defaults: 32 images at 1080p, 8 ms decode + 4 ms encode tails).
# Budget: ~2-4 min (one serving-free compile per lane).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/engine_ab_r06.out
: > "$out"
for depth in 1 2 4; do
  echo "=== inflight $depth ===" >> "$out"
  timeout 900 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
    --config engine_ab --inflight "$depth" >> "$out" 2>&1
done
commit_artifacts "TPU window: async engine serial-vs-overlap A/B (round 6)" "$out"
exit 0
