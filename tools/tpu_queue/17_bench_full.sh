#!/bin/bash
# Full bench.py campaign: headline pallas/xla + fused-ghost sharded config.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 5400 python bench.py > bench_r03_manual.out 2>&1 || exit $?
commit_artifacts "TPU window: full bench campaign incl. sharded path (round 3)" \
  BENCH_HISTORY.jsonl bench_r03_manual.out
