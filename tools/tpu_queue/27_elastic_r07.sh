#!/bin/bash
# Elastic-fabric lane (round 7): the PR 12 control loops on real
# hardware. The fabric_loadgen lane now carries an `elastic` sub-lane —
# an AUTOSCALED pod (replicas start at 1, ceiling at the lane's N) under
# the same saturating offered mix: scale-up latency, a SIGUSR1
# preemption absorbed mid-load (graceful drain + preempt dump +
# immediate no-backoff replacement), and the idle scale-down which must
# be recorded as "drained" (the victim's queue observed empty before
# SIGTERM). Headline columns gain shed% — on TPU the interesting number
# is how much offered load the pod sheds (503 + Retry-After) before the
# new replica's warmup finishes, i.e. the real cost of a scale-up on
# hardware where a compile-cache warm takes seconds. The elastic smoke
# runs after it for the canary-rollback and drain-observability asserts
# against a real pod. On TPU the per-dispatch device floor is OFF — the
# lane measures real chips contending for real HBM.
# Budget: ~6-10 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/elastic_r07.out
: > "$out"
timeout 1800 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config fabric_loadgen \
  --json-metrics artifacts/fabric_elastic_suite_r07.json >> "$out" 2>&1
timeout 900 python tools/elastic_smoke.py \
  artifacts/elastic_metrics_r07.prom >> "$out" 2>&1
commit_artifacts "TPU window: elastic fabric — autoscale/preempt/canary (round 7)" \
  "$out" artifacts/fabric_elastic_suite_r07.json artifacts/elastic_metrics_r07.prom
exit 0
