#!/bin/bash
# Cost observability on silicon (round 7): the measured-vs-model roofline
# record + one live fleet profile capture.
#
# PR 15 made every compile site extract the compiled executable's
# cost_analysis()/memory_analysis(); bench records now carry
# hbm_gb_s_measured / roofline_frac_measured next to the analytical
# hbm_gb_s_model / roofline_frac columns. On CPU those columns only
# prove plumbing — THIS step records them on the chip, where the
# question is real: does XLA's compiled-traffic figure corroborate the
# u8 one-read-one-write model the ~11% roofline_frac headline divides
# by, or does the measured series re-base the claim? Then a real fabric
# pod takes offered load while POST /control/profile captures one live
# window — the first committed merged host+device trace from a
# traffic-serving replica (until now every committed profile came from
# the offline capture shim).
# Budget: ~4-6 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/cost_r07.out
: > "$out"
# 1) measured-vs-model columns on the headline + stencil-class configs
for cfg in gaussian5_8k gaussian3_4k reference_pipeline_4k; do
  timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
    --config "$cfg" >> "$out" 2>&1
done
# 2) per-stage drift on silicon: the megakernel one-read-one-write gate
#    judged by the chip's own memory_analysis, fused AND fused-pallas
timeout 600 python - >> "$out" 2>&1 <<'EOF'
import json
from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost
from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
from mpi_cuda_imagemanipulation_tpu.plan import build_plan

ops = make_pipeline_ops("grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6")
for mode, pallas in (("fused", False), ("fused-pallas", True)):
    plan = build_plan(ops, mode)
    rows = obs_cost.attribute_plan(plan, (4320, 7680, 3), pallas=pallas)
    print(json.dumps({
        "lane": f"stage_drift_{mode}",
        "fingerprint": plan.fingerprint,
        "stages": [
            {k: r[k] for k in ("stage", "names", "modeled_bytes", "drift_ratio")}
            for r in rows
        ],
    }))
EOF
# 3) live profile capture under fabric offered load: pod up, loadgen on,
#    one POST /control/profile mid-stream, artifact committed
timeout 900 python - >> "$out" 2>&1 <<'EOF'
import json, shutil, threading, time, urllib.request
import numpy as np
from mpi_cuda_imagemanipulation_tpu.fabric.replica import ReplicaRuntime
from mpi_cuda_imagemanipulation_tpu.fabric.router import Router, RouterConfig
from mpi_cuda_imagemanipulation_tpu.io.image import encode_image_bytes, synthetic_image
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.serve.loadgen import http_run_offered_load
from mpi_cuda_imagemanipulation_tpu.serve.server import ServeConfig

obs_trace.configure(sample=0.05)  # sampled + tail-kept, like production
router = Router(RouterConfig(buckets=((1024, 1024),))).start()
rt = ReplicaRuntime("r0", router.url, ServeConfig(
    ops="grayscale,contrast:3.5,emboss:3", buckets=((1024, 1024),),
    channels=(3,), max_batch=4,
), heartbeat_s=0.3).start()
try:
    while not router._routable():
        time.sleep(0.05)
    blob = bytes(encode_image_bytes(
        np.asarray(synthetic_image(1000, 1000, channels=3, seed=7))
    ))
    prof = {}
    def capture():
        time.sleep(2.0)  # mid-loadgen
        req = urllib.request.Request(
            router.url + "/control/profile",
            data=json.dumps({"seconds": 3.0}).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            prof.update(json.loads(r.read()))
    t = threading.Thread(target=capture); t.start()
    rec = http_run_offered_load(router.url, [blob], 20.0, 8.0)
    t.join()
    rec.pop("results", None)
    print(json.dumps({"lane": "profile_under_load", "loadgen": rec,
                      "capture": {k: prof.get(k) for k in
                                  ("replica", "status", "seconds",
                                   "host_events", "device_events")}}))
    shutil.copyfile(prof["artifact"], "artifacts/profile_live_r07.json")
finally:
    rt.close(drain=False, deadline_s=5.0)
    router.close()
EOF
commit_artifacts "TPU window: measured-vs-model roofline + live fleet profile capture (round 7)" \
  "$out" artifacts/profile_live_r07.json
exit 0
