#!/bin/bash
# THE decisive experiment (VERDICT r3 priority #1): same-process
# interleaved A/B of production pallas vs xla vs packed on the 4K pointwise
# group and the 8K headline stencil (2 interleaved rounds). Also the
# datum that must explain the 01:03Z prod_xla>prod_pallas anomaly —
# all three production variants run in ONE process minutes apart.
# Partial output is a window's worth of evidence, so the .out commits
# even on a timeout/wedge partway through (round-3 lesson: the lone
# packed_ab fragment was the round's most-cited artifact).
# Wall-time budget: ~4-6 min warm (prod 4K pallas/xla/packed + 8K
# pallas/packed executables cached from earlier windows; proto packed_u32
# kernel is the only likely cold compile, ~60-90 s). Cold: ~12-15 min.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1500 python tools/packed_ab.py > artifacts/packed_ab_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: interleaved packed-u32 A/B (round 4)" \
  artifacts/packed_ab_r05.out
exit $rc
