#!/bin/bash
# Production SWAR headline capture (after the prototype timing in 12_):
# the packaged impl='swar' path (ops/swar_kernels.py) on the headline
# config, recorded to history. Promotion is best-by-value, so this only
# moves the artifact of record if SWAR actually wins on silicon — and if
# the 12_ prototype prediction (2-4x) holds, THIS record is the round's
# >=2x production headline, same window.
# Wall-time budget: ~2-4 min (one fresh compile of the swar kernel + pack).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1800 python tools/quick_headline.py --impls swar,pallas \
  > artifacts/quick_swar_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: production swar-impl headline capture (round 4)" \
  BENCH_HISTORY.jsonl artifacts/quick_swar_r05.out
exit $rc
