#!/bin/bash
# Round-robin roofline probe (VERDICT r3 priority #1, third leg): 3 rounds,
# per-case bests, with the pre-registered u32-anomaly discriminators —
# re-bases ELEM_G_S_MEASURED with per-case bests instead of the round-3
# single-shot sample whose adjacent cases drifted 4.7x.
# Wall-time budget: ~8-10 min warm (copy-probe kernels are tiny; most of
# the time is the 3x round-robin measurement itself). Streams per-round
# records; commits whatever landed on a mid-run wedge.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 2700 python tools/roofline_probe.py --rounds 3 > artifacts/roofline_rr_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: round-robin roofline probe (round 4)" \
  artifacts/roofline_rr_r05.out
exit $rc
