#!/bin/bash
# Round-7 burndown (consolidates the former 26/27/28/29/30 steps): the
# whole pending r07 backlog in ONE serialized window slot, ordered so an
# early cut still captures the decisive records first. Former steps:
#
#   26_plan_r07       folded into the megakernel section (plan_ab +
#                     plan autotune are the same window slot)
#   27_elastic_r07    elastic fabric: autoscale/preempt/canary loadgen
#   28_graph_r07      pipeline service: graph_loadgen + pod smoke
#   29_megakernel_r07 megakernel A/B + plan autotune incl. fused-pallas
#   30_cost_r07       measured-vs-model roofline + live profile capture
#
# Each section tolerates its own failure (the window drains on): the
# artifacts that did land are committed regardless.
# Budget: ~20-30 min warm, ~45 min cold.
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/burndown_r07.out
: > "$out"

# -- 1) megakernel + plan axis (former 29, incl. folded 26) ------------------
# megakernel_ab gates bit-exactness before timing; this is the
# work-per-HBM-byte record that moves roofline_frac past 0.11.
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config megakernel_ab >> "$out" 2>&1 || true
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config plan_ab >> "$out" 2>&1 || true
# plan autotune over all modes incl. fused-pallas — the ONLY way
# `--plan auto` ever routes to the megakernel
timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.cli autotune \
  --dimension plan \
  --ops grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6 \
  --height 4320 --width 7680 \
  --json-metrics artifacts/megakernel_autotune_r07.json >> "$out" 2>&1 || true
# sharded structure A/B: fused-XLA walker vs ghost-mode megakernel, both
# behind one ppermute pair per stage (bit-identical output)
python - <<'EOF'
from mpi_cuda_imagemanipulation_tpu.io.image import save_image, synthetic_image
save_image("artifacts/_mega_8k.ppm", synthetic_image(4320, 7680, channels=3, seed=7))
EOF
for plan in off fused fused-pallas; do
  timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.cli run \
    --input artifacts/_mega_8k.ppm --output artifacts/_mega_8k_out.ppm \
    --ops grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6 --impl xla \
    --shards 4 --plan "$plan" --show-timing \
    --json-metrics "artifacts/megakernel_sharded_${plan}_r07.json" \
    >> "$out" 2>&1 || true
done
rm -f artifacts/_mega_8k.ppm artifacts/_mega_8k_out.ppm

# -- 2) elastic fabric (former 27) -------------------------------------------
# autoscaled pod under saturating offered mix: scale-up latency, SIGUSR1
# preemption absorbed mid-load, idle scale-down recorded as drained
timeout 1800 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config fabric_loadgen \
  --json-metrics artifacts/fabric_elastic_suite_r07.json >> "$out" 2>&1 || true
timeout 900 python tools/elastic_smoke.py \
  artifacts/elastic_metrics_r07.prom >> "$out" 2>&1 || true

# -- 3) pipeline service (former 28) -----------------------------------------
# chain-vs-DAG doors gated byte-identical pre-timing; multi-tenant QoS
# mix; then the pod smoke against a real 2-replica pod on the chip
timeout 1800 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config graph_loadgen --tenants 3 \
  --json-metrics artifacts/graph_loadgen_r07.json >> "$out" 2>&1 || true
timeout 900 python tools/graph_smoke.py \
  artifacts/graph_metrics_r07.prom >> "$out" 2>&1 || true

# -- 4) cost observability (former 30) ---------------------------------------
# measured-vs-model roofline columns on the headline + stencil-class
# configs, on the chip's own cost_analysis
for cfg in gaussian5_8k gaussian3_4k reference_pipeline_4k; do
  timeout 1200 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
    --config "$cfg" >> "$out" 2>&1 || true
done
# per-stage drift on silicon: the megakernel one-read-one-write gate
# judged by the chip's own memory_analysis, fused AND fused-pallas
timeout 600 python - >> "$out" 2>&1 <<'EOF'
import json
from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost
from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
from mpi_cuda_imagemanipulation_tpu.plan import build_plan

ops = make_pipeline_ops("grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6")
for mode, pallas in (("fused", False), ("fused-pallas", True)):
    plan = build_plan(ops, mode)
    rows = obs_cost.attribute_plan(plan, (4320, 7680, 3), pallas=pallas)
    print(json.dumps({
        "lane": f"stage_drift_{mode}",
        "fingerprint": plan.fingerprint,
        "stages": [
            {k: r[k] for k in ("stage", "names", "modeled_bytes", "drift_ratio")}
            for r in rows
        ],
    }))
EOF
# live profile capture under fabric offered load: pod up, loadgen on,
# one POST /control/profile mid-stream, artifact committed
timeout 900 python - >> "$out" 2>&1 <<'EOF'
import json, shutil, threading, time, urllib.request
import numpy as np
from mpi_cuda_imagemanipulation_tpu.fabric.replica import ReplicaRuntime
from mpi_cuda_imagemanipulation_tpu.fabric.router import Router, RouterConfig
from mpi_cuda_imagemanipulation_tpu.io.image import encode_image_bytes, synthetic_image
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.serve.loadgen import http_run_offered_load
from mpi_cuda_imagemanipulation_tpu.serve.server import ServeConfig

obs_trace.configure(sample=0.05)  # sampled + tail-kept, like production
router = Router(RouterConfig(buckets=((1024, 1024),))).start()
rt = ReplicaRuntime("r0", router.url, ServeConfig(
    ops="grayscale,contrast:3.5,emboss:3", buckets=((1024, 1024),),
    channels=(3,), max_batch=4,
), heartbeat_s=0.3).start()
try:
    while not router._routable():
        time.sleep(0.05)
    blob = bytes(encode_image_bytes(
        np.asarray(synthetic_image(1000, 1000, channels=3, seed=7))
    ))
    prof = {}
    def capture():
        time.sleep(2.0)  # mid-loadgen
        req = urllib.request.Request(
            router.url + "/control/profile",
            data=json.dumps({"seconds": 3.0}).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            prof.update(json.loads(r.read()))
    t = threading.Thread(target=capture); t.start()
    rec = http_run_offered_load(router.url, [blob], 20.0, 8.0)
    t.join()
    rec.pop("results", None)
    print(json.dumps({"lane": "profile_under_load", "loadgen": rec,
                      "capture": {k: prof.get(k) for k in
                                  ("replica", "status", "seconds",
                                   "host_events", "device_events")}}))
    shutil.copyfile(prof["artifact"], "artifacts/profile_live_r07.json")
finally:
    rt.close(drain=False, deadline_s=5.0)
    router.close()
EOF

commit_artifacts "TPU window: round-7 burndown — megakernel/plan + elastic + graph + cost (consolidated 26-30)" \
  "$out" \
  artifacts/megakernel_autotune_r07.json \
  artifacts/megakernel_sharded_off_r07.json \
  artifacts/megakernel_sharded_fused_r07.json \
  artifacts/megakernel_sharded_fused-pallas_r07.json \
  artifacts/fabric_elastic_suite_r07.json \
  artifacts/elastic_metrics_r07.prom \
  artifacts/graph_loadgen_r07.json \
  artifacts/graph_metrics_r07.prom \
  artifacts/profile_live_r07.json
exit 0
