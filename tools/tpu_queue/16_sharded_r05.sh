#!/bin/bash
# First-ever on-chip record of the fused-ghost sharded config (VERDICT r3
# priority #3; round-2 directive #2, two rounds overdue): target per-chip
# parity +-10% with unsharded, proving parallel/api.py's traffic model on
# silicon. Quick-capture style so a short window suffices; pallas first.
# Wall-time budget: ~3-5 min warm (the mesh(1) sharded executable is NOT
# in the cache — first sharded compile on the tunnel may add ~2-4 min).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
timeout 1800 python tools/quick_headline.py \
  --config gaussian5_8k_sharded --impls pallas,xla \
  > artifacts/quick_sharded_r05.out 2>&1
rc=$?
commit_artifacts "TPU window: sharded-config on-chip record (round 4)" \
  BENCH_HISTORY.jsonl artifacts/quick_sharded_r05.out
exit $rc
