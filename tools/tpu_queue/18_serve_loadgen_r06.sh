#!/bin/bash
# Online-serving load sweep (round 6): the serve_loadgen bench lane on real
# hardware — open-loop offered-load sweep against a warmed ServeApp
# (serve/loadgen.py): achieved throughput vs p50/p95/p99 e2e latency, shed
# fraction and the batch-occupancy curve per offered rate, plus the compile
# cache counters proving zero post-warmup traces under live traffic.
# Knobs: the lane sizes itself for TPU (512/1024/2048 buckets, 4 s per
# rate); MCIM_SERVE_RPS / MCIM_SERVE_DURATION_S override the sweep. The
# offered rates below are chosen to cross saturation of one chip on the
# reference pipeline (~1-4 ms/dispatch warm), so the occupancy curve and
# the shed knee are both visible. Budget: ~2-4 min warm (the serving
# executables are new compiles on the first window: ~6-10 min cold).
set -u
cd "$(dirname "$0")/../.."
. tools/tpu_queue/_lib.sh
out=artifacts/serve_loadgen_r06.out
: > "$out"
MCIM_SERVE_RPS="${MCIM_SERVE_RPS:-64,256,1024}" \
  timeout 1800 python -m mpi_cuda_imagemanipulation_tpu.bench_suite \
  --config serve_loadgen >> "$out" 2>&1
commit_artifacts "TPU window: online-serving offered-load sweep (round 6)" "$out"
exit 0
