#!/usr/bin/env python
"""CI observability smoke — a short traced serve run over real HTTP.

The tier1.yml obs step runs this on CPU: stand up the full `Server`
(compile-cache warmup, scheduler, engine, HTTP listener on an ephemeral
port) with tracing armed and one injected transient dispatch failure,
drive a handful of requests through POST /v1/process, then assert the
whole observability contract end to end:

  1. GET /metrics parses as Prometheus text exposition
     (obs.metrics.parse_exposition) and carries the serve/engine/cache/
     health families;
  2. GET /stats agrees with /metrics on every shared quantity (single
     registry — no drift);
  3. the exported trace (argv[1]) contains the acceptance span chain for
     a retried request: serve.request -> serve.enqueue / serve.coalesce /
     serve.dispatch -> serve.retry event -> engine.force + engine.encode,
     all on ONE trace id, correctly parented;
  4. responses carry X-Trace-Id and the id appears in the trace file.

Exit 0 = contract holds; any assertion prints and fails the step. The
trace JSON is uploaded as a CI artifact either way.

Usage: python tools/obs_smoke.py [TRACE_OUT.json]
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_imagemanipulation_tpu.utils.platform import claim_platform  # noqa: E402

claim_platform(os.environ.get("JAX_PLATFORMS") or "cpu")

import numpy as np  # noqa: E402

from mpi_cuda_imagemanipulation_tpu.obs import parse_exposition  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.io.image import (  # noqa: E402
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.serve.server import (  # noqa: E402
    ServeConfig,
    Server,
)

REQUIRED_FAMILIES = (
    "mcim_serve_requests_total",
    "mcim_serve_retries_total",
    "mcim_serve_e2e_latency_seconds",
    "mcim_engine_submitted_total",
    "mcim_engine_stage_seconds",
    "mcim_cache_hits",
    "mcim_health_state",
)

# /stats key -> (family, label string) — the shared quantities the two
# endpoints must agree on
SHARED = {
    "submitted": ("mcim_serve_submitted_total", ""),
    "completed": ("mcim_serve_requests_total", 'status="ok"'),
    "retries": ("mcim_serve_retries_total", ""),
    "dispatches": ("mcim_serve_dispatches_total", ""),
    "queued": ("mcim_serve_queue_depth", ""),
}


def fetch(url: str):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read().decode(), dict(resp.headers)


def main() -> int:
    trace_out = sys.argv[1] if len(sys.argv) > 1 else "/tmp/obs_trace.json"
    obs_trace.configure(sample=1.0)
    # one transient dispatch failure: the trace must show the recovery
    failpoints.configure("serve.dispatch=once")
    cfg = ServeConfig(
        buckets=((64, 64), (128, 128)),
        channels=(3,),
        max_batch=4,
        max_delay_ms=2.0,
    )
    img = synthetic_image(60, 60, channels=3, seed=7)
    png = encode_image_bytes(np.asarray(img))
    with Server(cfg, port=0) as srv:
        base = f"http://127.0.0.1:{srv.address[1]}"
        trace_ids = []
        for _ in range(6):
            req = urllib.request.Request(f"{base}/v1/process", data=png)
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200, resp.status
                tid = resp.headers.get("X-Trace-Id")
                assert tid, "missing X-Trace-Id on a traced request"
                trace_ids.append(tid)
                resp.read()
        metrics_text, headers = fetch(f"{base}/metrics")
        assert headers.get("Content-Type", "").startswith("text/plain"), (
            headers.get("Content-Type")
        )
        stats = json.loads(fetch(f"{base}/stats")[0])
    failpoints.clear()

    # 1. exposition parses + required families present
    fams = parse_exposition(metrics_text)
    missing = [f for f in REQUIRED_FAMILIES if f not in fams]
    assert not missing, f"missing /metrics families: {missing}"
    print(f"/metrics: {len(fams)} families parse as exposition text")

    # 2. /stats == /metrics on every shared quantity
    for key, (family, labels) in SHARED.items():
        sample_key = next(
            (
                (name, ls)
                for (name, ls) in fams[family]["samples"]
                if ls == labels and not name.endswith(("_bucket",))
            ),
            None,
        )
        got = fams[family]["samples"].get(sample_key, 0.0) if sample_key else 0.0
        assert float(stats[key]) == got, (
            f"/stats[{key}]={stats[key]} != /metrics {family}{{{labels}}}={got}"
        )
    assert stats["retries"] >= 1, "injected failure produced no retry"
    print(
        f"/stats agrees with /metrics on {sorted(SHARED)} "
        f"(retries={stats['retries']})"
    )

    # 3. the trace: export + acceptance span chain on one trace id
    n = obs_trace.export(trace_out)
    print(f"trace: {n} events -> {trace_out}")
    events = json.load(open(trace_out))["traceEvents"]
    by_trace: dict[str, list[dict]] = {}
    for e in events:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    retried = [
        t for t, evs in by_trace.items()
        if any(e["name"] == "serve.retry" for e in evs)
    ]
    assert retried, "no trace carries the injected retry event"
    evs = by_trace[retried[0]]
    names = {e["name"] for e in evs}
    for want in ("serve.request", "serve.enqueue", "serve.coalesce",
                 "serve.dispatch", "serve.retry", "engine.force",
                 "engine.encode"):
        assert want in names, f"span {want!r} missing from trace {retried[0]}"
    # parentage: every non-root span's parent_id is a span_id in the trace
    ids = {
        e["args"].get("span_id") for e in evs if e["ph"] == "X"
    }
    for e in evs:
        pid = e["args"].get("parent_id")
        if pid:
            assert pid in ids, f"{e['name']} parent {pid} not in trace"
    print(
        f"trace {retried[0]}: {sorted(names)} — parentage closed"
    )

    # 4. response headers join the trace file
    assert set(trace_ids) <= set(by_trace), "X-Trace-Id not in trace file"
    print(f"{len(trace_ids)} X-Trace-Id headers all present in trace file")
    print("obs smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
