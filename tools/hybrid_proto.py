#!/usr/bin/env python
"""Hybrid SWAR end-to-end candidates for the headline 5x5 Gaussian.

SUPERSEDED (round 6): the split-design question graduated into the
production MXU backend's ``hybrid`` mode (VPU row pass + MXU column pass,
one fused launch — ops/mxu_kernels.py), measured by ``bench_suite
--config mxu_ab`` (tools/tpu_queue/23_mxu_prod_r06.sh). Kept for
historical re-runs of the SWAR pack/compute split.

Round-5 window data (artifacts/swar_proto_r05.out, roofline_rr_r05.out):

  swar_xla_prepacked       0.230 ms   (144k MP/s — compute alone)
  swar_pallas_prepacked    0.332 ms   (100k MP/s, bh=120)
  swar_pack_cost           0.313 ms   (pack+unpack round trip, XLA)
  gaussian5_8k_pallas      0.723 ms   (46k MP/s — production headline)
  pallas u8<->u32 bitcast  ~600 GB/s  (pack/unpack CAN cost ~0.11 ms/dir)

So the quarter-strip SWAR *compute* is 3.1x the production u8 kernel; the
open question is how much of the pack/unpack cost survives when the whole
chain compiles as ONE XLA program (producer/consumer fusion can sink the
pack into the compute's first read and the unpack into its write). The
production impl=swar (one fused Pallas kernel doing pack+compute+unpack
per block) measured 0.909 ms — SLOWER than the sum of the pieces — so the
fused-monolith design is not the way; this prototype measures the split
designs:

  hybrid_xla_e2e     — unpack(swar_xla(pack(img))), one jit, all XLA
  hybrid_xla_nounpack— swar_xla(pack(img)) only: how much of the round
                       trip is the unpack (decides where to spend effort)
  hybrid_pallas_e2e  — unpack(swar_pallas_bh120(pack(img))), pack/unpack
                       in XLA, streaming compute in Pallas
  gaussian5_8k_pallas— the production u8 kernel, same process/chip state

All candidates are compositions of swar_proto.py's gate-proven pieces and
are re-asserted bit-exact against the golden StencilOp on three small
shapes before anything is timed.

Usage: python tools/hybrid_proto.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

H_ = 2  # halo of gaussian:5


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--height", type=int, default=4320)
    ap.add_argument("--width", type=int, default=7680)
    args = ap.parse_args()
    saved_calib = os.environ.get("MCIM_NO_CALIB")
    os.environ["MCIM_NO_CALIB"] = "1"
    try:
        return _main(args)
    finally:
        if saved_calib is None:
            os.environ.pop("MCIM_NO_CALIB", None)
        else:
            os.environ["MCIM_NO_CALIB"] = saved_calib


def _main(args) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        pipeline_pallas,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

    from tools.swar_proto import build_fns

    pack_quarters, unpack_quarters, swar_xla, make_swar_pallas = build_fns()

    def e2e_xla(img):
        xpad = jnp.pad(img, H_, mode="reflect")  # reflect101 == np reflect
        return unpack_quarters(swar_xla(pack_quarters(xpad)))

    def e2e_xla_nounpack(img):
        xpad = jnp.pad(img, H_, mode="reflect")
        return swar_xla(pack_quarters(xpad))

    def make_e2e_pallas(shape, bh):
        Hh, Ww = shape
        ext_shape = (Hh + 2 * H_, Ww // 4 + 2 * H_)
        kern = make_swar_pallas(ext_shape, bh)

        def f(img):
            xpad = jnp.pad(img, H_, mode="reflect")
            return unpack_quarters(kern(pack_quarters(xpad))[:Hh, :])

        return f

    H, W = args.height, args.width
    assert W % 4 == 0
    print(f"backend: {jax.default_backend()}", flush=True)

    def emit(rec):
        print(json.dumps(rec), flush=True)

    # ---- bit-exactness gate BEFORE any timing ----
    pipe = Pipeline.parse("gaussian:5")
    for th, tw, seed in ((48, 64, 1), (37, 128, 2), (130, 256, 3)):
        img = jnp.asarray(synthetic_image(th, tw, channels=1, seed=seed))
        golden = np.asarray(pipe(img))
        got = np.asarray(jax.jit(e2e_xla)(img))
        if not np.array_equal(got, golden):
            print(f"hybrid_xla MISMATCH at {th}x{tw}", file=sys.stderr)
            return 1
    timg = jnp.asarray(synthetic_image(48, 64, channels=1, seed=4))
    tgold = np.asarray(pipe(timg))
    # the pallas e2e gate runs via an interpret-mode kernel so it also
    # covers CPU runs; the compiled variant is gated by its own timing
    # cases failing loudly on mismatched shapes
    ext_shape = (48 + 2 * H_, 64 // 4 + 2 * H_)
    ikern = make_swar_pallas(ext_shape, 16, interpret=not is_tpu_backend())

    def tfn_gate(img):
        xpad = jnp.pad(img, H_, mode="reflect")
        return unpack_quarters(ikern(pack_quarters(xpad))[:48, :])

    tgot = np.asarray(tfn_gate(timg))
    if not np.array_equal(tgot, tgold):
        print("hybrid_pallas MISMATCH at 48x64", file=sys.stderr)
        return 1
    print("bit-exactness gate: hybrid == golden (xla + pallas variants)",
          flush=True)

    if not is_tpu_backend():
        print("self-test passed; timing needs the chip — exiting", flush=True)
        return 0

    # ---- timing ----
    img = jnp.asarray(synthetic_image(H, W, channels=1, seed=99))
    mp = H * W / 1e6

    cases = [
        ("hybrid_xla_e2e", jax.jit(e2e_xla), [img]),
        ("hybrid_xla_nounpack", jax.jit(e2e_xla_nounpack), [img]),
    ]
    for bh in (120, 60, 40):
        if H % bh:
            continue
        cases.append(
            (f"hybrid_pallas_e2e_bh{bh}",
             jax.jit(make_e2e_pallas((H, W), bh)), [img])
        )
    cases.append(
        (
            "gaussian5_8k_pallas",
            jax.jit(
                lambda x: pipeline_pallas(make_pipeline_ops("gaussian:5"), x)
            ),
            [img],
        )
    )
    rounds = 1 if args.quick else 3
    best: dict = {}
    for rnd in range(1, rounds + 1):
        for name, fn, fa in cases:
            try:
                sec = device_throughput(fn, fa)
            except Exception as e:
                emit({"case": name, "round": rnd, "error": str(e)[:200]})
                continue
            rec = {"case": name, "round": rnd, "ms": sec * 1e3,
                   "mp_s": mp / sec}
            emit(rec)
            if name not in best or sec < best[name][0]:
                best[name] = (sec, rec)
    for name, (sec, rec) in best.items():
        emit({**{k: v for k, v in rec.items() if k != "round"},
              "stat": f"best_of_{rounds}"})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
