#!/usr/bin/env python
"""Continuous-autotuning CI smoke: a real 2-replica pod on CPU, pinned
to the SLOW plan, converges to the fast one with no human in the loop —
then a poisoned candidate proves the bit-exactness tripwire.

    python tools/tune_smoke.py METRICS_OUT SUMMARY_OUT

Asserts, against a REAL pod (replica worker processes, real HTTP):

  1. CONVERGENCE: the pod starts with `--plan off` (measured ~1.5x
     slower than fused on the headline chain — BENCH_HISTORY plan_ab).
     Under offered load the serve path streams dispatch timings into
     the online calibration store, the tune controller explores the
     unmeasured `plan:fused` arm through the canary gate, the canary's
     own measurements beat the incumbent, and the whole fleet is
     respawned onto the flip: `/control/tune` reports
     current_arm=plan:fused and both replicas serve the fused plan.
     Zero responses count unavailable; stable-lane responses stay
     bit-exact against the golden pipeline throughout.
  2. POISONED FLIP: with the `tune.candidate` failpoint armed in the
     router process, the controller's next proposal is swapped for a
     pixel-corrupting ops override. The FIRST shadow digest spot-check
     catches it: the gate rolls back instantly, the Fabric respawns the
     stable config, a `canary_rollback` recorder dump carries
     shadow.mismatch >= 1, and the arm is quarantined in the store so
     it is never proposed again.
  3. EXPOSITION: the router's federated /metrics parses
     (`obs.metrics.parse_exposition`) and carries the `mcim_tune_*`
     families from BOTH processes: controller decisions from the router
     registry, dispatch observations federated up from the replicas.

METRICS_OUT gets the final federated exposition; SUMMARY_OUT a JSON
record (convergence latency, decision counts) for CI artifacts.
"""

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the headline chain: pointwise-heavy, where the fused plan's single
# HBM pass is a measured ~1.5x over per-op dispatch on CPU
OPS = "grayscale,contrast:3.5,gaussian:5,quantize:6"
BUCKETS = "384"


def _build_cfg(tmp: str):
    from mpi_cuda_imagemanipulation_tpu.fabric.canary import CanaryConfig
    from mpi_cuda_imagemanipulation_tpu.fabric.router import RouterConfig
    from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import FabricConfig
    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets
    from mpi_cuda_imagemanipulation_tpu.tune.controller import TuneConfig

    return FabricConfig(
        replicas=2,
        ops=OPS,
        buckets=BUCKETS,
        channels="3",
        max_batch=4,
        max_delay_ms=4.0,
        queue_depth=32,
        heartbeat_s=0.2,
        plan="off",  # pinned SLOW: convergence must be earned
        tune=True,
        tune_arms="plan:off,plan:fused",
        tune_config=TuneConfig(
            tick_s=0.25,
            min_samples=6,
            explore_c=0.35,
            min_gain=1.02,
            flip_timeout_s=120.0,
            canary_frac=0.25,
        ),
        router=RouterConfig(
            buckets=parse_buckets(BUCKETS),
            stale_s=0.8,
            forward_attempts=3,
            canary=CanaryConfig(
                frac=0.25, shadow_every=2, min_requests=8,
                promote_requests=20,
            ),
        ),
    )


def main(metrics_out: str, summary_out: str) -> int:
    tmp = tempfile.mkdtemp(prefix="tune_smoke_")
    rec_dir = os.path.join(tmp, "recorder")
    os.environ["MCIM_RECORDER_DIR"] = rec_dir
    os.environ["MCIM_RECORDER_MIN_INTERVAL_S"] = "0"
    # the shared measurement bus: replicas flush dispatch observations
    # here, the router-process controller ranks from it
    os.environ["MCIM_CALIB_FILE"] = os.path.join(tmp, "calib.json")
    os.environ.pop("MCIM_NO_CALIB", None)
    os.environ["MCIM_TUNE"] = "1"
    os.environ["MCIM_TUNE_FLUSH_S"] = "0.25"

    from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import Fabric
    from mpi_cuda_imagemanipulation_tpu.io.image import (
        decode_image_bytes,
        synthetic_image,
    )
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.plan.ir import pipeline_fingerprint
    from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
    from mpi_cuda_imagemanipulation_tpu.serve import loadgen
    from mpi_cuda_imagemanipulation_tpu.tune.store import online_store

    pipe = Pipeline.parse(OPS)
    pipe_fp = pipeline_fingerprint(make_pipeline_ops(OPS))
    imgs = [
        synthetic_image(300 + 7 * i, 340 + 5 * i, channels=3, seed=40 + i)
        for i in range(4)
    ]
    blobs = [loadgen.encode_blob(im) for im in imgs]
    golden = [np.asarray(pipe.jit()(im)) for im in imgs]
    summary: dict = {"ops": OPS, "buckets": BUCKETS, "pipe_fp": pipe_fp}

    def check_bit_exact(results) -> int:
        n = 0
        for k, r in results:
            if r["code"] != 200:
                continue
            np.testing.assert_array_equal(
                decode_image_bytes(r["body"]), golden[k % len(golden)]
            )
            n += 1
        return n

    def run_load(fab, stop, recs):
        while not stop.is_set():
            recs.append(
                loadgen.http_run_offered_load(
                    fab.url, blobs, 40.0, 1.0, max_workers=32,
                    timeout_s=30.0,
                )
            )

    # ---- 1. convergence: pinned slow -> promoted fast ---------------------
    t0 = time.monotonic()
    stop, recs = threading.Event(), []
    with Fabric(_build_cfg(tmp)).start() as fab:
        assert fab.tuner is not None, "fabric --tune did not start a tuner"
        loader = threading.Thread(
            target=run_load, args=(fab, stop, recs), daemon=True
        )
        loader.start()
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            if fab.tuner.current_arm == "plan:fused":
                break
            time.sleep(0.2)
        converge_s = time.monotonic() - t0
        st = fab.http_stats()["tune"]
        assert st is not None and st["current_arm"] == "plan:fused", (
            f"pod never converged to plan:fused: {fab.tuner.status()}"
        )
        decisions = [e["decision"] for e in fab.tuner.events]
        assert "propose" in decisions and "promote" in decisions, decisions
        print(
            f"smoke: converged plan:off -> plan:fused in {converge_s:.1f}s "
            f"(decisions: {decisions})"
        )
        # the promotion is durable: a fresh process would resolve fused
        ent = online_store.promoted_entry(pipe_fp)
        assert ent is not None and ent["choice"] == "fused", ent
        # ... and the FLEET runs it: every replica was respawned with the
        # flip argv (argparse last-wins over the pinned --plan off)
        for rid in fab.supervisor.replica_ids():
            argv = fab.supervisor.spec_of(rid).argv
            assert argv[-2:] == ["--plan", "fused"], (rid, argv)
        stop.set()
        loader.join(timeout=60.0)
        unavailable = sum(r["unavailable"] for r in recs)
        assert unavailable == 0, (
            f"{unavailable} responses went dark during autotuning — the "
            "control loop must be invisible to clients"
        )
        checked = check_bit_exact(
            [kv for rec in recs[:2] for kv in rec["results"]]
        )
        print(
            f"smoke: load clean ({len(recs)} windows, unavailable 0, "
            f"{checked} pre-flip responses bit-exact)"
        )
        summary.update(
            converge_s=round(converge_s, 2),
            load_windows=len(recs),
            shed=sum(r["shed"] for r in recs),
            decisions=decisions,
        )

        # ---- 2. poisoned candidate: shadow digest -> rollback ------------
        # re-arm the drill IN THE SAME POD: force the bookkept incumbent
        # back to off so the controller must re-propose the (measured
        # faster) fused arm — but this time the failpoint swaps the flip
        # for a pixel-corrupting one before it reaches the gate
        fab.tuner.stop()
        fab.tuner.current_arm = "plan:off"
        failpoints.configure("tune.candidate=always")
        try:
            stop2, recs2 = threading.Event(), []
            loader2 = threading.Thread(
                target=run_load, args=(fab, stop2, recs2), daemon=True
            )
            loader2.start()
            fab.tuner.start()
            deadline = time.monotonic() + 240.0
            while time.monotonic() < deadline:
                if online_store.is_quarantined(pipe_fp, "plan:fused"):
                    break
                time.sleep(0.2)
            stop2.set()
            loader2.join(timeout=60.0)
            assert online_store.is_quarantined(pipe_fp, "plan:fused"), (
                f"poisoned flip never quarantined: {fab.tuner.status()}"
            )
        finally:
            failpoints.clear()
        fab.tuner.stop()
        assert "rollback" in [
            e["decision"] for e in fab.tuner.events
        ], fab.tuner.status()
        assert fab.tuner.current_arm == "plan:off"
        dumps = sorted(
            p for p in os.listdir(rec_dir)
            if p.startswith("recorder_canary_rollback")
        )
        assert dumps, f"no canary_rollback dump in {rec_dir}"
        with open(os.path.join(rec_dir, dumps[-1])) as f:
            dump = json.load(f)
        assert dump["extra"]["shadow"]["mismatch"] >= 1, dump["extra"]
        print(
            f"smoke: poisoned flip rolled back on shadow digest "
            f"({dump['extra']['reason']!r}) and quarantined; dump "
            f"{dumps[-1]}"
        )
        # quarantine means BANNED: ticks settle on hold, never re-propose
        for _ in range(3):
            d = fab.tuner.tick()
            assert d in ("hold", "insufficient_data"), d
        # the pod serves bit-exact stable traffic again after the revert
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if fab.router.canary.status()["state"] == "idle":
                break
            time.sleep(0.2)
        r = loadgen.http_post_image(fab.url, blobs[0])
        assert r["code"] == 200
        np.testing.assert_array_equal(
            decode_image_bytes(r["body"]), golden[0]
        )
        summary.update(
            poison_rollback_reason=dump["extra"]["reason"],
            quarantined=True,
        )

        # ---- 3. federated mcim_tune_* exposition parses -------------------
        text = fab.scrape()
    families = parse_exposition(text)  # raises on malformed lines
    tune_fams = sorted(f for f in families if f.startswith("mcim_tune_"))
    assert "mcim_tune_decisions_total" in tune_fams, tune_fams
    assert "mcim_tune_observations_total" in tune_fams, (
        "replica dispatch observations did not federate up: "
        f"{tune_fams}"
    )
    decided = {
        labels: v
        for (name, labels), v in
        families["mcim_tune_decisions_total"]["samples"].items()
    }
    assert any("promote" in k for k in decided), decided
    assert any("rollback" in k for k in decided), decided
    with open(metrics_out, "w") as f:
        f.write(text)
    summary["tune_families"] = tune_fams
    with open(summary_out, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
    print(
        f"smoke: federated exposition parses ({len(tune_fams)} mcim_tune_* "
        f"families) -> {metrics_out}; summary -> {summary_out}"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 3:
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1], sys.argv[2]))
