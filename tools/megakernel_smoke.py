#!/usr/bin/env python
"""CI megakernel smoke (tier1.yml): plan=fused-pallas acceptance, end to
end, in interpret mode on CPU.

One process proves, on chains that exercise the eligibility matrix
(temporally-blocked stencil pairs, interior/edge/reflect modes, channel
changes, LUT fallback, barriers):

  1. **bit-exactness** — the fused-pallas executor reproduces the per-op
     golden chain (`--plan off`) through jit AND the row-sharded
     ghost-mode path over fake XLA host devices;
  2. **structure** — the sharded fused-pallas chain compiles to exactly
     ONE ppermute pair per halo-carrying fused stage (the megakernel
     consumes the pre-exchanged rows — same wire structure as fused-XLA),
     and the commuted-geometry plan stops splitting pointwise runs;
  3. **fallback** — a LUT-bearing stage routes through the XLA walker
     (counted in mcim_plan_pallas_fallbacks_total) and stays bit-exact;
  4. **observability** — mcim_plan_pallas_* families render as parseable
     exposition with the launch counter populated;
  5. **the lane** — the megakernel_ab bench lane runs (its pre-timing
     bit-exactness gate must pass) and its record lands at argv[1].
     Interpret-mode timings are never asserted — the committed
     BENCH_HISTORY record is the gate anchor, the TPU window script
     (tools/tpu_queue/29_megakernel_r07.sh) carries the perf claim.

Usage: python tools/megakernel_smoke.py /tmp/megakernel_ab.json
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import numpy as np  # noqa: E402

H, W, C = 160, 96, 3


def main() -> int:
    import jax.numpy as jnp

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
    from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh
    from mpi_cuda_imagemanipulation_tpu.plan import build_plan, plan_metrics
    from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
        plan_callable_pallas,
        stage_pallas_reject,
    )

    # -- 1. bit-exactness: jit + sharded ghost mode -------------------------
    mesh = make_mesh(4)
    chains = (
        "grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6",  # blocked pair
        "grayscale,contrast:3.5,emboss:3",                       # interior
        "erode:5,dilate:3",                                      # edge mode
        "median:3,gaussian:3",                                   # median
    )
    for spec in chains:
        pipe = Pipeline.parse(spec)
        ch = 3 if spec.startswith("grayscale") else 1
        img = jnp.asarray(synthetic_image(H, W, channels=ch, seed=21))
        golden = np.asarray(pipe.apply(img))
        got = np.asarray(pipe.jit(plan="fused-pallas")(img))
        assert np.array_equal(got, golden), f"jit fused-pallas != golden: {spec}"
        got = np.asarray(pipe.sharded(mesh, plan="fused-pallas")(img))
        assert np.array_equal(got, golden), f"sharded fused-pallas: {spec}"
    print(f"bit-exact: {len(chains)} chains, jit + 4-shard ghost mode")

    # -- 2. structure: one ppermute pair per stage; commuted geometry ------
    pipe = Pipeline.parse("gaussian:3,sharpen,grayscale,sobel")
    img = jnp.asarray(synthetic_image(128, W, channels=3, seed=22))
    txt = pipe.sharded(mesh, plan="fused-pallas").lower(img).as_text()
    n = txt.count("collective_permute")
    assert n == 2, f"expected 1 ppermute pair for the fused stage, got {n}"
    commuted = build_plan(
        Pipeline.parse("invert,rot180,brightness:10,gaussian:3").ops,
        "fused-pallas",
    )
    assert [s.kind for s in commuted.stages] == ["geometric", "fused"], (
        commuted.describe()
    )
    print("structure: 1 ppermute pair/stage; rot180 commuted out of the run")

    # -- 3. fallback: LUT member -> XLA walker, counted, bit-exact ---------
    pipe = Pipeline.parse("gamma:2.2,gaussian:3")
    img = jnp.asarray(synthetic_image(H, W, channels=1, seed=23))
    golden = np.asarray(pipe.apply(img))
    plan = build_plan(pipe.ops, "fused-pallas")
    assert stage_pallas_reject(plan.stages[0], H, W, 1) == "lut-op"
    before = int(plan_metrics.pallas_fallbacks.value(reason="lut-op"))
    got = np.asarray(plan_callable_pallas(plan)(img))
    assert np.array_equal(got, golden), "LUT fallback diverged"
    after = int(plan_metrics.pallas_fallbacks.value(reason="lut-op"))
    assert after == before + 1, (before, after)
    print("fallback: lut-op stage walked in XLA, counted, bit-exact")

    # -- 4. exposition ------------------------------------------------------
    fams = parse_exposition(plan_metrics.registry.render())
    for fam in (
        "mcim_plan_pallas_stages_total",
        "mcim_plan_pallas_fallbacks_total",
    ):
        assert fam in fams, f"missing metric family {fam}"
    assert plan_metrics.snapshot()["pallas_stages"] >= 1
    print(f"exposition: {len(fams)} families parse; megakernel launches "
          f"counted ({plan_metrics.snapshot()['pallas_stages']})")

    # -- 5. the megakernel_ab lane (record -> CI artifact) ------------------
    out = sys.argv[1] if len(sys.argv) > 1 else None
    os.environ.setdefault("MCIM_MEGAKERNEL_AB_HEIGHT", "256")
    os.environ.setdefault("MCIM_MEGAKERNEL_AB_WIDTH", "384")
    from mpi_cuda_imagemanipulation_tpu.bench_suite import run_megakernel_ab

    rec = run_megakernel_ab(json_path=out, printer=lambda s: None)
    assert rec["bit_exact_gate"].startswith("passed"), rec["bit_exact_gate"]
    assert rec["megakernel_stages"] >= 1, rec["stage_eligibility"]
    print(
        f"megakernel_ab: gate passed, {rec['megakernel_stages']} megakernel "
        f"stage(s), pallas {rec['speedup_pallas_vs_fused'] or 0:.2f}x vs "
        "fused-XLA (interpret mode — gate record only)"
        + (f" -> {out}" if out else "")
    )
    print("megakernel smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
