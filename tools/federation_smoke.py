#!/usr/bin/env python
"""Federation CI smoke: an in-process front door over TWO real pods
(each a `fabric` CLI subprocess with its own router + 2 replica
processes), whole-pod SIGKILL, a front-door restart, and global quota
leases.

    python tools/federation_smoke.py METRICS_OUT

Asserts, end to end over real HTTP:

  1. both pods join by pushing pod heartbeats (`fabric --federate URL
     --pod-id NAME`) and a DAG spec registered ONCE at the front door
     serves BIT-EXACT from both pods — through the front door and
     straight at each pod's router — with zero per-pod registration;
  2. a quota tenant driving BOTH pods at once never exceeds its GLOBAL
     fixed-window budget: the front door leases each pod an integral
     share (federation/quota.py), shares sum to the budget, and the
     over-lease requests shed with 503 + Retry-After (FINAL, so a shed
     is never retried into a second pod's share);
  3. SIGKILLing a WHOLE pod (supervisor + both replicas) mid-traffic
     loses nothing: every request completes 200 bit-exact on the
     survivor, and the reroutes are counted in
     mcim_fed_reroutes_total under closed-vocabulary reasons —
     `pod_down` once the dead pod's heartbeat silence crosses the
     staleness window;
  4. the front door's /metrics parses as Prometheus exposition with
     the mcim_fed_* families populated (written to METRICS_OUT);
  5. a front-door RESTART on the same registry path rehydrates every
     tenant + spec from the fsync'd journal — zero client
     re-registration — the surviving pod rejoins by its next beat, and
     the cold front door re-pushes tenant state before its first
     forward (mcim_fed_pushes_total), serving the same spec bit-exact.

METRICS_OUT gets the pre-restart front-door exposition text (uploaded
as a CI artifact, .github/workflows/tier1.yml federation step).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# pods inherit this: fast beats keep the smoke's staleness waits short
os.environ["MCIM_FED_HEARTBEAT_S"] = "0.25"

import numpy as np  # noqa: E402

from mpi_cuda_imagemanipulation_tpu.federation.frontdoor import (  # noqa: E402
    REROUTE_REASONS,
    FrontDoor,
    FrontDoorConfig,
)
from mpi_cuda_imagemanipulation_tpu.graph import (  # noqa: E402
    compile_graph,
    graph_callable,
    parse_spec,
)
from mpi_cuda_imagemanipulation_tpu.io.image import (  # noqa: E402
    decode_image_bytes,
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.obs.metrics import (  # noqa: E402
    parse_exposition,
)
from mpi_cuda_imagemanipulation_tpu.serve.bucketing import (  # noqa: E402
    parse_buckets,
)

OPS = "grayscale,contrast:3.5"
BUCKETS = "48,96"
STALE_S = 1.2  # front-door staleness window (~5 pod beats)

SPEC = {
    "version": 1,
    "name": "unsharp",
    "nodes": [
        {"id": "src", "kind": "source"},
        {"id": "g", "kind": "op", "op": "grayscale", "input": "src"},
        {"id": "blur", "kind": "op", "op": "gaussian:5", "input": "g"},
        {"id": "mask", "kind": "merge", "merge": "subtract",
         "inputs": ["g", "blur"]},
    ],
    "outputs": {"image": "mask"},
}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Pod:
    """One whole pod — router + supervisor + 2 replicas — as a single
    `fabric` CLI subprocess joined to the front door by `--federate`.
    Out-of-process on purpose: `sigkill()` takes down the supervisor
    AND the replicas it spawned, the failure shape the federation tier
    exists to absorb (a pod-local replica death is the pod router's
    journal-tail problem and never reaches the front door)."""

    def __init__(self, pod_id: str, frontdoor_url: str):
        self.pod_id = pod_id
        self.port = _free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "mpi_cuda_imagemanipulation_tpu",
                "fabric",
                "--replicas", "2",
                "--ops", OPS,
                "--buckets", BUCKETS,
                "--channels", "3",
                "--max-batch", "4",
                "--queue-depth", "64",
                "--host", "127.0.0.1",
                "--port", str(self.port),
                "--heartbeat-s", "0.2",
                "--stale-s", "0.8",
                "--federate", frontdoor_url,
                "--pod-id", pod_id,
            ],
        )

    def replica_pids(self) -> list[int]:
        with urllib.request.urlopen(self.url + "/stats", timeout=10) as r:
            st = json.loads(r.read())
        return [rep["pid"] for rep in st["replicas"].values()]

    def sigkill(self) -> None:
        """The whole pod, hard: replicas first (their pids come from the
        router's own stats, grabbed while it still answers), then the
        supervisor — nothing drains, nothing hands over."""
        pids = []
        try:
            pids = self.replica_pids()
        except Exception:
            pass
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
        for pid in pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        self.proc.wait(timeout=10.0)

    def close(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=60.0)
            except Exception:
                self.proc.kill()
                self.proc.wait(timeout=10.0)


def _post(url: str, path: str, data: bytes, headers=None):
    req = urllib.request.Request(
        url + path, data=data, headers=headers or {}, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post_retry(url, path, data, headers=None, deadline_s=60.0):
    """Retry explicit sheds (503 + Retry-After) — a pod converging or a
    breaker probing is not a failure; anything else unexpected IS."""
    t_end = time.monotonic() + deadline_s
    while True:
        code, hdrs, body = _post(url, path, data, headers)
        if code != 503 or not hdrs.get("Retry-After"):
            return code, hdrs, body
        assert time.monotonic() < t_end, "requests never converged past sheds"
        time.sleep(0.2)


def _door_metrics(url: str) -> str:
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        return r.read().decode()


def _door_stats(url: str) -> dict:
    with urllib.request.urlopen(url + "/stats", timeout=10) as r:
        return json.loads(r.read())


def _reroute_counts(exposition: str) -> dict[str, float]:
    fams = parse_exposition(exposition)
    out: dict[str, float] = {}
    fam = fams.get("mcim_fed_reroutes_total")
    if fam:
        for (_n, labels), v in fam["samples"].items():
            reason = labels.split('reason="', 1)[1].split('"', 1)[0]
            out[reason] = out.get(reason, 0.0) + v
    return out


def _wait_pods(url: str, want: set[str], deadline_s: float = 240.0):
    """Until every wanted pod is fresh at the front door with its full
    replica capacity routable."""
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            pods = _door_stats(url)["pods"]
        except Exception:
            pods = {}
        ready = {
            pid
            for pid, v in pods.items()
            if v["fresh"] and v["routable"] >= 2
        }
        if want <= ready:
            return
        time.sleep(0.2)
    raise TimeoutError(f"pods {sorted(want)} never joined (saw {pods.keys()})")


def main(metrics_out: str) -> int:
    tmp = tempfile.mkdtemp(prefix="federation_smoke_")
    registry_path = os.path.join(tmp, "fed_registry.jsonl")
    fd_cfg = FrontDoorConfig(
        registry_path=registry_path,
        buckets=tuple(parse_buckets(BUCKETS)),
        stale_s=STALE_S,
        forward_timeout_s=30.0,
        forward_attempts=3,
    )
    door = FrontDoor(fd_cfg).start(host="127.0.0.1", port=0)
    fd_port = door.address[1]
    pods = {pid: _Pod(pid, door.url) for pid in ("pod0", "pod1")}
    img48 = synthetic_image(40, 44, channels=3, seed=50)
    img96 = synthetic_image(80, 72, channels=3, seed=51)
    blob48 = encode_image_bytes(img48)
    blob96 = encode_image_bytes(img96)
    golden = {
        id(blob48): np.asarray(
            graph_callable(compile_graph(parse_spec(SPEC)))(img48)["image"]
        ),
        id(blob96): np.asarray(
            graph_callable(compile_graph(parse_spec(SPEC)))(img96)["image"]
        ),
    }
    try:
        _wait_pods(door.url, {"pod0", "pod1"})
        print("smoke: pod0 + pod1 joined by pod heartbeat, 2 replicas each")

        # -- 1. one registration, served from both pods ---------------------
        code, _h, out = _post(
            door.url, "/v1/tenants",
            json.dumps({"tenant": "acme", "qos": "interactive"}).encode(),
        )
        assert code == 200, (code, out[:200])
        assert set(json.loads(out)["pods"]) == {"pod0", "pod1"}
        code, _h, out = _post(
            door.url, "/v1/pipelines",
            json.dumps({"tenant": "acme", "spec": SPEC}).encode(),
        )
        assert code == 200, (code, out[:300])
        reg = json.loads(out)
        pid = reg["pipeline"]
        assert reg["persisted"] and set(reg["pods"]) == {"pod0", "pod1"}, reg
        # both pods' NEXT heartbeats must echo the pipeline id back
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            views = _door_stats(door.url)["pods"]
            echoed = {
                p for p, v in views.items() if pid in (v["pipelines"] or ())
            }
            if echoed == {"pod0", "pod1"}:
                break
            time.sleep(0.2)
        assert echoed == {"pod0", "pod1"}, (
            f"only {sorted(echoed)} echo the registered pipeline"
        )
        acme_h = {"X-MCIM-Tenant": "acme", "X-MCIM-Pipeline": pid}
        served: dict[int, str] = {}
        for blob in (blob48, blob96):
            code, hdrs, out = _post_retry(door.url, "/v1/process", blob, acme_h)
            assert code == 200, (code, out[:200])
            np.testing.assert_array_equal(
                decode_image_bytes(out), golden[id(blob)]
            )
            served[id(blob)] = hdrs.get("X-Fed-Pod", "")
            assert served[id(blob)] in pods, hdrs
        # ...and straight at each pod's own router: the broadcast (not a
        # client retry) is what put the spec there
        for pod in pods.values():
            code, _h, out = _post_retry(pod.url, "/v1/process", blob48, acme_h)
            assert code == 200, (pod.pod_id, code, out[:200])
            np.testing.assert_array_equal(
                decode_image_bytes(out), golden[id(blob48)]
            )
        print(
            f"smoke: spec {pid} registered once serves bit-exact from "
            f"both pods (front-door picks: {sorted(set(served.values()))})"
        )

        # -- 2. global quota budget across both pods ------------------------
        code, _h, out = _post(
            door.url, "/v1/tenants",
            json.dumps({
                "tenant": "metered", "qos": "interactive",
                "quota_requests": 6, "window_s": 3600.0,
            }).encode(),
        )
        assert code == 200, (code, out[:200])
        code, _h, out = _post(
            door.url, "/v1/pipelines",
            json.dumps({"tenant": "metered", "spec": SPEC}).encode(),
        )
        assert code == 200, (code, out[:300])
        leases = _door_stats(door.url)["leases"]
        shares = [
            g["quota_requests"]
            for w in leases.get("windows", [])
            if w["tenant"] == "metered"
            for g in w["pods"].values()
        ]
        assert sum(s or 0 for s in shares) <= 6, (
            f"granted shares exceed the global budget: {shares}"
        )
        metered_h = {"X-MCIM-Tenant": "metered", "X-MCIM-Pipeline": pid}
        # drive BOTH pods directly — the adversarial client shape: if
        # leases were copies instead of shares, this would admit 12
        oks, sheds = 0, 0
        for pod in pods.values():
            for _ in range(6):
                code, hdrs, _out = _post(
                    pod.url, "/v1/process", blob48, metered_h
                )
                if code == 200:
                    oks += 1
                else:
                    assert code == 503 and hdrs.get("Retry-After"), (
                        pod.pod_id, code, _out[:200]
                    )
                    sheds += 1
        assert 1 <= oks <= 6, (
            f"global budget 6 violated across pods: {oks} accepted "
            f"({sheds} shed, leases {shares})"
        )
        print(
            f"smoke: metered tenant drove both pods, {oks}/12 accepted "
            f"<= global budget 6 ({sheds} shed 503+Retry-After)"
        )

        # -- 3. whole-pod SIGKILL mid-traffic -------------------------------
        victim = served[id(blob48)] or "pod0"
        survivor = next(p for p in pods if p != victim)
        pods[victim].sigkill()
        t_end = time.monotonic() + max(4.0 * STALE_S, 6.0)
        n_ok = 0
        while time.monotonic() < t_end:
            code, hdrs, out = _post(door.url, "/v1/process", blob48, acme_h)
            assert code == 200, (
                f"request lost during pod {victim} death: {code} "
                f"{out[:200]!r}"
            )
            np.testing.assert_array_equal(
                decode_image_bytes(out), golden[id(blob48)]
            )
            assert hdrs.get("X-Fed-Pod") == survivor, hdrs
            n_ok += 1
            time.sleep(0.1)
        reroutes = _reroute_counts(_door_metrics(door.url))
        assert reroutes, "no reroute was counted after whole-pod SIGKILL"
        unknown = set(reroutes) - set(REROUTE_REASONS)
        assert not unknown, f"reroute reasons outside the vocabulary: {unknown}"
        assert reroutes.get("pod_down", 0) >= 1, (
            f"pod staleness never produced a pod_down reroute ({reroutes})"
        )
        code, _h, out = _post_retry(door.url, "/v1/process", blob96, acme_h)
        assert code == 200
        np.testing.assert_array_equal(
            decode_image_bytes(out), golden[id(blob96)]
        )
        hz = json.loads(
            urllib.request.urlopen(door.url + "/healthz", timeout=10).read()
        )
        assert hz["pods"] == [survivor], hz
        print(
            f"smoke: SIGKILLed {victim} whole (supervisor + replicas); "
            f"{n_ok} mid-death requests all 200 bit-exact on {survivor}; "
            f"reroutes {reroutes}"
        )

        # -- 4. exposition snapshot (pre-restart, carries the reroutes) -----
        exposition = _door_metrics(door.url)
        fams = parse_exposition(exposition)
        for fam in (
            "mcim_fed_requests_total",
            "mcim_fed_forwards_total",
            "mcim_fed_reroutes_total",
            "mcim_fed_heartbeats_total",
            "mcim_fed_lease_grants_total",
            "mcim_fed_pods",
            "mcim_fed_tenants",
            "mcim_fed_specs",
        ):
            assert fam in fams, f"{fam} missing from front-door /metrics"
        with open(metrics_out, "w") as f:
            f.write(exposition)
        print(f"smoke: front-door /metrics parses -> {metrics_out}")

        # -- 5. front-door restart: durable registry, zero re-registration --
        door.close()
        door = FrontDoor(fd_cfg).start(host="127.0.0.1", port=fd_port)
        st = _door_stats(door.url)
        assert "acme" in st["tenants"] and "metered" in st["tenants"], st
        assert f"acme/{pid}" in st["specs"], st["specs"]
        assert st["registry"]["loaded_records"] >= 4, st["registry"]
        assert st["registry"]["skipped_lines"] == 0, st["registry"]
        _wait_pods(door.url, {survivor}, deadline_s=30.0)
        code, hdrs, out = _post_retry(door.url, "/v1/process", blob48, acme_h)
        assert code == 200, (code, out[:200])
        np.testing.assert_array_equal(
            decode_image_bytes(out), golden[id(blob48)]
        )
        post = parse_exposition(_door_metrics(door.url))
        pushes = sum(
            v for _k, v in post["mcim_fed_pushes_total"]["samples"].items()
        )
        assert pushes >= 1, (
            "cold front door never re-pushed tenant state before a forward"
        )
        print(
            f"smoke: front-door restart rehydrated "
            f"{st['registry']['loaded_records']} records from "
            f"{os.path.basename(st['registry']['path'])}, zero client "
            f"re-registration, {pushes:.0f} state push(es) on first forward"
        )
    finally:
        door.close()
        for pod in pods.values():
            pod.close()
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
