#!/usr/bin/env python
"""Fabric CI smoke: router + 2 CPU replica processes, injected heartbeat
loss, rerouting, and a closed router->replica span chain.

    python tools/fabric_smoke.py METRICS_OUT TRACE_OUT

Asserts, against a REAL pod (replica worker processes, real HTTP):

  1. both replicas register by heartbeat and serve bit-exact responses;
  2. injected heartbeat loss on r0 (`replica.heartbeat=after:N` in ITS
     env — the replica keeps serving, only its beats vanish) makes the
     router mark it stale and reroute everything to r1;
  3. the distributed trace is closed across the hop: one trace id covers
     the router's fabric.request/fabric.forward spans AND the replica's
     serve.request/serve.dispatch spans (the replica ADOPTS the
     X-Trace-Id; its spans come from its own --trace-out export, written
     on graceful drain);
  4. the router's /metrics snapshot parses as Prometheus exposition with
     the mcim_fabric_* families populated.

METRICS_OUT gets the router exposition text, TRACE_OUT the MERGED
(router + both replicas) Chrome trace JSON — both uploaded as CI
artifacts (.github/workflows/tier1.yml fabric step).
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

from mpi_cuda_imagemanipulation_tpu.fabric.router import RouterConfig
from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (
    Fabric,
    FabricConfig,
)
from mpi_cuda_imagemanipulation_tpu.io.image import (
    decode_image_bytes,
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
from mpi_cuda_imagemanipulation_tpu.serve import loadgen
from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets

OPS = "grayscale,contrast:3.5"
BUCKETS = "48,96"


def main(metrics_out: str, trace_out: str) -> int:
    tracer = obs_trace.configure(sample=1.0)  # router-side spans
    tmp = tempfile.mkdtemp(prefix="fabric_smoke_")
    rep_traces = {
        rid: os.path.join(tmp, f"{rid}_trace.json") for rid in ("r0", "r1")
    }
    cfg = FabricConfig(
        replicas=2,
        ops=OPS,
        buckets=BUCKETS,
        channels="3",
        max_batch=4,
        queue_depth=64,
        heartbeat_s=0.2,
        router=RouterConfig(
            buckets=parse_buckets(BUCKETS), stale_s=0.8, forward_attempts=3
        ),
        # heartbeat LOSS on r0 only: beats 9+ are dropped at the sender
        # while the process keeps serving — the router must notice the
        # silence and reroute
        replica_env={"r0": {"MCIM_FAILPOINTS": "replica.heartbeat=after:8"}},
        replica_argv_extra={
            rid: ["--trace-out", path] for rid, path in rep_traces.items()
        },
    )
    pipe = Pipeline.parse(OPS)
    imgs = [
        synthetic_image(40 + 9 * i, 44 + 7 * i, channels=3, seed=50 + i)
        for i in range(4)
    ]
    blobs = [encode_image_bytes(im) for im in imgs]
    golden = [np.asarray(pipe.jit()(im)) for im in imgs]
    trace_ids: list[str] = []

    with Fabric(cfg).start() as fab:
        # -- 1. both replicas serving, responses bit-exact ------------------
        served = set()
        for k, blob in enumerate(blobs * 4):
            r = loadgen.http_post_image(fab.url, blob)
            assert r["code"] == 200, (r["code"], r["body"][:200])
            np.testing.assert_array_equal(
                decode_image_bytes(r["body"]), golden[k % len(golden)]
            )
            served.add(r["replica"])
            if r["trace_id"]:
                trace_ids.append(r["trace_id"])
        print(f"smoke: {len(blobs) * 4} requests ok, replicas {sorted(served)}")

        # -- 2. heartbeat loss -> staleness -> rerouting --------------------
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            routable = [v.replica_id for v in fab.router._routable()]
            if routable == ["r1"]:
                break
            time.sleep(0.1)
        assert routable == ["r1"], (
            f"r0's heartbeat loss never made it stale (routable {routable})"
        )
        for blob in blobs:
            r = loadgen.http_post_image(fab.url, blob)
            assert r["code"] == 200
            assert r["replica"] == "r1", (
                f"request routed to stale replica {r['replica']}"
            )
            if r["trace_id"]:
                trace_ids.append(r["trace_id"])
        print("smoke: r0 stale after injected heartbeat loss; all traffic on r1")

        # -- 4. metrics snapshot (written before teardown) ------------------
        exposition = fab.scrape()
        with open(metrics_out, "w") as f:
            f.write(exposition)
    # graceful drain done: replicas exported their traces on SIGTERM

    fams = parse_exposition(exposition)
    for fam in (
        "mcim_fabric_requests_total",
        "mcim_fabric_forwards_total",
        "mcim_fabric_route_total",
        "mcim_fabric_heartbeats_total",
        "mcim_fabric_replicas_routable",
    ):
        assert fam in fams, f"{fam} missing from /metrics"
    ok = sum(
        v
        for (name, labels), v in fams["mcim_fabric_requests_total"][
            "samples"
        ].items()
        if 'status="ok"' in labels
    )
    assert ok >= len(blobs) * 5, f"requests_total{{ok}} = {ok}"
    print(f"smoke: /metrics parses; requests_total{{ok}} = {ok:.0f}")

    # -- 3. closed router->replica span chain ------------------------------
    router_events = tracer.chrome_events()
    merged = list(router_events)
    for rid, path in rep_traces.items():
        assert os.path.exists(path), f"{rid} never exported {path}"
        with open(path) as f:
            merged.extend(json.load(f)["traceEvents"])
    with open(trace_out, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)

    def spans_for(tid: str) -> dict[str, list[dict]]:
        out: dict[str, list[dict]] = {}
        for e in merged:
            if e.get("args", {}).get("trace_id") == tid:
                out.setdefault(e["name"], []).append(e)
        return out

    assert trace_ids, "no request carried a trace id"
    checked = 0
    for tid in trace_ids:
        spans = spans_for(tid)
        if "serve.request" not in spans:
            continue  # replica killed before export? not here — skip none
        for name in ("fabric.request", "fabric.forward", "serve.request",
                     "serve.dispatch"):
            assert name in spans, (
                f"trace {tid}: span {name!r} missing ({sorted(spans)})"
            )
        root_id = spans["fabric.request"][0]["args"]["span_id"]
        fwd = spans["fabric.forward"][0]["args"]
        assert fwd.get("parent_id") == root_id, (
            f"trace {tid}: fabric.forward not parented to fabric.request"
        )
        checked += 1
    assert checked >= len(trace_ids) * 0.9, (
        f"only {checked}/{len(trace_ids)} traces had the full "
        "router->replica chain"
    )
    print(
        f"smoke: {checked}/{len(trace_ids)} traces span the full "
        f"router->replica hop ({len(merged)} merged events -> {trace_out})"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 3:
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1], sys.argv[2]))
