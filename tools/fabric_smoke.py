#!/usr/bin/env python
"""Fabric CI smoke: router + 2 CPU replica processes, injected heartbeat
loss, rerouting, and a closed router->replica span chain.

    python tools/fabric_smoke.py METRICS_OUT TRACE_OUT

Asserts, against a REAL pod (replica worker processes, real HTTP):

  1. both replicas register by heartbeat and serve bit-exact responses;
  2. injected heartbeat loss on r0 (`replica.heartbeat=after:N` in ITS
     env — the replica keeps serving, only its beats vanish) makes the
     router mark it stale and reroute everything to r1;
  3. the distributed trace is closed across the hop: one trace id covers
     the router's fabric.request/fabric.forward spans AND the replica's
     serve.request/serve.dispatch spans (the replica ADOPTS the
     X-Trace-Id; its spans come from its own --trace-out export, written
     on graceful drain);
  4. the router's /metrics snapshot parses as Prometheus exposition with
     the mcim_fabric_* families populated;
  5. FEDERATION (obs/fleet.py): the router's federated families equal
     the SUM of the per-replica registries — `mcim_serve_requests_total`
     on the router's /metrics matches the total from each replica's
     `GET /fleet/snapshot`, and the federated e2e histogram count
     matches the pooled count;
  6. FLIGHT RECORDER (obs/recorder.py): SIGKILLing a replica makes the
     supervisor write a `replica_death` post-mortem dump that names the
     dead replica's warm buckets (lifted from its last heartbeat).

METRICS_OUT gets the router exposition text, TRACE_OUT the MERGED
(router + both replicas) Chrome trace JSON — both uploaded as CI
artifacts (.github/workflows/tier1.yml fabric step).
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

import numpy as np

from mpi_cuda_imagemanipulation_tpu.fabric.router import RouterConfig
from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (
    Fabric,
    FabricConfig,
)
from mpi_cuda_imagemanipulation_tpu.io.image import (
    decode_image_bytes,
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
from mpi_cuda_imagemanipulation_tpu.serve import loadgen
from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets

OPS = "grayscale,contrast:3.5"
BUCKETS = "48,96"


def _replica_ok_total(port: int) -> tuple[float, float]:
    """(requests ok, e2e count) straight from one replica's full fleet
    snapshot — the per-replica side of the federation equality check."""
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/fleet/snapshot", timeout=10.0
    ) as resp:
        snap = json.loads(resp.read())
    ok = 0.0
    for key, v in snap["metrics"]["mcim_serve_requests_total"]["series"]:
        if key == ["ok"]:
            ok = v
    e2e = sum(
        data["count"]
        for _k, data in snap["metrics"][
            "mcim_serve_e2e_latency_seconds"
        ]["series"]
    )
    return ok, e2e


def _federated_ok_total(exposition: str) -> tuple[float, float]:
    fams = parse_exposition(exposition)
    ok = sum(
        v
        for (_n, labels), v in fams["mcim_serve_requests_total"][
            "samples"
        ].items()
        if 'status="ok"' in labels
    )
    e2e = sum(
        v
        for (name, _labels), v in fams["mcim_serve_e2e_latency_seconds"][
            "samples"
        ].items()
        if name.endswith("_count")
    )
    return ok, e2e


def main(metrics_out: str, trace_out: str) -> int:
    tracer = obs_trace.configure(sample=1.0)  # router-side spans
    tmp = tempfile.mkdtemp(prefix="fabric_smoke_")
    # recorder dumps land somewhere inspectable (and never in the tree)
    rec_dir = os.path.join(tmp, "recorder")
    os.environ["MCIM_RECORDER_DIR"] = rec_dir
    rep_traces = {
        rid: os.path.join(tmp, f"{rid}_trace.json") for rid in ("r0", "r1")
    }
    cfg = FabricConfig(
        replicas=2,
        ops=OPS,
        buckets=BUCKETS,
        channels="3",
        max_batch=4,
        queue_depth=64,
        heartbeat_s=0.2,
        router=RouterConfig(
            buckets=parse_buckets(BUCKETS), stale_s=0.8, forward_attempts=3
        ),
        # heartbeat LOSS on r0 only: beats 9+ are dropped at the sender
        # while the process keeps serving — the router must notice the
        # silence and reroute
        replica_env={"r0": {"MCIM_FAILPOINTS": "replica.heartbeat=after:8"}},
        replica_argv_extra={
            rid: ["--trace-out", path] for rid, path in rep_traces.items()
        },
    )
    pipe = Pipeline.parse(OPS)
    imgs = [
        synthetic_image(40 + 9 * i, 44 + 7 * i, channels=3, seed=50 + i)
        for i in range(4)
    ]
    blobs = [encode_image_bytes(im) for im in imgs]
    golden = [np.asarray(pipe.jit()(im)) for im in imgs]
    trace_ids: list[tuple[str, str]] = []  # (trace id, serving replica)

    with Fabric(cfg).start() as fab:
        # -- 1. both replicas serving, responses bit-exact ------------------
        served = set()
        for k, blob in enumerate(blobs * 4):
            r = loadgen.http_post_image(fab.url, blob)
            assert r["code"] == 200, (r["code"], r["body"][:200])
            np.testing.assert_array_equal(
                decode_image_bytes(r["body"]), golden[k % len(golden)]
            )
            served.add(r["replica"])
            if r["trace_id"]:
                trace_ids.append((r["trace_id"], r["replica"]))
        print(f"smoke: {len(blobs) * 4} requests ok, replicas {sorted(served)}")

        # -- 2. heartbeat loss -> staleness -> rerouting --------------------
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            routable = [v.replica_id for v in fab.router._routable()]
            if routable == ["r1"]:
                break
            time.sleep(0.1)
        assert routable == ["r1"], (
            f"r0's heartbeat loss never made it stale (routable {routable})"
        )
        for blob in blobs:
            r = loadgen.http_post_image(fab.url, blob)
            assert r["code"] == 200
            assert r["replica"] == "r1", (
                f"request routed to stale replica {r['replica']}"
            )
            if r["trace_id"]:
                trace_ids.append((r["trace_id"], r["replica"]))
        print("smoke: r0 stale after injected heartbeat loss; all traffic on r1")

        # -- 5. federation: router view == sum of replica registries --------
        # r0 is heartbeat-silent by now, so its contribution arrives via
        # the router's full-scrape fallback (GET /fleet/snapshot) — this
        # check proves BOTH the delta path (r1) and the gap fallback (r0)
        ports = {
            rid: rep["port"]
            for rid, rep in fab.http_stats()["replicas"].items()
        }
        deadline = time.monotonic() + 30.0
        while True:
            want_ok = want_e2e = 0.0
            for port in ports.values():
                ok_i, e2e_i = _replica_ok_total(port)
                want_ok += ok_i
                want_e2e += e2e_i
            exposition = fab.scrape()
            got_ok, got_e2e = _federated_ok_total(exposition)
            if got_ok == want_ok and got_e2e == want_e2e:
                break
            assert time.monotonic() < deadline, (
                f"federated view never converged: requests ok "
                f"{got_ok} != {want_ok} or e2e count {got_e2e} != {want_e2e}"
            )
            time.sleep(0.2)
        print(
            f"smoke: federated /metrics == sum of replica registries "
            f"(ok {got_ok:.0f}, e2e count {got_e2e:.0f})"
        )
        with urllib.request.urlopen(fab.url + "/slo", timeout=10.0) as resp:
            slo_view = json.loads(resp.read())
        assert slo_view["slos"], "router /slo exposes no SLOs"
        assert slo_view["p99"]["p99_s"] is not None, slo_view["p99"]
        print(
            f"smoke: /slo live (federated p99 ~"
            f"{slo_view['p99']['p99_s'] * 1e3:.1f} ms, exemplar "
            f"{slo_view['p99']['exemplar_trace_id']})"
        )

        # -- 4. metrics snapshot (written before teardown) ------------------
        with open(metrics_out, "w") as f:
            f.write(exposition)

        # -- 6. SIGKILL -> replica_death flight-recorder dump ---------------
        # r0 is already heartbeat-silent: its warm buckets reach the dump
        # from the router ring's LAST heartbeat note — the exact shape of
        # a real post-mortem. (Killing r1 would also lose its graceful
        # trace export, which section 3 still needs.)
        victim = "r0"
        fab.kill_replica(victim)
        deadline = time.monotonic() + 30.0
        dump_path = None
        while time.monotonic() < deadline and dump_path is None:
            if os.path.isdir(rec_dir):
                dumps = sorted(
                    p
                    for p in os.listdir(rec_dir)
                    if p.startswith("recorder_replica_death")
                )
                if dumps:
                    dump_path = os.path.join(rec_dir, dumps[0])
                    break
            time.sleep(0.1)
        assert dump_path, "supervisor never wrote a replica_death dump"
        with open(dump_path) as f:
            dump = json.load(f)
        assert dump["extra"]["replica"] == victim, dump["extra"]
        assert dump["extra"].get("warm_buckets"), (
            f"replica_death dump does not name {victim}'s warm buckets: "
            f"{dump['extra']}"
        )
        print(
            f"smoke: replica_death dump ({os.path.basename(dump_path)}) "
            f"names {victim}'s warm buckets {dump['extra']['warm_buckets']}"
        )
    # graceful drain done: replicas exported their traces on SIGTERM

    fams = parse_exposition(exposition)
    for fam in (
        "mcim_fabric_requests_total",
        "mcim_fabric_forwards_total",
        "mcim_fabric_route_total",
        "mcim_fabric_heartbeats_total",
        "mcim_fabric_replicas_routable",
    ):
        assert fam in fams, f"{fam} missing from /metrics"
    ok = sum(
        v
        for (name, labels), v in fams["mcim_fabric_requests_total"][
            "samples"
        ].items()
        if 'status="ok"' in labels
    )
    assert ok >= len(blobs) * 5, f"requests_total{{ok}} = {ok}"
    print(f"smoke: /metrics parses; requests_total{{ok}} = {ok:.0f}")

    # -- 3. closed router->replica span chain ------------------------------
    router_events = tracer.chrome_events()
    merged = list(router_events)
    for rid, path in rep_traces.items():
        if rid == victim:
            # the SIGKILLed replica never drained; its respawn exports a
            # fresh (empty-of-our-traces) file, if it got that far
            continue
        assert os.path.exists(path), f"{rid} never exported {path}"
        with open(path) as f:
            merged.extend(json.load(f)["traceEvents"])
    with open(trace_out, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)

    def spans_for(tid: str) -> dict[str, list[dict]]:
        out: dict[str, list[dict]] = {}
        for e in merged:
            if e.get("args", {}).get("trace_id") == tid:
                out.setdefault(e["name"], []).append(e)
        return out

    assert trace_ids, "no request carried a trace id"
    survivors = [tid for tid, rid in trace_ids if rid != victim]
    checked = 0
    for tid in survivors:
        spans = spans_for(tid)
        if "serve.request" not in spans:
            continue
        for name in ("fabric.request", "fabric.forward", "serve.request",
                     "serve.dispatch"):
            assert name in spans, (
                f"trace {tid}: span {name!r} missing ({sorted(spans)})"
            )
        root_id = spans["fabric.request"][0]["args"]["span_id"]
        fwd = spans["fabric.forward"][0]["args"]
        assert fwd.get("parent_id") == root_id, (
            f"trace {tid}: fabric.forward not parented to fabric.request"
        )
        checked += 1
    assert checked >= len(survivors) * 0.9, (
        f"only {checked}/{len(survivors)} surviving-replica traces had "
        "the full router->replica chain"
    )
    print(
        f"smoke: {checked}/{len(survivors)} traces span the full "
        f"router->replica hop ({len(merged)} merged events -> {trace_out})"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 3:
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1], sys.argv[2]))
