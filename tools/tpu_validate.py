"""Real-hardware validation + timing sweep (run manually on a TPU host).

CI runs everything on CPU (interpret-mode Pallas, 8 fake devices); this
script is the hardware half of the test strategy (SURVEY.md §4): it
re-asserts cross-backend bit-exactness with *compiled* Mosaic kernels on
the real chip — the analogue of the reference's only existence proof for
its CUDA kernels, which are compiled-or-nothing (kernel.cu:31-94) — then
optionally times the headline configs with the N-scaling slope timer.
Results are written as a JSON artifact (default VALIDATE.json) so a round
record can be committed. Usage:

    python tools/tpu_validate.py                   # bit-exactness sweep
    python tools/tpu_validate.py --out VALIDATE_r02.json
    python tools/tpu_validate.py --bench           # + throughput table
    python tools/tpu_validate.py --quick           # fewer shapes (fast smoke)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# the checkout above us always wins over any installed copy — a stale
# non-editable install must never shadow the code being validated
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPECS = [
    ("gaussian:5", 1),
    ("gaussian:7", 1),
    ("sobel", 1),
    ("prewitt", 1),
    ("scharr", 1),
    ("laplacian:8", 1),
    ("unsharp", 1),
    ("filter:1/2/1/2/4/2/1/2/1:0.0625", 1),
    ("emboss:3", 1),
    ("emboss:5", 1),
    ("emboss101:5", 1),
    ("median", 1),
    ("median:5", 1),
    ("erode:5", 1),
    ("dilate:3", 1),
    ("box:7", 1),
    ("sharpen", 1),
    ("grayscale,contrast:3.5,emboss:3", 3),
    ("gaussian:5", 3),
    ("invert,gaussian:5,threshold:99", 1),
    ("grayscale,gaussian:7", 3),
]

# Explicit block-height overrides: Mosaic grid semantics differ with the
# block geometry (ragged last block, halo-in-block), so the --block knob is
# validated compiled, not just in interpret mode (advisor round-1 finding).
BLOCK_CASES = [
    ("gaussian:5", 1, 64),
    ("gaussian:5", 1, 224),
    ("sobel", 1, 96),
    ("grayscale,contrast:3.5,emboss:3", 3, 160),
]

# vmap-batched pipelines: the batch dim lowers as a Mosaic 'parallel' grid
# dim with per-core scratch carry — only a compiled run proves it.
BATCH_CASES = [
    ("gaussian:5", 1, 4),
    ("grayscale,contrast:3.5,emboss:3", 3, 3),
    ("sobel", 1, 2),
]

# sharded pipelines on a 1-device mesh: exercises the fused-ghost kernel
# (run_group ghost mode — tile streamed directly, ghost strips as
# separate refs) compiled by Mosaic, which CI only runs in interpret mode.
SHARDED_CASES = [
    ("gaussian:5", 1),
    ("grayscale,contrast:3.5,emboss:3", 3),
    ("erode:5", 1),
    ("median:5", 1),
]

# guarded (watchdog-subprocess) runs on the real chip: proves the
# --device-timeout path compiles/runs compiled Mosaic end-to-end and
# reports steady-state timing (VERDICT r2 directive #6).
GUARDED_CASES = [
    ("grayscale,contrast:3.5,emboss:3", 3, "pallas"),
    ("gaussian:5", 1, "pallas"),
]

# packed-u32 streaming kernels (tools/packed_kernels.py — DEMOTED round 5
# after this sweep found compiled-mode miscompares on planes narrower than
# one 128-lane tile, validate_r05.out): kept in the sweep as the archived
# module's compiled regression record. Shapes with W % 4 != 0 exercise the
# per-group u8 fallback under the packed flag.
PACKED_SPECS = [
    ("gaussian:5", 1),
    ("gaussian:7", 1),
    ("box:5", 1),
    ("erode:5", 1),
    ("dilate:7", 1),
    ("sobel", 1),
    ("unsharp", 1),
    ("emboss101:5", 1),
    ("median:3", 1),
    ("median:5", 1),
    ("emboss:5", 1),
    ("grayscale,contrast:3.5", 3),
    ("grayscale,contrast:3.5,emboss:3", 3),
    ("grayscale,gaussian:5", 3),
    ("invert,gaussian:3,threshold:99", 1),
]

SHAPES = [(129, 517), (40, 300), (257, 1024), (96, 2048), (65, 140)]
QUICK_SHAPES = [(129, 517), (65, 140)]

# MXU banded-matmul backend (ops/mxu_kernels.py, round 6): one spec per
# routed formulation class plus chains with per-op fallbacks. Shapes come
# from the sweep's shape list (ragged widths/heights, sub-block planes).
MXU_SPECS = [
    ("gaussian:5", 1, 101),  # sep5, the headline (64a+b split)
    ("gaussian:7", 1, 102),  # sep7, S=64 — the split's boundary case
    ("box:5", 1, 103),  # non-power-of-two scale replay
    ("emboss:5", 1, 104),  # corr5x5, interior guard
    ("emboss101:5", 1, 105),  # corr5x5, reflect101 + rint
    ("sobel", 1, 106),  # grad3x3 magnitude replay
    ("scharr", 1, 107),  # grad3x3, squares past 2^24 (fma replay)
    ("unsharp", 1, 108),  # corr5x5, 476-weight bf16-exactness case
    ("grayscale,contrast:3.5,emboss:3", 3, 109),  # VPU prefix + MXU body
    ("invert,gaussian:5,threshold:99", 1, 110),  # pre+post pointwise
    ("median:3,gaussian:5", 1, 111),  # per-op fallback mix
]

# Known compiled-mode miscompares of the ARCHIVED packed backend on planes
# narrower than one 128-lane tile, exactly as the round-5 hardware sweep
# recorded them (artifacts/validate_r05.out — the finding that demoted the
# backend). The xfail excusal keys on these exact (spec, shape) pairs: a
# NEW narrow-plane miscompare (different op family or shape) counts as a
# real sweep failure instead of silently riding the known defect (ADVICE
# r5 finding 3). median:3 @ (65, 140) is included although the first
# sweep wedged before reaching it — (40, 300) failed and the defect
# reproduces per (spec, narrow shape); any other unexercised case must
# earn its entry from a real sweep log.
PACKED_XFAIL_PAIRS = {
    (spec, shape)
    for spec in (
        "gaussian:5", "gaussian:7", "box:5", "erode:5", "sobel",
        "unsharp", "emboss101:5", "median:3",
    )
    for shape in ((40, 300), (65, 140))
}


def _check(results, name, spec, ch, hw, golden_fn, got_fn) -> bool:
    import numpy as np

    t0 = time.time()
    try:
        golden = np.asarray(golden_fn())
        got = np.asarray(got_fn())
        ok = bool(np.array_equal(got, golden))
        detail = ""
        if not ok:
            d = np.abs(got.astype(int) - golden.astype(int))
            detail = f"maxdiff {d.max()} ndiff {np.count_nonzero(d)}"
    except Exception as e:  # a Mosaic compile crash is a result, not an abort
        ok, detail = False, f"{type(e).__name__}: {e}"
    dt = time.time() - t0
    results.append(
        {"case": name, "spec": spec, "channels": ch, "shape": list(hw),
         "ok": ok, "seconds": round(dt, 2), **({"detail": detail[:300]} if detail else {})}
    )
    status = "ok  " if ok else "FAIL"
    print(f"{status} {name:8s} {spec:34s} ch{ch} {str(hw):12s} {dt:5.1f}s"
          + (f"  {detail[:120]}" if detail else ""), flush=True)
    return ok


def run_sweep(shapes, results) -> int:
    import jax
    import jax.numpy as jnp

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import pipeline_pallas
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops

    fails = 0

    def golden_of(ops, img):
        out = img
        for op in ops:
            out = op(out)
        return out

    for spec, ch in SPECS:
        ops = make_pipeline_ops(spec)
        for hw in shapes:
            img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=3))
            fails += not _check(
                results, "compiled", spec, ch, hw,
                lambda: golden_of(ops, img), lambda: pipeline_pallas(ops, img),
            )

    from tools.packed_kernels import pipeline_packed

    for spec, ch in PACKED_SPECS:
        ops = make_pipeline_ops(spec)
        for hw in shapes:
            img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=4))
            ok = _check(
                results, "packed", spec, ch, hw,
                lambda: golden_of(ops, img),
                lambda: pipeline_packed(ops, img),
            )
            if (
                not ok
                and (spec, tuple(hw)) in PACKED_XFAIL_PAIRS
                and results[-1].get("detail", "").startswith("maxdiff")
            ):
                # KNOWN archived-module defect (PACKED_XFAIL_PAIRS) —
                # recorded in the artifact as xfail, not counted as a
                # sweep failure, so the gate stays meaningful for
                # everything still in production. Only the exact known
                # (spec, shape) miscompare signature is excused: a compile
                # crash, a new shape, or a new op family still counts.
                results[-1]["status"] = "xfail-lane-tile"
                print(
                    f"     ^ excused: known archived-packed lane-tile "
                    f"miscompare ({spec} @ {hw})",
                    flush=True,
                )
                continue
            fails += not ok

    for spec, ch, bh in BLOCK_CASES:
        ops = make_pipeline_ops(spec)
        hw = shapes[0]
        img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=5))
        fails += not _check(
            results, f"block{bh}", spec, ch, hw,
            lambda: golden_of(ops, img),
            lambda: pipeline_pallas(ops, img, block_h=bh),
        )

    for spec, ch, n in BATCH_CASES:
        ops = make_pipeline_ops(spec)
        hw = shapes[-1] if len(shapes) > 1 else shapes[0]
        imgs = jnp.stack(
            [jnp.asarray(synthetic_image(*hw, channels=ch, seed=10 + i)) for i in range(n)]
        )
        batched = jax.vmap(lambda im: pipeline_pallas(ops, im))
        fails += not _check(
            results, f"batch{n}", spec, ch, hw,
            lambda: jnp.stack([golden_of(ops, imgs[i]) for i in range(n)]),
            lambda: batched(imgs),
        )

    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(1)
    for spec, ch in SHARDED_CASES:
        pipe = Pipeline.parse(spec)
        hw = shapes[0]
        img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=21))
        fails += not _check(
            results, "sharded", spec, ch, hw,
            lambda: golden_of(pipe.ops, img),
            lambda: pipe.sharded(mesh, backend="pallas")(img),
        )

    # 2-D tile runner (parallel/api2d) on a 1x1 device mesh: both
    # ppermute-free exchange paths + axis-general edge fixups get a
    # compiled silicon run without a pod (same rationale as the 1-D
    # make_mesh(1) cases above)
    from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh_2d

    mesh2 = make_mesh_2d(1, 1)
    for spec, ch in SHARDED_CASES:
        pipe = Pipeline.parse(spec)
        hw = shapes[0]
        img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=29))
        fails += not _check(
            results, "sharded2d", spec, ch, hw,
            lambda: golden_of(pipe.ops, img),
            lambda: pipe.sharded(mesh2)(img),
        )

    fails += run_wide_backends_sweep(shapes, results)

    from mpi_cuda_imagemanipulation_tpu.utils.guard import run_guarded

    for spec, ch, impl in GUARDED_CASES:
        pipe = Pipeline.parse(spec)
        hw = shapes[0]
        img_np = synthetic_image(*hw, channels=ch, seed=23)
        timings: dict = {}
        fails += not _check(
            results, "guarded", spec, ch, hw,
            lambda: golden_of(pipe.ops, jnp.asarray(img_np)),
            lambda: run_guarded(
                spec, img_np, 900.0, impl=impl, timings=timings
            ),
        )
        if timings:
            results[-1]["steady_ms"] = round(
                timings.get("steady_s", 0.0) * 1e3, 3
            )
            print(
                f"     guarded timings: compile+run "
                f"{timings.get('compile_and_run_s', 0):.2f}s, steady "
                f"{timings.get('steady_s', 0) * 1e3:.2f}ms",
                flush=True,
            )

    print("FAILS:", fails, flush=True)
    return fails


def run_wide_backends_sweep(shapes, results) -> int:
    """Compiled-mode sweep of the promoted wide backends — SWAR
    quarter-strip AND the MXU banded-matmul path (round 6) — runnable as
    its own queue lane (`--lane mxu_swar`,
    tools/tpu_queue/31_validate_compiled_r06.sh) so the compiled-only
    miscompare class that demoted the packed backend (and wedged the
    round-5 sweep mid-run) is caught by a short targeted step early in a
    window rather than on silicon by accident. On TPU every case runs the
    real Mosaic/XLA lowering; off-TPU the Pallas pieces interpret and the
    MXU einsums still compile (they are pure XLA)."""
    import jax
    import jax.numpy as jnp

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh
    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

    fails = 0

    def golden_of(ops, img):
        out = img
        for op in ops:
            out = op(out)
        return out

    mesh = make_mesh(1)
    _interp = not is_tpu_backend()

    # quarter-strip SWAR ghost path on the 1-device mesh: compiles the
    # sharded swar kernels (separable + corr2d + fused chain) with Mosaic
    for spec, ch, sseed in (
        ("contrast:3.5,gaussian:5", 1, 61),
        ("grayscale,contrast:3.5,emboss:3", 3, 62),
    ):
        pipe = Pipeline.parse(spec)
        hw = (128, 256)
        img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=sseed))
        fails += not _check(
            results, "sharded_swar", spec, ch, hw,
            lambda: golden_of(pipe.ops, img),
            lambda: pipe.sharded(mesh, backend="swar")(img),
        )

    # SWAR quarter-strip carry kernel (tools/swar_proto.py), compiled: the
    # Mosaic lowering of the u32 field algebra gets a hardware record even
    # before the timing step runs
    import importlib.util as _ilu

    _spec = _ilu.spec_from_file_location(
        "swar_proto",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "swar_proto.py"),
    )
    _swar = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_swar)
    _pack, _unpack, _, _mk = _swar.build_fns()
    import numpy as _np

    for sh, sbh in ((129, 32), (96, 48)):
        simg = jnp.asarray(synthetic_image(sh, 128, channels=1, seed=31))
        spipe = Pipeline.parse("gaussian:5")
        spad = jnp.asarray(
            _np.pad(_np.asarray(simg), _swar.H_, mode="reflect")
        )
        sext = _pack(spad)
        fails += not _check(
            results, f"swar_bh{sbh}", "gaussian:5", 1, (sh, 128),
            lambda: golden_of(spipe.ops, simg),
            lambda: _unpack(_mk(sext.shape, sbh, interpret=_interp)(sext)[:sh]),
        )

    # production swar backend (ops/swar_kernels.py): compiled Mosaic record
    # for the packaged pipeline path — eligible stencils, a chain staying
    # on the swar path, and the run-fallback mix
    from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import pipeline_swar

    for spec, ch, seed in (
        ("gaussian:5", 1, 41),
        ("gaussian:3", 1, 42),
        ("gaussian:3,gaussian:5", 1, 43),
        ("grayscale,gaussian:5", 3, 44),
        # round-5 additions: wide column mode (gaussian:7 S=64, box:3
        # non-power-of-two), fused affine chains (pre and post), and the
        # corr2d kernel — incl. the FULL reference pipeline, whose
        # contrast+emboss tail is one quarter-strip kernel
        ("gaussian:7", 1, 45),
        ("box:3", 1, 46),
        ("contrast:3.5,gaussian:5", 1, 47),
        ("gaussian:7,invert", 1, 48),
        ("emboss:3", 1, 49),
        ("emboss101:5", 1, 50),
        ("grayscale,contrast:3.5,emboss:3", 3, 51),
    ):
        pipe = Pipeline.parse(spec)
        hw = (130, 256)
        simg2 = jnp.asarray(synthetic_image(*hw, channels=ch, seed=seed))
        fails += not _check(
            results, "swar_prod", spec, ch, hw,
            lambda: golden_of(pipe.ops, simg2),
            lambda: pipeline_swar(pipe.ops, simg2, interpret=_interp),
        )

    # production MXU banded-matmul backend (ops/mxu_kernels.py, round 6):
    # every routed formulation class — separable banded (64a+b split),
    # one-einsum corr2d, magnitude combine — in both execution modes,
    # over ragged shapes incl. sub-block planes. The bf16 MXU lowering is
    # exactly what interpret-free CPU runs cannot prove.
    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import pipeline_mxu

    for spec, ch, seed in MXU_SPECS:
        pipe = Pipeline.parse(spec)
        for hw in shapes[:3]:
            mimg = jnp.asarray(synthetic_image(*hw, channels=ch, seed=seed))
            for mode in ("banded", "hybrid"):
                fails += not _check(
                    results, f"mxu_{mode}", spec, ch, hw,
                    lambda: golden_of(pipe.ops, mimg),
                    lambda: jax.jit(
                        lambda x, m=mode: pipeline_mxu(pipe.ops, x, mode=m)
                    )(mimg),
                )

    # f32 column-pass variant (the A/B alternative to the 64a+b split)
    saved_col = os.environ.get("MCIM_MXU_COL")
    os.environ["MCIM_MXU_COL"] = "f32"
    try:
        for spec in ("gaussian:5", "gaussian:7"):
            pipe = Pipeline.parse(spec)
            hw = shapes[0]
            fimg = jnp.asarray(synthetic_image(*hw, channels=1, seed=71))
            fails += not _check(
                results, "mxu_f32col", spec, 1, hw,
                lambda: golden_of(pipe.ops, fimg),
                lambda: jax.jit(lambda x: pipeline_mxu(pipe.ops, x))(fimg),
            )
    finally:
        if saved_col is None:
            os.environ.pop("MCIM_MXU_COL", None)
        else:
            os.environ["MCIM_MXU_COL"] = saved_col

    # sharded MXU on the 1-device mesh (materialised-ext + banded einsum,
    # global-coordinate finalize) and the serving bucket-padded executor
    # with the MXU contraction at a ragged dynamic true shape
    for spec, ch in (("gaussian:5", 1), ("grayscale,contrast:3.5,emboss:3", 3)):
        pipe = Pipeline.parse(spec)
        hw = shapes[0]
        img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=81))
        fails += not _check(
            results, "sharded_mxu", spec, ch, hw,
            lambda: golden_of(pipe.ops, img),
            lambda: pipe.sharded(mesh, backend="mxu")(img),
        )

    spipe = Pipeline.parse("gaussian:5")
    th, tw = 113, 201
    timg = _np.zeros((1, 128, 256), _np.uint8)
    true_img = synthetic_image(th, tw, channels=1, seed=91)
    timg[0, :th, :tw] = true_img
    serve_fn = spipe.serving(128, 256, 1, 1, backend="mxu")
    fails += not _check(
        results, "serve_mxu", "gaussian:5", 1, (th, tw),
        lambda: golden_of(spipe.ops, jnp.asarray(true_img)),
        lambda: serve_fn(
            jnp.asarray(timg),
            jnp.asarray([th], jnp.int32),
            jnp.asarray([tw], jnp.int32),
        )[0, :th, :tw],
    )

    return fails


def run_bench() -> None:
    from mpi_cuda_imagemanipulation_tpu.bench_suite import run_suite

    run_suite(impl="both")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", action="store_true")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--lane",
        choices=("all", "mxu_swar"),
        default="all",
        help="'mxu_swar' runs only the wide-backend compiled sweep (the "
        "SWAR quarter-strip + MXU banded-matmul lanes) — a short "
        "targeted step for the front of a chip window, so compiled-only "
        "miscompares in the promoted backends are caught before the "
        "long full sweep (tools/tpu_queue/31_validate_compiled_r06.sh)",
    )
    ap.add_argument("--out", default="VALIDATE.json", help="JSON artifact path")
    args = ap.parse_args()
    import jax

    platform = jax.default_backend()
    devices = [str(d) for d in jax.devices()]
    print("backend:", platform, devices, flush=True)
    results: list[dict] = []
    t0 = time.time()
    shapes = QUICK_SHAPES if args.quick else SHAPES
    if args.lane == "mxu_swar":
        fails = run_wide_backends_sweep(shapes, results)
        print("FAILS:", fails, flush=True)
    else:
        fails = run_sweep(shapes, results)
    artifact = {
        "platform": platform,
        "devices": devices,
        "interpret": False if platform == "tpu" else True,
        "quick": bool(args.quick),
        "lane": args.lane,
        "total_cases": len(results),
        "fails": fails,
        "wall_seconds": round(time.time() - t0, 1),
        "results": results,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {args.out}: {len(results)} cases, {fails} fails", flush=True)
    if args.bench:
        run_bench()
    return 1 if fails else 0


if __name__ == "__main__":
    raise SystemExit(main())
