"""Real-hardware validation + timing sweep (run manually on a TPU host).

CI runs everything on CPU (interpret-mode Pallas, 8 fake devices); this
script is the hardware half of the test strategy (SURVEY.md §4): it
re-asserts cross-backend bit-exactness with *compiled* Mosaic kernels on
the real chip, then times the headline configs with the N-scaling slope
timer. Usage:

    python tools/tpu_validate.py            # bit-exactness sweep
    python tools/tpu_validate.py --bench    # + throughput table
    python tools/tpu_validate.py --quick    # fewer shapes (fast smoke)
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPECS = [
    ("gaussian:5", 1),
    ("gaussian:7", 1),
    ("sobel", 1),
    ("prewitt", 1),
    ("scharr", 1),
    ("laplacian:8", 1),
    ("unsharp", 1),
    ("filter:1/2/1/2/4/2/1/2/1:0.0625", 1),
    ("emboss:3", 1),
    ("emboss:5", 1),
    ("emboss101:5", 1),
    ("median", 1),
    ("erode:5", 1),
    ("dilate:3", 1),
    ("box:7", 1),
    ("sharpen", 1),
    ("grayscale,contrast:3.5,emboss:3", 3),
    ("gaussian:5", 3),
    ("invert,gaussian:5,threshold:99", 1),
    ("grayscale,gaussian:7", 3),
]

SHAPES = [(129, 517), (40, 300), (257, 1024), (96, 2048), (65, 140)]
QUICK_SHAPES = [(129, 517), (65, 140)]


def run_sweep(shapes) -> int:
    import jax.numpy as jnp
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import pipeline_pallas
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops

    fails = 0
    for spec, ch in SPECS:
        for hw in shapes:
            t0 = time.time()
            img = jnp.asarray(synthetic_image(*hw, channels=ch, seed=3))
            ops = make_pipeline_ops(spec)
            golden = img
            for op in ops:
                golden = op(golden)
            got = pipeline_pallas(ops, img)
            ok = np.array_equal(np.asarray(got), np.asarray(golden))
            if not ok:
                d = np.abs(
                    np.asarray(got).astype(int) - np.asarray(golden).astype(int)
                )
                print(
                    f"FAIL {spec} ch{ch} {hw}: maxdiff {d.max()} "
                    f"ndiff {np.count_nonzero(d)}",
                    flush=True,
                )
                fails += 1
            else:
                print(
                    f"ok   {spec:34s} ch{ch} {str(hw):12s} {time.time()-t0:5.1f}s",
                    flush=True,
                )
    print("FAILS:", fails, flush=True)
    return fails


def run_bench() -> None:
    from mpi_cuda_imagemanipulation_tpu.bench_suite import run_suite

    run_suite(impl="both")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", action="store_true")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    import jax

    print("backend:", jax.default_backend(), jax.devices(), flush=True)
    fails = run_sweep(QUICK_SHAPES if args.quick else SHAPES)
    if args.bench:
        run_bench()
    return 1 if fails else 0


if __name__ == "__main__":
    raise SystemExit(main())
