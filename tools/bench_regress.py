#!/usr/bin/env python
"""bench_regress — noise-aware perf-regression sentinel over
BENCH_HISTORY.jsonl.

    python tools/bench_regress.py --history BENCH_HISTORY.jsonl
    python tools/bench_regress.py --history ... --candidate lane.json
    python tools/bench_regress.py --history ... --self-test

Perf claims land in BENCH_HISTORY.jsonl (PR 5's promotion mechanism);
until now only humans read the trajectory. This tool makes the committed
history a CI gate: group every record into SERIES keyed by
(config, impl, platform) — CPU smoke numbers never get compared against
TPU headlines — pick each config's headline metric (higher is better),
and check the newest point of every series against its own history.

Noise model (per series, all prior points):

    median  m, spread s = 1.4826 * MAD   (robust to the odd outlier run)
    allowed = m - max(K_MAD * s, REL_TOL * m)

A fresh value below `allowed` is a regression. The MAD term absorbs
series whose history is genuinely noisy (the TPU pallas trajectory swings
with tunnel health); the REL_TOL floor stops a zero-spread series (two
identical runs) from flagging a 0.1% wobble. With exactly one prior
point the tolerance widens to REL_TOL_SINGLE — one sample tells you
little about noise. Series with no prior point pass (nothing to compare).

Modes:
  * default: the LATEST record of each series is the candidate, its
    predecessors the history — "is the committed history self-consistent"
    (the CI step runs this; it must stay green).
  * --candidate FILE: a fresh lane record (the JSON a bench lane writes,
    a full history line, or a list of records) is checked against the
    ENTIRE committed trajectory — the pre-merge question.
  * --self-test: synthesize a regressed candidate from the history
    (headline metric scaled by 0.5) and assert the sentinel TRIPS — CI
    proves the gate can actually fire, then proves the real history
    passes.

Exit codes: 0 clean, 1 regression(s), 2 usage/data error.
"""

from __future__ import annotations

import argparse
import copy
import json
import sys

K_MAD = 4.0
REL_TOL = 0.25
REL_TOL_SINGLE = 0.40

# config -> list of (record field, higher_is_better). Configs not listed
# fall back to _DEFAULT_FIELDS (first present wins).
METRIC_FIELDS: dict[str, list[tuple[str, bool]]] = {
    "plan_ab": [("speedup_fused_vs_off", True)],
    "megakernel_ab": [("speedup_pallas_vs_fused", True)],
    # the in-stage-MXU lane: the best dot arm vs the VPU walk is the
    # headline (on CPU an interpret-mode gate anchor, on TPU the perf
    # claim), and the int8-vs-f32 ratio guards the cheaper accumulator
    "mxu_fused_ab": [
        ("speedup_fused_mxu_vs_fused_vpu", True),
        ("speedup_fused_mxu_int8_vs_f32", True),
    ],
    "stream_ab": [("speedup", True), ("memory_ratio", True)],
    "engine_ab": [("speedup", True)],
    "halo_ab": [("comms_hidden_frac", True)],
    "fabric_loadgen": [("scaling_vs_1", True)],
    # the chaos/brownout lane (tools/chaos_smoke.py): goodput within the
    # client deadline must not sag, and the hedged tail must not creep
    # back toward the brownout floor
    "chaos_loadgen": [("goodput_rps", True), ("e2e_p99_ms", False)],
    # the autotune-convergence lane (tune/): the loop must not get
    # slower to converge, and the throughput it converges ONTO must not
    # sag (the banked payoff is the whole point of the loop)
    "tune_convergence": [
        ("converge_s", False),
        ("tuned_mp_per_s_per_chip", True),
    ],
}
_DEFAULT_FIELDS: list[tuple[str, bool]] = [
    ("mp_per_s_per_chip", True),
    ("mp_per_s", True),
    ("speedup", True),
]

# MEASURED-cost columns (obs/cost via bench_suite): tracked on EVERY
# record that carries them, in addition to the config's headline metric
# — a regression in compiled-executable GB/s or measured roofline
# fraction is a perf claim going stale even when the analytical model
# still looks fine.
_MEASURED_FIELDS: list[tuple[str, bool]] = [
    ("hbm_gb_s_measured", True),
    ("roofline_frac_measured", True),
]


def _series_key(rec: dict) -> tuple | None:
    cfg = rec.get("config")
    if not cfg:
        return None
    return (cfg, str(rec.get("impl", "")), str(rec.get("platform", "")))


def _metrics_of(rec: dict) -> list[tuple[str, float, bool]]:
    """(field, value, higher_is_better) entries present on this record."""
    cfg = rec.get("config", "")
    fields = METRIC_FIELDS.get(cfg, _DEFAULT_FIELDS)
    out = []
    for field, higher in fields:
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append((field, float(v), higher))
            if cfg not in METRIC_FIELDS:
                break  # default list: first present metric only
    for field, higher in _MEASURED_FIELDS:
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append((field, float(v), higher))
    return out


def load_history(path: str) -> list[dict]:
    lines = []
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                lines.append(json.loads(raw))
            except ValueError as e:
                raise SystemExit(
                    f"{path}:{i}: unparsable history line ({e})"
                ) from None
    return lines


def build_series(lines: list[dict]) -> dict[tuple, list[tuple[str, float]]]:
    """(config, impl, platform, field) -> [(ts, value), ...] in history
    order."""
    series: dict[tuple, list[tuple[str, float]]] = {}
    for line in lines:
        ts = line.get("ts", "")
        for rec in line.get("records", ()):
            key = _series_key(rec)
            if key is None:
                continue
            for field, value, higher in _metrics_of(rec):
                series.setdefault((*key, field, higher), []).append(
                    (ts, value)
                )
    return series


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def check_value(
    history: list[float], value: float, *, higher: bool = True
) -> dict:
    """One candidate value vs its series history -> verdict dict."""
    if not history:
        return {"ok": True, "reason": "no history", "allowed": None}
    if not higher:
        history = [-v for v in history]
        value = -value
    m = _median(history)
    if len(history) == 1:
        # abs(): lower-is-better series arrive negated, and scaling a
        # negative median toward zero would flag an identical candidate
        allowed = m - REL_TOL_SINGLE * abs(m)
        reason = f"single prior point {m:.4g}, tol {REL_TOL_SINGLE:.0%}"
    else:
        mad = _median([abs(v - m) for v in history])
        spread = 1.4826 * mad
        slack = max(K_MAD * spread, REL_TOL * abs(m))
        allowed = m - slack
        reason = (
            f"median {m:.4g}, spread {spread:.4g} "
            f"(n={len(history)}), slack {slack:.4g}"
        )
    return {
        "ok": value >= allowed,
        "allowed": allowed if higher else -allowed,
        "median": m if higher else -m,
        "reason": reason,
    }


def _records_of_candidate(obj) -> list[dict]:
    if isinstance(obj, list):
        return [r for r in obj if isinstance(r, dict)]
    if isinstance(obj, dict):
        if "records" in obj:
            return list(obj["records"])
        return [obj]
    return []


def run_check(
    lines: list[dict],
    candidate_records: list[dict] | None = None,
    *,
    printer=print,
) -> int:
    """Returns the number of regressions found (0 = green)."""
    series = build_series(lines)
    regressions = 0
    checked = 0
    if candidate_records is None:
        # self-consistency: newest point of each series vs its elders
        for key, points in sorted(series.items()):
            if len(points) < 2:
                continue
            *cfg_key, field, higher = key
            hist = [v for _, v in points[:-1]]
            ts, value = points[-1]
            verdict = check_value(hist, value, higher=higher)
            checked += 1
            tag = "ok " if verdict["ok"] else "REGRESSION"
            printer(
                f"{tag} {'/'.join(map(str, cfg_key))}.{field}: "
                f"latest {value:.4g} vs {verdict['reason']} "
                f"(allowed >= {verdict['allowed']:.4g})"
            )
            if not verdict["ok"]:
                regressions += 1
    else:
        for rec in candidate_records:
            key = _series_key(rec)
            if key is None:
                continue
            for field, value, higher in _metrics_of(rec):
                points = series.get((*key, field, higher), [])
                hist = [v for _, v in points]
                if not hist:
                    printer(
                        f"new {'/'.join(map(str, key))}.{field}: "
                        f"{value:.4g} (no history — passes)"
                    )
                    continue
                verdict = check_value(hist, value, higher=higher)
                checked += 1
                tag = "ok " if verdict["ok"] else "REGRESSION"
                printer(
                    f"{tag} {'/'.join(map(str, key))}.{field}: "
                    f"candidate {value:.4g} vs {verdict['reason']} "
                    f"(allowed >= {verdict['allowed']:.4g})"
                )
                if not verdict["ok"]:
                    regressions += 1
    printer(
        f"bench_regress: {checked} series checked, "
        f"{regressions} regression(s)"
    )
    return regressions


def synthesize_regressed(lines: list[dict]) -> list[dict]:
    """A candidate built from the newest comparable record with its
    headline metric halved — the self-test's guaranteed trip."""
    series = build_series(lines)
    comparable = {k for k, pts in series.items() if len(pts) >= 1}
    for line in reversed(lines):
        for rec in reversed(line.get("records", ())):
            key = _series_key(rec)
            if key is None:
                continue
            for field, value, _higher in _metrics_of(rec):
                if (*key, field, True) in comparable:
                    bad = copy.deepcopy(rec)
                    bad[field] = value * 0.5
                    return [bad]
    raise SystemExit("self-test: no comparable record found in history")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_regress", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--history", default="BENCH_HISTORY.jsonl")
    ap.add_argument(
        "--candidate", default=None,
        help="fresh lane record JSON to check against the full history "
        "(default: check the history's own newest points)",
    )
    ap.add_argument(
        "--self-test", action="store_true",
        help="synthesize a regressed candidate from the history and "
        "REQUIRE the sentinel to trip (exit 0 iff it does)",
    )
    args = ap.parse_args(argv)
    try:
        lines = load_history(args.history)
    except OSError as e:
        print(f"bench_regress: cannot read {args.history}: {e}")
        return 2
    if args.self_test:
        bad = synthesize_regressed(lines)
        n = run_check(lines, bad)
        if n == 0:
            print(
                "bench_regress: SELF-TEST FAILED — the synthetic "
                "regression did not trip the sentinel"
            )
            return 1
        print(
            f"bench_regress: self-test ok (synthetic regression tripped "
            f"{n} check(s))"
        )
        return 0
    candidate_records = None
    if args.candidate:
        with open(args.candidate) as f:
            candidate_records = _records_of_candidate(json.load(f))
        if not candidate_records:
            print(f"bench_regress: no records in {args.candidate}")
            return 2
    n = run_check(lines, candidate_records)
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main())
