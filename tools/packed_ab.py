#!/usr/bin/env python
"""On-chip A/B: packed-u32 vs current u8 streaming for a pointwise group.

tools/tpu_window.sh runs this automatically as the last step of a healthy
TPU window (output lands in packed_ab.out); run it manually only when the
watcher is not active — chip access must stay serialized:

    python tools/packed_ab.py [--hw 2160,3840]

Times three compiled variants of the reference pointwise prologue
(grayscale + contrast 3.5) on the same chip, same process, interleaved:

  a) production path: Pipeline.jit('pallas') on (H, W, 3) u8
  b) production path: Pipeline.jit('xla')
  c) packed path: pallas kernel on three (H, W/4) u32 planes
     (tools/packed_proto.py), bit-exactness asserted before timing

If (c) beats (a) by ~the lane factor, the u8 streaming cap is element-rate
and a packed rewrite of the production kernels is justified (BASELINE.md
round-2 roofline question); if they tie, the cap is byte-rate and the
current kernels already saturate it. Prints one JSON line per variant.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# fixed-configuration A/B: a committed autotune calibration must not steer
# either side (utils/calibration.py kill-switch)
os.environ.setdefault("MCIM_NO_CALIB", "1")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hw", default="2160,3840", help="H,W (W % 4 == 0)")
    args = ap.parse_args()
    H, W = (int(v) for v in args.hw.split(","))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput
    from tools.packed_proto import pack_u8, packed_gray_contrast, unpack_u32

    backend_name = jax.default_backend()
    print(f"backend: {backend_name}", flush=True)
    rgb = jnp.asarray(synthetic_image(H, W, channels=3, seed=31))
    pipe = Pipeline.parse("grayscale,contrast:3.5")
    golden = np.asarray(pipe(rgb))

    if backend_name == "cpu":
        # compiled Mosaic doesn't exist on CPU; check bit-exactness in
        # interpret mode and skip the (meaningless there) timing
        from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
            pipeline_pallas,
        )

        assert np.array_equal(
            np.asarray(pipeline_pallas(pipe.ops, rgb, interpret=True)), golden
        )
        planes = [pack_u8(rgb[..., c]) for c in range(3)]
        got = np.asarray(
            unpack_u32(
                packed_gray_contrast(*planes, interpret=True).astype(jnp.uint32)
            )
        )
        assert np.array_equal(got, golden)
        print("cpu validation ok (timing needs the chip)", flush=True)
        return 0

    def emit(name, sec, extra=None):
        rec = {
            "case": name,
            "ms": sec * 1e3,
            "mp_s": H * W / 1e6 / sec,
            # one u8 read per input plane + one u8 write (packed moves the
            # same bytes in 1/4 the elements)
            "gb_s": 4 * H * W / sec / 1e9,
        }
        rec.update(extra or {})
        print(json.dumps(rec), flush=True)

    # a/b: production backends, plus the demoted packed path via its
    # archived runner (tools/packed_kernels.pipeline_packed — the
    # round-5 A/B this tool ran adjudicated packed out of production)
    from functools import partial

    from tools.packed_kernels import pipeline_packed

    backends = [(b, pipe.jit(b)) for b in ("pallas", "xla")]
    backends.append(("packed", jax.jit(partial(pipeline_packed, pipe.ops))))
    for backend, fn in backends:
        got = np.asarray(fn(rgb))
        assert np.array_equal(got, golden), f"{backend} mismatch"
        # packed is no longer a production impl (demoted round 5); label
        # it archived_* so prod_* artifact parsing can't misclassify it
        label = "archived_packed" if backend == "packed" else f"prod_{backend}"
        emit(label, device_throughput(fn, [rgb]))

    # c: prototype packed path (pack once outside the timed region — the
    # zero-bitcast-cost bound for the packed production kernels). The
    # kernel is row-block-gridded since the whole-image form OOMed scoped
    # VMEM on a real v5e; a failure here is now a real signal, but it is
    # still only a bound, so it must not abort the decisive interleaved
    # 8K A/B below.
    try:
        planes = [pack_u8(rgb[..., c]) for c in range(3)]
        packed_fn = jax.jit(packed_gray_contrast)
        got = np.asarray(unpack_u32(packed_fn(*planes).astype(jnp.uint32)))
        assert np.array_equal(got, golden), "packed mismatch"
        emit("packed_u32", device_throughput(packed_fn, list(planes)))
    except Exception as e:  # noqa: BLE001 — recorded, not fatal
        print(
            json.dumps({"case": "packed_u32", "error": str(e)[:300]}),
            flush=True,
        )

    # d: the headline workload itself, production u8 vs production packed,
    # same process, interleaved twice (the tunnel's cross-process variance
    # is +-20-50%, so only same-process interleaved A/Bs are decisive)
    Hh, Wh = 4320, 7680
    gray8k = jnp.asarray(synthetic_image(Hh, Wh, channels=1, seed=7))
    gpipe = Pipeline.parse("gaussian:5")
    ggold = np.asarray(gpipe(gray8k))
    fns = {}
    for backend, fn in (
        ("pallas", gpipe.jit("pallas")),
        ("packed", jax.jit(partial(pipeline_packed, gpipe.ops))),
    ):
        got = np.asarray(fn(gray8k))
        assert np.array_equal(got, ggold), f"gaussian5 {backend} mismatch"
        fns[backend] = fn
    for rnd in (1, 2):
        for backend, fn in fns.items():
            sec = device_throughput(fn, [gray8k])
            print(
                json.dumps(
                    {
                        "case": f"g5_8k_{backend}_r{rnd}",
                        "ms": sec * 1e3,
                        "mp_s": Hh * Wh / 1e6 / sec,
                        "gb_s": 2 * Hh * Wh / sec / 1e9,
                    }
                ),
                flush=True,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
