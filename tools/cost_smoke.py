#!/usr/bin/env python
"""CI cost-observability smoke — the acceptance gate for ISSUE 15.

The tier1.yml cost step runs this on CPU and asserts the whole cost
layer end to end:

  1. **drift ≈ 1.0 for `--plan off`** — every per-op executable of the
     headline chain attributes with a measured-boundary/modelled ratio
     inside [MCIM_COST_DRIFT_MIN, MCIM_COST_DRIFT_MAX]: the planner's
     one-read-one-write byte model is structurally TRUE per op, checked
     against XLA's own memory_analysis, on CPU.
  2. **per-stage attribution for fused and fused-pallas** — each stage
     of the built plan attributes under the plan's fingerprint with an
     in-band ratio (the megakernel one-read-one-write claim, judged
     per stage; fused-pallas runs interpret-mode on CPU — structure,
     never timings).
  3. **a deliberately mis-modelled stage trips the drift alert** — the
     `cost.model` failpoint corrupts the model 4x and
     mcim_cost_drift_alerts_total must move.
  4. **`POST /control/profile` under live traffic** — a REAL router +
     replica pod serves offered load while the front door relays a
     rate-limited jax.profiler capture; the merged host+device trace
     must parse, contain both host spans and profiler events, and the
     artifact is copied to argv[1] for CI upload. A second immediate
     capture must be 429-rate-limited.
  5. **an injected-error request's trace survives a sampled-out root**
     — with MCIM_TRACE_SAMPLE tiny and the tail buffer armed, a
     quarantined request's trace id resolves in the export while a
     plain ok request's does not.
  6. the mcim_cost_* / mcim_devmem_* families parse as exposition text
     through the replica's /metrics and the router's federated view.

Usage: python tools/cost_smoke.py [MERGED_TRACE_OUT.json] [METRICS_OUT.prom]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_imagemanipulation_tpu.utils.platform import claim_platform  # noqa: E402

claim_platform(os.environ.get("JAX_PLATFORMS") or "cpu")

os.environ.setdefault("MCIM_PROFILE_DIR", "/tmp/_cost_smoke_profile")
os.environ.setdefault("MCIM_RECORDER_DIR", "/tmp/_cost_smoke_recorder")

import numpy as np  # noqa: E402

from mpi_cuda_imagemanipulation_tpu.fabric.replica import ReplicaRuntime  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.fabric.router import Router, RouterConfig  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.io.image import (  # noqa: E402
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.plan import build_plan  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.serve.server import ServeConfig  # noqa: E402

OPS = "grayscale,contrast:3.5,gaussian:5,quantize:6"
H, W, C = 192, 256, 3


def check_per_op_drift() -> None:
    """Gate 1: --plan off per-op dispatch, drift within the band."""
    import jax

    lo, hi = obs_cost.drift_band()
    ops = make_pipeline_ops(OPS)
    cur = np.asarray(synthetic_image(H, W, channels=C, seed=3))
    for op in ops:
        fn = jax.jit(lambda x, o=op: o(x))
        out = np.asarray(fn(cur))
        modeled = float(cur.size + out.size)
        wrapped, cost = obs_cost.attribute_jit(
            "bench", f"off:{op.name}", fn, (cur,), modeled_bytes=modeled
        )
        assert cost is not None, f"no cost extracted for {op.name}"
        ratio = obs_cost.cost_ledger.drift("bench", f"off:{op.name}")
        assert ratio is not None and lo <= ratio <= hi, (
            f"per-op drift for {op.name}: {ratio} outside [{lo}, {hi}]"
        )
        assert np.array_equal(np.asarray(wrapped(cur)), out), op.name
        cur = out
    print(f"gate 1: per-op dispatch drift in [{lo}, {hi}] for {OPS}")


def check_stage_drift() -> None:
    """Gate 2: fused + fused-pallas per-stage drift, keyed by
    fingerprint. The mixed chain builds MULTIPLE stages (two fused
    regions around a geometric barrier), so "per stage" is exercised
    across stage kinds, not just on a single-stage chain."""
    lo, hi = obs_cost.drift_band()
    ops = make_pipeline_ops(OPS + ",rot180,sharpen")
    for mode, pallas in (("fused", False), ("fused-pallas", True)):
        plan = build_plan(ops, mode)
        assert len(plan.stages) >= 3, plan.describe()
        rows = obs_cost.attribute_plan(
            plan, (H, W, C), pallas=pallas, interpret=True if pallas else None
        )
        assert len(rows) == len(plan.stages)
        for row in rows:
            r = row["drift_ratio"]
            assert r is not None and lo <= r <= hi, (
                f"{mode} stage {row['stage']} ({row['names']}): drift "
                f"{r} outside [{lo}, {hi}]"
            )
            # the ledger keys megakernel/fused stage cost by fingerprint
            assert (
                obs_cost.cost_ledger.drift("plan", plan.fingerprint,
                                           row["stage"]) == r
            )
        print(
            f"gate 2: {mode} per-stage drift in band "
            f"({[r['stage'] for r in rows]}, key {plan.fingerprint})"
        )


def check_mis_model_alert() -> None:
    """Gate 3: the cost.model failpoint trips the drift alert."""
    import jax

    before = obs_cost.cost_ledger.drift_alerts.value(site="bench")
    failpoints.configure("cost.model=always")
    try:
        img = np.zeros((64, 64), np.uint8)
        fn = jax.jit(lambda x: (x.astype(np.float32) * 2).astype(np.uint8))
        obs_cost.attribute_jit(
            "bench", "mismodel", fn, (img,),
            modeled_bytes=float(2 * img.size),
        )
    finally:
        failpoints.clear()
    after = obs_cost.cost_ledger.drift_alerts.value(site="bench")
    assert after == before + 1, (
        f"mis-modelled stage did not trip the alert ({before} -> {after})"
    )
    ratio = obs_cost.cost_ledger.drift("bench", "mismodel")
    lo, hi = obs_cost.drift_band()
    assert ratio is not None and not lo <= ratio <= hi, ratio
    print(f"gate 3: deliberate mis-model tripped the alert (ratio {ratio})")


def post(url: str, payload: dict | bytes, timeout: float = 60.0):
    data = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def main() -> int:
    trace_out = sys.argv[1] if len(sys.argv) > 1 else "/tmp/_cost_profile.json"
    metrics_out = sys.argv[2] if len(sys.argv) > 2 else "/tmp/_cost_metrics.prom"

    check_per_op_drift()
    check_stage_drift()
    check_mis_model_alert()

    # gates 4-6 need a live pod: router + one in-process replica, with
    # sampled-out roots and the tail buffer armed
    obs_trace.configure(sample=1e-6, tail=128)
    router = Router(RouterConfig(buckets=((64, 64),), stale_s=5.0)).start()
    cfg = ServeConfig(
        ops="grayscale,contrast:3.5,emboss:3",
        buckets=((64, 64),), channels=(3,),
        max_batch=2, max_delay_ms=2.0,
    )
    rt = ReplicaRuntime("r0", router.url, cfg, heartbeat_s=0.2).start()
    png = encode_image_bytes(
        np.asarray(synthetic_image(60, 60, channels=3, seed=7))
    )
    try:
        deadline = time.time() + 20
        while not router._routable() and time.time() < deadline:
            time.sleep(0.05)
        assert router._routable(), "replica never registered"

        # benign traffic (sampled out, dropped by the tail buffer)
        ok_tid = ""
        for _ in range(4):
            code, _body, hdrs = post(f"{router.url}/v1/process", png)
            assert code == 200, code
            ok_tid = hdrs.get("X-Trace-Id", ok_tid)

        # gate 5: injected-error request under a sampled-out root
        failpoints.configure("serve.dispatch=always")
        try:
            code, _body, hdrs = post(f"{router.url}/v1/process", png)
        finally:
            failpoints.clear()
        assert code == 422, f"expected quarantine, got {code}"
        err_tid = hdrs.get("X-Trace-Id", "")
        assert err_tid, "quarantined request carried no trace id"

        # gate 4: profile capture under live offered traffic
        stop = threading.Event()

        def offered():
            while not stop.is_set():
                post(f"{router.url}/v1/process", png)
                time.sleep(0.02)

        t = threading.Thread(target=offered, daemon=True)
        t.start()
        try:
            code, body, _h = post(
                f"{router.url}/control/profile", {"seconds": 1.0}
            )
        finally:
            stop.set()
            t.join()
        assert code == 200, f"profile capture answered {code}: {body[:200]}"
        prof = json.loads(body)
        assert prof["replica"] == "r0" and prof["status"] == "ok", prof
        merged = json.load(open(prof["artifact"]))
        events = merged["traceEvents"]
        assert prof["host_events"] > 0, "no host spans in the capture"
        assert prof["device_events"] > 0, "no profiler events in the capture"
        assert any(e.get("ph") == "X" for e in events), "no duration events"
        shutil.copyfile(prof["artifact"], trace_out)
        print(
            f"gate 4: /control/profile -> {len(events)} merged events "
            f"(host {prof['host_events']} + device {prof['device_events']}) "
            f"-> {trace_out}"
        )
        # the second immediate capture must be rate-limited
        code2, body2, hdrs2 = post(
            f"{router.url}/control/profile", {"seconds": 0.5}
        )
        assert code2 == 429, f"second capture not rate-limited: {code2}"
        assert hdrs2.get("Retry-After"), "rate-limited capture lost Retry-After"
        print("gate 4b: immediate second capture rate-limited (429)")

        # a profile_capture recorder dump exists
        rec_dir = os.environ["MCIM_RECORDER_DIR"]
        dumps = [
            f for f in os.listdir(rec_dir) if "profile_capture" in f
        ] if os.path.isdir(rec_dir) else []
        assert dumps, "no profile_capture recorder dump"

        # gate 5 (cont.): the error trace resolves, the ok trace does not
        obs_trace.export("/tmp/_cost_tail_trace.json")
        evs = json.load(open("/tmp/_cost_tail_trace.json"))["traceEvents"]
        tids = {e.get("args", {}).get("trace_id") for e in evs}
        assert err_tid in tids, (
            f"error trace {err_tid} missing from export despite tail keep"
        )
        assert obs_trace.trace_kept(err_tid)
        assert ok_tid not in tids and not obs_trace.trace_kept(ok_tid), (
            "benign sampled-out trace was kept — tail keep is not selective"
        )
        print(
            f"gate 5: error trace {err_tid} exported under a sampled-out "
            f"root; benign trace dropped "
            f"(tail {obs_trace.get_tracer().counts()['tail']})"
        )

        # gate 6: the families parse through both doors
        with urllib.request.urlopen(
            f"http://127.0.0.1:{rt.server.address[1]}/metrics", timeout=10
        ) as resp:
            replica_text = resp.read().decode()
        fams = parse_exposition(replica_text)
        for fam in (
            "mcim_cost_executables_total",
            "mcim_cost_model_drift_ratio",
            "mcim_cost_drift_alerts_total",
            "mcim_devmem_devices",
        ):
            assert fam in fams, f"{fam} missing from replica /metrics"
        drift_samples = {
            ls: v
            for (name, ls), v in fams["mcim_cost_model_drift_ratio"][
                "samples"
            ].items()
            if 'site="serve"' in ls
        }
        assert drift_samples, "no serve-site drift samples in exposition"
        with urllib.request.urlopen(
            f"{router.url}/metrics", timeout=10
        ) as resp:
            fed_text = resp.read().decode()
        fed = parse_exposition(fed_text)
        assert "mcim_fabric_profile_captures_total" in fed
        assert "mcim_cost_model_drift_ratio" in fed, (
            "cost families not federated to the router"
        )
        with open(metrics_out, "w") as f:
            f.write(fed_text)
        print(
            f"gate 6: cost/devmem families parse on replica + federated "
            f"router exposition -> {metrics_out}"
        )
    finally:
        rt.close(drain=False, deadline_s=5.0)
        router.close()
    print("cost smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
