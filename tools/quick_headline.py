#!/usr/bin/env python
"""First-minutes TPU capture: ONE headline record, committed fast.

The round-2 postmortem (VERDICT.md weak #1): capture ran as an end-of-round
batch job and a 4-hour tunnel wedge erased the round's TPU scoreboard. This
is the antidote — the cheapest measurement that makes the round's artifact
of record a hardware number: run the headline config (BASELINE.json: 8K 5x5
Gaussian, Pallas) once, in-process, and append a bench.py-shaped entry to
BENCH_HISTORY.jsonl. tools/tpu_window.sh runs it as the FIRST step of the
first healthy window and commits the history line immediately, so even a
window too short for the full campaign leaves a same-round TPU headline
that bench.py's fallback path can promote (see bench.py:_same_round_tpu).

Refuses to write history off-TPU: a CPU number here would poison the
same-round lookup.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import jax

    from mpi_cuda_imagemanipulation_tpu.bench_suite import (
        CONFIGS,
        HEADLINE,
        headline_record,
        run_config,
    )

    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)
    if backend not in ("tpu", "axon"):
        print("not a TPU backend; refusing to record", file=sys.stderr)
        return 3

    # pallas first (the committed baseline impl — worth having even if the
    # window dies mid-step), then the packed-u32 candidate. Each impl's
    # record is appended to BENCH_HISTORY.jsonl IMMEDIATELY after its
    # measurement (and the queue step commits whatever landed even when a
    # later impl wedges), so a window only long enough for one compile
    # still leaves a committed same-round TPU headline.
    records = []
    for impl in ("pallas", "packed"):
        try:
            rec = run_config(CONFIGS[HEADLINE], impl)
        except Exception as e:  # one impl crashing must not lose the other
            print(f"{impl} failed: {e}", file=sys.stderr)
            continue
        records.append(rec)
        print(json.dumps(rec), flush=True)
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "headline": headline_record(records),
            "records": list(records),
            "note": f"quick_headline (first-window fast capture, {impl})",
        }
        if not os.environ.get("MCIM_NO_HISTORY"):
            with open(os.path.join(REPO, "BENCH_HISTORY.jsonl"), "a") as f:
                f.write(json.dumps(entry) + "\n")
    if not records:
        return 4
    print(json.dumps(headline_record(records)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
