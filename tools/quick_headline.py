#!/usr/bin/env python
"""First-minutes TPU capture: ONE headline record, committed fast.

The round-2 postmortem (VERDICT.md weak #1): capture ran as an end-of-round
batch job and a 4-hour tunnel wedge erased the round's TPU scoreboard. This
is the antidote — the cheapest measurement that makes the round's artifact
of record a hardware number: run the headline config (BASELINE.json: 8K 5x5
Gaussian, Pallas) once, in-process, and append a bench.py-shaped entry to
BENCH_HISTORY.jsonl. tools/tpu_window.sh runs it as the FIRST step of the
first healthy window and commits the history line immediately, so even a
window too short for the full campaign leaves a same-round TPU headline
that bench.py's fallback path can promote (see bench.py:_same_round_tpu).

Refuses to write history off-TPU: a CPU number here would poison the
same-round lookup.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--config",
        default=None,
        help="bench config to capture (default: the headline); e.g. "
        "gaussian5_8k_sharded for the fused-ghost shard_map record "
        "(VERDICT r2 directive #2)",
    )
    ap.add_argument(
        "--impls",
        default="pallas",
        help="comma-separated impls, measured in order (first = the one "
        "worth having if the window dies mid-step)",
    )
    args = ap.parse_args()

    import jax

    from mpi_cuda_imagemanipulation_tpu.bench_suite import (
        CONFIGS,
        HEADLINE,
        headline_record,
        run_config,
    )

    cfg_name = args.config or HEADLINE
    if cfg_name not in CONFIGS:
        print(f"unknown config {cfg_name!r}", file=sys.stderr)
        return 2

    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)
    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

    if not is_tpu_backend():
        print("not a TPU backend; refusing to record", file=sys.stderr)
        return 3

    # Each impl's record is appended to BENCH_HISTORY.jsonl IMMEDIATELY
    # after its measurement (and the queue step commits whatever landed even
    # when a later impl wedges), so a window only long enough for one
    # compile still leaves a committed same-round TPU record.
    impls = [s.strip() for s in args.impls.split(",") if s.strip()]
    bad = [s for s in impls if s not in ("xla", "pallas", "swar", "auto")]
    if bad or not impls:
        print(f"unknown impls {bad or args.impls!r}", file=sys.stderr)
        return 2

    records = []
    for impl in impls:
        try:
            rec = run_config(CONFIGS[cfg_name], impl)
        except Exception as e:  # one impl crashing must not lose the other
            print(f"{impl} failed: {e}", file=sys.stderr)
            continue
        records.append(rec)
        print(json.dumps(rec), flush=True)
        # headline_record qualifies the headline config AND its _sharded
        # variant (on a pod the sharded run is the relevant headline); for
        # any other config it is None and the entry carries records only.
        # A sharded capture's headline competes in bench.py's same-round
        # promotion best-by-value, so a slower sharded record never
        # displaces a faster same-round unsharded one.
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "records": list(records),
            "note": f"quick capture ({cfg_name}, {impl})",
        }
        # commit identity for promotion provenance (advisor r3 finding);
        # bench.py is jax-free so the import is safe here
        from bench import git_head_sha

        sha = git_head_sha()
        if sha:
            entry["git_sha"] = sha
        head = headline_record(records)
        if head is not None:
            entry["headline"] = head
        if not os.environ.get("MCIM_NO_HISTORY"):
            with open(os.path.join(REPO, "BENCH_HISTORY.jsonl"), "a") as f:
                f.write(json.dumps(entry) + "\n")
    if not records:
        return 4
    final = headline_record(records)
    print(json.dumps(final if final is not None else records[-1]), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
