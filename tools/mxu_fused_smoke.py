#!/usr/bin/env python
"""CI in-stage-MXU smoke (tier1.yml): the fused-mxu arms end to end, in
interpret mode on CPU.

One process proves:

  1. **bit-exactness** — `plan=fused-pallas-mxu` and every forced
     `MCIM_MXU_STAGE` setting (f32, int8, on) reproduce the per-op
     golden chain (`--plan off`) on odd shapes, through chains that
     exercise the eligible family (separable + dense stencils), the
     in-stage `family` fallback (morphology) and an ineligible member
     (median);
  2. **structure** — the lowered HLO of the mxu lowering contains a
     `dot_general` contraction and the VPU control does NOT (the MXU is
     structurally engaged inside the `pallas_call`, not inferred from
     timing);
  3. **fallback accounting** — forced-off and family rejections land on
     `mcim_plan_mxu_in_stage_fallback_total` with closed-vocabulary
     reasons, chosen arms on `mcim_plan_mxu_in_stage_total`, and both
     families render as parseable exposition;
  4. **the control loop** — a real TuneController + CanaryGate
     propose/promote `plan:fused-pallas-mxu` end to end with REAL
     shadow digests: every canary-lane output is the actual
     fused-pallas-mxu pipeline result, digest-compared against the
     stable `--plan off` output. Zero mismatches is the gate's promote
     condition, so the promotion itself certifies the new arm's
     bit-exactness. (Dispatch timings fed to the store are synthetic —
     interpret-mode wall time is meaningless off-chip, the repo-wide
     rule — the gate's digests are not.) The promotion must be durable:
     `promoted_entry` resolves to `fused-pallas-mxu`.
  5. **the lane** — the mxu_fused_ab bench lane runs (its pre-timing
     bit-exactness gate over three odd shapes must pass) and its record
     lands at argv[1]. Interpret-mode timings are never asserted; the
     committed BENCH_HISTORY record is the gate anchor, the TPU window
     script (tools/tpu_queue/36_mxu_fused_r08.sh) carries the perf
     claim.

Usage: python tools/mxu_fused_smoke.py /tmp/mxu_fused_ab.json
"""

import hashlib
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

H, W = 97, 131
OPS = "gaussian:5,sharpen,box:5"


class _Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def main() -> int:
    import jax
    import jax.numpy as jnp

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import (
        Registry,
        parse_exposition,
    )
    from mpi_cuda_imagemanipulation_tpu.ops import mxu_kernels
    from mpi_cuda_imagemanipulation_tpu.plan import build_plan, plan_metrics
    from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
        plan_callable_pallas,
    )

    # -- 1. bit-exactness: fused-pallas-mxu + every forced setting ----------
    chains = (
        OPS,                                   # all members eligible
        "grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6",
        "gaussian:5,erode:3,sharpen",          # morphology: family fallback
        "median:3,gaussian:3",                 # median: never a candidate
    )
    for spec in chains:
        pipe = Pipeline.parse(spec)
        ch = 3 if spec.startswith("grayscale") else 1
        img = jnp.asarray(synthetic_image(H, W, channels=ch, seed=31))
        golden = np.asarray(pipe.apply(img))
        got = np.asarray(pipe.jit(plan="fused-pallas-mxu")(img))
        assert np.array_equal(got, golden), f"fused-pallas-mxu: {spec}"
        plan = build_plan(pipe.ops, "fused-pallas")
        for setting in ("f32", "int8", "on"):
            got = np.asarray(
                plan_callable_pallas(plan, mxu_stage=setting)(img)
            )
            assert np.array_equal(got, golden), f"{setting}: {spec}"
    print(f"bit-exact: {len(chains)} chains x (plan arm + f32/int8/on)")

    # -- 2. structure: dot_general inside the lowered megakernel ------------
    pipe = Pipeline.parse(OPS)
    img = jnp.asarray(synthetic_image(H, W, channels=1, seed=32))
    plan = build_plan(pipe.ops, "fused-pallas")
    mxu_txt = (
        jax.jit(plan_callable_pallas(plan, mxu_stage="on"))
        .lower(img).as_text()
    )
    vpu_txt = (
        jax.jit(plan_callable_pallas(plan, mxu_stage="off"))
        .lower(img).as_text()
    )
    assert "dot_general" in mxu_txt, "no contraction in the mxu lowering"
    assert "dot_general" not in vpu_txt, "VPU control contains a contraction"
    print("structure: dot_general in the mxu lowering, absent from the VPU")

    # -- 3. fallback accounting + exposition --------------------------------
    before_off = int(
        plan_metrics.mxu_stage_fallbacks.value(reason="off")
    )
    before_fam = int(
        plan_metrics.mxu_stage_fallbacks.value(reason="family")
    )
    for op in Pipeline.parse(OPS).ops:
        mxu_kernels.stage_arm_for(op, W, setting="off")
    mxu_kernels.stage_arm_for(
        Pipeline.parse("erode:3").ops[0], W, setting="on"
    )
    assert (
        int(plan_metrics.mxu_stage_fallbacks.value(reason="off"))
        == before_off + 3
    )
    assert (
        int(plan_metrics.mxu_stage_fallbacks.value(reason="family"))
        == before_fam + 1
    )
    fams = parse_exposition(plan_metrics.registry.render())
    for fam in (
        "mcim_plan_mxu_in_stage_total",
        "mcim_plan_mxu_in_stage_fallback_total",
    ):
        assert fam in fams, f"missing metric family {fam}"
    print(f"fallbacks: off/family counted; {len(fams)} families parse")

    # -- 4. the control loop promotes the arm on real shadow digests --------
    from mpi_cuda_imagemanipulation_tpu.fabric.canary import (
        PROMOTED,
        CanaryConfig,
        CanaryGate,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.plan.ir import pipeline_fingerprint
    from mpi_cuda_imagemanipulation_tpu.tune import store as tune_store
    from mpi_cuda_imagemanipulation_tpu.tune.controller import (
        TuneConfig,
        TuneController,
    )

    tmp = tempfile.mkdtemp(prefix="mxu_fused_smoke_")
    os.environ["MCIM_CALIB_FILE"] = os.path.join(tmp, "calib.json")
    os.environ.pop("MCIM_NO_CALIB", None)
    clock = _Clock()
    store = tune_store.OnlineStore(clock=clock)
    pipe = Pipeline.parse(OPS)
    pipe_fp = pipeline_fingerprint(make_pipeline_ops(OPS))
    imgs = [
        jnp.asarray(synthetic_image(H + 2 * i, W + 3 * i, channels=1,
                                    seed=50 + i))
        for i in range(4)
    ]
    stable = pipe.jit(plan="off")
    candidate = pipe.jit(plan="fused-pallas-mxu")

    gate = CanaryGate(
        CanaryConfig(frac=0.5, min_requests=2, shadow_every=1,
                     bad_frac=0.5, burn_ratio=2.0, promote_requests=4),
        clock=clock,
    )
    deployed: list = []
    promoted: list = []

    def deploy(flip):
        deployed.append(flip)
        gate.start("r1", flip)

    ctl = TuneController(
        gate=gate,
        deploy=deploy,
        pipe_fp=pipe_fp,
        current_arm="plan:off",
        arms=("plan:off", "plan:fused-pallas-mxu"),
        registry=Registry(),
        on_promote=promoted.append,
        on_revert=lambda flip: (_ for _ in ()).throw(
            AssertionError(f"unexpected revert: {flip}")
        ),
        store=store,
        config=TuneConfig(tick_s=0.01, min_samples=3, explore_c=0.0,
                          min_gain=1.05, flip_timeout_s=600),
        clock=clock,
    )
    # incumbent measured -> the unmeasured candidate is proposed
    for v in (0.015, 0.015, 0.016, 0.015):
        store.record_dispatch(pipe_fp, W, "plan:off", v)
    assert ctl.tick() == "propose", ctl.status()
    assert deployed[0] == {"argv": ["--plan", "fused-pallas-mxu"]}
    # the canary serves: every lane output is the REAL fused-pallas-mxu
    # result, shadow-digested against the REAL stable output
    for im in imgs:
        got = np.asarray(candidate(im))
        want = np.asarray(stable(im))
        match = (
            hashlib.sha256(got.tobytes()).hexdigest()
            == hashlib.sha256(want.tobytes()).hexdigest()
        )
        gate.record("canary", True)
        gate.record_shadow(match)
    assert gate.shadow_mismatch == 0, "fused-pallas-mxu diverged in shadow"
    assert gate.state == PROMOTED, gate.status()
    # promote arithmetic: the candidate must be measured faster. The
    # timings are synthetic (interpret wall time proves nothing); the
    # digests above are the real acceptance.
    for v in (0.010, 0.010, 0.011, 0.010):
        store.record_dispatch(pipe_fp, W, "plan:fused-pallas-mxu", v)
    assert ctl.tick() == "promote", ctl.status()
    assert promoted == [{"argv": ["--plan", "fused-pallas-mxu"]}]
    assert ctl.current_arm == "plan:fused-pallas-mxu"
    ent = store.promoted_entry(pipe_fp)
    assert ent is not None and ent["choice"] == "fused-pallas-mxu", ent
    print(
        f"control loop: plan:fused-pallas-mxu proposed + promoted, "
        f"{gate.shadow_match} real shadow digests matched, 0 mismatches, "
        f"promotion durable in the store"
    )

    # -- 5. the mxu_fused_ab lane (record -> CI artifact) -------------------
    out = sys.argv[1] if len(sys.argv) > 1 else None
    os.environ.setdefault("MCIM_MXU_FUSED_AB_HEIGHT", "96")
    os.environ.setdefault("MCIM_MXU_FUSED_AB_WIDTH", "160")
    from mpi_cuda_imagemanipulation_tpu.bench_suite import run_mxu_fused_ab

    rec = run_mxu_fused_ab(json_path=out, printer=lambda s: None)
    assert rec["bit_exact_gate"].startswith("passed"), rec["bit_exact_gate"]
    assert rec["interpret_mode"] is True
    print(
        f"mxu_fused_ab: gate passed, best arm {rec['best_mxu_lane']}, "
        f"stage arms {rec['stage_arms']} (interpret mode — gate record "
        "only)" + (f" -> {out}" if out else "")
    )
    print("mxu-fused smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
