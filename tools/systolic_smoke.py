#!/usr/bin/env python
"""Pod-level systolic CI smoke: a REAL pod (router + 2 replica
processes) running one DAG pipeline stage-sharded across both replicas.

    python tools/systolic_smoke.py METRICS_OUT

Asserts, end to end over real HTTP:

  1. an 8-stage chain registered at the front door gets PLACED across
     both replicas (the router's /stats placement map names two
     contiguous step ranges with two distinct owners);
  2. the systolic response is bit-exact against the in-process golden
     executor — the u8 exact-integer carry survives the cross-replica
     handoff;
  3. exactly ONE transport forward per stage boundary: after N systolic
     requests the federated mcim_systolic_tiles_forwarded_total reads
     N * (ranges - 1), not one more, not one less;
  4. SIGKILL of a stage-owning replica mid-load degrades to the PINNED
     lane: every accepted request stays BYTE-IDENTICAL to the systolic
     response (never a wrong answer), the fallback is counted under a
     closed-vocabulary reason, and the router files a
     `systolic_fallback` flight-recorder dump;
  5. the router /metrics exposition parses with every mcim_systolic_*
     family present (router-side + federated replica-side).

METRICS_OUT gets the router exposition text (uploaded as a CI artifact,
.github/workflows/tier1.yml systolic step). MCIM_SYSTOLIC_AB_JSON, when
set, gets a one-line JSON summary of the counts the asserts consumed.
"""

import glob
import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the router process files the systolic_fallback post-mortem; pin the
# recorder dir so the smoke can assert the artifact landed
_REC_DIR = os.environ.setdefault(
    "MCIM_RECORDER_DIR", tempfile.mkdtemp(prefix="systolic_smoke_rec_")
)

import numpy as np  # noqa: E402

from mpi_cuda_imagemanipulation_tpu.fabric.router import (  # noqa: E402
    RouterConfig,
)
from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (  # noqa: E402
    Fabric,
    FabricConfig,
)
from mpi_cuda_imagemanipulation_tpu.graph import (  # noqa: E402
    compile_graph,
    graph_callable,
    parse_spec,
)
from mpi_cuda_imagemanipulation_tpu.graph.spec import (  # noqa: E402
    chain_as_spec,
)
from mpi_cuda_imagemanipulation_tpu.graph.systolic import (  # noqa: E402
    ENV_AB_JSON,
    FALLBACK_REASONS,
)
from mpi_cuda_imagemanipulation_tpu.io.image import (  # noqa: E402
    decode_image_bytes,
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.obs.metrics import (  # noqa: E402
    parse_exposition,
)
from mpi_cuda_imagemanipulation_tpu.serve.bucketing import (  # noqa: E402
    parse_buckets,
)
from mpi_cuda_imagemanipulation_tpu.utils import (  # noqa: E402
    env as env_registry,
)

# 8 per-op stages (>= the 6 the acceptance floor asks for); every op is
# streamable and channel-preserving, so the chain is systolic-eligible
CHAIN = "invert,gaussian:3,sharpen,box:3,quantize:6,gaussian:5,posterize:4,median"
BUCKETS = "48,96"
N_WARM = 4  # systolic requests before the kill


def _post(url: str, path: str, data: bytes, headers=None):
    req = urllib.request.Request(
        url + path, data=data, headers=headers or {}, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post_retry(url, path, data, headers=None, deadline_s=30.0):
    t_end = time.monotonic() + deadline_s
    while True:
        code, hdrs, body = _post(url, path, data, headers)
        if code != 503 or not hdrs.get("Retry-After"):
            return code, hdrs, body
        assert time.monotonic() < t_end, "pod never converged past sheds"
        time.sleep(0.2)


def _counter(fams, name, label=None):
    fam = fams.get(name)
    if not fam:
        return 0.0
    return sum(
        v for (_n, labels), v in fam["samples"].items()
        if label is None or label in labels
    )


def main(metrics_out: str) -> int:
    cfg = FabricConfig(
        replicas=2,
        ops="grayscale,contrast:3.5,emboss:3",
        buckets=BUCKETS,
        channels="3",
        max_batch=4,
        queue_depth=64,
        heartbeat_s=0.2,
        systolic=True,
        router=RouterConfig(
            buckets=parse_buckets(BUCKETS), stale_s=2.0,
            forward_attempts=3, systolic=True,
        ),
    )
    img = synthetic_image(44, 40, channels=3, seed=61)
    blob = encode_image_bytes(img)
    spec = chain_as_spec(CHAIN)
    golden = np.asarray(
        graph_callable(compile_graph(parse_spec(spec)))(img)["image"]
    )

    with Fabric(cfg).start() as fab:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            views = fab.router._routable()
            if len(views) == 2 and all(v.hb.systolic for v in views):
                break
            time.sleep(0.1)
        views = fab.router._routable()
        assert len(views) == 2 and all(v.hb.systolic for v in views), (
            "replicas never advertised systolic stage ownership"
        )

        code, _h, out = _post(
            fab.url, "/v1/pipelines",
            json.dumps({"tenant": "acme", "spec": spec}).encode(),
        )
        assert code == 200, (code, out[:300])
        pid = json.loads(out)["pipeline"]

        # -- 1+2. systolic dispatch: placed across BOTH replicas, golden --
        q = f"/v1/process?tenant=acme&pipeline={pid}"
        code, _h, sys_body = _post_retry(fab.url, q, blob)
        assert code == 200, (code, sys_body[:300])
        np.testing.assert_array_equal(decode_image_bytes(sys_body), golden)
        st = fab.http_stats()["systolic"]
        assert st["enabled"], st
        pl = st["placements"][pid]
        ranges = [tuple(r) for r in pl["ranges"]]
        owners = list(pl["owners"])
        assert len(ranges) == 2 and len(set(owners)) == 2, pl
        assert ranges[0][0] == 0 and ranges[0][1] == ranges[1][0], ranges
        assert ranges[1][1] == len(CHAIN.split(",")), ranges
        print(
            f"smoke: 8-stage chain placed {ranges} on {owners} "
            f"(weights {pl['weights']}, {pl['source']}) — response "
            "bit-exact vs the in-process golden"
        )

        for _ in range(N_WARM - 1):
            code, _h, body = _post_retry(fab.url, q, blob)
            assert code == 200 and body == sys_body

        # -- 3. one transport forward per stage boundary ------------------
        boundaries = len(ranges) - 1
        deadline = time.monotonic() + 30.0
        while True:
            fams = parse_exposition(fab.scrape())
            forwards = _counter(fams, "mcim_systolic_tiles_forwarded_total")
            if forwards >= N_WARM * boundaries:
                break
            assert time.monotonic() < deadline, (
                f"federated forward count stuck at {forwards}"
            )
            time.sleep(0.2)
        assert forwards == N_WARM * boundaries, (
            f"{forwards} forwards for {N_WARM} requests x {boundaries} "
            "boundaries — the one-forward-per-boundary contract broke"
        )
        xbytes = _counter(fams, "mcim_systolic_exchange_bytes_total")
        assert xbytes > 0
        placed = _counter(fams, "mcim_systolic_stages_placed_total")
        assert placed == N_WARM * len(ranges), (placed, N_WARM, ranges)
        print(
            f"smoke: exactly one exchange per stage boundary — "
            f"{forwards:.0f} forwards / {N_WARM} requests, "
            f"{xbytes:.0f} exchange bytes"
        )

        # -- 4. SIGKILL a stage owner mid-load: pinned, never wrong -------
        victim = owners[0]
        fab.kill_replica(victim)
        accepted = 0
        for _ in range(12):
            code, _h, body = _post(fab.url, q, blob)
            if code == 200:
                accepted += 1
                assert body == sys_body, (
                    "a fallback response differed from the systolic "
                    "bytes — WRONG ANSWER"
                )
            time.sleep(0.1)
        assert accepted > 0, "pod never accepted after the owner kill"
        fams = parse_exposition(fab.scrape())
        fallbacks = {
            labels: v
            for (_n, labels), v in fams.get(
                "mcim_systolic_fallbacks_total", {"samples": {}}
            )["samples"].items()
        }
        n_fallbacks = sum(fallbacks.values())
        assert n_fallbacks > 0, "owner death was never counted as fallback"
        for labels in fallbacks:
            reason = labels.split('"')[1]
            assert reason in FALLBACK_REASONS, (labels, FALLBACK_REASONS)
        dumps = glob.glob(
            os.path.join(_REC_DIR, "recorder_systolic_fallback_*.json")
        )
        assert dumps, f"no systolic_fallback recorder dump in {_REC_DIR}"
        with open(dumps[0]) as f:
            assert json.load(f)["trigger"] == "systolic_fallback"
        print(
            f"smoke: killed stage owner {victim} mid-load — "
            f"{accepted}/12 accepted, ALL byte-identical to the systolic "
            f"response; fallbacks counted {fallbacks}; post-mortem "
            f"{os.path.basename(dumps[0])}"
        )

        # -- 5. exposition parses with every systolic family --------------
        exposition = fab.scrape()
        fams = parse_exposition(exposition)
        for fam in (
            "mcim_systolic_requests_total",
            "mcim_systolic_stages_placed_total",
            "mcim_systolic_fallbacks_total",
            "mcim_systolic_tiles_forwarded_total",
            "mcim_systolic_exchange_bytes_total",
        ):
            assert fam in fams, f"{fam} missing from /metrics"
        with open(metrics_out, "w") as f:
            f.write(exposition)
        print(f"smoke: /metrics parses federated -> {metrics_out}")

        summary_path = env_registry.get(ENV_AB_JSON)
        if summary_path:
            with open(summary_path, "w") as f:
                json.dump({
                    "lane": "systolic_smoke",
                    "placement": {"ranges": ranges, "owners": owners},
                    "requests_warm": N_WARM,
                    "forwards": forwards,
                    "exchange_bytes": xbytes,
                    "accepted_after_kill": accepted,
                    "fallbacks": {
                        k.split('"')[1]: v for k, v in fallbacks.items()
                    },
                }, f, indent=2)
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
