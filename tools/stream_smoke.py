#!/usr/bin/env python
"""CI stream smoke (tier1.yml): the constant-memory acceptance, end to end.

A synthetic 8192-row image runs through the streaming tile engine in
32-row bands (windowed synthetic decode -> seam-stitched tiles ->
double-buffered dispatch -> incremental PNG encode) with tracing armed,
and the run must prove, in one process:

  1. **bit-exactness** — the streamed PNG decodes identical to the
     whole-image golden pipeline output (a >= 3-op chain whose
     accumulated halo crosses every seam);
  2. **constant memory** — measured peak resident bytes at least 20x
     smaller than the frame, AND flat: a 4x shorter image must report
     the same peak (within tolerance), because the bound is a function
     of (tile_rows, inflight, halo) only;
  3. **observability** — the metrics registry renders as parseable
     Prometheus exposition with the mcim_stream_* families populated
     (incl. the peak gauge), and the exported trace holds the
     stream.prefetch / stream.stitch / stream.tile / stream.write span
     chain with every span carrying the run's trace id.

The trace JSON lands at argv[1] (uploaded as a CI artifact); the
metrics snapshot at argv[2] when given.

Usage: python tools/stream_smoke.py /tmp/stream_trace.json [/tmp/stream.prom]
"""

import io
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

OPS = "grayscale,contrast:3.5,emboss:3"
HEIGHT = 8192
SHORT_HEIGHT = 2048
WIDTH = 256
CHANNELS = 3
TILE_ROWS = 32
MIN_MEMORY_RATIO = 20.0
FLATNESS = 1.25  # peak(8192 rows) / peak(2048 rows) must stay under this


def run_stream(height: int, metrics, engine_name: str):
    import jax

    from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
    from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
        PNGTileWriter,
        SyntheticTileReader,
    )
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.stream import stream_pipeline
    from mpi_cuda_imagemanipulation_tpu.stream.tiles import out_channels

    pipe = Pipeline.parse(OPS)
    sink = io.BytesIO()
    writer = PNGTileWriter(
        sink, height, WIDTH, out_channels(pipe.ops, CHANNELS)
    )
    engine = Engine(
        inflight=2,
        # ordered delivery serializes writes anyway; one worker keeps the
        # encode backlog (and so the tracked in-flight extensions) minimal
        io_threads=1,
        stage=jax.device_put,
        metrics=EngineMetrics(registry=metrics.registry),
        ordered_done=True,
        name=engine_name,
    )
    root = obs_trace.start_trace("stream", ops=OPS, h=height, w=WIDTH)
    try:
        with root:
            res = stream_pipeline(
                SyntheticTileReader(height, WIDTH, channels=CHANNELS, seed=11),
                writer,
                pipe.ops,
                tile_rows=TILE_ROWS,
                metrics=metrics,
                engine=engine,
                trace_parent=root.context(),
            )
    finally:
        engine.close()
    writer.close()
    return res, sink.getvalue(), root.trace_id


def main() -> int:
    trace_out = sys.argv[1] if len(sys.argv) > 1 else "/tmp/_stream_trace.json"
    prom_out = sys.argv[2] if len(sys.argv) > 2 else None

    from mpi_cuda_imagemanipulation_tpu.io.image import (
        decode_image_bytes,
        synthetic_image,
    )
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
    from mpi_cuda_imagemanipulation_tpu.stream import StreamMetrics

    obs_trace.configure(sample=1.0)

    # -- the headline run: 8192 rows through a 64-row tile budget ----------
    metrics = StreamMetrics()
    res, png, trace_id = run_stream(HEIGHT, metrics, "stream-smoke")
    frame_bytes = HEIGHT * WIDTH * CHANNELS
    peak = res.peak_resident_bytes
    ratio = frame_bytes / peak
    print(
        f"streamed {HEIGHT}x{WIDTH}x{CHANNELS} "
        f"({frame_bytes / 2**20:.1f} MiB) as {res.tiles} tiles of "
        f"{TILE_ROWS} rows in {res.wall_s:.2f}s — peak resident "
        f"{peak / 2**20:.2f} MiB ({ratio:.1f}x smaller), "
        f"{res.compiles} compiles"
    )
    assert ratio >= MIN_MEMORY_RATIO, (
        f"memory bound broken: frame/peak = {ratio:.1f}x < "
        f"{MIN_MEMORY_RATIO}x"
    )
    assert res.compiles <= 4, f"unbounded compiles: {res.compiles}"

    # bit-exactness vs the whole-image golden (the one allocation this
    # smoke makes on purpose — the oracle)
    golden = np.asarray(
        Pipeline.parse(OPS).jit()(
            synthetic_image(HEIGHT, WIDTH, channels=CHANNELS, seed=11)
        )
    )
    got = decode_image_bytes(png)
    assert got.shape == golden.shape, (got.shape, golden.shape)
    assert np.array_equal(got, golden), "streamed output != golden"
    print("bit-exact vs whole-image golden: OK")

    # -- flatness: 4x fewer rows, same peak --------------------------------
    short = StreamMetrics()
    res_s, png_s, _ = run_stream(SHORT_HEIGHT, short, "stream-smoke-s")
    flat = metrics.peak_resident_bytes / max(short.peak_resident_bytes, 1)
    print(
        f"peak flatness {SHORT_HEIGHT}->{HEIGHT} rows: "
        f"{short.peak_resident_bytes / 2**20:.2f} -> "
        f"{metrics.peak_resident_bytes / 2**20:.2f} MiB ({flat:.2f}x)"
    )
    assert flat <= FLATNESS, (
        f"peak resident grew {flat:.2f}x with 4x the rows — not constant"
    )

    # -- metrics contract --------------------------------------------------
    text = metrics.registry.render()
    fams = parse_exposition(text)
    for fam in (
        "mcim_stream_tiles_total",
        "mcim_stream_rows_total",
        "mcim_stream_stage_seconds",
        "mcim_stream_peak_resident_bytes",
        "mcim_engine_stage_seconds",
    ):
        assert fam in fams, f"missing metric family {fam}"
    assert metrics.tiles.value(outcome="ok") == res.tiles
    assert metrics.rows.value() == HEIGHT
    if prom_out:
        with open(prom_out, "w") as f:
            f.write(text)
        print(f"metrics snapshot -> {prom_out}")

    # -- trace contract ----------------------------------------------------
    n = obs_trace.export(trace_out)
    import json

    events = json.load(open(trace_out))["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    by_name: dict[str, int] = {}
    for e in spans:
        by_name[e["name"]] = by_name.get(e["name"], 0) + 1
    for name in (
        "stream", "stream.prefetch", "stream.stitch", "stream.tile",
        "engine.force", "engine.encode", "stream.write",
    ):
        assert by_name.get(name), f"span {name!r} missing from the trace"
    on_trace = [
        e for e in spans if e["args"].get("trace_id") == trace_id
    ]
    assert len(on_trace) >= res.tiles * 3, "trace chain incomplete"
    print(
        f"trace: {n} events -> {trace_out} "
        f"({by_name.get('stream.tile')} tile spans on trace {trace_id})"
    )
    print("stream smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
