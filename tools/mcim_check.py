#!/usr/bin/env python
"""mcim-check CLI — run the repo-native static analysis suite.

    python tools/mcim_check.py                       # human output
    python tools/mcim_check.py --format json --out analysis.json
    python tools/mcim_check.py --rules concurrency,obs
    python tools/mcim_check.py --list-rules

Exit status: 0 when the tree is clean (no unsuppressed error-severity
findings), 1 otherwise — the blocking contract the CI `analyze` job
enforces. False positives are waived inline with
`# mcim: allow(<rule>: reason)`; stale waivers are themselves findings.
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="mcim-check", description=__doc__)
    ap.add_argument(
        "--root", default=_ROOT, help="repo root to analyze (default: "
        "the checkout containing this script)"
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--out", default=None,
        help="also write the report to this path (the CI artifact)",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule families to run "
        "(concurrency,tracer,obs,surface; default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = ap.parse_args(argv)

    from mpi_cuda_imagemanipulation_tpu.analysis import core

    if args.list_rules:
        # importing the rule modules populates the catalog
        from mpi_cuda_imagemanipulation_tpu.analysis import (  # noqa: F401
            rules_concurrency,
            rules_obs,
            rules_surface,
            rules_tracer,
        )

        for r in sorted(core.RULES.values(), key=lambda r: (r.family, r.id)):
            print(f"{r.family:12s} {r.id:28s} [{r.severity}] {r.doc}")
        return 0

    families = (
        {f.strip() for f in args.rules.split(",") if f.strip()}
        if args.rules
        else None
    )
    findings, repo = core.run(args.root, families=families)
    report = (
        core.render_json(findings, repo)
        if args.format == "json"
        else core.render_text(findings)
    )
    sys.stdout.write(report)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(
                core.render_json(findings, repo)
                if args.out.endswith(".json")
                else report
            )
    return 1 if any(f.severity == "error" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
