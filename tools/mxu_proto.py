#!/usr/bin/env python
"""MXU banded-matmul prototype for the headline 5x5 Gaussian.

SUPERSEDED (round 6): this design graduated into the production backend
``ops/mxu_kernels.py`` (``impl='mxu'``, auto routing, sharded + serving
wiring) with the same identities pytest-gated in tests/test_mxu_backend.py;
the production A/B lane is ``bench_suite --config mxu_ab``
(tools/tpu_queue/23_mxu_prod_r06.sh). Kept for historical re-runs.

Round-5 roofline data (artifacts/roofline_rr_r05.out) killed the
element-rate-ceiling theory: Pallas u8 copy kernels sustain ~550 GB/s, so
the production u8 compute kernel (~91 GB/s effective, 45.9k MP/s) is
VPU-COMPUTE-bound — the separable 5x5 costs 10 u16 multiply-adds per
pixel on the VPU (~460 G MAC/s sustained). The v5e's idle resource is the
MXU (~197 TFLOP/s bf16): this prototype reformulates each separable pass
as a blocked-banded matmul so the taps contract on the MXU instead.

Formulation (row pass; column pass is the mirror):

    out[h, B*j + n] = sum_k in_pad[h, B*j + n + k] * t[k],  k in [0, 5)

With block width B=128, gather In_ext[j] = in_pad[:, B*j : B*j + B+4]
(static slices) and build the banded tap matrix C[i, n] = t[i - n + 2]
(shape (B+4, B)); then out_block_j = In_ext[j] @ C — an einsum
'bhk,kn->bhn' with M=H, K=B+4, N=B=128: real MXU shapes. FLOPs are
(B+4)/5 ~ 26x the arithmetic minimum, but the MXU has ~430x the VPU's
MAC rate, so the roofline still clears the VPU path by an order of
magnitude if utilisation holds.

Exactness (the non-negotiable): u8 values (<= 255) and binomial taps
(<= 6) are exactly representable in bf16, and jnp.einsum with
preferred_element_type=f32 accumulates exactly (every partial product is
an integer <= 255*6 < 2^11, every row sum <= 4080 < 2^24). The COLUMN
pass input is the row-pass sums (<= 4080, 12 bits — NOT bf16-exact), so
two variants:

  mxu_f32col    — column einsum in f32 (exact directly; MXU f32 rate is
                  lower but K=132 is tiny)
  mxu_bf16split — tmp = 64*a + b with a, b in [0, 63] (both bf16-exact);
                  colsum(tmp) = 64*colsum(a) + colsum(b): two bf16
                  matmuls, recombined in f32. Integer-exact by linearity.

The final quantize replays the golden op on the exact integer sums:
s / 256 is exact in f32 (s <= 65280, power-of-two divisor), jnp.rint is
round-half-to-even — identical to the golden rint_clip quantizer.
Both variants are asserted bit-exact against the golden StencilOp on
three shapes before anything is timed (the same gate discipline as
tools/swar_proto.py / tools/hybrid_proto.py).

Usage: python tools/mxu_proto.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TAPS = (1, 4, 6, 4, 1)  # binomial_1d(5), scale 1/256 (ops/filters.py)
H_ = 2  # halo
B = 128  # block width (one MXU/lane tile)


def build_fns():
    import jax.numpy as jnp
    import numpy as np

    n_taps = len(TAPS)
    # banded tap matrix: C[i, n] = t[i - n + H_] for the valid band
    C = np.zeros((B + 2 * H_, B), np.float32)
    for n in range(B):
        for k in range(n_taps):
            C[n + k, n] = TAPS[k]
    C_bf16 = jnp.asarray(C, jnp.bfloat16)
    C_f32 = jnp.asarray(C, jnp.float32)

    def _band_blocks(xp, axis):
        """Static sliding blocks of width B+2h along `axis` with stride B:
        (nb, ..., B+2h) stacked on a new leading axis. `xp` must already
        carry the 2h halo at both ends of `axis`."""
        n = (xp.shape[axis] - 2 * H_) // B
        slices = []
        for j in range(n):
            idx = [slice(None)] * xp.ndim
            idx[axis] = slice(j * B, j * B + B + 2 * H_)
            slices.append(xp[tuple(idx)])
        return jnp.stack(slices, axis=0)

    def row_pass(xpad_core):
        """(H, Wp+2h) bf16 (reflect-padded width) -> (H, Wb) f32 row sums
        (Wb = padded-to-block width; cols past the real width are garbage
        the caller crops)."""
        ext = _band_blocks(xpad_core, axis=1)  # (nb, H, B+2h) bf16
        out = jnp.einsum(
            "jhk,kn->hjn", ext, C_bf16,
            preferred_element_type=jnp.float32,
        )
        return out.reshape(out.shape[0], -1)  # (H, nb*B)

    def col_pass_f32(tmp_pad):
        """(Hp+2h, W) f32 row sums (reflect-padded height, block-padded)
        -> (Hb, W) f32 column sums via an f32 MXU einsum."""
        ext = _band_blocks(tmp_pad, axis=0)  # (nb, B+2h, W) f32
        out = jnp.einsum(
            "jkw,km->jmw", ext, C_f32,
            preferred_element_type=jnp.float32,
        )
        return out.reshape(-1, out.shape[-1])  # (nb*B, W)

    def col_pass_bf16split(tmp_pad):
        """Same contraction with bf16 inputs: tmp = 64*a + b, a,b <= 63
        exactly representable in bf16; exact by linearity."""
        a = jnp.floor(tmp_pad * (1.0 / 64.0))
        b = tmp_pad - a * 64.0
        ea = _band_blocks(a.astype(jnp.bfloat16), axis=0)
        eb = _band_blocks(b.astype(jnp.bfloat16), axis=0)
        oa = jnp.einsum("jkw,km->jmw", ea, C_bf16,
                        preferred_element_type=jnp.float32)
        ob = jnp.einsum("jkw,km->jmw", eb, C_bf16,
                        preferred_element_type=jnp.float32)
        out = oa * 64.0 + ob
        return out.reshape(-1, out.shape[-1])

    def make_gaussian5(col_variant):
        col = {"f32": col_pass_f32, "bf16split": col_pass_bf16split}[
            col_variant
        ]

        def f(img):
            Hh, Ww = img.shape
            xpad = jnp.pad(img, H_, mode="reflect")  # reflect101 == np pad
            # width: keep the halo, block-pad the core region
            core = xpad.astype(jnp.bfloat16)
            wpad = (-Ww) % B
            if wpad:
                core = jnp.pad(core, ((0, 0), (0, wpad)))
            tmp = row_pass(core)  # (H+2h, Wb) f32, halo rows intact
            hpad = (-Hh) % B
            if hpad:
                tmp = jnp.pad(tmp, ((0, hpad), (0, 0)))
            s = col(tmp)[:Hh, :Ww]  # exact integer column sums
            q = jnp.rint(s * (1.0 / 256.0))  # round-half-even, exact
            return jnp.clip(q, 0.0, 255.0).astype(jnp.uint8)

        return f

    return make_gaussian5


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--height", type=int, default=4320)
    ap.add_argument("--width", type=int, default=7680)
    args = ap.parse_args()
    saved_calib = os.environ.get("MCIM_NO_CALIB")
    os.environ["MCIM_NO_CALIB"] = "1"
    try:
        return _main(args)
    finally:
        if saved_calib is None:
            os.environ.pop("MCIM_NO_CALIB", None)
        else:
            os.environ["MCIM_NO_CALIB"] = saved_calib


def _main(args) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        pipeline_pallas,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

    make_gaussian5 = build_fns()

    print(f"backend: {jax.default_backend()}", flush=True)

    def emit(rec):
        print(json.dumps(rec), flush=True)

    # ---- bit-exactness gate BEFORE any timing ----
    pipe = Pipeline.parse("gaussian:5")
    for variant in ("f32", "bf16split"):
        fn = jax.jit(make_gaussian5(variant))
        for th, tw, seed in ((48, 64, 1), (37, 200, 2), (130, 384, 3)):
            img = jnp.asarray(synthetic_image(th, tw, channels=1, seed=seed))
            golden = np.asarray(pipe(img))
            got = np.asarray(fn(img))
            if not np.array_equal(got, golden):
                d = np.argwhere(got != golden)
                print(
                    f"MXU {variant} MISMATCH at {th}x{tw}: {len(d)} px, "
                    f"first {d[0]} got {got[tuple(d[0])]} "
                    f"want {golden[tuple(d[0])]}",
                    file=sys.stderr,
                )
                return 1
    print("bit-exactness gate: MXU f32 + bf16split == golden on 3 shapes",
          flush=True)

    if not is_tpu_backend():
        print("self-test passed; timing needs the chip — exiting", flush=True)
        return 0

    # ---- timing ----
    H, W = args.height, args.width
    img = jnp.asarray(synthetic_image(H, W, channels=1, seed=99))
    mp = H * W / 1e6

    cases = [
        ("mxu_f32col", jax.jit(make_gaussian5("f32")), [img]),
        ("mxu_bf16split", jax.jit(make_gaussian5("bf16split")), [img]),
        (
            "gaussian5_8k_pallas",
            jax.jit(
                lambda x: pipeline_pallas(make_pipeline_ops("gaussian:5"), x)
            ),
            [img],
        ),
    ]
    rounds = 1 if args.quick else 3
    best: dict = {}
    for rnd in range(1, rounds + 1):
        for name, fn, fa in cases:
            try:
                sec = device_throughput(fn, fa)
            except Exception as e:
                emit({"case": name, "round": rnd, "error": str(e)[:200]})
                continue
            rec = {"case": name, "round": rnd, "ms": sec * 1e3,
                   "mp_s": mp / sec}
            emit(rec)
            if name not in best or sec < best[name][0]:
                best[name] = (sec, rec)
    for name, (sec, rec) in best.items():
        emit({**{k: v for k, v in rec.items() if k != "round"},
              "stat": f"best_of_{rounds}"})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
