#!/usr/bin/env python
"""Randomized differential soak: random op chains on random shapes, all
backends must agree bit-exactly.

The framework's correctness story rests on one invariant (docs/design.md):
golden jnp ops, XLA-jitted pipelines, fused Pallas kernels and the
ppermute-sharded runner produce *identical* uint8 images. The example- and
property-based suites check that pointwise on fixed op lists; this tool
drives it across the whole registry — random chains (channel-count aware),
random parameters, pathological shapes (narrow, sub-halo, lane-boundary
widths), random shard counts including non-dividing ones.

Usage:
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/soak.py [--iters N] [--seconds S] [--seed K] [--verbose]

Any mismatch prints one REPRO json line (spec, h, w, seed, backend) and the
tool exits 1. Pure CPU — safe to run while the TPU tunnel is down.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_imagemanipulation_tpu.utils.platform import claim_platform

claim_platform("cpu", n_host_devices=8, keep_existing_count=True)

# differential soak compares FIXED configurations; a committed autotune
# calibration steering the bh=None trials would make REPRO lines depend on
# hidden store state (review finding)
os.environ.setdefault("MCIM_NO_CALIB", "1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline  # noqa: E402
from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (  # noqa: E402
    pipeline_pallas,
)
from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh  # noqa: E402


def _rand_filter(rng: random.Random) -> str:
    k = rng.choice((3, 5))
    vals = [str(rng.randint(-4, 4)) for _ in range(k * k)]
    return "filter:" + "/".join(vals)


# template builders; channel compatibility is derived from the op
# instances themselves in random_chain (make_op), never annotated here
_POOL = [
    lambda r: "grayscale",
    lambda r: "grayscale601",
    lambda r: "sepia",
    lambda r: "gray2rgb",
    lambda r: f"contrast:{r.uniform(0.5, 6):.1f}",
    lambda r: f"brightness:{r.randint(-80, 80)}",
    lambda r: "invert",
    lambda r: f"threshold:{r.randint(1, 254)}",
    lambda r: f"gamma:{r.uniform(0.3, 4):.2f}",
    lambda r: f"posterize:{r.randint(1, 8)}",
    lambda r: f"solarize:{r.randint(1, 254)}",
    lambda r: f"emboss:{r.choice((3, 5))}",
    lambda r: f"emboss101:{r.choice((3, 5))}",
    lambda r: f"gaussian:{r.choice((3, 5, 7))}",
    lambda r: f"box:{r.choice((3, 5, 7))}",
    lambda r: "sobel",
    lambda r: "prewitt",
    lambda r: "scharr",
    lambda r: f"laplacian:{r.choice((4, 8))}",
    lambda r: "sharpen",
    lambda r: "unsharp",
    _rand_filter,
    lambda r: f"erode:{r.choice((3, 5, 7))}",
    lambda r: f"dilate:{r.choice((3, 5, 7))}",
    lambda r: f"median:{r.choice((3, 5))}",
    lambda r: r.choice(("fliph", "flipv", "transpose")),
    lambda r: f"rot:{r.choice((90, 180, 270))}",
    lambda r: f"rotate:{r.uniform(-170, 170):.1f}"
     + (":nearest" if r.random() < 0.5 else ""),
    lambda r: f"pad:{r.randint(1, 6)}:{r.choice(('zero', 'edge', 'reflect101'))}",
    lambda r: f"resize:{r.randint(10, 90)}x{r.randint(10, 90)}"
     + (":nearest" if r.random() < 0.5 else ""),
    lambda r: f"scale:{r.uniform(0.4, 2.2):.2f}"
     + (":nearest" if r.random() < 0.5 else ""),
    lambda r: "equalize",
    lambda r: "autocontrast",
    lambda r: "otsu",
]


def random_chain(rng: random.Random, max_len: int = 5) -> str:
    """A registry-wide random chain, valid for a 3-channel input. Channel
    compatibility comes from the op instances themselves (make_op), not a
    hand-maintained table, so new registry ops soak automatically once
    added to _POOL."""
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_op

    chan = 3
    parts: list[str] = []
    for _ in range(rng.randint(1, max_len)):
        for _attempt in range(30):
            build = rng.choice(_POOL)
            spec = build(rng)
            op = make_op(spec)
            need = getattr(op, "in_channels", 0)
            if need and need != chan:
                continue
            parts.append(spec)
            out = getattr(op, "out_channels", 0)
            chan = out or need or chan
            break
    return ",".join(parts) or "invert"


def _crop_for(rng: random.Random, h: int, w: int) -> str:
    ch = rng.randint(max(1, h // 2), h)
    cw = rng.randint(max(1, w // 2), w)
    return f"crop:{rng.randint(0, h - ch)}:{rng.randint(0, w - cw)}:{ch}:{cw}"


def random_shape(rng: random.Random) -> tuple[int, int]:
    kind = rng.random()
    if kind < 0.25:  # tiny / sub-halo heights
        return rng.randint(9, 24), rng.randint(9, 40)
    if kind < 0.5:  # lane-boundary widths
        return rng.randint(20, 90), rng.choice((127, 128, 129, 255, 256, 257))
    if kind < 0.75:  # generic small; half the time a word-aligned width so
        # the packed-u32 path's eligible branch (W % 4 == 0, W/4 >= 8)
        # soaks as often as its fallback
        w = rng.randint(25, 160)
        if rng.random() < 0.5:
            w = max(32, w & ~3)
        return rng.randint(25, 120), w
    return rng.randint(120, 300), rng.randint(40, 120)  # tall, shardable


def run_trial(
    rng: random.Random,
    trial_seed: int,
    verbose: bool,
    stats: dict | None = None,
) -> dict | None:
    h, w = random_shape(rng)
    spec = random_chain(rng)
    if rng.random() < 0.2:  # crop needs in-bounds params for this shape
        spec = _crop_for(rng, h, w) + "," + spec
    img = jnp.asarray(synthetic_image(h, w, channels=3, seed=trial_seed))
    pipe = Pipeline.parse(spec)

    def repro(backend, detail=""):
        return {
            "spec": spec, "h": h, "w": w, "seed": trial_seed,
            "backend": backend, "detail": detail[:300],
        }

    golden = np.asarray(pipe(img))
    if verbose:
        print(f"  {spec!r} ({h}x{w}) -> {golden.shape}", flush=True)

    try:
        got = np.asarray(pipe.jit("xla")(img))
    except Exception as e:  # noqa: BLE001 — any crash is a finding
        return repro("xla", f"raised {type(e).__name__}: {e}")
    if not np.array_equal(got, golden):
        return repro("xla", "mismatch")

    # random explicit block height: the autotune calibration path
    # (utils/calibration.py) can shrink production blocks below the
    # heuristic at any time, so bit-exactness must hold for EVERY legal
    # height, not just the default (None = heuristic, weighted 2x)
    bh = rng.choice((None, None, 32, 64, 96))

    def bh_repro(backend, detail=""):
        r = repro(backend, detail)
        if bh is not None:
            r["block_h"] = bh
        return r

    try:
        got = np.asarray(pipeline_pallas(pipe.ops, img, interpret=True, block_h=bh))
    except Exception as e:  # noqa: BLE001
        return bh_repro("pallas", f"raised {type(e).__name__}: {e}")
    if not np.array_equal(got, golden):
        return bh_repro("pallas", "mismatch")

    if rng.random() < 0.5:  # archived packed path (tools/packed_kernels)
        from tools.packed_kernels import pipeline_packed

        try:
            got = np.asarray(
                pipeline_packed(pipe.ops, img, interpret=True, block_h=bh)
            )
        except Exception as e:  # noqa: BLE001
            return bh_repro("packed", f"raised {type(e).__name__}: {e}")
        if not np.array_equal(got, golden):
            return bh_repro("packed", "mismatch")

    if rng.random() < 0.4:  # swar path (eligible stencils + run fallback)
        from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import (
            pipeline_swar,
        )

        # the random 3-channel pipeline mostly exercises the FALLBACK
        # (swar needs a single u8 plane with W % 4 == 0), so first run a
        # dedicated plane trial that hits the SWAR kernel itself on
        # fuzzed shapes/heights (review finding)
        w4 = w - (w % 4)
        if w4 >= 24 and h >= 8:
            sbh = rng.choice((None, 8, 16, 24, 32, 64))
            gimg = jnp.asarray(
                synthetic_image(h, w4, channels=1, seed=trial_seed + 77)
            )
            gspec = rng.choice(
                (
                    "gaussian:3",
                    "gaussian:5",
                    "gaussian:3,gaussian:5",
                    # round-5 widening: wide column mode
                    "gaussian:7",
                    "box:3",
                    "box:5",
                    # fused affine chains (pre / post / both)
                    "contrast:3.5,gaussian:5",
                    "gaussian:5,invert",
                    "brightness:20,gaussian:7,invert",
                    # corr2d kernel (incl. the reference interior guard)
                    "emboss:3",
                    "emboss:5",
                    "emboss101:3",
                    "sharpen",
                    "laplacian:8",
                    "contrast:3.5,emboss:3",
                )
            )
            gpipe = Pipeline.parse(gspec)
            try:
                got = np.asarray(
                    pipeline_swar(gpipe.ops, gimg, interpret=True, block_h=sbh)
                )
            except Exception as e:  # noqa: BLE001
                return repro(
                    "swar-plane", f"{gspec} bh={sbh}: raised "
                    f"{type(e).__name__}: {e}"
                )
            if not np.array_equal(got, np.asarray(gpipe(gimg))):
                return repro("swar-plane", f"{gspec} bh={sbh}: mismatch")
        # the mixed random pipeline still runs through pipeline_swar: its
        # run-fallback + shape gates must stay bit-exact on any chain
        try:
            got = np.asarray(
                pipeline_swar(pipe.ops, img, interpret=True, block_h=bh)
            )
        except Exception as e:  # noqa: BLE001
            return bh_repro("swar", f"raised {type(e).__name__}: {e}")
        if not np.array_equal(got, golden):
            return bh_repro("swar", "mismatch")

    if rng.random() < 0.35:  # batched (vmap) path: per-image bit-equality
        k = rng.randint(2, 3)
        imgs = jnp.stack(
            [jnp.asarray(synthetic_image(h, w, channels=3, seed=trial_seed + t))
             for t in range(k)]
        )
        backend_b = rng.choice(("xla", "pallas"))
        try:
            outs = np.asarray(pipe.batched(backend_b)(imgs))
        except Exception as e:  # noqa: BLE001
            return repro(f"batched-{backend_b}",
                         f"raised {type(e).__name__}: {e}")
        for t in range(k):
            if not np.array_equal(outs[t], np.asarray(pipe(imgs[t]))):
                return repro(f"batched-{backend_b}", f"mismatch at image {t}")

    if rng.random() < 0.3 and len(jax.devices()) >= 4:
        # 2-D tile mesh (parallel/api2d): corner-carrying two-phase exchange
        r, c = rng.choice(((2, 2), (2, 4), (4, 2), (2, 3)))
        if r * c <= len(jax.devices()):
            from mpi_cuda_imagemanipulation_tpu.parallel.mesh import (
                make_mesh_2d,
            )

            try:
                got = np.asarray(pipe.sharded(make_mesh_2d(r, c))(img))
            except ValueError as e:
                if "below the minimum" not in str(e):
                    return repro(f"sharded2d-{r}x{c}",
                                 f"raised ValueError: {e}")
                got = None  # image too small for this mesh; skip silently
            except Exception as e:  # noqa: BLE001
                return repro(f"sharded2d-{r}x{c}",
                             f"raised {type(e).__name__}: {e}")
            if got is not None and not np.array_equal(got, golden):
                return repro(f"sharded2d-{r}x{c}", "mismatch")

    if rng.random() < 0.25 and len(jax.devices()) >= 2:
        # data-parallel stack (Pipeline.data_parallel), uneven N included
        k = rng.randint(2, 5)
        dimgs = jnp.stack(
            [jnp.asarray(synthetic_image(h, w, channels=3, seed=trial_seed + t))
             for t in range(k)]
        )
        n_dp = rng.choice([s for s in (2, 4) if s <= len(jax.devices())])
        from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh as _mm

        try:
            douts = np.asarray(pipe.data_parallel(_mm(n_dp))(dimgs))
        except Exception as e:  # noqa: BLE001
            return repro(f"dp-{k}over{n_dp}", f"raised {type(e).__name__}: {e}")
        for t in range(k):
            if not np.array_equal(douts[t], np.asarray(pipe(dimgs[t]))):
                return repro(f"dp-{k}over{n_dp}", f"mismatch at image {t}")

    n_dev = len(jax.devices())
    if n_dev >= 2:
        shards = rng.choice([s for s in (2, 3, 5, n_dev) if s <= n_dev])
        backend = rng.choice(("xla", "pallas", "auto", "swar"))
        # small images reject large shard counts (documented min-rows-per-
        # shard guard); fall back toward 2 shards so pathological shapes
        # still get sharded coverage, and *count* trials that lose it so
        # the final report can't silently overstate coverage
        while True:
            try:
                got = np.asarray(
                    pipe.sharded(make_mesh(shards), backend=backend)(img)
                )
            except ValueError as e:
                if "below the minimum" in str(e):
                    if shards > 2:
                        shards = 2
                        continue
                    if stats is not None:
                        stats["shard_skips"] = stats.get("shard_skips", 0) + 1
                    return None
                return repro(f"sharded-{shards}-{backend}",
                             f"raised {type(e).__name__}: {e}")
            except Exception as e:  # noqa: BLE001
                return repro(f"sharded-{shards}-{backend}",
                             f"raised {type(e).__name__}: {e}")
            break
        if not np.array_equal(got, golden):
            return repro(f"sharded-{shards}-{backend}", "mismatch")
    return None


def run_repro(line: str) -> int:
    """Re-run one REPRO json line deterministically: same spec, shape and
    image seed, every backend (all shard counts), verbose verdicts."""
    from tools.packed_kernels import pipeline_packed as _pipeline_packed
    d = json.loads(line)
    spec, h, w, seed = d["spec"], d["h"], d["w"], d["seed"]
    img = jnp.asarray(synthetic_image(h, w, channels=3, seed=seed))
    pipe = Pipeline.parse(spec)
    golden = np.asarray(pipe(img))
    print(f"repro {spec!r} ({h}x{w}, seed {seed}) -> {golden.shape}")
    rc = 0

    def check(name, fn, skip_on_min_guard=False, golden_override=None):
        nonlocal rc
        expect = golden if golden_override is None else golden_override
        try:
            got = np.asarray(fn())
        except ValueError as e:
            if skip_on_min_guard and "below the minimum" in str(e):
                print(f"  {name}: skipped (image too short)")
                return
            print(f"  {name}: RAISED ValueError: {str(e)[:200]}")
            rc = 1
            return
        except Exception as e:  # noqa: BLE001
            print(f"  {name}: RAISED {type(e).__name__}: {str(e)[:200]}")
            rc = 1
            return
        ok = np.array_equal(got, expect)
        print(f"  {name}: {'ok' if ok else 'MISMATCH'}")
        rc |= 0 if ok else 1

    check("xla", lambda: pipe.jit("xla")(img))
    # a REPRO from a block-height trial carries "block_h"; re-check both the
    # recorded height and the default heuristic
    for bh in dict.fromkeys((d.get("block_h"), None)):
        tag = "" if bh is None else f"[bh={bh}]"
        check(
            f"pallas{tag}",
            lambda bh=bh: pipeline_pallas(
                pipe.ops, img, interpret=True, block_h=bh
            ),
        )
        check(
            f"packed{tag}",
            lambda bh=bh: _pipeline_packed(
                pipe.ops, img, interpret=True, block_h=bh
            ),
        )
    # same batch construction as run_trial (k distinct images seeded
    # trial_seed + t) so batched REPROs actually reproduce; k=3 supersets
    # the fuzzer's k in {2, 3}, and every index is compared
    imgs = jnp.stack(
        [jnp.asarray(synthetic_image(h, w, channels=3, seed=seed + t))
         for t in range(3)]
    )
    for b in ("xla", "pallas"):
        for t in range(3):
            check(
                f"batched-{b}[{t}]",
                lambda b=b, t=t: pipe.batched(b)(imgs)[t],
                golden_override=np.asarray(pipe(imgs[t])),
            )
    n_dev = len(jax.devices())
    for shards in sorted({s for s in (2, 3, 5, n_dev) if s <= n_dev}):
        for b in ("xla", "pallas", "auto", "swar"):
            check(
                f"sharded-{shards}-{b}",
                lambda shards=shards, b=b: pipe.sharded(
                    make_mesh(shards), backend=b
                )(img),
                skip_on_min_guard=True,
            )
    # the paths the fuzzer samples randomly get deterministic repro
    # coverage too: every 2-D mesh geometry it draws, and a DP stack
    if n_dev >= 4:
        from mpi_cuda_imagemanipulation_tpu.parallel.mesh import make_mesh_2d

        for r, c in ((2, 2), (2, 3), (2, 4), (4, 2)):
            if r * c <= n_dev:
                check(
                    f"sharded2d-{r}x{c}",
                    lambda r=r, c=c: pipe.sharded(make_mesh_2d(r, c))(img),
                    skip_on_min_guard=True,
                )
    if n_dev >= 2:
        imgs_dp = jnp.stack(
            [jnp.asarray(synthetic_image(h, w, channels=3, seed=seed + t))
             for t in range(3)]
        )
        for t in range(3):
            check(
                f"dp[{t}]",
                lambda t=t: pipe.data_parallel(make_mesh(2))(imgs_dp)[t],
                golden_override=np.asarray(pipe(imgs_dp[t])),
            )
    return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--seconds", type=float, default=None,
                    help="stop after this much wall time (overrides --iters)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--repro", default=None,
                    help="re-run one REPRO json line instead of fuzzing")
    args = ap.parse_args()

    if args.repro:
        return run_repro(args.repro)

    rng = random.Random(args.seed)
    t0 = time.time()
    failures = 0
    i = 0
    stats: dict = {}
    while True:
        if args.seconds is not None:
            if time.time() - t0 > args.seconds:
                break
        elif i >= args.iters:
            break
        trial_seed = rng.randint(0, 2**31 - 1)
        bad = run_trial(rng, trial_seed, args.verbose, stats=stats)
        if bad is not None:
            failures += 1
            print("REPRO " + json.dumps(bad), flush=True)
        i += 1
        if i % 25 == 0:
            print(f"soak: {i} trials, {failures} failures, "
                  f"{time.time() - t0:.0f}s", flush=True)
    print(f"soak done: {i} trials, {failures} failures, "
          f"{stats.get('shard_skips', 0)} without sharded coverage "
          f"(too short even for 2 shards), {time.time() - t0:.0f}s",
          flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
