"""Packed-u32 streaming Pallas kernels — 4 pixels per 32-bit lane.

DEMOTED from the production surface (round 5). The on-chip interleaved A/B
this design was waiting on (artifacts/packed_ab_r05.out, 2026-08-01)
adjudicated against it decisively:

  * 8K gaussian:5 (headline): 11,340 MP/s packed vs 46,248 MP/s u8
    streaming — 4.1x SLOWER (two interleaved rounds, same process).
  * reference pipeline: 11,172 MP/s packed vs 33,863 MP/s u8 Pallas vs
    73,329 MP/s XLA.
  * The element-rate-cap hypothesis that motivated the design was
    falsified the same window: Pallas u8 copy kernels sustain ~550 GB/s
    (artifacts/roofline_rr_r05.out), so the u8 path was never
    element-capped — the packed unpack-to-f32-lanes inner loop just adds
    VPU work on the same element count.
  * The compiled validation sweep (artifacts/validate_r05.out) found the
    packed kernels MISCOMPARE on planes narrower than one 128-lane tile
    (W/4 < 128, e.g. 40x300 / 65x140: maxdiff up to 127) — the lane
    rotations assume a full lane tile; interpret mode (where all packed
    tests ran) does not model Mosaic's lane layout and hid it.

The module is kept under tools/ as the measured record of the design and
for the archival A/B tools (tools/packed_ab.py, tools/packed_proto.py);
`pipeline_packed` below preserves a runnable entry for the interpret-mode
regression tests (tests/test_packed.py). It is no longer reachable from
any production path: the `--impl packed` choice, the MCIM_PREFER_PACKED
promotion switch, the packed sharded ghost mode, and the bench plan entry
were all removed with this demotion.

The round-2 roofline analysis (BASELINE.md) pinned the u8 streaming kernels
at ~92 GB/s effective against the v5e's 819 GB/s datasheet peak, invariant
under block geometry and VPU work — consistent with an *element-rate* cap
on the u8 load/store path rather than a byte-rate DMA ceiling. This module
is the production exploitation of that hypothesis: HBM keeps the exact same
bytes, but the kernels view each (H, W) u8 plane as an (H, W/4) i32 word
array (one `lax.bitcast_convert_type`, no host work), moving 4 pixels per
32-bit element; kernels unpack to byte lanes with i32 shifts/masks in VMEM
(Mosaic-native ops — no u8 anywhere inside the kernel body, which also
sidesteps Mosaic's missing unsigned<->float casts).

Lane space: word j's byte k is image column 4j + k, so a plane becomes 4
interleaved "lane" planes of width W/4 (lane k = columns k, k+4, ...). Two
structural facts make the integration small and bit-exact:

  * Pointwise math is elementwise, so the whole fused pointwise chain runs
    unchanged on lane-concatenated (rows, W) f32 arrays — same core
    functions from ops/spec.py, same values, different column order.
  * The streaming kernel's vertical machinery — scratch carries, top
    strips, the ragged-last-block beyond-row fixes (_assemble_ext), and
    the separable COLUMN pass — is row-structured and lane-agnostic, so it
    is reused verbatim from ops/pallas_kernels. Only the ROW pass needs
    lane-space code: interior taps become lane rotations + word shifts,
    and the op's width-edge extension is re-synthesised exactly for the
    first/last `halo` global columns (halo <= 3 keeps every fix inside the
    first/last word of one lane).

Bit-exactness with the u8 path is structural: per output column the same
weights are accumulated by the same `_weighted_terms` in the same order,
the same column pass from `_split_passes` runs on the same row values, and
the same quantizer applies — asserted across the registry by
tests/test_packed.py.

Scope (`packed_supported`): pointwise-only groups and every stencil with
halo <= 3 except zero-mode — separable correlations (Gaussian, box —
incl. the BASELINE.json headline, 8K gaussian:5), square-window min/max
morphology (erode/dilate), non-separable correlations incl. magnitude
combines (Sobel/Prewitt/Scharr, Laplacian, sharpen/unsharp, arbitrary
`filter:`, emboss101), the median networks, and interior-mode ops (emboss
— the reference pipeline runs fully packed) via a lane-space interior
mask with orig passthrough. Only zero-mode, LUT/geometric/global steps
and W % 4 != 0 images fall back to the u8 streaming path, per group, so
`packed=True` is always safe to request.

Reference analogue: kernel.cu processes one pixel per CUDA thread
(kernel.cu:33-38); the packed layout is the TPU-native inversion — one VPU
lane processes four pixels per op.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend
from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
    _COMPILER_PARAMS,
    _apply_pointwise_planes,
    _assemble_ext,
    _channels_after,
    _live_f32_temps,
    _pick_block_h,
    _split_passes,
    _src_col,
    _top_strip,
    _weighted_terms,
)
from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    U8,
    _MEDIAN_NETWORKS,
    _sort2,
    PointwiseOp,
    QUANTIZERS_F32,
    StencilOp,
)

I32 = jnp.int32


# --------------------------------------------------------------------------
# XLA-side views: u8 plane <-> i32 word plane (same bytes, no host work)
# --------------------------------------------------------------------------


def pack_words(plane: jnp.ndarray) -> jnp.ndarray:
    """(H, W) u8 -> (H, W/4) i32; word j's byte k is column 4j + k."""
    H, W = plane.shape
    words = jax.lax.bitcast_convert_type(
        plane.reshape(H, W // 4, 4), jnp.uint32
    )
    return jax.lax.bitcast_convert_type(words, I32)


def unpack_words(words: jnp.ndarray, width: int) -> jnp.ndarray:
    """(H, W/4) i32 -> (H, W) u8 (inverse of pack_words)."""
    H = words.shape[0]
    return jax.lax.bitcast_convert_type(words, U8).reshape(H, width)


# --------------------------------------------------------------------------
# In-kernel lane algebra (i32 shifts/masks only — Mosaic-native)
# --------------------------------------------------------------------------


def _lanes_f32(words: jnp.ndarray) -> list[jnp.ndarray]:
    """Split (rows, Wp) i32 words into 4 f32 lane planes (values 0..255)."""
    m = jnp.int32(0xFF)
    return [
        (words & m).astype(F32),
        ((words >> 8) & m).astype(F32),
        ((words >> 16) & m).astype(F32),
        ((words >> 24) & m).astype(F32),
    ]


def _unpack_concat_f32(words: jnp.ndarray) -> jnp.ndarray:
    """(rows, Wp) i32 -> lane-concat (rows, 4*Wp) f32: [lane0|lane1|lane2|lane3]."""
    return jnp.concatenate(_lanes_f32(words), axis=1)


def _pack_concat_i32(xc: jnp.ndarray) -> jnp.ndarray:
    """Lane-concat (rows, W) f32 of exact u8 integers -> (rows, W/4) i32
    words (the write-side inverse of _unpack_concat_f32)."""
    Wp = xc.shape[1] // 4
    l0, l1, l2, l3 = (
        xc[:, k * Wp : (k + 1) * Wp].astype(I32) for k in range(4)
    )
    return l0 | (l1 << 8) | (l2 << 16) | (l3 << 24)


def _split_lanes(xc: jnp.ndarray) -> list[jnp.ndarray]:
    Wp = xc.shape[1] // 4
    return [xc[:, k * Wp : (k + 1) * Wp] for k in range(4)]


def _lane_shifted(lanes: list[jnp.ndarray], k: int, d: int) -> jnp.ndarray:
    """Lane view of global column offset d for output lane k: source lane
    (k+d) mod 4, word shift (k+d)//4 (in {-1, 0, 1} for |d| <= 3) with
    boundary-word replication — which only pollutes global columns < halo
    or >= W - halo, exactly the ones _apply_edge_fixes overwrites."""
    src = lanes[(k + d) % 4]
    ws = (k + d) // 4
    if ws == 0:
        return src
    if ws > 0:
        return jnp.concatenate([src[:, ws:]] + [src[:, -1:]] * ws, axis=1)
    return jnp.concatenate([src[:, :1]] * -ws + [src[:, :ws]], axis=1)


def _lane_col(lanes: list[jnp.ndarray], c: int) -> jnp.ndarray:
    """Global column c as a (rows, 1) slice of its lane."""
    return lanes[c % 4][:, c // 4 : c // 4 + 1]


def _apply_edge_fixes(out_lanes, edge_col, h: int, W: int) -> jnp.ndarray:
    """Overwrite the first/last h global columns with their exact
    edge-synthesised values and return the lane-concat result. h <= 3 < 4:
    each fixed column is the first (left) or last (right) word of its
    lane, so each fix is a 1-column rebuild."""
    for j in range(h):
        k = j % 4
        out_lanes[k] = jnp.concatenate(
            [edge_col(j), out_lanes[k][:, 1:]], axis=1
        )
    for j in range(W - h, W):
        k = j % 4
        out_lanes[k] = jnp.concatenate(
            [out_lanes[k][:, :-1], edge_col(j)], axis=1
        )
    return jnp.concatenate(out_lanes, axis=1)


def _row_corr_packed(
    xc: jnp.ndarray, w1d: np.ndarray, h: int, mode: str | None
) -> jnp.ndarray:
    """Row pass of a separable correlation in lane space: `xc` is
    lane-concat (rows, W) f32; returns lane-concat (rows, W) f32,
    bit-identical per output column to pallas_kernels._row_corr (same
    _weighted_terms, same clamped-source edge columns)."""
    W = xc.shape[1]
    lanes = _split_lanes(xc)
    wv = np.asarray(w1d, dtype=np.float32).reshape(-1)

    out_lanes = [
        _weighted_terms(wv, lambda t, k=k: _lane_shifted(lanes, k, t - h))
        for k in range(4)
    ]

    def edge_col(j: int) -> jnp.ndarray:
        def sl(t: int) -> jnp.ndarray:
            c = _src_col(j + t - h, W, mode)
            if c is None:
                return jnp.zeros((xc.shape[0], 1), xc.dtype)
            return _lane_col(lanes, c)

        return _weighted_terms(wv, sl)

    return _apply_edge_fixes(out_lanes, edge_col, h, W)


def _interior_mask_lanes(
    stencil: StencilOp, rows: int, W: int, y0, global_h: int
) -> jnp.ndarray:
    """StencilOp.interior_mask in lane-concat layout: lane k's word m is
    global column 4m + k, so each lane gets its own column iota; row
    coordinates are global via the traced block offset y0."""
    o = stencil.halo
    Wp = W // 4
    yy = y0 + lax.broadcasted_iota(jnp.int32, (rows, Wp), 0)
    row_ok = (yy > o) & (yy <= global_h - 1 - o)
    masks = []
    for k in range(4):
        xx = 4 * lax.broadcasted_iota(jnp.int32, (rows, Wp), 1) + k
        masks.append(row_ok & (xx > o) & (xx <= W - 1 - o))
    return jnp.concatenate(masks, axis=1)


def _combine_scale(stencil: StencilOp, accs: list[jnp.ndarray]) -> jnp.ndarray:
    """Combine + scale exactly as StencilOp.valid does."""
    if stencil.combine == "single":
        acc = accs[0]
    else:  # magnitude (Sobel class)
        acc = jnp.sqrt(accs[0] * accs[0] + accs[1] * accs[1])
    if stencil.scale != 1.0:
        acc = acc * np.float32(stencil.scale)
    return acc


def _make_col2d_packed(stencil: StencilOp, W: int):
    """Lane-space column pass for NON-separable stencils: the full 2-D
    correlation (or the median selection network) over a raw lane-concat
    ext block, with horizontal taps as lane shifts.

    Bit-exactness with the u8 path is by construction: taps accumulate in
    corr_valid's exact (dy-major, dx-minor) order with the same zero-weight
    skips and w==1 fast path; median wires are built in median_valid's
    dy-major order and run the same exchange network; combine/scale follow
    StencilOp.valid. The boundary-word pollution of _lane_shifted only
    reaches global columns < halo or >= W - halo, which the clamped-source
    edge fix recomputes (same tap order) and overwrites.
    """
    h = stencil.halo
    mode = stencil.edge_mode

    def col_pass(ext: jnp.ndarray) -> jnp.ndarray:
        rows = ext.shape[0] - 2 * h
        lanes_ext = _split_lanes(ext)
        bands = [[l[dy : dy + rows] for l in lanes_ext] for dy in range(2 * h + 1)]

        if stencil.reduce == "median":
            size = stencil.kernels[0].shape[0]
            exchanges, mid = _MEDIAN_NETWORKS[size]

            def median_of(wires):
                p = list(wires)
                for i, j in exchanges:
                    p[i], p[j] = _sort2(p[i], p[j])
                return p[mid]

            def lane_out(k):
                wires = [
                    _lane_shifted(bands[dy], k, dx - h)
                    for dy in range(size)
                    for dx in range(size)
                ]
                return median_of(wires)

            def edge_col(j):
                wires = []
                for dy in range(size):
                    for dx in range(size):
                        c = _src_col(j + dx - h, W, mode)
                        wires.append(
                            jnp.zeros((rows, 1), F32)
                            if c is None
                            else _lane_col(bands[dy], c)
                        )
                return median_of(wires)

        else:  # 2-D correlation (+ optional magnitude combine)

            def corr(k_or_j, is_edge):
                accs = []
                for kmat in stencil.kernels:
                    kh, kw = kmat.shape
                    acc = None
                    for dy in range(kh):
                        for dx in range(kw):
                            w = float(kmat[dy, dx])
                            if w == 0.0:
                                continue
                            if is_edge:
                                c = _src_col(k_or_j + dx - h, W, mode)
                                win = (
                                    jnp.zeros((rows, 1), F32)
                                    if c is None
                                    else _lane_col(bands[dy], c)
                                )
                            else:
                                win = _lane_shifted(bands[dy], k_or_j, dx - h)
                            term = win if w == 1.0 else win * np.float32(w)
                            acc = term if acc is None else acc + term
                    if acc is None:
                        shape = (rows, 1) if is_edge else (rows, W // 4)
                        acc = jnp.zeros(shape, F32)
                    accs.append(acc)
                return _combine_scale(stencil, accs)

            def lane_out(k):
                return corr(k, False)

            def edge_col(j):
                return corr(j, True)

        return _apply_edge_fixes(
            [lane_out(k) for k in range(4)], edge_col, h, W
        )

    return col_pass


def _row_reduce_packed(
    xc: jnp.ndarray, kw: int, h: int, mode: str | None, fn
) -> jnp.ndarray:
    """Row pass of a sliding min/max in lane space (erode/dilate), the
    packed twin of pallas_kernels._row_reduce: same left-assoc fold order
    over taps, same clamped-source edge columns."""
    W = xc.shape[1]
    lanes = _split_lanes(xc)

    def fold(sl):
        acc = None
        for t in range(kw):
            win = sl(t)
            if win is None:
                continue
            acc = win if acc is None else fn(acc, win)
        return acc

    out_lanes = [
        fold(lambda t, k=k: _lane_shifted(lanes, k, t - h)) for k in range(4)
    ]

    def edge_col(j: int) -> jnp.ndarray:
        def sl(t: int):
            c = _src_col(j + t - h, W, mode)
            return None if c is None else _lane_col(lanes, c)

        return fold(sl)

    return _apply_edge_fixes(out_lanes, edge_col, h, W)


# --------------------------------------------------------------------------
# Eligibility
# --------------------------------------------------------------------------


def packed_supported(
    pointwise: list[PointwiseOp], stencil: StencilOp | None, width: int
) -> bool:
    """Whether this [pointwise*, stencil?] group can run packed; callers
    fall back to the u8 streaming path otherwise (see module docstring)."""
    if width % 4 or width // 4 < 8:
        return False
    if any(not op.kernel_safe for op in pointwise):
        return False
    if stencil is None:
        return bool(pointwise)
    if stencil.reduce not in ("corr", "min", "max", "median"):
        return False
    if stencil.combine not in ("single", "magnitude"):
        return False
    if stencil.edge_mode == "interior":
        # supported via the non-separable path only: identity row pass
        # keeps the raw rows the orig-passthrough mask needs
        if stencil.separable is not None or stencil.reduce != "corr":
            return False
    elif stencil.edge_mode not in ("reflect101", "edge"):
        return False
    if not 1 <= stencil.halo <= 3:
        return False
    if 2 * stencil.halo >= width // 4:
        return False
    return True


# --------------------------------------------------------------------------
# Kernels
# --------------------------------------------------------------------------


def _pointwise_kernel_packed(*refs, pointwise, n_in, n_out):
    planes = [_unpack_concat_f32(r[:]) for r in refs[:n_in]]
    for op in pointwise:
        planes = _apply_pointwise_planes(op, planes)
    assert len(planes) == n_out
    for out_ref, plane in zip(refs[n_in:], planes):
        out_ref[:] = _pack_concat_i32(plane)


def _stream_kernel_packed(
    *refs,
    pointwise: list[PointwiseOp],
    stencil: StencilOp,
    n_in: int,
    n_out: int,
    block_h: int,
    nb: int,
    global_h: int,
    global_w: int,
    ghosts: bool = False,
    image_h: int | None = None,
):
    """Packed twin of pallas_kernels._stream_kernel. The vertical streaming
    structure — one lagged column pass over row-passed carries, with the
    ragged-last-block beyond-row fixes — is shared via _assemble_ext /
    _top_strip; only the refs' word layout and the lane-space row pass
    differ. Sharded ghost mode mirrors the u8 kernel's: a leading SMEM y0
    scalar plus two packed (halo, Wp) ghost-strip refs per input plane,
    row-passed once into dedicated scratch at the first emit step;
    beyond-tile rows come from the bottom strip, and the interior mask
    follows global coordinates y0 + j*block_h against image_h."""
    h = stencil.halo
    mode = stencil.edge_mode

    if ghosts:
        y0_ref = refs[0]
        in_refs = refs[1 : 1 + n_in]
        top_refs = refs[1 + n_in : 1 + 2 * n_in]
        bot_refs = refs[1 + 2 * n_in : 1 + 3 * n_in]
        out_refs = refs[1 + 3 * n_in : 1 + 3 * n_in + n_out]
        scratch = refs[1 + 3 * n_in + n_out :]  # (main, tail, tscr, bscr)/plane
        per_plane = 4
    else:
        in_refs = refs[:n_in]
        out_refs = refs[n_in : n_in + n_out]
        scratch = refs[n_in + n_out :]  # (main, tail) per output plane
        per_plane = 2

    i = pl.program_id(0)
    j = i - 1  # output block index computed this step

    def run_pointwise(rs):
        planes = [_unpack_concat_f32(r[:]) for r in rs]
        for op in pointwise:
            planes = _apply_pointwise_planes(op, planes)
        return planes

    planes = run_pointwise(in_refs)
    assert len(planes) == n_out

    # Separable ops keep the u8 path's column pass verbatim (it only
    # slices rows, so lane-concat columns flow through untouched) with a
    # lane-space row pass; non-separable ops carry raw lane-concat rows
    # and do the whole 2-D correlation / median network in the lane-space
    # column pass.
    if stencil.reduce in ("min", "max"):
        red_fn = jnp.minimum if stencil.reduce == "min" else jnp.maximum
        kw = stencil.kernels[0].shape[1]
        row_pass = partial(_row_reduce_packed, kw=kw, h=h, mode=mode, fn=red_fn)
        _, col_pass, _, _ = _split_passes(stencil, global_w)
    elif stencil.separable is not None:
        w1d = np.asarray(stencil.separable, dtype=np.float32).reshape(-1)
        row_pass = partial(_row_corr_packed, w1d=w1d, h=h, mode=mode)
        _, col_pass, _, _ = _split_passes(stencil, global_w)
    else:
        row_pass = lambda x: x  # noqa: E731 — raw lane-concat carry
        col_pass = _make_col2d_packed(stencil, global_w)

    if ghosts:
        # the strips never change across the grid: pointwise + row-pass
        # them once into dedicated scratch at the first emit step
        @pl.when(i == 1)
        def _():
            tops = run_pointwise(top_refs)
            bots = run_pointwise(bot_refs)
            for p_idx in range(n_out):
                scratch[per_plane * p_idx + 2][:] = row_pass(tops[p_idx])
                scratch[per_plane * p_idx + 3][:] = row_pass(bots[p_idx])

    # last-block geometry (static) — see _stream_kernel
    r1 = (global_h - 1) - (nb - 1) * block_h
    a = min(r1 + 1, block_h)
    nfix = min(h, block_h - a)

    for p_idx, x in enumerate(planes):
        main_ref = scratch[per_plane * p_idx]
        tail_ref = scratch[per_plane * p_idx + 1]
        rp = row_pass(x)

        @pl.when(i >= 1)
        def _(rp=rp, main_ref=main_ref, tail_ref=tail_ref, p_idx=p_idx):
            main = main_ref[:]
            if ghosts:
                first_top = scratch[per_plane * p_idx + 2][:]
                bscr = scratch[per_plane * p_idx + 3][:]
            else:
                first_top = _top_strip(main, h, mode)
            top = jnp.where(j == 0, first_top, tail_ref[:])

            if ghosts:

                def beyond(t, bscr=bscr):
                    # tile row H + t is strip row t; rows past the strip
                    # feed only cropped outputs, so the clamp is safe
                    c = min(t, h - 1)
                    return bscr[c : c + 1]

                beyond_pen = beyond
            else:

                def beyond(t):
                    # identical to _stream_kernel's full-image beyond():
                    # the row-pass row holding the edge extension of image
                    # row H + t, sourced at a static offset from the last
                    # block
                    if mode == "reflect101":
                        gp = 2 * (global_h - 1) - (global_h + t)
                    else:  # edge
                        gp = global_h - 1
                    p = min(max(gp - (nb - 1) * block_h, -h), block_h - 1)
                    if p >= 0:
                        return main[p : p + 1]
                    return top[h + p : h + p + 1]

                def beyond_pen(t):
                    p = (r1 - 1 - t) if mode == "reflect101" else r1
                    if p >= 0:
                        return rp[p : p + 1]
                    return main[block_h + p : block_h + p + 1]

            ext = _assemble_ext(
                j, top, main, rp, beyond, beyond_pen,
                nb=nb, bh=block_h, h=h, a=a, nfix=nfix,
                # full-image interior mode: the mask passes through exactly
                # the outputs whose windows could touch garbage rows (same
                # reasoning as the u8 kernel). In ghost mode the
                # beyond-tile rows are real neighbour data — always fixed.
                skip_fixes=(mode == "interior" and not ghosts),
            )
            q = QUANTIZERS_F32[stencil.quantize](col_pass(ext))
            if mode == "interior":
                # orig passthrough: `main` is the raw lane-concat carry
                # (interior stencils are non-separable -> identity row
                # pass), exactly the block being emitted
                base = (
                    y0_ref[0] + j * block_h if ghosts else j * block_h
                )
                mask = _interior_mask_lanes(
                    stencil,
                    block_h,
                    global_w,
                    base,
                    image_h if ghosts else global_h,
                )
                q = jnp.where(mask, q, main)
            out_refs[p_idx][:] = _pack_concat_i32(q)

        tail_ref[:] = main_ref[block_h - h :]
        main_ref[:] = rp


# --------------------------------------------------------------------------
# Group runner
# --------------------------------------------------------------------------


def run_group_packed(
    pointwise: list[PointwiseOp],
    stencil: StencilOp | None,
    planes: list[jnp.ndarray],
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
    ghosts: tuple[list[jnp.ndarray], list[jnp.ndarray]] | None = None,
    y0=None,
    image_h: int | None = None,
) -> list[jnp.ndarray]:
    """Packed twin of pallas_kernels.run_group. Takes/returns u8 planes —
    the i32 word views are bitcasts at the call boundary (see
    run_group_packed_words to keep words across consecutive groups).
    Caller must have checked packed_supported. `ghosts=(tops, bots)`
    switches to sharded ghost mode (raw pre-pointwise (halo, W) u8 strips
    per input plane, packed at the boundary like the tiles; requires a
    stencil and `y0` + `image_h` for interior masks)."""
    height, width = planes[0].shape
    gw = None
    if ghosts is not None:
        tops, bots = ghosts
        gw = ([pack_words(t) for t in tops], [pack_words(b) for b in bots])
    outs = run_group_packed_words(
        pointwise,
        stencil,
        [pack_words(p) for p in planes],
        height,
        width,
        interpret=interpret,
        block_h=block_h,
        ghosts=gw,
        y0=y0,
        image_h=image_h,
    )
    return [unpack_words(o, width) for o in outs]


def run_group_packed_words(
    pointwise: list[PointwiseOp],
    stencil: StencilOp | None,
    words: list[jnp.ndarray],
    height: int,
    width: int,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
    ghosts: tuple[list[jnp.ndarray], list[jnp.ndarray]] | None = None,
    y0=None,
    image_h: int | None = None,
) -> list[jnp.ndarray]:
    """Word-level packed group runner: takes and returns (H, W/4) i32 word
    planes. On TPU the u8<->u32 view is a real copy (different tilings), so
    pipeline_pallas keeps consecutive eligible groups in word form and only
    converts at the run's ends."""
    Wp = width // 4
    n_in = len(words)
    n_out = _channels_after(pointwise, n_in)
    h = stencil.halo if stencil is not None else 0
    if stencil is not None and height <= h:
        raise ValueError(f"image height {height} too small for halo {h}")
    # word blocks are Wp i32 columns = width bytes/row, same as the u8
    # path's working set; reuse its VMEM heuristic unchanged
    bh = block_h or _pick_block_h(
        width, n_in, n_out, h, _live_f32_temps(stencil), impl="packed"
    )
    if interpret is None:
        interpret = not is_tpu_backend()

    if stencil is None:
        grid = (-(-height // bh),)
        outs = pl.pallas_call(
            partial(
                _pointwise_kernel_packed,
                pointwise=pointwise,
                n_in=n_in,
                n_out=n_out,
            ),
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh, Wp), lambda i: (i, 0), memory_space=pltpu.VMEM)
                for _ in range(n_in)
            ],
            out_specs=[
                pl.BlockSpec((bh, Wp), lambda i: (i, 0), memory_space=pltpu.VMEM)
                for _ in range(n_out)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((height, Wp), I32) for _ in range(n_out)
            ],
            interpret=interpret,
            compiler_params=_COMPILER_PARAMS,
        )(*words)
        outs = outs if isinstance(outs, (tuple, list)) else [outs]
        return list(outs)

    if 2 * h > bh:
        raise ValueError(f"block_h {bh} too small for halo {h}")

    nb = -(-height // bh)
    padded_h = nb * bh
    kernel = partial(
        _stream_kernel_packed,
        pointwise=pointwise,
        stencil=stencil,
        n_in=n_in,
        n_out=n_out,
        block_h=bh,
        nb=nb,
        global_h=height,
        global_w=width,
        ghosts=ghosts is not None,
        image_h=image_h,
    )
    per_plane_scratch = 2 if ghosts is None else 4
    scratch_shapes = []
    for _ in range(n_out):
        scratch_shapes.append(pltpu.VMEM((bh, width), F32))  # main (lane-concat)
        scratch_shapes.append(pltpu.VMEM((h, width), F32))  # tail
        if per_plane_scratch == 4:
            scratch_shapes.append(pltpu.VMEM((h, width), F32))  # top rp
            scratch_shapes.append(pltpu.VMEM((h, width), F32))  # bot rp
    in_specs = [
        pl.BlockSpec(
            (bh, Wp),
            partial(lambda i, n: (jnp.minimum(i, n - 1), 0), n=nb),
            memory_space=pltpu.VMEM,
        )
        for _ in range(n_in)
    ]
    args = list(words)
    if ghosts is not None:
        tops, bots = ghosts
        strip_spec = pl.BlockSpec(
            (h, Wp), lambda i: (0, 0), memory_space=pltpu.VMEM
        )
        in_specs = (
            [pl.BlockSpec(memory_space=pltpu.SMEM)]
            + in_specs
            + [strip_spec] * (2 * n_in)
        )
        args = (
            [jnp.asarray(y0, jnp.int32).reshape(1)]
            + args
            + list(tops)  # already word planes (packed by the wrapper)
            + list(bots)
        )
    outs = pl.pallas_call(
        kernel,
        grid=(nb + 1,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (bh, Wp),
                lambda i: (jnp.maximum(i - 1, 0), 0),
                memory_space=pltpu.VMEM,
            )
            for _ in range(n_out)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded_h, Wp), I32) for _ in range(n_out)
        ],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(*args)
    outs = outs if isinstance(outs, (tuple, list)) else [outs]
    return [o[:height] for o in outs]


def pipeline_packed(ops, img, *, interpret=None, block_h=None):
    """Archival pipeline runner for the demoted packed backend: the word-
    carrying group loop that used to live inside pipeline_pallas
    (packed=True), preserved so tests/test_packed.py and the A/B tools can
    still drive the kernels end-to-end. Groups `packed_supported` rejects
    fall back to the u8 streaming path, exactly as production did."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        group_ops,
        run_group,
    )

    if img.ndim == 3:
        planes = [img[..., c] for c in range(img.shape[2])]
    else:
        planes = [img]
    words = None  # non-None: planes currently live as packed i32 words
    height = width = None
    for pointwise, stencil in group_ops(ops):
        if words is None:
            height, width = planes[0].shape
        if packed_supported(pointwise, stencil, width):
            # consecutive eligible groups stay in word form (the u8<->u32
            # view is a real copy on TPU — different tilings)
            if words is None:
                words = [pack_words(p) for p in planes]
            words = run_group_packed_words(
                pointwise, stencil, words, height, width,
                interpret=interpret, block_h=block_h,
            )
            continue
        if words is not None:
            planes = [unpack_words(w, width) for w in words]
            words = None
        planes = run_group(
            pointwise, stencil, planes, interpret=interpret, block_h=block_h
        )
    if words is not None:
        planes = [unpack_words(w, width) for w in words]
    if len(planes) == 1:
        return planes[0]
    return jnp.stack(planes, axis=-1)
