#!/bin/bash
# Round-3 continuous TPU capture watcher (VERDICT r2 directive #3: capture
# must be continuous from round start and commit results the moment it has
# them, not an end-of-round batch job).
#
# Design: tools/tpu_queue/ holds numbered step scripts ([0-9]*.sh), each
# self-contained — runs one serialized chip campaign under its own timeout
# and commits its own artifacts (pathspec commits via _lib.sh). The watcher
# probes the tunnel every 4 min; in a healthy window it drains the queue in
# lexical order, renaming each completed step to .done (kept for the
# record). A failed step keeps its place; the watcher re-probes after the
# failure and the try only counts against the step's 3-try budget if the
# tunnel was still healthy (a mid-step wedge is the tunnel's fault, not the
# step's). After 3 healthy-tunnel failures the step is parked as .failed.
# New steps can be queued mid-round (e.g. re-bench after a kernel
# promotion) by dropping a new NN_name.sh in the directory — the watcher
# never exits while the round runs.
#
# Chip access stays serialized: ALL on-chip work this round goes through
# this queue (concurrent clients are a suspected wedge trigger; see
# BASELINE.md's measurement notes and VERDICT.md round 2). Probe timeout
# is 480s: cold backend init over the tunnel has taken up to ~10 min, and
# a shorter timeout would kill a would-be-successful probe mid-RPC — the
# suspected wedge trigger — exactly when the tunnel is trying to recover.
set -u
cd "$(dirname "$0")/.."
LOG=tools/tpu_window.log
QUEUE=tools/tpu_queue
PIDFILE=tools/tpu_window.pid
log() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

# single-instance guard: two watchers means two concurrent TPU clients —
# the exact wedge trigger this script exists to avoid
if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
  echo "watcher already running (pid $(cat "$PIDFILE")); exiting" >&2
  exit 3
fi
echo $$ > "$PIDFILE"
trap 'rm -f "$PIDFILE"' EXIT

# a cpu pin inherited from a test/soak shell would probe cpu forever
unset JAX_PLATFORMS

# rc 0 = healthy, 2 = env pinned to cpu (fatal), else wedged
probe() {
  timeout 480 python -c "
import sys
import jax, jax.numpy as jnp
if jax.default_backend() == 'cpu':
    print('MISCONFIG: backend resolved to cpu (no accelerator plugin '
          'registered in this env)', flush=True)
    sys.exit(2)
float(jnp.sum(jnp.arange(64.0)))
print('HEALTHY', flush=True)" >> "$LOG" 2>&1
}

log "watcher r3 start pid=$$"
while true; do
  next=$(ls "$QUEUE"/[0-9]*.sh 2>/dev/null | head -1)
  if [ -z "$next" ]; then
    log "queue empty; sleeping 600s"
    sleep 600
    continue
  fi
  probe
  rc=$?
  if [ "$rc" -eq 2 ]; then
    log "environment pinned to cpu — fix the env and re-run; exiting"
    exit 2
  fi
  if [ "$rc" -ne 0 ]; then
    log "probe failed rc=$rc; sleeping 240s"
    sleep 240
    continue
  fi
  log "healthy window; draining queue"
  while next=$(ls "$QUEUE"/[0-9]*.sh 2>/dev/null | head -1); [ -n "$next" ]; do
    name=$(basename "$next")
    tries_f="$next.tries"
    tries=$(( $(cat "$tries_f" 2>/dev/null || echo 0) + 1 ))
    echo "$tries" > "$tries_f"
    log "step $name start (try $tries)"
    bash "$next" >> "$LOG" 2>&1
    rc=$?
    log "step $name rc=$rc"
    if [ "$rc" -eq 0 ]; then
      mv "$next" "${next%.sh}.done"
      rm -f "$tries_f"
      continue
    fi
    # failed: was it the step or the tunnel? only a healthy-tunnel failure
    # counts against the try budget
    if probe; then
      if [ "$tries" -ge 3 ]; then
        log "step $name parked after $tries healthy-tunnel failures"
        mv "$next" "${next%.sh}.failed"
        rm -f "$tries_f"
        continue
      fi
      log "step $name failed on a healthy tunnel (try $tries counted)"
    else
      echo $((tries - 1)) > "$tries_f"
      log "step $name failed during a tunnel wedge; try not counted"
    fi
    break
  done
  log "window pass done; sleeping 240s"
  sleep 240
done
