#!/bin/bash
# Wait for a healthy TPU-tunnel window, then capture the round's pending
# measurements back-to-back (serialized — concurrent clients are a
# suspected wedge trigger on this relay):
#   1. tools/roofline_probe.py  -> roofline_r02.out
#   2. bench.py                 -> bench_manual.out (+ BENCH_HISTORY.jsonl)
# Logs to tools/tpu_window.log. Safe to re-run; exits after one capture.
#
# Probe attempts are spaced 4 min apart and each probe distinguishes a
# wedged tunnel (hang -> timeout kill) from an env pinned to cpu (exit 2,
# watcher stops immediately with a diagnosis instead of burning the retry
# budget). Timeout-killed probes are unavoidable for health checks; the
# long spacing keeps mid-RPC kills rare.
set -u
cd "$(dirname "$0")/.."
LOG=tools/tpu_window.log
log() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

# the accelerator plugin must be reachable for this watcher to make sense;
# a cpu pin inherited from a test/soak shell would probe cpu forever
unset JAX_PLATFORMS

log "watcher start pid=$$"
for attempt in $(seq 1 60); do
  timeout 150 python -c "
import sys
import jax, jax.numpy as jnp
if jax.default_backend() == 'cpu':
    print('MISCONFIG: backend resolved to cpu (no accelerator plugin '
          'registered in this env)', flush=True)
    sys.exit(2)
float(jnp.sum(jnp.arange(64.0)))
print('HEALTHY', flush=True)" >> "$LOG" 2>&1
  rc=$?
  if [ "$rc" -eq 0 ]; then
    log "healthy window found (attempt $attempt); running roofline probe"
    timeout 2400 python tools/roofline_probe.py > roofline_r02.out 2>&1
    log "roofline probe rc=$? ; running bench.py"
    timeout 5400 python bench.py > bench_manual.out 2>&1
    log "bench.py rc=$? ; capturing headline profiler trace"
    timeout 300 python -c "
from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image, save_image
save_image('/tmp/mcim_8k.pgm', synthetic_image(4320, 7680, channels=1, seed=5))" \
      >> "$LOG" 2>&1
    log "image save rc=$?"
    timeout 900 python -m mpi_cuda_imagemanipulation_tpu run \
      --input /tmp/mcim_8k.pgm --output /tmp/mcim_8k_out.pgm \
      --ops gaussian:5 --impl pallas --profile-dir profile_r02 \
      --show-timing >> "$LOG" 2>&1
    log "profile capture rc=$? ; running packed A/B"
    timeout 900 python tools/packed_ab.py > packed_ab.out 2>&1
    log "packed A/B rc=$? ; done"
    exit 0
  fi
  if [ "$rc" -eq 2 ]; then
    log "environment pinned to cpu — fix the env and re-run; exiting"
    exit 2
  fi
  log "probe attempt $attempt failed rc=$rc; sleeping 240s"
  sleep 240
done
log "gave up after 60 attempts"
exit 1
