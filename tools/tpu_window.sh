#!/bin/bash
# Wait for a healthy TPU-tunnel window, then capture the round's pending
# measurements back-to-back (serialized — concurrent clients and killed
# mid-RPC processes are suspected wedge triggers on this relay):
#   1. tools/roofline_probe.py  -> roofline_r02.out
#   2. bench.py                 -> bench_manual.out (+ BENCH_HISTORY.jsonl)
# Logs to tools/tpu_window.log. Safe to re-run; exits after one capture.
set -u
cd "$(dirname "$0")/.."
LOG=tools/tpu_window.log
log() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

log "watcher start pid=$$"
for attempt in $(seq 1 120); do
  if timeout 150 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() in ('tpu', 'axon')
float(jnp.sum(jnp.arange(64.0)))
print('HEALTHY')" >> "$LOG" 2>&1; then
    log "healthy window found (attempt $attempt); running roofline probe"
    timeout 2400 python tools/roofline_probe.py > roofline_r02.out 2>&1
    log "roofline probe rc=$? ; running bench.py"
    timeout 5400 python bench.py > bench_manual.out 2>&1
    log "bench.py rc=$? ; done"
    exit 0
  fi
  log "probe attempt $attempt failed; sleeping 180s"
  sleep 180
done
log "gave up after 120 attempts"
exit 1
