"""Measure the chip's *achievable* streaming bandwidth ceilings (run on TPU).

BASELINE.md's roofline fraction divides the streaming kernels' modeled HBM
traffic by the v5e datasheet peak (819 GB/s). Observed throughput pins near
~92 GB/s effective regardless of compute variant or block height, so the
open question is what ceiling this chip/access pattern actually supports:

  a) XLA device copy of the same u8 array        (upper bound, XLA's own DMA)
  b) Pallas streaming copy, u8, several block_h  (our kernels' structure)
  c) Pallas streaming copy, f32                  (is the cap byte-based?)
  d) the headline gaussian5 kernel               (for the same-run contrast)

Writes one JSON line per measurement; commit the results into BASELINE.md's
analysis. Usage:  python tools/roofline_probe.py [--quick]

PRODUCTION FOLD (PR 15): the probe's question — measured traffic vs the
analytical model — now rides every bench record via obs/cost: run_config
emits `hbm_gb_s_measured`/`roofline_frac_measured` from the compiled
executable's own cost_analysis (tools/bench_regress.py tracks the
series), and the per-stage boundary drift gate (`mcim_cost_model_drift_
ratio`) checks the one-read-one-write model continuously. This probe
stays as the raw copy-kernel CEILING instrument (achievable-bandwidth
cases XLA's cost model cannot answer); use obs/cost for everything that
was "run the probe to sanity-check a bench claim".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# this probe compares fixed kernel configurations; a committed autotune
# calibration steering block heights would contaminate the cross-case story
os.environ.setdefault("MCIM_NO_CALIB", "1")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--rounds",
        type=int,
        default=None,
        help="measure every case this many times in round-robin order and "
        "report per-case bests — cross-case comparisons on the shared "
        "tunneled chip are otherwise contaminated by multi-second "
        "other-tenant load drifts (observed 4.7x swings between adjacent "
        "single-shot cases in round 3's first window). Default 3, or 1 "
        "with --quick.",
    )
    args = ap.parse_args()
    n_rounds = args.rounds if args.rounds else (1 if args.quick else 3)

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _COMPILER_PARAMS,
        pipeline_pallas,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

    H, W = 4320, 7680
    img_u8 = jnp.asarray(synthetic_image(H, W, channels=1, seed=99))
    img_f32 = img_u8.astype(jnp.float32)
    print(f"backend: {jax.default_backend()}", flush=True)

    def emit(rec):
        print(json.dumps(rec), flush=True)

    # Registration: every case is built (compiled lazily on first call) up
    # front, then ALL cases are measured --rounds times in round-robin
    # order with per-case bests reported at the end. Keys starting with
    # "_" are measurement parameters, not record fields.
    cases: list[tuple[dict, object, list]] = []

    def register(base, fn, fn_args):
        cases.append((base, fn, fn_args))

    def copy_call(dtype, bh, width=None):
        w = W if width is None else width

        def copy_kernel(in_ref, out_ref):
            out_ref[:] = in_ref[:]

        return pl.pallas_call(
            copy_kernel,
            grid=(-(-H // bh),),
            in_specs=[
                pl.BlockSpec((bh, w), lambda i: (i, 0), memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec(
                (bh, w), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((H, w), dtype),
            compiler_params=_COMPILER_PARAMS,
        )

    # a) XLA's own device copy (copy = x + 0 defeats aliasing elision)
    for name, arr, bpe in (("xla_copy_u8", img_u8, 1), ("xla_copy_f32", img_f32, 4)):
        f = jax.jit(lambda x: x + jnp.zeros((), x.dtype))
        register({"case": name, "_nbytes": 2 * H * W * bpe}, f, [arr])

    # packed view: the same bytes as img_u8 but 1/4 the elements — if the
    # u8 cap is element-rate (not byte-rate), the u32 copy moves the image
    # ~4x faster and a packed-load kernel redesign pays off
    img_u32 = jax.lax.bitcast_convert_type(
        img_u8.reshape(H, W // 4, 4), jnp.uint32
    ).reshape(H, W // 4)

    # b/c) Pallas streaming copies
    bhs = (128,) if args.quick else (64, 128, 256, 512)
    for dtype, name, bpe in (
        (jnp.uint8, "pallas_copy_u8", 1),
        (jnp.float32, "pallas_copy_f32", 4),
        (jnp.uint32, "pallas_copy_u32_packed", 4),
    ):
        arr = img_u32 if dtype == jnp.uint32 else (img_u8 if bpe == 1 else img_f32)
        nbytes = 2 * arr.size * arr.dtype.itemsize  # one read + one write
        for bh in bhs:
            f = jax.jit(copy_call(dtype, bh, width=arr.shape[1]))
            register({"case": name, "block_h": bh, "_nbytes": nbytes}, f, [arr])

    # b2) discriminators for the first window's anomaly (roofline_r03.out:
    # u32_packed copy hit ~120 GB/s while f32 hit ~403 GB/s — both 4-byte
    # dtypes). Two confounds differ between those cases: element count
    # (packed is 4x smaller, so fixed dispatch/DMA-ramp overhead weighs 4x
    # more) and integer-vs-float dtype. Separate them:
    #   - u32 copy at FULL element count (H x W u32): same elements as the
    #     f32 case; if it matches f32's GB/s, int32 tiles stream fine and
    #     the packed case was overhead-dominated.
    #   - f32 copy at the PACKED shape (H x W/4): same size as the packed
    #     case; if it also drops to ~120 GB/s, small-array overhead (not
    #     dtype) explains the anomaly and the packed ceiling estimate must
    #     come from larger inputs.
    img_u32_full = img_u8.astype(jnp.uint32)
    img_f32_small = img_f32[:, : W // 4]
    for name, arr in (
        ("pallas_copy_u32_fullelems", img_u32_full),
        ("pallas_copy_f32_packedsize", img_f32_small),
    ):
        nbytes = 2 * arr.size * arr.dtype.itemsize
        f = jax.jit(copy_call(arr.dtype, 128, width=arr.shape[1]))
        register({"case": name, "block_h": 128, "_nbytes": nbytes}, f, [arr])

    # d) lagged copy through VMEM scratch: the streaming kernels' exact
    # grid/dependency structure (out block j written at step j+1 from a
    # scratch carried across steps) with zero stencil compute — isolates
    # whether the carry structure itself, not the VPU work, sets the cap
    def lagged_copy_call(bh):
        nb = -(-H // bh)

        def kernel(in_ref, out_ref, scr_ref):
            i = pl.program_id(0)

            @pl.when(i >= 1)
            def _():
                out_ref[:] = scr_ref[:]

            scr_ref[:] = in_ref[:]

        return pl.pallas_call(
            kernel,
            grid=(nb + 1,),
            in_specs=[
                pl.BlockSpec(
                    (bh, W),
                    lambda i, n=nb: (jnp.minimum(i, n - 1), 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (bh, W), lambda i: (jnp.maximum(i - 1, 0), 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((nb * bh, W), jnp.uint8),
            scratch_shapes=[pltpu.VMEM((bh, W), jnp.uint8)],
            compiler_params=_COMPILER_PARAMS,
        )

    for bh in bhs[:2]:
        f = jax.jit(lambda x, bh=bh: lagged_copy_call(bh)(x)[:H])
        register(
            {"case": "pallas_lagged_copy_u8", "block_h": bh,
             "_nbytes": 2 * H * W},
            f, [img_u8],
        )

    # e) the XLA-level u8<->u32 bitcast views the (now-demoted) packed
    # path used at group boundaries (tools/packed_kernels.pack_words): on
    # TPU the tilings differ ((32,128) u8 vs (8,128) u32), so this may
    # compile to a real copy — its cost decides whether wide-word
    # pipelines should keep words end-to-end between groups
    from tools.packed_kernels import pack_words, unpack_words

    for name, f, arg in (
        ("xla_pack_bitcast", jax.jit(pack_words), img_u8),
        (
            "xla_unpack_bitcast",
            jax.jit(lambda w: unpack_words(w, W)),
            jax.jit(pack_words)(img_u8),
        ),
    ):
        register({"case": name, "_nbytes": 2 * H * W}, f, [arg])

    # f) in-kernel pltpu.bitcast (sublane repack, HBM stays u8): if the u8
    # cap is the vector load/store path rather than the DMA, a kernel that
    # loads u8 and stores u32 (or vice versa) isolates which direction pays
    def bitcast_store_call(bh):
        def kernel(in_ref, out_ref):
            out_ref[:] = pltpu.bitcast(in_ref[:], jnp.uint32)

        return pl.pallas_call(
            kernel,
            grid=(-(-H // bh),),
            in_specs=[pl.BlockSpec((bh, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((bh // 4, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((H // 4, W), jnp.uint32),
            compiler_params=_COMPILER_PARAMS,
        )

    def bitcast_load_call(bh):
        def kernel(in_ref, out_ref):
            out_ref[:] = pltpu.bitcast(in_ref[:], jnp.uint8)

        return pl.pallas_call(
            kernel,
            grid=(-(-(H // 4) // bh),),
            in_specs=[pl.BlockSpec((bh, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((4 * bh, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((H, W), jnp.uint8),
            compiler_params=_COMPILER_PARAMS,
        )

    for name, make, arg_builder in (
        ("pallas_u8load_u32store_bitcast", bitcast_store_call,
         lambda: img_u8),
        ("pallas_u32load_u8store_bitcast", bitcast_load_call,
         lambda: jax.jit(lambda x: bitcast_store_call(128)(x))(img_u8)),
    ):
        for bh in (128,):
            try:
                arg = arg_builder()
            except Exception as e:
                emit({"case": name, "block_h": bh, "error": str(e)[:200]})
                continue
            register(
                {"case": name, "block_h": bh, "_nbytes": 2 * H * W},
                jax.jit(make(bh)), [arg],
            )

    # g) the headline kernel in the same process/chip state, u8 and the
    # archived packed variant (tools/packed_kernels.pipeline_packed)
    from tools.packed_kernels import pipeline_packed

    ops = make_pipeline_ops("gaussian:5")
    for name, runner in (("gaussian5_8k_pallas", pipeline_pallas),
                         ("gaussian5_8k_packed", pipeline_packed)):
        f = jax.jit(lambda x, r=runner: r(ops, x))
        register(
            {"case": name, "_nbytes": 2 * H * W, "_mp": H * W},
            f, [img_u8],
        )

    # measurement: round-robin over every registered case so each case
    # samples the chip across the full probe duration; a case is skipped
    # for the rest of the run only after two failures (a compile error is
    # persistent, but a transient tunnel hiccup deserves a free retry next
    # round — losing a case loses a cross-case comparison, the probe's
    # whole point). Per-case best (min time — the right statistic under
    # other-tenant contention, each sample already being a
    # median-of-slopes) is emitted at the end.
    best: dict[tuple, tuple[float, dict]] = {}
    failures: dict[tuple, int] = {}
    successes: dict[tuple, int] = {}
    for rnd in range(1, max(1, n_rounds) + 1):
        for base, fn, fn_args in cases:
            key = (base["case"], base.get("block_h"))
            if failures.get(key, 0) >= 2:
                continue
            pub = {k: v for k, v in base.items() if not k.startswith("_")}
            try:
                sec = device_throughput(fn, fn_args)
            except Exception as e:
                failures[key] = failures.get(key, 0) + 1
                emit({**pub, "round": rnd, "error": str(e)[:200]})
                continue
            rec = {**pub, "round": rnd, "ms": sec * 1e3,
                   "gb_s": base["_nbytes"] / sec / 1e9}
            if "_mp" in base:
                rec["mp_s"] = base["_mp"] / 1e6 / sec
            emit(rec)
            successes[key] = successes.get(key, 0) + 1
            if key not in best or sec < best[key][0]:
                best[key] = (sec, rec)
    for key, (sec, rec) in best.items():
        summary = {k: v for k, v in rec.items() if k != "round"}
        # label with the ACTUAL sample count, not the requested rounds — a
        # case that failed some rounds has lower-confidence bests and the
        # committed evidence must say so
        summary["stat"] = f"best_of_{successes[key]}_rounds"
        emit(summary)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
