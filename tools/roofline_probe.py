"""Measure the chip's *achievable* streaming bandwidth ceilings (run on TPU).

BASELINE.md's roofline fraction divides the streaming kernels' modeled HBM
traffic by the v5e datasheet peak (819 GB/s). Observed throughput pins near
~92 GB/s effective regardless of compute variant or block height, so the
open question is what ceiling this chip/access pattern actually supports:

  a) XLA device copy of the same u8 array        (upper bound, XLA's own DMA)
  b) Pallas streaming copy, u8, several block_h  (our kernels' structure)
  c) Pallas streaming copy, f32                  (is the cap byte-based?)
  d) the headline gaussian5 kernel               (for the same-run contrast)

Writes one JSON line per measurement; commit the results into BASELINE.md's
analysis. Usage:  python tools/roofline_probe.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _COMPILER_PARAMS,
        pipeline_pallas,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

    H, W = 4320, 7680
    img_u8 = jnp.asarray(synthetic_image(H, W, channels=1, seed=99))
    img_f32 = img_u8.astype(jnp.float32)
    print(f"backend: {jax.default_backend()}", flush=True)

    def emit(rec):
        print(json.dumps(rec), flush=True)

    def copy_call(dtype, bh, width=None):
        w = W if width is None else width

        def copy_kernel(in_ref, out_ref):
            out_ref[:] = in_ref[:]

        return pl.pallas_call(
            copy_kernel,
            grid=(-(-H // bh),),
            in_specs=[
                pl.BlockSpec((bh, w), lambda i: (i, 0), memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec(
                (bh, w), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((H, w), dtype),
            compiler_params=_COMPILER_PARAMS,
        )

    # a) XLA's own device copy (copy = x + 0 defeats aliasing elision)
    for name, arr, bpe in (("xla_copy_u8", img_u8, 1), ("xla_copy_f32", img_f32, 4)):
        f = jax.jit(lambda x: x + jnp.zeros((), x.dtype))
        sec = device_throughput(f, [arr])
        emit({"case": name, "ms": sec * 1e3, "gb_s": 2 * H * W * bpe / sec / 1e9})

    # packed view: the same bytes as img_u8 but 1/4 the elements — if the
    # u8 cap is element-rate (not byte-rate), the u32 copy moves the image
    # ~4x faster and a packed-load kernel redesign pays off
    img_u32 = jax.lax.bitcast_convert_type(
        img_u8.reshape(H, W // 4, 4), jnp.uint32
    ).reshape(H, W // 4)

    # b/c) Pallas streaming copies
    bhs = (128,) if args.quick else (64, 128, 256, 512)
    for dtype, name, bpe in (
        (jnp.uint8, "pallas_copy_u8", 1),
        (jnp.float32, "pallas_copy_f32", 4),
        (jnp.uint32, "pallas_copy_u32_packed", 4),
    ):
        arr = img_u32 if dtype == jnp.uint32 else (img_u8 if bpe == 1 else img_f32)
        nbytes = 2 * arr.size * arr.dtype.itemsize  # one read + one write
        for bh in bhs:
            try:
                f = jax.jit(copy_call(dtype, bh, width=arr.shape[1]))
                sec = device_throughput(f, [arr])
                emit({"case": name, "block_h": bh, "ms": sec * 1e3,
                      "gb_s": nbytes / sec / 1e9})
            except Exception as e:
                emit({"case": name, "block_h": bh, "error": str(e)[:200]})

    # d) lagged copy through VMEM scratch: the streaming kernels' exact
    # grid/dependency structure (out block j written at step j+1 from a
    # scratch carried across steps) with zero stencil compute — isolates
    # whether the carry structure itself, not the VPU work, sets the cap
    def lagged_copy_call(bh):
        nb = -(-H // bh)

        def kernel(in_ref, out_ref, scr_ref):
            i = pl.program_id(0)

            @pl.when(i >= 1)
            def _():
                out_ref[:] = scr_ref[:]

            scr_ref[:] = in_ref[:]

        return pl.pallas_call(
            kernel,
            grid=(nb + 1,),
            in_specs=[
                pl.BlockSpec(
                    (bh, W),
                    lambda i, n=nb: (jnp.minimum(i, n - 1), 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (bh, W), lambda i: (jnp.maximum(i - 1, 0), 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((nb * bh, W), jnp.uint8),
            scratch_shapes=[pltpu.VMEM((bh, W), jnp.uint8)],
            compiler_params=_COMPILER_PARAMS,
        )

    for bh in bhs[:2]:
        try:
            f = jax.jit(lambda x, bh=bh: lagged_copy_call(bh)(x)[:H])
            sec = device_throughput(f, [img_u8])
            emit({"case": "pallas_lagged_copy_u8", "block_h": bh,
                  "ms": sec * 1e3, "gb_s": 2 * H * W / sec / 1e9})
        except Exception as e:
            emit({"case": "pallas_lagged_copy_u8", "block_h": bh,
                  "error": str(e)[:200]})

    # e) the XLA-level u8<->u32 bitcast views the packed production path
    # uses at group boundaries (ops/packed_kernels.pack_words): on TPU the
    # tilings differ ((32,128) u8 vs (8,128) u32), so this may compile to
    # a real copy — its cost decides whether packed pipelines should keep
    # words end-to-end between groups
    from mpi_cuda_imagemanipulation_tpu.ops.packed_kernels import (
        pack_words,
        unpack_words,
    )

    for name, f, arg in (
        ("xla_pack_bitcast", jax.jit(pack_words), img_u8),
        (
            "xla_unpack_bitcast",
            jax.jit(lambda w: unpack_words(w, W)),
            jax.jit(pack_words)(img_u8),
        ),
    ):
        try:
            sec = device_throughput(f, [arg])
            emit({"case": name, "ms": sec * 1e3,
                  "gb_s": 2 * H * W / sec / 1e9})
        except Exception as e:
            emit({"case": name, "error": str(e)[:200]})

    # f) in-kernel pltpu.bitcast (sublane repack, HBM stays u8): if the u8
    # cap is the vector load/store path rather than the DMA, a kernel that
    # loads u8 and stores u32 (or vice versa) isolates which direction pays
    def bitcast_store_call(bh):
        def kernel(in_ref, out_ref):
            out_ref[:] = pltpu.bitcast(in_ref[:], jnp.uint32)

        return pl.pallas_call(
            kernel,
            grid=(-(-H // bh),),
            in_specs=[pl.BlockSpec((bh, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((bh // 4, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((H // 4, W), jnp.uint32),
            compiler_params=_COMPILER_PARAMS,
        )

    def bitcast_load_call(bh):
        def kernel(in_ref, out_ref):
            out_ref[:] = pltpu.bitcast(in_ref[:], jnp.uint8)

        return pl.pallas_call(
            kernel,
            grid=(-(-(H // 4) // bh),),
            in_specs=[pl.BlockSpec((bh, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((4 * bh, W), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((H, W), jnp.uint8),
            compiler_params=_COMPILER_PARAMS,
        )

    for name, make, arg_builder in (
        ("pallas_u8load_u32store_bitcast", bitcast_store_call,
         lambda: img_u8),
        ("pallas_u32load_u8store_bitcast", bitcast_load_call,
         lambda: jax.jit(lambda x: bitcast_store_call(128)(x))(img_u8)),
    ):
        for bh in (128,):
            try:
                arg = arg_builder()
                f = jax.jit(make(bh))
                sec = device_throughput(f, [arg])
                emit({"case": name, "block_h": bh, "ms": sec * 1e3,
                      "gb_s": 2 * H * W / sec / 1e9})
            except Exception as e:
                emit({"case": name, "block_h": bh, "error": str(e)[:200]})

    # g) the headline kernel in the same process/chip state, u8 and packed
    ops = make_pipeline_ops("gaussian:5")
    for name, packed in (("gaussian5_8k_pallas", False),
                         ("gaussian5_8k_packed", True)):
        try:
            f = jax.jit(lambda x, p=packed: pipeline_pallas(ops, x, packed=p))
            sec = device_throughput(f, [img_u8])
            emit({"case": name, "ms": sec * 1e3,
                  "mp_s": H * W / 1e6 / sec, "gb_s": 2 * H * W / sec / 1e9})
        except Exception as e:
            emit({"case": name, "error": str(e)[:200]})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
