#!/usr/bin/env python
"""Pipeline-service CI smoke: a REAL pod (router + 2 replica processes)
serving registered DAG pipelines to two tenants.

    python tools/graph_smoke.py METRICS_OUT

Asserts, end to end over real HTTP:

  1. a spec registered at the FRONT DOOR (`POST /v1/pipelines`)
     broadcasts to every replica, and both replicas' heartbeats report
     the pipeline id;
  2. an unsharp-mask DAG (branch + subtract merge + histogram/stats
     side outputs) serves through the router from TWO tenants — the
     response PNG matches the in-process golden executor bit for bit
     and the X-MCIM-Histogram header matches the decoded image's
     histogram exactly;
  3. the degenerate linear-chain DAG's response is BYTE-IDENTICAL to
     the baked-in chain path for the same request (the acceptance
     contract: a chain written as a DAG is indistinguishable);
  4. the quota tenant's over-budget requests shed with 503 +
     Retry-After and are counted as SHED, not error (the federated
     mcim_graph_requests_total splits prove it);
  5. the router's /metrics parses as Prometheus exposition with the
     mcim_fabric_graph_* and federated mcim_graph_* families populated.

METRICS_OUT gets the router exposition text (uploaded as a CI artifact,
.github/workflows/tier1.yml graph step).
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from mpi_cuda_imagemanipulation_tpu.fabric.router import (  # noqa: E402
    RouterConfig,
)
from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (  # noqa: E402
    Fabric,
    FabricConfig,
)
from mpi_cuda_imagemanipulation_tpu.graph import (  # noqa: E402
    compile_graph,
    graph_callable,
    parse_spec,
)
from mpi_cuda_imagemanipulation_tpu.graph.spec import (  # noqa: E402
    chain_as_spec,
)
from mpi_cuda_imagemanipulation_tpu.io.image import (  # noqa: E402
    decode_image_bytes,
    encode_image_bytes,
    synthetic_image,
)
from mpi_cuda_imagemanipulation_tpu.obs.metrics import (  # noqa: E402
    parse_exposition,
)
from mpi_cuda_imagemanipulation_tpu.serve.bucketing import (  # noqa: E402
    parse_buckets,
)

OPS = "grayscale,contrast:3.5"
BUCKETS = "48,96"

UNSHARP = {
    "version": 1,
    "name": "unsharp",
    "nodes": [
        {"id": "src", "kind": "source"},
        {"id": "g", "kind": "op", "op": "grayscale", "input": "src"},
        {"id": "blur", "kind": "op", "op": "gaussian:5", "input": "g"},
        {"id": "mask", "kind": "merge", "merge": "subtract",
         "inputs": ["g", "blur"]},
    ],
    "outputs": {"image": "mask", "histogram": "mask", "stats": "mask"},
}


def _post(url: str, path: str, data: bytes, headers=None):
    req = urllib.request.Request(
        url + path, data=data, headers=headers or {}, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post_retry(url, path, data, headers=None, deadline_s=30.0):
    """Retry explicit sheds (503 + Retry-After) — the pod converging is
    not a failure; anything else unexpected IS."""
    t_end = time.monotonic() + deadline_s
    while True:
        code, hdrs, body = _post(url, path, data, headers)
        if code != 503 or not hdrs.get("Retry-After"):
            return code, hdrs, body
        assert time.monotonic() < t_end, "pod never converged past sheds"
        time.sleep(0.2)


def main(metrics_out: str) -> int:
    cfg = FabricConfig(
        replicas=2,
        ops=OPS,
        buckets=BUCKETS,
        channels="3",
        max_batch=4,
        queue_depth=64,
        heartbeat_s=0.2,
        router=RouterConfig(
            buckets=parse_buckets(BUCKETS), stale_s=2.0, forward_attempts=3
        ),
    )
    img = synthetic_image(40, 44, channels=3, seed=50)
    blob = encode_image_bytes(img)

    with Fabric(cfg).start() as fab:
        # both replicas must be ROUTABLE before the control-plane posts:
        # broadcasts cover the live set, re-pushes cover later joiners —
        # the smoke wants the broadcast path proven on both
        deadline = time.monotonic() + 30.0
        while (
            time.monotonic() < deadline
            and len(fab.router._routable()) < 2
        ):
            time.sleep(0.1)
        assert len(fab.router._routable()) == 2, "replicas never registered"

        # -- tenants: acme (standard), smol (batch + 3-request quota) ------
        for tenant_body in (
            {"tenant": "acme", "qos": "standard"},
            {"tenant": "smol", "qos": "batch", "quota_requests": 3,
             "window_s": 300.0},
        ):
            code, _h, out = _post(
                fab.url, "/v1/tenants", json.dumps(tenant_body).encode()
            )
            assert code == 200, (code, out[:200])
            pushed = json.loads(out)["replicas"]
            assert len(pushed) == 2 and all(
                v == 200 for v in pushed.values()
            ), pushed

        # -- 1. front-door registration broadcasts to every replica --------
        pids = {}
        for tenant in ("acme", "smol"):
            for name, spec in (
                ("unsharp", UNSHARP), ("chain", chain_as_spec(OPS)),
            ):
                code, _h, out = _post(
                    fab.url, "/v1/pipelines",
                    json.dumps({"tenant": tenant, "spec": spec}).encode(),
                )
                assert code == 200, (code, out[:300])
                reg = json.loads(out)
                assert len(reg["replicas"]) == 2 and all(
                    v == 200 for v in reg["replicas"].values()
                ), reg["replicas"]
                pids[name] = reg["pipeline"]
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            reps = fab.http_stats()["replicas"]
            # the stats view reflects the last heartbeat; both replicas
            # must report both pipelines once a post-registration beat
            # lands (Heartbeat.pipelines -> the router's re-push signal)
            beats = [
                v
                for v in fab.router.table.views()
                if set(pids.values()) <= set(v.hb.pipelines or ())
            ]
            if len(beats) == 2:
                break
            time.sleep(0.1)
        assert len(beats) == 2, (
            f"only {len(beats)} replicas report the registered pipelines"
        )
        print(
            f"smoke: both replicas report pipelines "
            f"{sorted(pids.values())} in their heartbeats "
            f"({len(reps)} replicas up)"
        )

        # -- 2. unsharp DAG from two tenants, golden + histogram ------------
        golden = np.asarray(
            graph_callable(compile_graph(parse_spec(UNSHARP)))(img)["image"]
        )
        for tenant in ("acme", "smol"):
            code, hdrs, out = _post_retry(
                fab.url, "/v1/process", blob,
                {"X-MCIM-Tenant": tenant,
                 "X-MCIM-Pipeline": pids["unsharp"]},
            )
            assert code == 200, (tenant, code, out[:200])
            got = decode_image_bytes(out)
            np.testing.assert_array_equal(got, golden)
            hist = json.loads(hdrs["X-MCIM-Histogram"])
            want = [int(v) for v in np.bincount(got.ravel(), minlength=256)]
            assert hist == want, "histogram side output mismatches"
            stats = json.loads(hdrs["X-MCIM-Stats"])
            assert stats["max"] == int(got.max()), stats
        print(
            "smoke: unsharp DAG served from both tenants through the "
            "router — image golden-exact, histogram+stats side outputs "
            "consistent"
        )

        # -- 3. linear DAG byte-identical to the chain path -----------------
        c1, _h1, chain_png = _post_retry(fab.url, "/v1/process", blob)
        c2, _h2, dag_png = _post_retry(
            fab.url, "/v1/process", blob,
            {"X-MCIM-Tenant": "acme", "X-MCIM-Pipeline": pids["chain"]},
        )
        assert (c1, c2) == (200, 200)
        assert chain_png == dag_png, (
            "linear-DAG response is not byte-identical to the chain path"
        )
        print(
            f"smoke: linear-chain DAG ({pids['chain']}) byte-identical "
            "to the --ops chain path through the fabric"
        )

        # -- 4. smol exceeds its quota: shed (503+Retry-After), not error --
        # (affinity pins (tenant, pipeline, bucket) to one replica, so
        # the per-replica quota window sees every request)
        smol_h = {"X-MCIM-Tenant": "smol", "X-MCIM-Pipeline": pids["chain"]}
        outcomes = []
        for _ in range(5):
            code, hdrs, _out = _post(fab.url, "/v1/process", blob, smol_h)
            outcomes.append((code, bool(hdrs.get("Retry-After"))))
        sheds = [o for o in outcomes if o == (503, True)]
        oks = [o for o in outcomes if o[0] == 200]
        # smol's step-2 unsharp request spent 1 of the budget IF its
        # (tenant, pipeline, bucket) affinity landed on the same replica
        # as the chain pipeline's — so 2 or 3 of the 5 admit, the rest
        # shed finally (the router must NOT reroute a quota shed to the
        # sibling, which would double the tenant's budget)
        assert len(oks) in (2, 3), outcomes
        assert len(oks) + len(sheds) == 5, outcomes
        print(
            f"smoke: smol's quota window shed {len(sheds)}/5 requests "
            "with 503 + Retry-After (explicit shed, not an error)"
        )

        # -- 5. exposition: router + federated graph families ---------------
        deadline = time.monotonic() + 30.0
        while True:
            exposition = fab.scrape()
            fams = parse_exposition(exposition)
            have_graph = "mcim_graph_requests_total" in fams
            if have_graph:
                samples = fams["mcim_graph_requests_total"]["samples"]
                shed_n = sum(
                    v for (_n, labels), v in samples.items()
                    if 'status="shed"' in labels
                )
                err_n = sum(
                    v for (_n, labels), v in samples.items()
                    if 'status="error"' in labels
                )
                if shed_n >= len(sheds):
                    break
            assert time.monotonic() < deadline, (
                "federated graph families never converged"
            )
            time.sleep(0.2)
        assert err_n == 0, f"quota sheds were miscounted as errors ({err_n})"
        for fam in (
            "mcim_fabric_graph_specs",
            "mcim_fabric_requests_total",
            "mcim_graph_pipelines",
            "mcim_graph_shed_total",
        ):
            assert fam in fams, f"{fam} missing from /metrics"
        with open(metrics_out, "w") as f:
            f.write(exposition)
        print(
            f"smoke: /metrics parses; federated graph shed={shed_n:.0f} "
            f"error={err_n:.0f} -> {metrics_out}"
        )
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(__doc__)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
