#!/usr/bin/env python
"""Driver benchmark entry point: prints ONE JSON line with the headline
metric (BASELINE.json): megapixels/sec/chip on 8K 5x5 Gaussian.

Runs the 8K 5x5 separable-Gaussian config through both backends (XLA-fused
golden ops and the Pallas fused kernel) on the available TPU chip(s) and
reports the best, relative to the estimated reference CUDA+MPI 4xV100
number (derivation in BASELINE.md — the reference publishes no numbers).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    from mpi_cuda_imagemanipulation_tpu.bench_suite import (
        HEADLINE,
        headline_record,
        run_suite,
    )

    import jax

    names = [HEADLINE]
    if len(jax.devices()) > 1:
        names.append(HEADLINE + "_sharded")
    records = run_suite(
        names=names,
        impl="both",
        printer=lambda s: print(s, file=sys.stderr),
    )
    rec = headline_record(records)
    if rec is None:
        print(json.dumps({"error": "no benchmark record produced"}))
        return 1
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
