#!/usr/bin/env python
"""Driver benchmark entry point: prints ONE JSON line with the headline
metric (BASELINE.json): megapixels/sec/chip on 8K 5x5 Gaussian.

Runs the 8K 5x5 separable-Gaussian config through both backends (XLA-fused
golden ops and the Pallas fused kernel) on the available TPU chip(s) and
reports the best, relative to the estimated reference CUDA+MPI 4xV100
number (derivation in BASELINE.md — the reference publishes no numbers).
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _probe_accelerator(timeout_s: float = 150.0) -> str:
    """Return the default backend platform ('tpu', 'cpu', ...) probed in a
    subprocess with a hard timeout, or 'wedged' on hang/failure.

    The tunnelled chip on this machine can wedge in a way that makes any
    backend call block forever (observed after a Mosaic compiler crash);
    probing in-process would hang the whole benchmark."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            return "wedged"
        return proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "wedged"
    except subprocess.TimeoutExpired:
        return "wedged"


def main() -> int:
    platform = _probe_accelerator()
    wedged = platform == "wedged"
    if wedged:
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        platform = "cpu"
        print("TPU unresponsive; falling back to CPU", file=sys.stderr)
    on_tpu = platform in ("tpu", "axon")

    from mpi_cuda_imagemanipulation_tpu.bench_suite import (
        HEADLINE,
        headline_record,
        run_suite,
    )

    import jax

    if wedged:
        jax.config.update("jax_platforms", "cpu")

    names = [HEADLINE]
    if len(jax.devices()) > 1:
        names.append(HEADLINE + "_sharded")
    records = run_suite(
        names=names,
        # off-TPU (wedged fallback, or a CPU-only host): XLA only —
        # interpret-mode Pallas on an 8K image would take longer than the
        # driver's patience
        impl="both" if on_tpu else "xla",
        printer=lambda s: print(s, file=sys.stderr),
    )
    rec = headline_record(records)
    if rec is None:
        print(json.dumps({"error": "no benchmark record produced"}))
        return 1
    if wedged:
        rec["platform"] = "cpu-fallback (TPU tunnel unresponsive)"
    elif not on_tpu:
        rec["platform"] = platform
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
