#!/usr/bin/env python
"""Driver benchmark entry point: prints ONE JSON line with the headline
metric (BASELINE.json): megapixels/sec/chip on 8K 5x5 Gaussian.

Hardened orchestrator (round-2 redesign, after round 1 lost its TPU number
to a single wedged probe): this process never imports jax — the tunnelled
TPU on this machine can wedge so that merely initializing its backend
blocks forever. All device work happens in per-config subprocesses
(`python -m mpi_cuda_imagemanipulation_tpu.bench_suite --config ... --impl
...`, each printing one JSON record), so a Mosaic crash or tunnel wedge
costs one config, not the suite. The TPU probe retries with backoff, is
re-checked after any config failure, and the CPU fallback is a labelled
last resort only after every probe attempt fails.

The reference's analogue is its self-timing (kernel.cu:190,226-232); the
vs_baseline denominator derivation is in BASELINE.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))

# per-config subprocesses share the queue steps' persistent XLA compilation
# cache (tools/tpu_queue/_lib.sh): a driver bench run after any earlier
# window skips the slow 8K compiles and measures in seconds — exactly when
# windows are scarce. Keyed on HLO + options, so results cannot change.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, "tools", ".jax_cache")
)

HEADLINE = "gaussian5_8k"  # mirrors bench_suite.HEADLINE (jax-free here)
# mirrors bench_suite.REFERENCE_BASELINE_MP_S_PER_CHIP — duplicated because
# importing bench_suite would initialize the (possibly wedged) TPU backend
# in this process; tests/test_io_cli.py asserts the two stay equal.
REFERENCE_BASELINE_MP_S_PER_CHIP = 1850.0

CONFIG_TIMEOUT_S = 900


def _cpu_only_env(env=None) -> bool:
    """True when JAX_PLATFORMS pins this process to cpu (every entry) —
    there is no TPU to wait for, so probe backoff is pure wasted wall."""
    environ = os.environ if env is None else env
    plats = (environ.get("JAX_PLATFORMS") or "").strip().lower()
    return bool(plats) and all(
        p.strip() == "cpu" for p in plats.split(",") if p.strip()
    )


def _default_probe_schedule(env=None):
    """(timeout_s, sleep_before_s) attempts. On a possibly-wedged TPU:
    four attempts spanning ~19 minutes worst case (observed round-2 wedges
    last an hour, so late attempts back off hard; first compile over the
    tunnel is slow, ~20-40 s, so even the healthy path needs a generous
    first timeout). CPU-only rounds (JAX_PLATFORMS=cpu) fail fast with a
    single attempt instead of burning the backoff tail before a committed
    record can promote."""
    if _cpu_only_env(env):
        return ((90, 0),)
    return ((90, 0), (120, 20), (180, 60), (180, 480))


def _default_retry_probe_schedule(env=None):
    if _cpu_only_env(env):
        return ((90, 0),)
    return ((90, 0), (120, 30))


def _env_schedule(var: str, default):
    """Override a probe schedule via e.g. MCIM_PROBE_SCHEDULE='10:0,20:5'
    (timeout:sleep pairs) — attempts AND sleeps are the schedule's length
    and entries, so both are configurable here. Used by tests, manual runs
    and CPU-only drivers that want something other than the defaults."""
    raw = os.environ.get(var)
    if not raw:
        return default
    return tuple(
        (float(t), float(s)) for t, s in (item.split(":") for item in raw.split(","))
    )


PROBE_SCHEDULE = _env_schedule("MCIM_PROBE_SCHEDULE", _default_probe_schedule())
RETRY_PROBE_SCHEDULE = _env_schedule(
    "MCIM_RETRY_PROBE_SCHEDULE", _default_retry_probe_schedule()
)


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def git_head_sha() -> str | None:
    """Short SHA of HEAD, or None outside a usable git checkout. Stamped
    into every history entry so a promoted committed record is attributable
    to the code that measured it (advisor round-3 finding: without commit
    identity, best-of-round promotion can mask a mid-round perf
    regression)."""
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return proc.stdout.strip() or None


# a promoted committed record measured this many commits behind HEAD gets
# a loud staleness warning: the round-5 headline was measured 9 commits
# before HEAD and nothing flagged it (ISSUE r6 satellite)
STALENESS_WARN_COMMITS = 5


def git_commits_between(measured_sha: str, head_sha: str) -> int | None:
    """Commit distance `measured_sha..head_sha` (how many commits HEAD is
    ahead of the commit that produced a measurement), or None when git
    cannot answer (shallow clone, unknown SHA, no repo)."""
    if measured_sha == head_sha:
        return 0
    try:
        proc = subprocess.run(
            ["git", "rev-list", "--count", f"{measured_sha}..{head_sha}"],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    try:
        return int(proc.stdout.strip())
    except ValueError:
        return None


def _cpu_env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize skips axon without it
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _probe_once(timeout_s: float, env: dict | None = None):
    """(platform, n_devices) via a tiny real computation in a subprocess, or
    None on hang/failure. A real reduction matters: the backend can finish
    initializing and still wedge at the first compute dispatch."""
    code = (
        "import jax, jax.numpy as jnp; "
        "b = jax.default_backend(); n = len(jax.devices()); "
        "s = float(jnp.sum(jnp.arange(64.0))); "
        "print('PROBE_OK', b, n, flush=True)"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        parts = line.split()
        if len(parts) == 3 and parts[0] == "PROBE_OK":
            return parts[1], int(parts[2])
    return None


def _probe_with_backoff(schedule) -> tuple[str, int] | None:
    for i, (timeout_s, sleep_s) in enumerate(schedule):
        if sleep_s:
            _log(f"probe: sleeping {sleep_s}s before retry")
            time.sleep(sleep_s)
        got = _probe_once(timeout_s)
        if got is not None:
            _log(f"probe: platform={got[0]} devices={got[1]}")
            return got
        _log(f"probe attempt {i + 1}/{len(schedule)} failed (timeout {timeout_s}s)")
    return None


def _run_config(name: str, impl: str, env: dict | None = None):
    """One (config, impl) in an isolated subprocess -> (record, error)."""
    cmd = [
        sys.executable,
        "-m",
        "mpi_cuda_imagemanipulation_tpu.bench_suite",
        "--config",
        name,
        "--impl",
        impl,
    ]
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd,
            timeout=CONFIG_TIMEOUT_S,
            capture_output=True,
            text=True,
            env=env,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return None, f"{name}/{impl}: timeout after {CONFIG_TIMEOUT_S}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip()[-500:]
        return None, f"{name}/{impl}: rc={proc.returncode}: {tail}"
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            _log(
                f"bench {name}/{impl}: {rec['mp_per_s_per_chip']:.0f} MP/s/chip "
                f"({time.time() - t0:.0f}s wall)"
            )
            return rec, None
    return None, f"{name}/{impl}: no JSON record in output"


def _headline(records: list[dict]) -> dict | None:
    """Best MP/s/chip over the headline configs (mirrors
    bench_suite.headline_record, kept jax-free here)."""
    cands = [r for r in records if r["config"] in (HEADLINE, HEADLINE + "_sharded")]
    if not cands:
        return None
    best = max(cands, key=lambda r: r["mp_per_s_per_chip"])
    rec = {
        "metric": "megapixels/sec/chip on 8K 5x5 Gaussian",
        "value": round(best["mp_per_s_per_chip"], 1),
        "unit": "MP/s/chip",
        "impl": best["impl"],
        "chips": best["chips"],
        "platform": best.get("platform"),
    }
    # measured-ceiling fraction leads (VERDICT r4 #7): it rests on a
    # measured same-chip reference rate, while vs_baseline divides by a
    # first-principles ESTIMATE of the reference's hardware (BASELINE.md)
    # — lead with the number that doesn't require trusting the estimate.
    # Round-5 re-basing: the roofline RR probe measured u8 COPY kernels at
    # ~550 GB/s, so this is NOT a hardware element-rate wall — it is the
    # best observed u8 compute-kernel-class rate (the kernels are
    # VPU-compute-bound; BASELINE.md round-5 section), kept as the
    # same-class measured reference point
    if "elem_ceiling_frac" in best:
        rec["ceiling_frac"] = round(best["elem_ceiling_frac"], 4)
        rec["ceiling_basis"] = (
            "measured u8 compute-kernel element rate (roofline probe; "
            "bench_suite.ELEM_G_S_MEASURED — a kernel-class reference, "
            "not a hardware wall: u8 copy measures ~550 GB/s)"
        )
    rec["vs_baseline"] = round(
        best["mp_per_s_per_chip"] / REFERENCE_BASELINE_MP_S_PER_CHIP, 2
    )
    if "roofline_frac" in best:
        rec["roofline_frac"] = round(best["roofline_frac"], 4)
        rec["tpu_gen"] = best.get("tpu_gen")
    if "elem_ceiling_frac" in best:
        rec["elem_ceiling_frac"] = round(best["elem_ceiling_frac"], 4)
    if "last_tpu_record" in best:
        rec["last_tpu_record"] = best["last_tpu_record"]
    return rec


def main() -> int:
    errors: list[str] = []
    probed = _probe_with_backoff(PROBE_SCHEDULE)
    on_tpu = probed is not None and probed[0] in ("tpu", "axon")

    records: list[dict] = []
    if on_tpu:
        # the sharded config runs even on one chip: it exercises the
        # fused-ghost shard_map path (run_group ghost mode), which is
        # the configuration that matters on a pod. The headline reports
        # whichever impl measures fastest, so the u8-vs-wide A/B rides
        # every TPU bench run ("packed" was demoted round 5 after losing
        # its A/B 4.1x — tools/packed_kernels.py).
        plan = [
            (HEADLINE, "pallas"),
            # the round-6 promotion: the MXU banded-matmul backend rides
            # every TPU bench run as a headline candidate, so a win is
            # cashed on the committed record (the headline reports
            # whichever impl measures fastest — same contract the SWAR
            # and packed A/Bs ran under)
            (HEADLINE, "mxu"),
            (HEADLINE, "swar"),
            (HEADLINE, "xla"),
            (HEADLINE + "_sharded", "pallas"),
            # the sharded swar ghost path (round 5): a SWAR win must
            # show up sharded too, per-chip parity with unsharded swar
            (HEADLINE + "_sharded", "swar"),
            # the reference's OWN benchmark pipeline as a first-class
            # record (round-5 A/B measured auto->XLA at 73.3k MP/s vs
            # 33.9k Pallas there — the routing win should be on the
            # committed record, not only in an A/B artifact)
            ("reference_pipeline_4k", "auto"),
        ]
        for name, impl in plan:
            rec, err = _run_config(name, impl)
            if rec is None:
                errors.append(err)
                _log(f"bench failed: {err}; re-probing TPU")
                # one backoff cycle + one retry: a transient wedge or a
                # single Mosaic crash should not forfeit the config
                if _probe_with_backoff(RETRY_PROBE_SCHEDULE) is not None:
                    rec, err = _run_config(name, impl)
                    if rec is None:
                        errors.append(err)
            if rec is not None:
                records.append(rec)

    # the fallback gate keys on HEADLINE-family records specifically:
    # _headline() filters to them, so a run where only a non-headline
    # config (reference_pipeline_4k) survived must still fall back or
    # main() would hand a None headline to the partial-marking code
    # (review finding)
    if not any(
        r.get("config") in (HEADLINE, HEADLINE + "_sharded") for r in records
    ):
        # preferred fallback (VERDICT r2 directive #3): a TPU headline this
        # round's watcher already measured and committed beats re-measuring
        # on CPU — the round's artifact of record should be a hardware
        # number whenever even one healthy window occurred all round
        same = _same_round_tpu_headline()
        if same is not None:
            out = _promote_committed(
                same,
                errors,
                platform_note=(
                    "same-round committed TPU record; tunnel unresponsive "
                    "at bench time"
                ),
            )
            spread = _same_round_tpu_spread(impl=out.get("impl"))
            if spread:
                out["spread"] = spread
            _log(
                "tunnel unresponsive; promoting same-round committed TPU "
                f"record from {same['ts']}"
            )
            # the append-only 'every run's records' contract: a surviving
            # non-headline record (e.g. reference_pipeline_4k) must reach
            # BENCH_HISTORY.jsonl even though the headline is promoted
            # from history (ADVICE r5 finding 1)
            if records:
                _append_history(out, records)
            print(json.dumps(out))
            return 0
        # last resort: labelled CPU number so the driver gets *a* record
        _log("no TPU records; falling back to CPU (labelled)")
        rec, err = _run_config(HEADLINE, "xla", env=_cpu_env())
        if rec is None:
            errors.append(err)
            print(json.dumps({"error": "no benchmark record produced", "errors": errors}))
            return 1
        rec["platform"] = "cpu-fallback (TPU tunnel unresponsive)"
        last_tpu = _last_tpu_headline()
        if last_tpu is not None:
            # clearly-labelled pointer to the most recent healthy-window TPU
            # measurement (committed in BENCH_HISTORY.jsonl) so a wedge at
            # the round-end run doesn't hide that a hardware number exists
            rec["last_tpu_record"] = last_tpu
        records.append(rec)

    out = _headline(records)
    if not on_tpu and records:
        out["platform"] = records[0]["platform"]
    if errors:
        out["partial"] = True
        out["errors"] = errors
    appended = _append_history(out, records)
    if on_tpu:
        fresh = out.get("value")
        fresh_impl = out.get("impl")
        out = _best_of_run_and_committed(out, errors)
        # same-round sighting spread; the fresh measurement is one of the n
        # sightings either via the entry just appended or, when the append
        # was disabled/failed, via the extra argument (carrying its impl so
        # the spread's impl filter applies to it too)
        spread = _same_round_tpu_spread(
            extra=None
            if appended
            else (
                fresh,
                time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                fresh_impl,
            ),
            impl=out.get("impl"),
        )
        if spread:
            out["spread"] = spread
    print(json.dumps(out))
    return 0


def _best_of_run_and_committed(
    out: dict, errors: list, path: str | None = None,
    round_start_path: str | None = None,
) -> dict:
    """Window-noise guard for a healthy-tunnel round-end run: throughput
    swings >3x with other-tenant load (observed same-kernel 14,075 vs
    37,667 MP/s minutes apart), and the metric is peak capability — a cold
    round-end window must not bury a warmer committed same-round
    measurement. Both are same-round hardware numbers; report the better
    one, with provenance. (The fresh records were already appended to
    history, so no measurement is lost either way.)"""
    same = _same_round_tpu_headline(path, round_start_path)
    if same is None or same["headline"].get("value", 0) <= out.get("value", 0):
        return out
    promoted = _promote_committed(
        same,
        errors,
        source=(
            "same-round committed TPU record (better than this run's "
            f"{out.get('value')} {out.get('unit', 'MP/s/chip')} — "
            "window-noise guard)"
        ),
    )
    # the fresh HEAD measurement stays visible as a first-class field, not
    # just prose in `source` (advisor round-3 finding: promotion must not
    # hide a mid-round regression — the reader sees both numbers)
    promoted["fresh_value"] = out.get("value")
    return promoted


def _promote_committed(
    same: dict, errors: list, *, source: str | None = None,
    platform_note: str | None = None,
) -> dict:
    """Copy a committed history headline for promotion, stripping the
    run-scoped keys (partial/errors/source/measured_ts) its ORIGINAL run
    may have attached — a clean current run must not inherit a historical
    run's failure flags (review finding) — then stamp provenance and the
    CURRENT run's errors."""
    h = {
        k: v
        for k, v in same["headline"].items()
        if k not in ("partial", "errors", "source", "measured_ts")
    }
    h["measured_ts"] = same["ts"]
    # provenance SHAs: the commit whose code produced the promoted
    # measurement vs the HEAD this bench run executed at — unequal SHAs
    # flag that the promoted number may not reflect current code
    if same.get("git_sha"):
        h["measured_git_sha"] = same["git_sha"]
    head = git_head_sha()
    if head:
        h["head_git_sha"] = head
    # staleness accounting: a promoted number is only as current as the
    # commit that measured it — emit the distance and warn loudly past the
    # threshold (the round-5 headline was 9 commits stale, silently)
    if same.get("git_sha") and head:
        staleness = git_commits_between(same["git_sha"], head)
        if staleness is not None:
            h["staleness_commits"] = staleness
            if staleness > STALENESS_WARN_COMMITS:
                h["staleness_warning"] = (
                    f"promoted record measured {staleness} commits behind "
                    f"HEAD (threshold {STALENESS_WARN_COMMITS}); re-measure "
                    "on the next healthy window"
                )
                _log(
                    f"WARNING: promoted headline is {staleness} commits "
                    f"stale (measured at {same['git_sha']}, HEAD {head})"
                )
    if platform_note:
        h["platform"] = f"{h.get('platform')} ({platform_note})"
    if source:
        h["source"] = source
    if errors:
        h["partial"] = True
        h["errors"] = errors
    return h


def _tpu_history_headlines(path: str | None = None):
    """Yield (ts, headline, git_sha) for every BENCH_HISTORY.jsonl entry
    whose headline was measured on real TPU hardware. Platform is the
    criterion; impl is informational (a TPU xla number from a window where
    Mosaic crashed still counts). git_sha is None for entries predating the
    stamping (round <= 3)."""
    path = path or os.path.join(REPO, "BENCH_HISTORY.jsonl")
    try:
        with open(path) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                h = e.get("headline") or {}
                if h.get("platform") in ("tpu", "axon"):
                    yield e.get("ts"), h, e.get("git_sha")
    except OSError:
        return


def _last_tpu_headline(path: str | None = None) -> dict | None:
    """Most recent committed TPU headline, summarized for the
    `last_tpu_record` pointer on CPU-fallback records."""
    best = None
    for ts, h, sha in _tpu_history_headlines(path):
        best = {
            "ts": ts,
            "value": h.get("value"),
            "unit": h.get("unit"),
            "vs_baseline": h.get("vs_baseline"),
            "impl": h.get("impl"),
            "platform": h.get("platform"),
        }
        if sha:
            best["git_sha"] = sha
    return best


def _same_round_tpu_headline(
    path: str | None = None, round_start_path: str | None = None
) -> dict | None:
    """Best committed TPU headline measured THIS round, i.e. with a
    timestamp >= the committed ROUND_START marker (both are
    %Y-%m-%dT%H:%M:%SZ strings, so lexical comparison is chronological).

    Best by value, not most recent: window-to-window throughput on the
    shared tunneled chip swings >3x with other-tenant load (round 3's
    first window measured the identical compiled kernel at 14,075 then
    37,667 MP/s minutes apart), the metric is peak capability, and a
    later noisy window must not bury an earlier healthy one.
    Returns {ts, headline, git_sha} with the full headline record, or
    None."""
    round_start = _read_round_start(round_start_path)
    if not round_start:
        return None
    best = None
    for ts, h, sha in _tpu_history_headlines(path):
        if ts and ts >= round_start:
            if best is None or h.get("value", 0) > best["headline"].get("value", 0):
                best = {"ts": ts, "headline": h, "git_sha": sha}
    return best


def _read_round_start(round_start_path: str | None = None) -> str | None:
    rs_path = round_start_path or os.path.join(REPO, "ROUND_START")
    try:
        with open(rs_path) as f:
            return f.read().strip() or None
    except OSError:
        return None


def _count_windows(timestamps: list[str], gap_s: float = 900.0) -> int:
    """Cluster %Y-%m-%dT%H:%M:%SZ timestamps into 'windows': sightings more
    than gap_s apart came from distinct healthy-tunnel windows (observed
    windows are minutes long, wedges are hours)."""
    import calendar

    times = []
    for ts in timestamps:
        try:
            # timegm, not mktime: the Z timestamps are UTC, and local-time
            # parsing would distort gaps across a DST transition
            times.append(
                calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
            )
        except (TypeError, ValueError):
            continue
    times.sort()
    n = 0
    last = None
    for t in times:
        if last is None or t - last > gap_s:
            n += 1
        last = t
    return n


def _same_round_tpu_spread(
    path: str | None = None,
    round_start_path: str | None = None,
    extra: tuple[float, str, str | None] | None = None,
    impl: str | None = None,
) -> dict | None:
    """Variance summary {n, n_windows, best, median, min} over committed
    same-round TPU headline sightings (VERDICT r3 weak #1 / directive #2:
    the best-of-round promotion is a ratchet unless the headline of record
    carries the spread it was chosen from).

    `impl` restricts the sightings to the promoted headline's impl (when
    both sides carry the field): round 5's A/B campaigns committed
    deliberately-slower impls (xla at 11.4k MP/s beside pallas at 45k), and
    mixing those into min/median turns an impl difference into fake
    variance. Sightings without an impl field still count — old entries
    predate the stamping.

    `extra` is a (value, ts, impl) sighting NOT in the history file — the
    fresh run when its append was disabled (MCIM_NO_HISTORY) or failed —
    so the emitted spread can never contradict its own headline. It passes
    the same impl filter as committed sightings: a fresh run of a
    deliberately-slower impl must not contaminate a promoted headline's
    min/median (ADVICE r5 finding 2)."""
    round_start = _read_round_start(round_start_path)
    if not round_start:
        return None
    vals, tss = [], []
    for ts, h, _sha in _tpu_history_headlines(path):
        v = h.get("value")
        if impl is not None and h.get("impl") not in (None, impl):
            continue
        if ts and ts >= round_start and isinstance(v, (int, float)):
            vals.append(float(v))
            tss.append(ts)
    if (
        extra is not None
        and isinstance(extra[0], (int, float))
        and (impl is None or extra[2] in (None, impl))
    ):
        vals.append(float(extra[0]))
        tss.append(extra[1])
    if not vals:
        return None
    vals.sort()
    n = len(vals)
    median = vals[n // 2] if n % 2 else (vals[n // 2 - 1] + vals[n // 2]) / 2
    return {
        "n": n,
        "n_windows": _count_windows(tss),
        "best": vals[-1],
        "median": round(median, 1),
        "min": vals[0],
    }


def _append_history(headline: dict, records: list[dict]) -> bool:
    """Append every run's records to BENCH_HISTORY.jsonl (committed), so a
    tunnel wedge at the driver's round-end run cannot erase evidence of an
    earlier healthy-window TPU measurement (the round-1 failure mode).
    MCIM_NO_HISTORY (any non-empty value) disables the append — test runs
    must not pollute the committed history (tests/conftest.py sets it).
    Returns True iff the entry was written (the spread computation needs to
    know whether the fresh run is already a committed sighting)."""
    if os.environ.get("MCIM_NO_HISTORY"):
        return False
    try:
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "headline": headline,
            "records": records,
        }
        sha = git_head_sha()
        if sha:
            entry["git_sha"] = sha
        with open(os.path.join(REPO, "BENCH_HISTORY.jsonl"), "a") as f:
            f.write(json.dumps(entry) + "\n")
        return True
    except OSError as e:  # never let bookkeeping break the bench record
        _log(f"history append failed: {e}")
        return False


if __name__ == "__main__":
    sys.exit(main())
