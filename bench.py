#!/usr/bin/env python
"""Driver benchmark entry point: prints ONE JSON line with the headline
metric (BASELINE.json): megapixels/sec/chip on 8K 5x5 Gaussian.

Runs the 8K 5x5 separable-Gaussian config through both backends (XLA-fused
golden ops and the Pallas fused kernel) on the available TPU chip(s) and
reports the best, relative to the estimated reference CUDA+MPI 4xV100
number (derivation in BASELINE.md — the reference publishes no numbers).
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _tpu_responsive(timeout_s: float = 150.0) -> bool:
    """Probe the TPU in a subprocess with a hard timeout.

    The tunnelled chip on this machine can wedge in a way that makes any
    backend call block forever (observed after a Mosaic compiler crash);
    probing in-process would hang the whole benchmark. A dead probe means
    we fall back to CPU and say so in the record, rather than hanging the
    driver."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    tpu_ok = os.environ.get("JAX_PLATFORMS", "") in ("", "axon", "tpu")
    if tpu_ok and not _tpu_responsive():
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        tpu_ok = False
        print("TPU unresponsive; falling back to CPU", file=sys.stderr)

    from mpi_cuda_imagemanipulation_tpu.bench_suite import (
        HEADLINE,
        headline_record,
        run_suite,
    )

    import jax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")

    names = [HEADLINE]
    if len(jax.devices()) > 1:
        names.append(HEADLINE + "_sharded")
    records = run_suite(
        names=names,
        # CPU fallback: XLA only — interpret-mode Pallas on an 8K image
        # would take longer than the driver's patience
        impl="both" if tpu_ok else "xla",
        printer=lambda s: print(s, file=sys.stderr),
    )
    rec = headline_record(records)
    if rec is None:
        print(json.dumps({"error": "no benchmark record produced"}))
        return 1
    if not tpu_ok:
        rec["platform"] = "cpu-fallback (TPU tunnel unresponsive)"
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
