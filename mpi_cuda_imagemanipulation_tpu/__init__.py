"""mpi_cuda_imagemanipulation_tpu — a TPU-native image-manipulation framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the MPI+CUDA
reference (Dohruba/MPI-CUDA-ImageManipulation): per-pixel ops (grayscale,
contrast) and stencil filters (emboss, Gaussian, Sobel, ...) over HWC uint8
images, distributed by row-sharding a single image over a device mesh with
`lax.ppermute` ghost-row halo exchange — replacing the reference's
MPI_Scatter/MPI_Gather row blocks (reference kern.cpp:55,81-83;
kernel.cu:137,223-225) and fixing its slice-seam and in-place-race bugs by
construction.

Public API:
  - `ops`      : op registry + golden uint8-exact semantics
  - `models`   : `Pipeline` (composable op graph, jit-compiled)
  - `parallel` : mesh construction + sharded (halo-exchanged) execution
  - `io`       : image load/save (PIL, plus native C++ codec when built)
"""

from mpi_cuda_imagemanipulation_tpu import io, models, ops, parallel, utils
from mpi_cuda_imagemanipulation_tpu._version import __version__
from mpi_cuda_imagemanipulation_tpu.io.image import load_image, save_image
from mpi_cuda_imagemanipulation_tpu.models.pipeline import (
    Pipeline,
    reference_pipeline,
)
from mpi_cuda_imagemanipulation_tpu.ops.registry import make_op, make_pipeline_ops

__all__ = [
    "__version__",
    "io",
    "models",
    "ops",
    "parallel",
    "utils",
    "load_image",
    "save_image",
    "Pipeline",
    "reference_pipeline",
    "make_op",
    "make_pipeline_ops",
]
