"""Pipeline — the framework's composable "model": an op graph over an image.

The reference hardwires one pipeline (grayscale -> contrast 3.5 -> emboss 3x3,
kernel.cu:192-195) as three sequential host-driven kernel launches with a
device round-trip on either side (kernel.cu:163,202). Here a pipeline is a
declarative op sequence compiled into ONE XLA program — scatter, compute and
gather fuse into a single dispatch (SURVEY.md §3.4) — with three backends:

  * ``backend='xla'``    : the golden jnp ops, fused by XLA (oracle + default)
  * ``backend='pallas'`` : hand-tiled Pallas kernels for the hot stencils
  * ``mesh=...``         : sharded over a ('rows',) device mesh with ppermute
                           halo exchange (parallel.api)
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from mpi_cuda_imagemanipulation_tpu.ops.registry import (
    REFERENCE_CPU_PIPELINE_SPEC,
    REFERENCE_PIPELINE_SPEC,
    make_pipeline_ops,
)
from mpi_cuda_imagemanipulation_tpu.ops.spec import Op

BACKENDS = ("xla", "pallas", "swar", "mxu", "auto")

# the fusion-planner knob on every compiled entry point (plan/planner.py):
# 'off' = per-op golden execution; 'pointwise' absorbs pointwise runs into
# their neighbouring stencil's pass; 'fused' additionally temporally
# blocks consecutive stencils (one grown halo per stage); 'fused-pallas'
# executes each eligible fused stage as ONE VMEM-resident Pallas
# megakernel (plan/pallas_exec.py — intermediates never touch HBM);
# 'fused-pallas-mxu' is the megakernel with the per-op in-stage MXU dot
# contractions forced on (ops/mxu_kernels.stage_arm_for — the tuner's
# arm for "VMEM residency AND matrix-unit throughput at once");
# 'auto' resolves per (pipeline, backend, device kind, width) through the
# calibration store — `autotune --dimension plan` records the measured
# winner, and fused-pallas enters auto routing only behind such a win
PLAN_MODES = ("auto", "off", "pointwise", "fused", "fused-pallas",
              "fused-pallas-mxu")

def _silence_unused_donation_warning() -> None:
    """Donation here is opportunistic: shape-changing pipelines (e.g.
    grayscale 3ch→1ch) can't reuse the input buffer and XLA says so with a
    once-per-compile UserWarning. That's expected, not actionable — the
    engine donates whenever it's safe and lets XLA take it when it fits.
    Registered per donating-jit construction (not once): test harnesses
    reset the filter list between tests."""
    import warnings

    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable"
    )


@dataclasses.dataclass(frozen=True)
class Pipeline:
    ops: tuple[Op, ...]

    @classmethod
    def parse(cls, spec: str) -> "Pipeline":
        return cls(ops=make_pipeline_ops(spec))

    @property
    def name(self) -> str:
        return ",".join(op.name for op in self.ops)

    @property
    def max_halo(self) -> int:
        return max((op.halo for op in self.ops), default=0)

    # -- golden / XLA path ------------------------------------------------

    def apply(self, img: jnp.ndarray) -> jnp.ndarray:
        for op in self.ops:
            img = op(img)
        return img

    def __call__(self, img: jnp.ndarray) -> jnp.ndarray:
        return self.apply(img)

    # -- compiled entry points -------------------------------------------

    def _planned_callable(self, backend: str, plan: str):
        """`(executor, built_plan)` for this (backend, plan) pair, or
        `(None, None)` when the resolution says per-op (then
        `_callable`'s legacy paths run unchanged). Pure-XLA/MXU backends
        execute plans directly; `auto` engages only behind a calibrated
        plan choice, keeping the measured Pallas group routing by
        default (plan/planner.py). The built plan rides back so `jit`
        can key cost attribution by its fingerprint (obs/cost)."""
        if backend not in ("xla", "mxu", "auto"):
            return None, None
        from mpi_cuda_imagemanipulation_tpu.plan import (
            build_plan,
            resolve_plan_mode,
        )
        from mpi_cuda_imagemanipulation_tpu.plan.exec import plan_callable

        mode = resolve_plan_mode(self.ops, plan, backend=backend)
        if mode == "off":
            return None, None
        built = build_plan(self.ops, mode)
        if mode in ("fused-pallas", "fused-pallas-mxu"):
            from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
                plan_callable_pallas,
            )

            return plan_callable_pallas(
                built, impl=backend,
                mxu_stage="on" if mode == "fused-pallas-mxu" else None,
            ), built
        return plan_callable(built, impl=backend), built

    def _callable(
        self,
        backend: str,
        block_h: int | None = None,
        plan: str = "auto",
    ):
        planned, _built = self._planned_callable(backend, plan)
        if planned is not None:
            return planned
        if backend == "xla":
            return self.apply
        if backend == "pallas":
            from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
                pipeline_pallas,
            )

            return partial(pipeline_pallas, self.ops, block_h=block_h)
        if backend == "swar":
            # quarter-strip 16-bit-field streaming for eligible binomial
            # stencils, per-op u8-kernel fallback otherwise — explicit
            # opt-in (the round-5 on-chip A/B measured it 0.83x the u8
            # kernels, so auto never picks it; ops/swar_kernels.py)
            from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import (
                pipeline_swar,
            )

            return partial(pipeline_swar, self.ops, block_h=block_h)
        if backend == "mxu":
            # banded-matmul stencil contraction on the matrix unit for the
            # eligible correlation families, per-op golden fallback
            # otherwise; pure XLA, so pointwise prefixes fuse into the
            # same launch (ops/mxu_kernels.py). `auto` joins only behind
            # a measured per-device-kind calibration win.
            from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
                pipeline_mxu,
            )

            return partial(pipeline_mxu, self.ops, block_h=block_h)
        if backend == "auto":
            from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
                pipeline_auto,
            )

            return partial(pipeline_auto, self.ops, block_h=block_h)
        raise ValueError(f"unknown backend {backend!r}; known: {BACKENDS}")

    def jit(
        self,
        backend: str = "xla",
        block_h: int | None = None,
        *,
        donate: bool = False,
        plan: str = "auto",
    ):
        """A jitted image -> image function on the current default device.

        `block_h` overrides the Pallas row-block height (the reference's
        BLOCK_SIZE knob, kernel.cu:13); None auto-tunes to VMEM.

        `plan` selects the fusion-planner execution structure
        (PLAN_MODES): fused stages do one pass per stencil group instead
        of one per op, bit-identical to `plan='off'` (the per-op golden
        reference). 'auto' resolves through the calibration store
        (plan/planner.resolve_plan_mode).

        `donate=True` donates the input buffer to the computation
        (`donate_argnums`) so same-shape u8→u8 pipelines recycle it into
        the output and steady-state batch loops run without per-dispatch
        HBM allocation (the engine's contract, engine/core.py). Only safe
        when every call's input is fresh — a donated device buffer is
        invalidated; host numpy inputs are unaffected (each call uploads a
        new buffer). Results are bit-identical either way."""
        if donate:
            _silence_unused_donation_warning()
            jitted = jax.jit(
                self._callable(backend, block_h=block_h, plan=plan),
                donate_argnums=0,
            )
        else:
            jitted = jax.jit(
                self._callable(backend, block_h=block_h, plan=plan)
            )
        _planned, built = self._planned_callable(backend, plan)
        if built is None:
            return jitted
        # a PLANNED executable is a compile site the cost layer tracks
        # (obs/cost): the first call attributes the compiled artifact
        # under the plan's fingerprint — one u8 image in, one out, no
        # matter how many stages the plan holds — so a planner change
        # that leaks structure across the boundary trips the drift gate
        from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost

        def modeled(args):
            # one u8 image in + one u8 image out; the out aval is
            # trace-determined (eval_shape — geometric barriers may
            # re-shape), never read from the compiled artifact
            import numpy as np

            img = args[0]
            out_aval = jax.eval_shape(
                jitted, jax.ShapeDtypeStruct(tuple(img.shape), np.uint8)
            )
            return float(
                int(np.prod(img.shape, dtype=np.int64))
                + int(np.prod(out_aval.shape, dtype=np.int64))
                * out_aval.dtype.itemsize
            )

        return obs_cost.wrap_cache_fn(
            "plan", built.fingerprint, jitted, modeled_fn=modeled
        )

    def batched(
        self, backend: str = "xla", *, donate: bool = False,
        plan: str = "auto",
    ):
        """A jitted (N, H, W[, C]) -> (N, ...) batch function: one compiled
        dispatch for a stack of same-shape images (`jax.vmap`; the Pallas
        kernels batch through their vmap rule as an extra grid dimension).

        The reference has no batch concept — one hardcoded image per
        process launch (kernel.cu:110). Batching amortises dispatch
        overhead, which dominates small images on remote-attached TPUs.
        `donate` as in `.jit`; `plan` as in `.jit` (the planned executor
        vmaps like any backend callable)."""
        if donate:
            _silence_unused_donation_warning()
            return jax.jit(
                jax.vmap(self._callable(backend, plan=plan)), donate_argnums=0
            )
        return jax.jit(jax.vmap(self._callable(backend, plan=plan)))

    def sharded(
        self, mesh, backend: str = "xla", halo_mode: str = "serial",
        plan: str = "auto",
    ):
        """A jitted function running this pipeline sharded over `mesh` with
        ppermute ghost halo exchange.

        A 1-D ('rows',) mesh row-shards the image (parallel.api — Pallas
        fused-ghost fast path available); a 2-D ('rows', 'cols') mesh
        tile-shards it with the two-phase corner-carrying exchange
        (parallel.api2d — XLA tile compute; `backend` must be "xla" or
        "auto" there).

        `halo_mode='overlap'` selects the interior-first overlapped halo
        execution (parallel.api.HALO_MODES): eligible stencil groups
        compute interior rows while the ICI ghost-strip ppermutes are in
        flight, and multi-group pipelines prefetch the next group's
        exchange from the previous group's boundary outputs. Bit-identical
        output either way — the knob only changes execution structure.

        `plan` (PLAN_MODES) engages the fusion planner: on the 1-D
        runner a fused stage exchanges ONE `Stage.halo`-row ghost strip
        pair per stage (one ppermute pair) instead of one per stencil op
        — temporal blocking over the wire — and `plan='fused-pallas'`
        additionally streams each eligible stage through the ghost-mode
        VMEM megakernel (plan/pallas_exec), consuming that same
        pre-exchanged halo. On a 2-D mesh a fused stage pays ONE
        two-phase corner-carrying exchange round for its grown halo
        (parallel/api2d stage forms; tile compute stays XLA). 'auto'
        resolves to fused for the pure-XLA/MXU backends under
        halo_mode='serial'; the overlap mode keeps its measured
        per-group prefetch structure unless a plan is explicitly
        requested (then 1-D stages run interior-first at stage
        granularity)."""
        if len(mesh.axis_names) == 2:
            if backend not in ("xla", "auto"):
                raise ValueError(
                    "2-D sharding computes tiles with XLA (the fused-ghost "
                    "Pallas kernel is full-width by design, parallel/api2d "
                    f"docstring); got backend={backend!r}"
                )
            if backend == "auto":
                # 'auto' routes 1-D meshes to the fused-ghost Pallas kernel
                # but 2-D tiles to XLA — say so instead of silently
                # diverging from the 1-D behavior (VERDICT r3 weak #4)
                from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

                get_logger().info(
                    "2-D mesh: tile compute uses XLA (the fused-ghost Pallas "
                    "streaming kernel is 1-D full-width by design; "
                    "parallel/api2d.py scope note)"
                )
            from mpi_cuda_imagemanipulation_tpu.parallel.api2d import (
                sharded_pipeline_2d,
            )

            fn = sharded_pipeline_2d(
                self, mesh, halo_mode=halo_mode, plan=plan
            )
        else:
            from mpi_cuda_imagemanipulation_tpu.parallel.api import (
                sharded_pipeline,
            )

            fn = sharded_pipeline(
                self, mesh, backend=backend, halo_mode=halo_mode, plan=plan
            )

        mesh_desc = str(dict(mesh.shape))  # hoisted: no per-call build

        def run(img, _fn=fn):
            # failpoint at halo-exchange entry (resilience/failpoints.py):
            # host-side, before the sharded program launches, so an armed
            # `halo.exchange` site simulates a mid-collective rank failure
            # without wedging the other shards (the reference's actual
            # failure mode, kernel.cu:150)
            from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
            from mpi_cuda_imagemanipulation_tpu.resilience import failpoints

            # the host-side enqueue of the sharded halo program as a span
            # (obs/trace.py): under an engine dispatch or a traced run this
            # nests below the caller's span; untraced it is the shared
            # no-op
            with obs_trace.span(
                "sharded.dispatch", mesh=mesh_desc, halo_mode=halo_mode
            ):
                failpoints.maybe_fail("halo.exchange", mesh_shape=mesh.shape)
                return _fn(img)

        # keep the jitted function's AOT surface reachable (the halo
        # overlap tests lower the sharded program to inspect its module)
        run.lower = getattr(fn, "lower", None)
        run.__wrapped__ = fn
        return run

    def data_parallel(self, mesh, backend: str = "xla", plan: str = "auto"):
        """A jitted (N, H, W[, C]) -> (N, ...) batch function with the
        stack sharded over `mesh`'s first axis: each device runs the whole
        pipeline on its slice of the images (SPMD data parallelism — zero
        collectives, since images are independent; global-statistics ops
        reduce per image under vmap, not across the batch).

        This is the TPU-native analogue of launching the reference binary
        once per GPU/node for throughput (kernel.cu has one hardcoded image
        per process, kernel.cu:110), composing the `.batched` vmap with a
        batch-axis sharding instead of a process manager. `.sharded` splits
        ONE image's rows across devices (latency); `.data_parallel` splits
        MANY images across devices (throughput). Per-image results are
        bit-identical to `.jit` / `.batched` (asserted by
        tests/test_batch_dp.py). N need not divide the device count: jit
        batch-axis shardings require divisibility, so an uneven stack is
        padded by repeating the last image (same scheme as the CLI's
        partial-stack pad) and the padded outputs are sliced off — one
        compiled shape per (N rounded up), never a ragged recompile."""
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(mesh.axis_names[0])
        sharding = NamedSharding(mesh, spec)
        n_dev = mesh.devices.size
        fn = jax.jit(
            jax.vmap(self._callable(backend, plan=plan)),
            in_shardings=sharding,
            out_shardings=sharding,
        )

        def run(imgs):
            n = imgs.shape[0]
            pad = -n % n_dev
            if pad:
                imgs = jnp.concatenate(
                    [imgs, jnp.repeat(imgs[-1:], pad, axis=0)], axis=0
                )
            out = fn(imgs)
            return out[:n] if pad else out

        return run

    def serving(
        self,
        bucket_h: int,
        bucket_w: int,
        channels: int,
        batch: int,
        *,
        backend: str = "xla",
        mesh=None,
        on_trace=None,
        plan: str = "auto",
    ):
        """The online-serving executable for one shape-bucket cell: a jitted
        (imgs[B, Hb, Wb(,C)], true_h[B], true_w[B]) -> out[B, ...] function
        where requests are padded up to the bucket but compute BIT-IDENTICAL
        results to the per-request `.jit` path (the padded executor rebuilds
        each op's border extension at the dynamic true shape —
        serve/padded.py). This is the cache-warm hook `serve/cache.py`
        pre-compiles per (pipeline, bucket, batch) at server startup so no
        live request ever pays a trace. With `mesh`, the batch axis shards
        over it (the `.data_parallel` layout). `backend='mxu'` keeps the
        same executor but contracts eligible stencils on the matrix unit
        (a drop-in for op.valid — bit-identical; ops/mxu_kernels.py);
        'auto' follows the calibration-gated MXU routing. `plan`
        (PLAN_MODES) stages the executor through the fusion planner:
        fused stages keep the f32 carry between member ops (border
        reconstruction stays per-op — the dynamic true border is what the
        gathers implement), and the compile cache keys executables by the
        resolved plan's fingerprint (serve/cache.py)."""
        from mpi_cuda_imagemanipulation_tpu.serve.padded import make_serving_fn

        return make_serving_fn(
            self, bucket_h, bucket_w, channels, batch,
            backend=backend, mesh=mesh, on_trace=on_trace, plan=plan,
        )


def reference_pipeline() -> Pipeline:
    """The reference's exact pipeline: grayscale -> contrast 3.5 -> emboss 3x3
    (kernel.cu:192-195, smallEmboss=true)."""
    return Pipeline.parse(REFERENCE_PIPELINE_SPEC)


def reference_cpu_pipeline() -> Pipeline:
    """The reference's CPU/OpenCV program (kern.cpp:73-75): Rec.601
    grayscale, contrast 3, reflect-101 emboss — the variant whose numeric
    choices differ from kernel.cu's (SURVEY.md §2.2)."""
    return Pipeline.parse(REFERENCE_CPU_PIPELINE_SPEC)
