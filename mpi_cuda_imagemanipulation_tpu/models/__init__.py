from mpi_cuda_imagemanipulation_tpu.models.pipeline import (
    Pipeline,
    reference_pipeline,
)

__all__ = ["Pipeline", "reference_pipeline"]
