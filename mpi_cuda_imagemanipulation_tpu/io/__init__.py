from mpi_cuda_imagemanipulation_tpu.io.image import (
    load_image,
    save_image,
    synthetic_image,
)

__all__ = ["load_image", "save_image", "synthetic_image"]
