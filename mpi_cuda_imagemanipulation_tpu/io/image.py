"""Image I/O: HWC uint8 numpy arrays at the framework boundary.

Replaces the reference's OpenCV imread/imshow/imwrite layer (kern.cpp:33,89-92;
kernel.cu:110,120-122,233-236) with PIL for the long-tail formats, plus a
native C++ codec (runtime/) for PPM/PGM on the hot batch path when built.
Interactive `imshow` has no headless-TPU equivalent and is intentionally
replaced by file output (SURVEY.md §2.5).

Convention: colour images are (H, W, 3) RGB uint8; grayscale are (H, W)
uint8. (The reference works in OpenCV BGR order; ops are defined per-colour,
so only the channel indices differ — see ops.registry.grayscale_u8.)
"""

from __future__ import annotations

import os

import numpy as np

_NATIVE_EXTS = {".ppm", ".pgm"}


def _native_codec():
    """The C++ codec module, or None when the shared library isn't built."""
    try:
        from mpi_cuda_imagemanipulation_tpu.runtime import codec

        return codec if codec.available() else None
    except Exception:
        return None


def load_image(path: str | os.PathLike, *, grayscale: bool = False) -> np.ndarray:
    """Load an image file to (H, W, 3) RGB uint8, or (H, W) if grayscale.

    `grayscale=True` on a *colour* source always reduces with the framework's
    golden grayscale op (identical results whether the native codec or PIL
    decoded the file); a single-channel source is returned as stored.
    """
    from mpi_cuda_imagemanipulation_tpu.resilience import failpoints

    failpoints.maybe_fail("io.decode", path=str(path))
    ext = os.path.splitext(str(path))[1].lower()
    native = _native_codec() if ext in _NATIVE_EXTS else None
    if native is not None:
        arr = native.read_image(str(path))
    else:
        from PIL import Image

        with Image.open(path) as im:
            if im.mode in ("L", "1", "I", "I;16", "F"):
                arr = np.asarray(im.convert("L"), dtype=np.uint8)
            else:
                arr = np.asarray(im.convert("RGB"), dtype=np.uint8)
    if grayscale and arr.ndim == 3:
        import jax.numpy as jnp

        from mpi_cuda_imagemanipulation_tpu.ops.registry import grayscale_u8

        arr = np.asarray(grayscale_u8(jnp.asarray(arr)))
    if not grayscale and arr.ndim == 2:
        arr = gray_to_rgb(arr)
    return arr


def gray_to_rgb(img: np.ndarray) -> np.ndarray:
    """Replicate a (H, W) gray image to (H, W, 3) — the reference's
    GRAY2BGR output convention (kernel.cu:210)."""
    return np.broadcast_to(img[..., None], (*img.shape, 3)).copy()


def save_image(path: str | os.PathLike, img: np.ndarray) -> None:
    """Save (H, W) or (H, W, 3) uint8 to `path` (format from extension)."""
    img = np.asarray(img)
    if img.dtype != np.uint8:
        raise TypeError(f"expected uint8 image, got {img.dtype}")
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[..., 0]
    ext = os.path.splitext(str(path))[1].lower()
    native = _native_codec() if ext in _NATIVE_EXTS else None
    if native is not None:
        native.write_image(str(path), np.ascontiguousarray(img))
        return
    from PIL import Image

    Image.fromarray(img).save(path)


def decode_image_bytes(data: bytes) -> np.ndarray:
    """Decode an in-memory image (any PIL-readable format) with the same
    normalisation as `load_image`: (H, W, 3) RGB uint8, or (H, W) uint8 for
    single-channel sources. The serving HTTP front end's request codec."""
    import io as _io

    from PIL import Image

    from mpi_cuda_imagemanipulation_tpu.resilience import failpoints

    failpoints.maybe_fail("io.decode", n_bytes=len(data))
    with Image.open(_io.BytesIO(data)) as im:
        if im.mode in ("L", "1", "I", "I;16", "F"):
            return np.asarray(im.convert("L"), dtype=np.uint8)
        return np.asarray(im.convert("RGB"), dtype=np.uint8)


def encode_image_into(img: np.ndarray, sink, format: str = "PNG") -> None:
    """Encode (H, W) or (H, W, 3) uint8 straight into a writable binary
    file object — the single-copy handoff for the engine's encode-worker
    path: the encoder writes into the response/file sink directly
    instead of materialising the full byte string and copying it out
    again (`encode_image_bytes` keeps the bytes-returning contract for
    callers that need one)."""
    from PIL import Image

    img = np.asarray(img)
    if img.dtype != np.uint8:
        raise TypeError(f"expected uint8 image, got {img.dtype}")
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[..., 0]
    Image.fromarray(img).save(sink, format=format)


def encode_image_bytes(img: np.ndarray, format: str = "PNG") -> bytes:
    """Encode (H, W) or (H, W, 3) uint8 to image bytes (the serving
    response codec; PNG keeps the bit-exactness contract lossless)."""
    import io as _io

    buf = _io.BytesIO()
    encode_image_into(img, buf, format=format)
    return buf.getvalue()


def batch_load(
    paths,
    *,
    n_threads: int = 4,
    on_error: str = "raise",
    with_digests: bool = False,
):
    """Yield (index, image) over `paths` in order, decoding ahead on worker
    threads. Uses the native C++ prefetch loader when built and all inputs
    are PPM/PGM; otherwise a Python thread pool with PIL.

    Yields the same shapes as load_image (gray sources normalised to
    (H, W, 3)) regardless of which decoder ran. `on_error='skip'` logs and
    drops undecodable files instead of raising (failed indices are absent
    from the stream).

    `with_digests=True` yields (index, image, sha256-hex) with the content
    digest hashed on the DECODE worker alongside the decode itself — the
    journaling path (cli.py cmd_batch) then never hashes on the dispatch
    thread, so a large input cannot stall the device feed. (The native
    loader owns its decode threads, so on that path the hash runs on the
    consumer thread — still ahead of dispatch, and cheap next to decode.)"""
    if on_error not in ("raise", "skip"):
        raise ValueError(f"on_error must be 'raise' or 'skip', got {on_error!r}")
    paths = [str(p) for p in paths]

    def _digest(path: str) -> str:
        from mpi_cuda_imagemanipulation_tpu.resilience.journal import (
            content_digest,
        )

        return content_digest(path)

    def _load_one(path: str):
        arr = load_image(path)
        return (arr, _digest(path)) if with_digests else arr

    def _deliver(i, arr, digest=None):
        if arr.ndim == 2:
            arr = gray_to_rgb(arr)
        return (i, arr, digest) if with_digests else (i, arr)

    def _failed(path, exc):
        if on_error == "raise":
            raise exc
        from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

        # the exception text names the file when `path` is unknown (native)
        get_logger().warning("skipping %s: %s", path or "input", exc)

    native = _native_codec()
    if native is not None and all(
        os.path.splitext(p)[1].lower() in _NATIVE_EXTS for p in paths
    ):
        with native.BatchLoader(paths, n_threads=n_threads) as loader:
            for _ in range(len(paths)):
                try:
                    i, arr = next(loader)
                except StopIteration:
                    break
                except IOError as e:
                    _failed(None, e)  # file named in the message
                    continue
                yield _deliver(
                    i, arr, _digest(paths[i]) if with_digests else None
                )
        return
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    max_ahead = 16  # bound decoded-image memory like the native loader
    with ThreadPoolExecutor(max_workers=n_threads) as pool:
        pending: deque = deque()
        it = iter(enumerate(paths))
        exhausted = False
        while pending or not exhausted:
            while not exhausted and len(pending) < max_ahead:
                try:
                    i, p = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append((i, pool.submit(_load_one, p)))
            if not pending:
                break
            i, fut = pending.popleft()
            try:
                got = fut.result()
            except Exception as e:
                _failed(paths[i], e)
                continue
            if with_digests:
                arr, digest = got
                yield _deliver(i, arr, digest)
            else:
                yield _deliver(i, got)


# Row-block granularity of the synthetic generator: every block of rows
# draws from its own seeded stream, so any row window can be produced
# without materialising the rows before it (synthetic_tile).
_SYNTH_BLOCK_ROWS = 256


def _synthetic_block(
    block: int, rows: int, width: int, channels: int, seed: int
) -> np.ndarray:
    rng = np.random.default_rng((seed, width, channels, block))
    shape = (rows, width, channels) if channels > 1 else (rows, width)
    return rng.integers(0, 256, size=shape, dtype=np.uint8)


def synthetic_image(height: int, width: int, *, channels: int = 3, seed: int = 0) -> np.ndarray:
    """Deterministic pseudo-random test/bench image (uint8).

    Generated in fixed row blocks, each from its own seeded stream, so
    `synthetic_tile` can produce any row window bit-identically WITHOUT
    allocating the full frame — the gigapixel stream tests and benches
    depend on that equivalence (tile == full[rows] is asserted by
    tests/test_stream.py)."""
    return synthetic_tile(
        0, height, width, channels=channels, seed=seed
    )


def synthetic_tile(
    row0: int, rows: int, width: int, *, channels: int = 3, seed: int = 0
) -> np.ndarray:
    """Rows ``[row0, row0 + rows)`` of ``synthetic_image(H, width, ...)``
    for any H > row0 + rows — bit-identical to slicing the full frame,
    at cost proportional to the WINDOW, not the image. The windowed
    decoder the streaming engine's synthetic reader and the gigapixel
    benches use (a 100k x 100k scan must never exist host-side)."""
    if rows < 0 or row0 < 0:
        raise ValueError(f"bad window row0={row0} rows={rows}")
    b0 = row0 // _SYNTH_BLOCK_ROWS
    b1 = (row0 + rows + _SYNTH_BLOCK_ROWS - 1) // _SYNTH_BLOCK_ROWS
    parts = [
        _synthetic_block(b, _SYNTH_BLOCK_ROWS, width, channels, seed)
        for b in range(b0, max(b1, b0 + 1))
    ]
    band = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
    off = row0 - b0 * _SYNTH_BLOCK_ROWS
    return np.ascontiguousarray(band[off : off + rows])
