"""Image I/O: HWC uint8 numpy arrays at the framework boundary.

Replaces the reference's OpenCV imread/imshow/imwrite layer (kern.cpp:33,89-92;
kernel.cu:110,120-122,233-236) with PIL for the long-tail formats, plus a
native C++ codec (runtime/) for PPM/PGM on the hot batch path when built.
Interactive `imshow` has no headless-TPU equivalent and is intentionally
replaced by file output (SURVEY.md §2.5).

Convention: colour images are (H, W, 3) RGB uint8; grayscale are (H, W)
uint8. (The reference works in OpenCV BGR order; ops are defined per-colour,
so only the channel indices differ — see ops.registry.grayscale_u8.)
"""

from __future__ import annotations

import os

import numpy as np

_NATIVE_EXTS = {".ppm", ".pgm"}


def _native_codec():
    """The C++ codec module, or None when the shared library isn't built."""
    try:
        from mpi_cuda_imagemanipulation_tpu.runtime import codec

        return codec if codec.available() else None
    except Exception:
        return None


def load_image(path: str | os.PathLike, *, grayscale: bool = False) -> np.ndarray:
    """Load an image file to (H, W, 3) RGB uint8, or (H, W) if grayscale.

    `grayscale=True` on a *colour* source always reduces with the framework's
    golden grayscale op (identical results whether the native codec or PIL
    decoded the file); a single-channel source is returned as stored.
    """
    ext = os.path.splitext(str(path))[1].lower()
    native = _native_codec() if ext in _NATIVE_EXTS else None
    if native is not None:
        arr = native.read_image(str(path))
    else:
        from PIL import Image

        with Image.open(path) as im:
            if im.mode in ("L", "1", "I", "I;16", "F"):
                arr = np.asarray(im.convert("L"), dtype=np.uint8)
            else:
                arr = np.asarray(im.convert("RGB"), dtype=np.uint8)
    if grayscale and arr.ndim == 3:
        import jax.numpy as jnp

        from mpi_cuda_imagemanipulation_tpu.ops.registry import grayscale_u8

        arr = np.asarray(grayscale_u8(jnp.asarray(arr)))
    if not grayscale and arr.ndim == 2:
        arr = gray_to_rgb(arr)
    return arr


def gray_to_rgb(img: np.ndarray) -> np.ndarray:
    """Replicate a (H, W) gray image to (H, W, 3) — the reference's
    GRAY2BGR output convention (kernel.cu:210)."""
    return np.broadcast_to(img[..., None], (*img.shape, 3)).copy()


def save_image(path: str | os.PathLike, img: np.ndarray) -> None:
    """Save (H, W) or (H, W, 3) uint8 to `path` (format from extension)."""
    img = np.asarray(img)
    if img.dtype != np.uint8:
        raise TypeError(f"expected uint8 image, got {img.dtype}")
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[..., 0]
    ext = os.path.splitext(str(path))[1].lower()
    native = _native_codec() if ext in _NATIVE_EXTS else None
    if native is not None:
        native.write_image(str(path), np.ascontiguousarray(img))
        return
    from PIL import Image

    Image.fromarray(img).save(path)


def synthetic_image(height: int, width: int, *, channels: int = 3, seed: int = 0) -> np.ndarray:
    """Deterministic pseudo-random test/bench image (uint8)."""
    rng = np.random.default_rng(seed)
    shape = (height, width, channels) if channels > 1 else (height, width)
    return rng.integers(0, 256, size=shape, dtype=np.uint8)
