"""Windowed decode + incremental encode — pixels never fully materialise.

`io/image.py` is the whole-image boundary: decode to one (H, W[, 3])
array, encode from one. That ceiling IS the repo's old problem-size
ceiling — a 100k x 100k scan cannot exist host-side. This module is the
row-band boundary the streaming tile engine (stream/) runs on:

  * **TileReader** — sequential row-band decode: ``read_rows(n)`` hands
    out the next ``n`` rows and forgets them; ``skip_rows`` fast-forwards
    (seek where the container allows, decode-and-discard where it
    doesn't — journal resume needs both). Implementations: PNM (P5/P6,
    header + seek — the native-codec formats), PNG (chunk walk + a
    zlib ``decompressobj`` + per-scanline unfiltering: None/Sub/Up are
    vectorised, Average/Paeth fall back to a per-pixel row loop — PIL
    emits all of them), synthetic (``io.image.synthetic_tile`` — the
    gigapixel bench source), and an in-memory array wrapper.
  * **TileWriter** — incremental encode: ``write_rows`` appends a band,
    ``close`` finalises the container. PNM appends raw bytes (and
    supports reopening at a row offset — the journal-resume path); PNG
    streams one IDAT chunk per band from a live ``compressobj`` (filter
    0 scanlines) so the compressor state is the only buffered state.

Both sides deal in the `load_image` conventions: (rows, W, 3) RGB uint8
or (rows, W) gray uint8. 16-bit, paletted and interlaced sources are
rejected loudly (`UnsupportedStreamFormat`) and the CLI falls back to a
whole-image decode with a warning — constant memory is a property worth
failing loudly over, not silently losing.
"""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np

from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_tile

_PNG_SIG = b"\x89PNG\r\n\x1a\n"


class UnsupportedStreamFormat(ValueError):
    """The container cannot be streamed row-wise (or not by this codec)."""


# --------------------------------------------------------------------------
# Readers
# --------------------------------------------------------------------------


class TileReader:
    """Sequential row-band decoder. Subclasses set height/width/channels
    in __init__ and implement _read(n) -> uint8 rows."""

    height: int
    width: int
    channels: int  # 1 or 3

    def __init__(self):
        self._row = 0  # next row to hand out

    @property
    def rows_read(self) -> int:
        return self._row

    def read_rows(self, n: int) -> np.ndarray | None:
        """The next min(n, remaining) rows as uint8 (rows, W[, 3]);
        None once the image is exhausted."""
        n = min(n, self.height - self._row)
        if n <= 0:
            return None
        out = self._read(n)
        self._row += n
        return out

    def skip_rows(self, n: int) -> None:
        """Fast-forward past n rows (resume support). Default: decode and
        discard; seekable containers override."""
        n = min(n, self.height - self._row)
        if n > 0:
            self._read(n)
            self._row += n

    def _read(self, n: int) -> np.ndarray:  # pragma: no cover - abstract
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self) -> "TileReader":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class ArrayTileReader(TileReader):
    """Row-band view over an in-memory array (tests, video frames, and
    the serial lanes of the stream_ab bench)."""

    def __init__(self, arr: np.ndarray):
        super().__init__()
        arr = np.asarray(arr)
        if arr.dtype != np.uint8 or arr.ndim not in (2, 3):
            raise ValueError(f"expected uint8 (H,W[,3]) array, got {arr.shape} {arr.dtype}")
        self._arr = arr
        self.height, self.width = arr.shape[:2]
        self.channels = arr.shape[2] if arr.ndim == 3 else 1

    def _read(self, n: int) -> np.ndarray:
        return np.ascontiguousarray(self._arr[self._row : self._row + n])

    def skip_rows(self, n: int) -> None:
        self._row = min(self._row + n, self.height)


class SyntheticTileReader(TileReader):
    """Windowed synthetic source: each band comes from
    `io.image.synthetic_tile`, bit-identical to slicing the full
    `synthetic_image` — so a 100k-row scan is a few integers of state."""

    def __init__(self, height: int, width: int, *, channels: int = 3, seed: int = 0):
        super().__init__()
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed

    def _read(self, n: int) -> np.ndarray:
        return synthetic_tile(
            self._row, n, self.width, channels=self.channels, seed=self.seed
        )

    def skip_rows(self, n: int) -> None:
        self._row = min(self._row + n, self.height)


class PNMTileReader(TileReader):
    """P5 (gray) / P6 (RGB) binary PNM: one header parse, then every
    band is a seek + read — the ideal streaming container (and the
    native C++ codec's format, runtime/codec.py)."""

    def __init__(self, path: str | os.PathLike):
        super().__init__()
        self._f = open(path, "rb")
        try:
            magic = self._f.read(2)
            if magic not in (b"P5", b"P6"):
                raise UnsupportedStreamFormat(
                    f"{path}: not binary PNM (magic {magic!r})"
                )
            self.channels = 3 if magic == b"P6" else 1
            vals = []
            while len(vals) < 3:
                tok = self._token()
                vals.append(int(tok))
            self.width, self.height, maxval = vals
            if maxval != 255:
                raise UnsupportedStreamFormat(
                    f"{path}: maxval {maxval} (only 8-bit supported)"
                )
            self._data0 = self._f.tell()
        except Exception:
            self._f.close()
            raise

    def _token(self) -> bytes:
        """Next whitespace-delimited header token, skipping # comments."""
        tok = b""
        while True:
            c = self._f.read(1)
            if not c:
                raise UnsupportedStreamFormat("truncated PNM header")
            if c == b"#":
                while c and c != b"\n":
                    c = self._f.read(1)
                continue
            if c.isspace():
                if tok:
                    return tok
                continue
            tok += c

    def _stride(self) -> int:
        return self.width * self.channels

    def _read(self, n: int) -> np.ndarray:
        raw = self._f.read(n * self._stride())
        if len(raw) != n * self._stride():
            raise OSError("truncated PNM pixel data")
        arr = np.frombuffer(raw, dtype=np.uint8)
        if self.channels == 1:
            return arr.reshape(n, self.width)
        return arr.reshape(n, self.width, self.channels)

    def skip_rows(self, n: int) -> None:
        n = min(n, self.height - self._row)
        self._f.seek(n * self._stride(), os.SEEK_CUR)
        self._row += n

    def close(self) -> None:
        self._f.close()


def _unfilter_scanline(
    ftype: int, raw: np.ndarray, prev: np.ndarray, bpp: int
) -> np.ndarray:
    """One PNG scanline filter inversion. raw/prev are uint8 (stride,);
    prev is the RECONSTRUCTED previous scanline (zeros for the first)."""
    if ftype == 0:  # None
        return raw
    if ftype == 2:  # Up (uint8 add wraps mod 256 — the PNG spec's math)
        return raw + prev
    if ftype == 1:  # Sub: prefix sum per byte lane, stride bpp
        lanes = raw.reshape(-1, bpp).astype(np.uint32)
        recon = np.cumsum(lanes, axis=0, dtype=np.uint32) % 256
        return recon.astype(np.uint8).reshape(-1)
    out = np.zeros_like(raw)
    if ftype == 3:  # Average — sequential in x (left term)
        r = raw.astype(np.int32)
        p = prev.astype(np.int32)
        o = out.astype(np.int32)
        for x in range(len(raw)):
            left = o[x - bpp] if x >= bpp else 0
            o[x] = (r[x] + (left + p[x]) // 2) % 256
        return o.astype(np.uint8)
    if ftype == 4:  # Paeth — sequential in x (left + upleft terms)
        r = raw.astype(np.int32)
        p = prev.astype(np.int32)
        o = np.zeros(len(raw), np.int32)
        for x in range(len(raw)):
            a = o[x - bpp] if x >= bpp else 0
            b = p[x]
            c = p[x - bpp] if x >= bpp else 0
            pa, pb, pc = abs(b - c), abs(a - c), abs(a + b - 2 * c)
            if pa <= pb and pa <= pc:
                pred = a
            elif pb <= pc:
                pred = b
            else:
                pred = c
            o[x] = (r[x] + pred) % 256
        return o.astype(np.uint8)
    raise UnsupportedStreamFormat(f"bad PNG filter type {ftype}")


class PNGTileReader(TileReader):
    """Streaming scanline decode of non-interlaced 8-bit gray/RGB PNG:
    IDAT chunks feed one zlib decompressobj, scanlines unfilter against
    only the previous reconstructed row — O(width) state regardless of
    image height. RGBA/16-bit/palette/interlaced raise
    UnsupportedStreamFormat (the CLI falls back to whole-image decode)."""

    def __init__(self, path: str | os.PathLike):
        super().__init__()
        self._f = open(path, "rb")
        try:
            if self._f.read(8) != _PNG_SIG:
                raise UnsupportedStreamFormat(f"{path}: not a PNG")
            ln, typ = struct.unpack(">I4s", self._f.read(8))
            if typ != b"IHDR" or ln != 13:
                raise UnsupportedStreamFormat(f"{path}: malformed IHDR")
            w, h, depth, color, comp, filt, interlace = struct.unpack(
                ">IIBBBBB", self._f.read(13)
            )
            self._f.read(4)  # IHDR crc
            if depth != 8 or color not in (0, 2) or interlace != 0:
                raise UnsupportedStreamFormat(
                    f"{path}: only non-interlaced 8-bit gray/RGB streams "
                    f"(depth={depth} color={color} interlace={interlace})"
                )
            self.width, self.height = w, h
            self.channels = 3 if color == 2 else 1
            self._z = zlib.decompressobj()
            self._buf = bytearray()  # decompressed-but-unparsed bytes
            self._prev = np.zeros(w * self.channels, np.uint8)
            self._eof = False
        except Exception:
            self._f.close()
            raise

    def _stride(self) -> int:
        return self.width * self.channels

    def _fill(self, want: int) -> None:
        """Decompress until `want` bytes are buffered (or IEND)."""
        while len(self._buf) < want and not self._eof:
            hdr = self._f.read(8)
            if len(hdr) < 8:
                self._eof = True
                break
            ln, typ = struct.unpack(">I4s", hdr)
            data = self._f.read(ln)
            self._f.read(4)  # crc
            if typ == b"IDAT":
                self._buf += self._z.decompress(data)
            elif typ == b"IEND":
                self._buf += self._z.flush()
                self._eof = True
            # ancillary chunks are skipped

    def _scanlines(self, n: int) -> np.ndarray:
        stride = self._stride()
        need = n * (stride + 1)
        self._fill(need)
        if len(self._buf) < need:
            raise OSError("truncated PNG pixel data")
        raw = np.frombuffer(bytes(self._buf[:need]), np.uint8).reshape(
            n, stride + 1
        )
        del self._buf[:need]
        out = np.empty((n, stride), np.uint8)
        prev = self._prev
        for r in range(n):
            prev = _unfilter_scanline(int(raw[r, 0]), raw[r, 1:], prev, self.channels)
            out[r] = prev
        self._prev = prev
        return out

    def _read(self, n: int) -> np.ndarray:
        flat = self._scanlines(n)
        if self.channels == 1:
            return flat.reshape(n, self.width)
        return flat.reshape(n, self.width, self.channels)

    def close(self) -> None:
        self._f.close()


class _FullDecodeTileReader(ArrayTileReader):
    """Fallback for containers without a streaming decode (JPEG, ...):
    whole-image `load_image`, row-band interface. NOT constant-memory —
    `open_tile_reader` logs when it has to resort to this."""

    def __init__(self, path: str | os.PathLike):
        from mpi_cuda_imagemanipulation_tpu.io.image import load_image

        super().__init__(np.asarray(load_image(path)))


def open_tile_reader(path: str | os.PathLike, *, allow_fallback: bool = True) -> TileReader:
    """Open `path` with the best row-band decoder for its container:
    seekable PNM, streaming PNG, else (with `allow_fallback`) a logged
    whole-image fallback."""
    ext = os.path.splitext(str(path))[1].lower()
    if ext in (".ppm", ".pgm", ".pnm"):
        return PNMTileReader(path)
    if ext == ".png":
        try:
            return PNGTileReader(path)
        except UnsupportedStreamFormat:
            if not allow_fallback:
                raise
    elif not allow_fallback:
        raise UnsupportedStreamFormat(
            f"{path}: no streaming decoder for {ext!r} (use ppm/pgm/png)"
        )
    from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

    get_logger().warning(
        "%s: no constant-memory decoder for this container — falling back "
        "to whole-image decode (stream memory bound does not hold)", path,
    )
    return _FullDecodeTileReader(path)


# --------------------------------------------------------------------------
# Writers
# --------------------------------------------------------------------------


class TileWriter:
    """Incremental row-band encoder; subclasses implement _write/close."""

    height: int
    width: int
    channels: int

    def __init__(self, height: int, width: int, channels: int):
        if channels not in (1, 3):
            raise ValueError(f"channels must be 1 or 3, got {channels}")
        self.height, self.width, self.channels = height, width, channels
        self.rows_written = 0

    def _check(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows)
        if rows.dtype != np.uint8:
            raise TypeError(f"expected uint8 rows, got {rows.dtype}")
        if rows.ndim == 3 and rows.shape[2] == 1:
            rows = rows[..., 0]
        got_c = rows.shape[2] if rows.ndim == 3 else 1
        if rows.shape[1] != self.width or got_c != self.channels:
            raise ValueError(
                f"rows shape {rows.shape} does not match stream "
                f"({self.width} wide, {self.channels}ch)"
            )
        if self.rows_written + rows.shape[0] > self.height:
            raise ValueError("more rows than the declared image height")
        return rows

    def write_rows(self, rows: np.ndarray) -> None:
        rows = self._check(rows)
        self._write(np.ascontiguousarray(rows))
        self.rows_written += rows.shape[0]

    def _write(self, rows: np.ndarray) -> None:  # pragma: no cover
        raise NotImplementedError

    def flush(self) -> None:
        """Push written rows toward durability (the stream runner calls
        this before journaling a tile ok — a journal record must never
        claim rows still sitting in a userland buffer)."""

    def close(self) -> None:
        pass

    def __enter__(self) -> "TileWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class ArrayTileWriter(TileWriter):
    """Accumulate into one preallocated array (tests / in-memory golden
    compares — the one writer that deliberately materialises)."""

    def __init__(self, height: int, width: int, channels: int):
        super().__init__(height, width, channels)
        shape = (height, width, channels) if channels > 1 else (height, width)
        self.array = np.zeros(shape, np.uint8)

    def _write(self, rows: np.ndarray) -> None:
        self.array[self.rows_written : self.rows_written + rows.shape[0]] = rows


class PNMTileWriter(TileWriter):
    """Raw P5/P6 append — and the one container where a killed stream can
    RESUME: the byte offset of row k is header + k*stride, so `resume()`
    verifies the partial file's length and reopens positioned at the
    next whole row (the stream journal records which tiles those rows
    came from)."""

    def __init__(self, path: str | os.PathLike, height: int, width: int,
                 channels: int, *, _append_rows: int = 0):
        super().__init__(height, width, channels)
        self.path = str(path)
        header = (
            f"{'P6' if channels == 3 else 'P5'}\n{width} {height}\n255\n"
        ).encode()
        if _append_rows:
            self._f = open(self.path, "r+b")
            self._f.seek(len(header) + _append_rows * width * channels)
            self._f.truncate()
            self.rows_written = _append_rows
        else:
            self._f = open(self.path, "wb")
            self._f.write(header)

    @classmethod
    def resume(cls, path: str | os.PathLike, height: int, width: int,
               channels: int, rows_done: int) -> "PNMTileWriter":
        """Reopen a partial stream output at `rows_done` complete rows
        (any trailing partial row is truncated away)."""
        w = cls(path, height, width, channels, _append_rows=rows_done)
        return w

    def _write(self, rows: np.ndarray) -> None:
        self._f.write(rows.tobytes())

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self.flush()
        self._f.close()


class PNGTileWriter(TileWriter):
    """Incremental PNG: IHDR up front, one zlib-compressed IDAT chunk per
    band (filter-0 scanlines), IEND at close. The live compressor is the
    only cross-band state, so encoding a gigapixel output holds one
    band + O(32 KiB) of zlib window — never the image. The output reads
    back bit-identically (PNG is lossless; tests decode and compare)."""

    def __init__(self, sink, height: int, width: int, channels: int,
                 *, level: int = 6):
        super().__init__(height, width, channels)
        self._own = isinstance(sink, (str, os.PathLike))
        self._f = open(sink, "wb") if self._own else sink
        self._z = zlib.compressobj(level)
        self._closed = False
        self._f.write(_PNG_SIG)
        color = 2 if channels == 3 else 0
        self._chunk(
            b"IHDR",
            struct.pack(">IIBBBBB", width, height, 8, color, 0, 0, 0),
        )

    def _chunk(self, typ: bytes, data: bytes) -> None:
        self._f.write(struct.pack(">I", len(data)))
        self._f.write(typ)
        self._f.write(data)
        self._f.write(struct.pack(">I", zlib.crc32(typ + data) & 0xFFFFFFFF))

    def _write(self, rows: np.ndarray) -> None:
        n = rows.shape[0]
        flat = rows.reshape(n, -1)
        # filter byte 0 per scanline, then one compressor feed per band
        scan = np.empty((n, flat.shape[1] + 1), np.uint8)
        scan[:, 0] = 0
        scan[:, 1:] = flat
        out = self._z.compress(scan.tobytes())
        out += self._z.flush(zlib.Z_SYNC_FLUSH)
        if out:
            self._chunk(b"IDAT", out)

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.rows_written != self.height:
            # still finalise the container so the partial file parses,
            # but the height lie must not pass silently
            from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

            get_logger().warning(
                "PNG stream closed at %d/%d rows", self.rows_written, self.height
            )
        tail = self._z.flush()
        if tail:
            self._chunk(b"IDAT", tail)
        self._chunk(b"IEND", b"")
        self._f.flush()
        if self._own:
            self._f.close()


def open_tile_writer(
    path: str | os.PathLike, height: int, width: int, channels: int
) -> TileWriter:
    """The incremental encoder for `path`'s extension (PNM append/resume,
    streaming PNG); other extensions are rejected — a format that needs
    the whole image in memory to encode defeats the stream."""
    ext = os.path.splitext(str(path))[1].lower()
    if ext in (".ppm", ".pnm"):
        return PNMTileWriter(path, height, width, 3 if channels == 3 else channels)
    if ext == ".pgm":
        return PNMTileWriter(path, height, width, channels)
    if ext == ".png":
        return PNGTileWriter(path, height, width, channels)
    raise UnsupportedStreamFormat(
        f"{path}: no incremental encoder for {ext!r} (use ppm/pgm/png)"
    )
