"""Plan execution: the one stage walker every fused path shares.

A fused stage runs over an *extended region*: a buffer that covers its
output rows plus up to `Stage.halo` rows of real context on each
interior side. The walker applies the stage's ops in order on a float32
carry holding exact u8 integer values (the package's cross-backend
exactness invariant — every core maps exact integers to exact integers,
so one u8 materialisation per stage is bit-identical to one per op):

  * pointwise ops run their `core`/`planes_core` on the carry (fn-only
    ops — LUT gathers, gray2rgb — round-trip through u8, which is exact);
  * each stencil consumes `op.halo` context rows per interior side and
    PADS (`pad2d`, the op's own edge mode, asymmetric) at sides that are
    the true image boundary, then finalizes at GLOBAL row offsets so
    'interior' masks (the reference guard) see image coordinates — the
    same walk the streaming tile engine proved out per-op
    (stream/tiles.py), generalized to a fused stage.

Three consumers, three context conventions, one walker:

  * full image (`plan_callable`): lead = tail = 0 — every stencil pads
    both sides per its mode; literally the golden computation, staged.
  * stream tiles: lead/tail from the tile plan (real rows at interior
    seams, pad at true image edges), threaded ACROSS stages.
  * sharded tiles (parallel/api): context is always materialised (the
    stage's single ghost exchange), and an `edge_fix` callback rewrites
    out-of-image rows per op *before* each stencil reads them — the
    dynamic-gather equivalent of pad2d (parallel.api._fix_edge_axis),
    re-applied per op so no commuting assumption is ever made between
    an op's output and the next op's border extension.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from mpi_cuda_imagemanipulation_tpu.ops.registry import op_family
from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    U8,
    StencilOp,
    _check_channels,
    exact_f32,
    pad2d,
)
from mpi_cuda_imagemanipulation_tpu.plan.ir import Plan

PLAN_IMPLS = ("xla", "mxu", "auto")


def stencil_acc_fn(op: StencilOp, impl: str, width: int | None):
    """The valid-region accumulator for one stencil under `impl`: the
    golden VPU path (`op.valid`), the forced MXU formulation (banded
    contraction for corr ops; threshold-decomposition morphology since
    erode/dilate joined `mxu_eligible`), or — for 'auto' — the
    calibration-gated routing decision, made ONCE at build time
    (ops/mxu_kernels.use_mxu_for_stencil), never inside the trace.
    Shared by the plan executors and the streaming tile engine so
    per-stencil backend routing cannot drift between them. The in-stage
    contraction point inside the fused-pallas megakernel resolves its
    own arms (ops/mxu_kernels.stage_arm_for); a stage the megakernel
    rejects re-enters here under the pipeline's backend impl, so under
    'mxu'/'auto' a counted megakernel rejection does not also forfeit
    the whole-op MXU formulation."""
    if impl == "xla":
        return op.valid
    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
        mxu_eligible,
        mxu_valid,
        use_mxu_for_stencil,
    )

    if impl == "mxu":
        if mxu_eligible(op):
            return partial(mxu_valid, op)
        return op.valid
    # auto: MXU only behind a measured calibration win on this device kind
    mode = use_mxu_for_stencil(op, width)
    if mode is not None:
        return partial(mxu_valid, op, mode=mode)
    return op.valid


def acc_fns_for(ops, impl: str, width: int | None) -> dict:
    if impl not in PLAN_IMPLS:
        raise ValueError(f"unknown plan impl {impl!r}; known: {PLAN_IMPLS}")
    return {
        id(op): stencil_acc_fn(op, impl, width)
        for op in ops
        if isinstance(op, StencilOp)
    }


def apply_pointwise_f32(op, cur: jnp.ndarray) -> jnp.ndarray:
    """One pointwise op on the f32 exact-integer carry."""
    _check_channels(op.name, op.in_channels, cur)
    if op.planes_core is not None and cur.ndim == 3:
        planes = op.planes_core(cur[..., 0], cur[..., 1], cur[..., 2])
        if isinstance(planes, (list, tuple)):
            return jnp.stack(list(planes), axis=-1)
        return planes
    if op.core is not None:
        return op.core(cur)
    # fn-only op (LUT gather, gray2rgb): the u8 round trip is exact on
    # integer-valued f32, and XLA fuses the casts into the gather pass
    return exact_f32(op.fn(cur.astype(U8)))


def _stencil_region(
    op: StencilOp,
    buf: jnp.ndarray,
    acc_fn,
    take_top: int,
    take_bot: int,
    y0,
    global_h: int,
    global_w: int,
) -> jnp.ndarray:
    """One stencil over an extended f32 region: consume `take_*` real
    context rows, pad the rest per the op's edge mode (asymmetric — only
    at true-image-edge sides), finalize at global coordinates."""
    h = op.halo
    pad_top, pad_bot = h - take_top, h - take_bot

    def plane(x: jnp.ndarray) -> jnp.ndarray:
        xpad = pad2d(x, op.edge_mode, pad_top, pad_bot, h, h)
        acc = acc_fn(xpad)
        orig = x[take_top : x.shape[0] - take_bot]
        return op.finalize_f32(acc, orig, y0, 0, global_h, global_w)

    if buf.ndim == 3:
        return jnp.stack(
            [plane(buf[..., c]) for c in range(buf.shape[2])], axis=-1
        )
    return plane(buf)


def walk_stage(
    ops,
    cur: jnp.ndarray,
    *,
    y_lo,
    lead_rem: int,
    tail_rem: int,
    global_h: int,
    global_w: int,
    acc_fns: dict,
    edge_fix=None,
):
    """Apply one fused stage's ops over the f32 region `cur`, whose first
    row sits at (traced) global row `y_lo` with `lead_rem`/`tail_rem`
    real context rows still unconsumed at each end.

    `edge_fix(cur, op, y_lo)` — the sharded convention — marks context as
    always materialised: every stencil consumes its full halo and the
    callback rewrites out-of-image rows per that op's edge mode first.
    Without it (full-image/stream convention), a stencil consumes context
    only while `*_rem > 0` and pads otherwise.

    Returns ``(cur, y_lo, lead_rem, tail_rem)`` so stream tiles can
    thread the context budget across consecutive stages.
    """
    for op in ops:
        fam = op_family(op)
        if fam == "pointwise":
            cur = apply_pointwise_f32(op, cur)
            continue
        if fam != "stencil":  # pragma: no cover - planner invariant
            raise ValueError(
                f"op {op.name!r} ({fam}) cannot appear inside a fused stage"
            )
        _check_channels(op.name, op.in_channels, cur)
        h = op.halo
        if h == 0:
            # degenerate stencil (box1): shape-preserving, no context
            cur = _stencil_region(
                op, cur, acc_fns[id(op)], 0, 0, y_lo, global_h, global_w
            )
            continue
        if edge_fix is not None:
            cur = edge_fix(cur, op, y_lo)
            take_top = take_bot = h
        else:
            take_top = h if lead_rem > 0 else 0
            take_bot = h if tail_rem > 0 else 0
        y0 = y_lo + take_top
        cur = _stencil_region(
            op, cur, acc_fns[id(op)], take_top, take_bot,
            y0, global_h, global_w,
        )
        lead_rem -= take_top
        tail_rem -= take_bot
        y_lo = y0
    return cur, y_lo, lead_rem, tail_rem


def run_stage_full(stage, img: jnp.ndarray, impl: str) -> jnp.ndarray:
    """One fused stage over a whole u8 image (lead = tail = 0)."""
    global_h, global_w = img.shape[0], img.shape[1]
    acc_fns = acc_fns_for(stage.ops, impl, global_w)
    cur, _, _, _ = walk_stage(
        stage.ops,
        exact_f32(img),
        y_lo=0,
        lead_rem=0,
        tail_rem=0,
        global_h=global_h,
        global_w=global_w,
        acc_fns=acc_fns,
    )
    return cur.astype(U8)


def plan_callable(plan: Plan, *, impl: str = "xla"):
    """The full-image executor for a plan: an image -> image function
    (jit it / vmap it like any backend callable). Barrier stages run
    their golden op; fused stages run as one pass each."""
    if impl not in PLAN_IMPLS:
        raise ValueError(f"unknown plan impl {impl!r}; known: {PLAN_IMPLS}")

    def run(img: jnp.ndarray) -> jnp.ndarray:
        for stage in plan.stages:
            if stage.kind in ("geometric", "global"):
                img = stage.ops[0](img)
            else:
                with jax.named_scope(f"plan_stage_{stage.kind}"):
                    img = run_stage_full(stage, img, impl)
        return img

    return run


def unfused_callables(ops, *, jit: bool = True) -> list:
    """One independently compiled callable per op — the op-at-a-time
    execution model (each op a full HBM round trip, like the reference's
    sequential kernel launches). This is the `--plan off` golden lane the
    plan_ab bench and the smoke gate time the fused plan against."""
    if jit:
        # close over the op rather than jitting the (frozen, ndarray-
        # holding, hence unhashable) spec dataclass itself
        return [jax.jit(lambda x, o=op: o(x)) for op in ops]
    return list(ops)


def run_unfused(fns, img):
    for f in fns:
        img = f(img)
    return img
