"""The fusion planner: op chain -> `Plan`, plus the mode resolution every
entry point shares.

Three build modes, all bit-identical in output (the property tests
hammer this) — they differ only in execution structure:

  * ``off``       — one stage per op: the per-op golden reference
                    execution (`--plan off`). What every fused plan is
                    gated bit-exact against.
  * ``pointwise`` — pointwise absorption only: each stage carries at most
                    one stencil with its adjacent pointwise run; stencils
                    never merge with each other (no temporal blocking).
  * ``fused``     — full fusion: maximal pointwise/stencil runs become one
                    stage whose halo is the run's chain_halo (temporal
                    blocking: ONE ghost exchange / seam strip / extension
                    buys the whole stage).

``resolve_plan_mode`` maps the user-facing ``plan`` knob ('auto' plus the
three modes) to a build mode per (backend, pipeline, width): 'auto'
consults the calibration store's plan-choice table (`autotune
--dimension plan`) keyed by (pipeline fingerprint, device kind, width
window), defaults to 'fused' on the pure-XLA/MXU backends, and stays
'off' for backends with their own in-kernel group fusion (pallas/swar)
and for `impl=auto` without a calibrated win — so the measured Pallas
routing keeps its structure unless a plan measurement beats it.
"""

from __future__ import annotations

from mpi_cuda_imagemanipulation_tpu.ops.registry import op_family
from mpi_cuda_imagemanipulation_tpu.ops.spec import chain_halo
from mpi_cuda_imagemanipulation_tpu.plan.ir import Plan, Stage
from mpi_cuda_imagemanipulation_tpu.plan.metrics import plan_metrics
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

# the user-facing knob ('on' is an accepted alias for 'fused'); build
# modes are the subset without 'auto'/'on'. 'fused-pallas' partitions
# exactly like 'fused' but executes each eligible stage as ONE
# VMEM-resident megakernel (plan/pallas_exec.py) — a distinct build mode
# so Plan.fingerprint (the serving compile-cache key) distinguishes the
# two executions. 'fused-pallas-mxu' is the same megakernel with the
# per-op in-stage MXU arms FORCED on (ops/mxu_kernels.stage_arm_for
# setting 'on'): eligible stencils contract as dot_generals inside the
# pallas_call body instead of walking the VPU — again a distinct build
# mode, so the tune controller can propose it as an arm and the compile
# cache rebuilds on a flip. Under plain 'fused-pallas' the arms still
# resolve per op via MCIM_MXU_STAGE / the stage_arm calibration table —
# the forced mode exists for A/Bs and for the tuner's arm vocabulary.
PLAN_MODES = ("auto", "off", "pointwise", "fused", "fused-pallas",
              "fused-pallas-mxu")
BUILD_MODES = ("off", "pointwise", "fused", "fused-pallas",
               "fused-pallas-mxu")

# geometric ops that are pure pixel permutations with unchanged (H, W):
# a per-pixel (pointwise) op commutes with them exactly —
# p(g(x)) == g(p(x)) element for element — so the planner may hoist them
# left past pointwise runs to merge runs a geometric barrier would
# otherwise split (PR 10 leftover). Shape-changing permutations
# (transpose/rot90) and interpolating ops (resize/rotate) stay barriers
# in place; pad does too (pointwise(0) != 0 in general).
_COMMUTE_GEOMS = ("rot180", "fliph", "flipv")

# backends whose kernels carry their own measured group fusion — the
# planner must not restructure what their in-kernel streaming already
# fused (ops/pallas_kernels.run_group, ops/swar_kernels.swar_stencil)
_SELF_FUSING_BACKENDS = ("pallas", "swar")


def _norm_mode(plan: str) -> str:
    mode = (plan or "auto").strip().lower()
    if mode == "on":
        mode = "fused"
    if mode not in PLAN_MODES:
        raise ValueError(f"unknown plan mode {plan!r}; known: {PLAN_MODES}")
    return mode


def resolve_plan_mode(
    ops,
    plan: str = "auto",
    *,
    backend: str = "xla",
    width: int | None = None,
) -> str:
    """The build mode for this (pipeline, backend, width) — 'off',
    'pointwise' or 'fused'. Pure resolution, no tracing; safe on the
    build path (it may touch the live backend's device kind for the
    calibration lookup, like every other calibrated decision)."""
    mode = _norm_mode(plan)
    if mode == "auto":
        env_mode = env_registry.get("MCIM_PLAN")
        if env_mode:
            mode = _norm_mode(env_mode)
    if mode != "auto":
        if mode != "off" and backend in _SELF_FUSING_BACKENDS:
            from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

            get_logger().info(
                "plan=%s ignored for backend %r (its kernels fuse groups "
                "in-stream already); running per-op", mode, backend,
            )
            return "off"
        return mode
    if backend in _SELF_FUSING_BACKENDS:
        return "off"
    from mpi_cuda_imagemanipulation_tpu.plan.ir import pipeline_fingerprint
    from mpi_cuda_imagemanipulation_tpu.tune.store import (
        effective_plan_choice,
    )

    # newest-wins across the offline autotune record and the online
    # tuner's promoted choice (tune/store — freshness precedence;
    # subsumes the plain calibration.lookup_plan_choice this used to do)
    calibrated = effective_plan_choice(
        pipeline_fingerprint(ops), width=width
    )
    if calibrated is not None:
        return calibrated
    # no measured choice: the pure-XLA/MXU executors default to fused (the
    # structural win is one-sided there); impl=auto keeps its measured
    # Pallas group routing until a plan calibration beats it. 'auto'
    # NEVER defaults to fused-pallas — the megakernel enters only behind
    # a recorded `autotune --dimension plan` win (the standard
    # new-backend discipline).
    return "off" if backend == "auto" else "fused"


def commute_geometrics(ops) -> tuple:
    """Bubble commuting geometric ops (rot180/flip — pixel permutations)
    LEFT past adjacent pointwise ops, so a permutation sandwiched between
    pointwise runs stops splitting an otherwise-fusable stage:

        pw1, rot180, pw2, stencil  ->  rot180, pw1, pw2, stencil

    Each swap (pointwise, geom) -> (geom, pointwise) is bit-exact — a
    per-pixel op composed with a pixel permutation commutes element for
    element — so the reordered chain's output is identical (the seeded
    property sweep in tests/test_plan.py asserts it). Stage count never
    increases: hoisting only ever merges pointwise runs. Disable with
    MCIM_PLAN_COMMUTE=0 (A/B escape hatch)."""
    if not env_registry.get_bool("MCIM_PLAN_COMMUTE"):
        return tuple(ops)
    out = list(ops)
    for i in range(1, len(out)):
        if (
            op_family(out[i]) == "geometric"
            and out[i].name in _COMMUTE_GEOMS
        ):
            j = i
            while j > 0 and op_family(out[j - 1]) == "pointwise":
                out[j - 1], out[j] = out[j], out[j - 1]
                j -= 1
    return tuple(out)


def build_plan(ops, mode: str = "fused") -> Plan:
    """Partition `ops` into execution stages per `mode` (a BUILD mode —
    resolve 'auto' with resolve_plan_mode first). Fusing modes first
    hoist commuting geometric ops out of pointwise runs
    (`commute_geometrics`); `mode='off'` keeps the user's op order — the
    golden reference never restructures."""
    ops = tuple(ops)
    if mode not in BUILD_MODES:
        raise ValueError(f"unknown build mode {mode!r}; known: {BUILD_MODES}")
    if mode != "off":
        ops = commute_geometrics(ops)
        # the injectable planner fault (resilience/failpoints.py): an armed
        # `plan.fuse` site fails the fusion decision loudly at build time —
        # before any executable exists — so callers' build-path error
        # handling is testable without a real planner bug
        failpoints.maybe_fail(
            "plan.fuse", n_ops=len(ops), mode=mode
        )
    stages: list[Stage] = []
    run: list = []  # current pointwise/stencil run

    def flush_run() -> None:
        if not run:
            return
        if mode == "off":
            for op in run:
                stages.append(Stage("fused", (op,), op.halo))
        elif mode == "pointwise":
            # split so each stage holds at most one stencil: a stencil
            # closes its stage, absorbing the pointwise run before it; a
            # trailing pointwise run rides the last stage's write
            cur: list = []
            for op in run:
                cur.append(op)
                if op_family(op) == "stencil":
                    stages.append(Stage("fused", tuple(cur), chain_halo(cur)))
                    cur = []
            if cur:
                if stages and stages[-1].kind == "fused" and run[0] is not cur[0]:
                    prev = stages.pop()
                    merged = prev.ops + tuple(cur)
                    stages.append(Stage("fused", merged, prev.halo))
                else:
                    stages.append(Stage("fused", tuple(cur), 0))
        else:  # fused: the whole run is one temporally-blocked stage
            stages.append(Stage("fused", tuple(run), chain_halo(run)))
        run.clear()

    for op in ops:
        fam = op_family(op)
        if fam == "geometric":
            flush_run()
            stages.append(Stage("geometric", (op,), 0))
        elif fam == "global-stat":
            flush_run()
            stages.append(Stage("global", (op,), 0))
        else:
            run.append(op)
    flush_run()
    plan = Plan(stages=tuple(stages), mode=mode)
    plan_metrics.on_build(plan)
    return plan
