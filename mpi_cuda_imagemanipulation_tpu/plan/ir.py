"""The op-graph IR: a pipeline compiled into fused execution stages.

A `Plan` is a partition of the op chain into `Stage`s, in op order:

  * ``fused``     — a run of pointwise/stencil ops executed as ONE pass:
                    the carried image stays in f32 (exact u8 integer
                    values — the package's cross-backend invariant, see
                    ops/spec.py) between ops, stencils consume context
                    rows from a stage-level halo grown ONCE
                    (`Stage.halo` = the chain_halo of the stage), and u8
                    is materialised only at the stage boundary. A fused
                    stage with zero stencils is a pure elementwise pass.
  * ``geometric`` — one shape-changing data-movement op; a barrier
                    (re-indexes globally, so nothing fuses across it).
  * ``global``    — one full-image-statistic op; a barrier (its stats
                    pass needs every pixel before its apply pass).

The IR is deliberately tiny: stages are the only structure any executor
needs — the sharded runner exchanges `Stage.halo` ghost rows once per
stage, the stream engine sizes its seam strips per stage, and the
full-image executor walks each stage as one fusion region. Classification
comes from `ops.registry.op_family` (the explicit per-op family export),
never from planner-side isinstance sniffing.
"""

from __future__ import annotations

import dataclasses
import hashlib

from mpi_cuda_imagemanipulation_tpu.ops.registry import op_family
from mpi_cuda_imagemanipulation_tpu.ops.spec import Op, chain_halo

STAGE_KINDS = ("fused", "geometric", "global")


def _op_hbm_passes(op: Op) -> int:
    """Whole-image HBM passes the per-op execution model charges for one
    op: 1 read+write pass, except global-statistics ops, whose stats and
    apply halves each read the image (2)."""
    return 2 if op_family(op) == "global-stat" else 1


@dataclasses.dataclass(frozen=True)
class Stage:
    """One fused execution region, in global op order."""

    kind: str  # one of STAGE_KINDS
    ops: tuple[Op, ...]
    halo: int  # sum of member stencil halos (the stage's grown halo)

    def __post_init__(self):
        if self.kind not in STAGE_KINDS:  # pragma: no cover - planner bug
            raise ValueError(f"unknown stage kind {self.kind!r}")

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(op.name for op in self.ops)

    @property
    def n_stencils(self) -> int:
        return sum(1 for op in self.ops if op_family(op) == "stencil")

    @property
    def hbm_passes(self) -> int:
        """Passes this stage costs under the fused model: one for a fused
        region regardless of member count; barriers keep their op cost."""
        if self.kind == "fused":
            return 1
        return _op_hbm_passes(self.ops[0])


@dataclasses.dataclass(frozen=True)
class Plan:
    """A compiled stage partition of one op chain."""

    stages: tuple[Stage, ...]
    mode: str  # 'off' | 'pointwise' | 'fused' (how it was built)

    @property
    def ops(self) -> tuple[Op, ...]:
        return tuple(op for s in self.stages for op in s.ops)

    @property
    def total_halo(self) -> int:
        """Sum of stage halos — equals chain_halo(ops) by construction
        (asserted by the property tests): fusing never changes the total
        row context the chain needs."""
        return sum(s.halo for s in self.stages)

    @property
    def fused_stages(self) -> tuple[Stage, ...]:
        return tuple(s for s in self.stages if s.kind == "fused")

    @property
    def n_absorbed_ops(self) -> int:
        """Ops that ride another op's HBM pass instead of paying their
        own (member count minus one, per multi-op fused stage)."""
        return sum(len(s.ops) - 1 for s in self.fused_stages)

    @property
    def hbm_passes(self) -> int:
        return sum(s.hbm_passes for s in self.stages)

    @property
    def hbm_passes_unfused(self) -> int:
        return sum(_op_hbm_passes(op) for op in self.ops)

    @property
    def hbm_passes_saved(self) -> int:
        return self.hbm_passes_unfused - self.hbm_passes

    @property
    def fingerprint(self) -> str:
        """Stable identity of the *execution structure*: pipeline ops plus
        the stage partition. The serving compile cache keys executables by
        this, so a calibration flip (auto resolving to a different mode)
        can never serve a stale executable built for another structure."""
        key = pipeline_fingerprint(self.ops) + "|" + self.mode + "|" + ";".join(
            f"{s.kind}:{','.join(s.names)}:h{s.halo}" for s in self.stages
        )
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def describe(self) -> str:
        """One human line per stage (CLI/log exposition)."""
        rows = []
        for i, s in enumerate(self.stages):
            rows.append(
                f"  stage {i} [{s.kind}] halo={s.halo}: {'+'.join(s.names)}"
            )
        head = (
            f"plan mode={self.mode}: {len(self.ops)} ops -> "
            f"{len(self.stages)} stages, hbm passes "
            f"{self.hbm_passes_unfused} -> {self.hbm_passes}"
        )
        return "\n".join([head, *rows])


def pipeline_fingerprint(ops) -> str:
    """Stable identity of an op chain (names + halos + families) — the
    calibration store's plan-choice key, shared by autotune and the
    `plan='auto'` resolution so they can never drift."""
    key = "|".join(f"{op.name}/{op_family(op)}/h{op.halo}" for op in ops)
    return hashlib.sha256(key.encode()).hexdigest()[:16]
