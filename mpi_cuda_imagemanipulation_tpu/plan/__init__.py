"""Op-graph fusion planner (ROADMAP item 3).

`plan/` compiles an op chain into fused execution *stages* before any
backend dispatches: pointwise prefixes/suffixes are absorbed into their
neighbouring stencil's read/write, and consecutive stencils are
temporally blocked — the stage grows its halo once (`ops.spec.chain_halo`
over the stage) instead of extending/exchanging per op. Every executor
that consumes a plan (`Pipeline.jit/batched/sharded/serving`, the
streaming tile engine) then does one HBM pass per stage, one ppermute
ghost exchange per stage on the sharded path, and one seam strip per
stage on the stream path — while staying bit-identical to the per-op
golden chain (`--plan off`), which remains the reference execution.
"""

from mpi_cuda_imagemanipulation_tpu.plan.ir import (
    Plan,
    Stage,
    pipeline_fingerprint,
)
from mpi_cuda_imagemanipulation_tpu.plan.metrics import PlanMetrics, plan_metrics
from mpi_cuda_imagemanipulation_tpu.plan.planner import (
    PLAN_MODES,
    build_plan,
    resolve_plan_mode,
)

__all__ = [
    "PLAN_MODES",
    "Plan",
    "PlanMetrics",
    "Stage",
    "build_plan",
    "pipeline_fingerprint",
    "plan_metrics",
    "resolve_plan_mode",
]
