"""The fused-pallas stage executor: `plan='fused-pallas'`.

`plan/exec.py` walks a fused stage as ONE XLA computation — one HBM pass
per stage, but the carry between member ops still materialises in HBM
between the stage's internal device passes whenever XLA's fusion gives
up (multi-stencil stages, wide live sets). This module lowers an entire
eligible fused `Stage` into ONE `pallas_call`
(ops/pallas_kernels.fused_stage_call): the pointwise runs, every member
stencil, the per-op edge extension and the finalize all execute
block-by-block with intermediates resident in VMEM/registers, and the
HBM traffic per stage drops to one u8 read (+ a ~5% halo-strip overlap)
plus one u8 write — the road past the 0.11 roofline fraction the
BENCH_HISTORY plan_ab record measures for the fused-XLA plan.

Gating is the package's standard backend discipline:

  * bit-exactness — the megakernel reproduces `--plan off` bit for bit
    (the in-kernel walk is the sharded `edge_fix` convention of
    plan/exec.walk_stage, built from the same ops/spec tile functions;
    hammered by tests/test_plan.py's fused-pallas lanes and the
    megakernel smoke);
  * per-op fallback — a stage the eligibility matrix rejects (LUT
    member, oversized halo, image too small for in-kernel edge
    synthesis, VMEM budget) runs through the XLA stage walker instead,
    counted per reason in `mcim_plan_pallas_fallbacks_total`;
  * measured entry — `plan='auto'` only resolves to 'fused-pallas'
    behind a calibration win recorded by `autotune --dimension plan`
    (utils/calibration.PLAN_CHOICES);
  * CPU — kernels run `interpret=True` off-TPU, exactly like the
    existing `backend='pallas'` guard rails (ops/pallas_kernels).

Eligibility matrix (the docs/design.md table is rendered from this):

  consumer               fused-pallas execution
  ---------------------  -------------------------------------------
  jit / batched / dp     megakernel per eligible stage (this module)
  sharded serial (1-D)   ghost-mode megakernel per eligible stage —
                         the stage's ONE ppermute pair is preserved;
                         the kernel consumes the pre-exchanged rows
                         (parallel/api._run_segment_planned)
  sharded overlap        XLA stage walker (the interior-first split is
                         a measured structure; not restructured)
  serving (bucket pad)   XLA stage walker — dynamic true-shape borders
                         are gather-built per op, which is exactly what
                         a static-block Mosaic kernel cannot express;
                         the resolved fingerprint still keys the cache
  stream tiles           XLA stage walker — seam budgets thread across
                         stages on the host-tiled path unchanged
  2-D tile shards        XLA stage walker (parallel/api2d stage forms)
"""

from __future__ import annotations

import jax.numpy as jnp

from mpi_cuda_imagemanipulation_tpu.ops.registry import op_family
from mpi_cuda_imagemanipulation_tpu.ops.spec import StencilOp
from mpi_cuda_imagemanipulation_tpu.plan.ir import Plan, Stage
from mpi_cuda_imagemanipulation_tpu.plan.metrics import plan_metrics

# stage halos past this would make the context strips a material fraction
# of the block read; no registry chain comes close (gaussian:7 x2 = 6)
STAGE_MAX_HALO = 16


def stage_pallas_reject(
    stage: Stage, height: int, width: int, channels: int
) -> str | None:
    """Why this stage cannot run as a megakernel, or None when it can.

    The closed reason vocabulary labels
    `mcim_plan_pallas_fallbacks_total`; every reason maps to a fallback
    path that is bit-exact by construction (the XLA stage walker)."""
    if stage.kind != "fused":
        return "barrier"
    for op in stage.ops:
        fam = op_family(op)
        if fam == "pointwise":
            if not op.kernel_safe:
                return "lut-op"  # gather LUTs cannot lower in Mosaic
            if (
                op.core is None
                and op.planes_core is None
                and op.name != "gray2rgb"
            ):
                return "no-f32-core"
        elif fam != "stencil":  # pragma: no cover - planner invariant
            return "barrier"
    H = stage.halo
    if H > STAGE_MAX_HALO:
        return "halo-too-large"
    max_op_halo = max((op.halo for op in stage.ops), default=0)
    # in-kernel edge synthesis feasibility: vertical reflect sources must
    # be real rows (height > 2H covers the one-block case where both
    # edges land in the same carry), and the width extension's reflected
    # columns must exist
    if H and height <= 2 * H:
        return "image-too-small"
    if max_op_halo and width <= max_op_halo:
        return "image-too-small"
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        fused_stage_block_h,
    )

    if fused_stage_block_h(stage.ops, H, width, max(channels, 1)) is None:
        return "vmem-budget"
    return None


def stage_io_scale(plan: Plan, i: int) -> float | None:
    """The measured cost-ledger drift for stage `i` of `plan` — the
    ratio of measured boundary bytes to the one-read-one-write model the
    block-height picker reserves for (obs/cost.attribute_plan records it
    under the plan fingerprint + `s<i>/<kind>` label). A live in-process
    ledger record wins; failing that, the online tuning store's
    PERSISTED ratio (recorded by any replica that ran this fingerprint,
    tune/store) — so a fresh process corrects its VMEM model from fleet
    measurement instead of starting analytical every time. None when
    nothing was measured anywhere; the analytical model stays the
    fallback."""
    from mpi_cuda_imagemanipulation_tpu.obs.cost import cost_ledger

    st = plan.stages[i]
    label = f"s{i}/{st.kind}"
    ratio = cost_ledger.drift("plan", plan.fingerprint, label)
    if ratio is not None:
        return ratio
    from mpi_cuda_imagemanipulation_tpu.tune.store import persisted_io_scale

    return persisted_io_scale(plan.fingerprint, label)


def run_stage_pallas(
    stage: Stage,
    img: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
    io_scale: float | None = None,
    mxu_stage: str | None = None,
) -> jnp.ndarray:
    """One eligible fused stage over a whole u8 image as one megakernel
    launch (planar channel decomposition at the stage boundary, like
    every Pallas path). `mxu_stage` overrides MCIM_MXU_STAGE for the
    per-op in-stage MXU arm resolution."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        fused_stage_call,
    )

    if img.ndim == 3:
        planes = [img[..., c] for c in range(img.shape[2])]
    else:
        planes = [img]
    outs = fused_stage_call(
        stage.ops, planes, halo=stage.halo,
        interpret=interpret, block_h=block_h, io_scale=io_scale,
        mxu_stage=mxu_stage,
    )
    return outs[0] if len(outs) == 1 else jnp.stack(outs, axis=-1)


def run_stage_pallas_ext(
    stage: Stage,
    ext: jnp.ndarray,
    *,
    y0,
    image_h: int,
    image_w: int,
    interpret: bool | None = None,
    block_h: int | None = None,
    mxu_stage: str | None = None,
) -> jnp.ndarray:
    """Ghost-mode megakernel over a (local_h + 2*Stage.halo, W[, C]) tile
    whose context rows were materialised by the stage's single ppermute
    pair (parallel/api). `y0` is the tile's traced global row offset."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        fused_stage_call,
    )

    if ext.ndim == 3:
        planes = [ext[..., c] for c in range(ext.shape[2])]
    else:
        planes = [ext]
    outs = fused_stage_call(
        stage.ops, planes, halo=stage.halo,
        interpret=interpret, block_h=block_h,
        ghosts=True, y0=y0, image_h=image_h, image_w=image_w,
        mxu_stage=mxu_stage,
    )
    return outs[0] if len(outs) == 1 else jnp.stack(outs, axis=-1)


def plan_callable_pallas(
    plan: Plan,
    *,
    impl: str = "xla",
    interpret: bool | None = None,
    block_h: int | None = None,
    mxu_stage: str | None = None,
):
    """The full-image fused-pallas executor: an image -> image function
    (jit/vmap it like any backend callable). Eligible fused stages run
    as megakernels; rejected stages fall back to the shared XLA stage
    walker (plan/exec.run_stage_full, `impl` = its accumulator routing);
    barrier stages run their golden op. `mxu_stage` forces the per-op
    in-stage MXU arm setting ('on' under plan=fused-pallas-mxu; None =
    MCIM_MXU_STAGE / calibration auto). Eligibility is re-judged per
    traced shape — the same chain can megakernel an 8K frame and walk a
    thumbnail — and every decision is counted (mcim_plan_pallas_*)."""
    from mpi_cuda_imagemanipulation_tpu.plan.exec import (
        PLAN_IMPLS,
        run_stage_full,
    )

    if impl not in PLAN_IMPLS:
        raise ValueError(f"unknown plan impl {impl!r}; known: {PLAN_IMPLS}")

    def run(img: jnp.ndarray) -> jnp.ndarray:
        import jax

        for i, stage in enumerate(plan.stages):
            if stage.kind in ("geometric", "global"):
                img = stage.ops[0](img)
                continue
            h, w = img.shape[0], img.shape[1]
            ch = img.shape[2] if img.ndim == 3 else 1
            reason = stage_pallas_reject(stage, h, w, ch)
            if reason is None:
                plan_metrics.pallas_stages.inc()
                with jax.named_scope("plan_stage_pallas"):
                    img = run_stage_pallas(
                        stage, img, interpret=interpret, block_h=block_h,
                        io_scale=stage_io_scale(plan, i),
                        mxu_stage=mxu_stage,
                    )
            else:
                plan_metrics.pallas_fallbacks.inc(reason=reason)
                with jax.named_scope("plan_stage_fallback"):
                    img = run_stage_full(stage, img, impl)
        return img

    return run
