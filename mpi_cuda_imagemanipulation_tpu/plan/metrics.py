"""Planner instrumentation — the `mcim_plan_*` metric family.

One module-level registry: plans are built at executable-construction
time from many entry points (jit/batched/sharded/serving/stream), and a
per-call registry would fragment the counters across them. The smoke
gate (tools/plan_smoke.py) asserts from these that a fused build
actually reduced modelled HBM passes, and `--json-metrics` surfaces
`snapshot()` wherever a plan ran.

This registry also federates: a fabric replica's heartbeat delta
snapshots include it (serve/server.ServeApp.fleet_registries), so a
calibration flip that rebuilds plans mid-flight shows up in the router's
fleet view as `mcim_plan_builds_total` movement next to the serving
counters it affects.
"""

from __future__ import annotations

from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry


class PlanMetrics:
    def __init__(self, registry: Registry | None = None):
        self.registry = registry or Registry()
        r = self.registry
        self.builds = r.counter(
            "mcim_plan_builds_total",
            "Plans built, by build mode (off/pointwise/fused).",
            labels=("mode",),
        )
        self.stages = r.counter(
            "mcim_plan_stages_total",
            "Stages emitted across all built plans, by kind.",
            labels=("kind",),
        )
        self.fused_ops = r.counter(
            "mcim_plan_fused_ops_total",
            "Ops absorbed into another op's HBM pass (fused-stage members "
            "beyond the first).",
        )
        self.passes_saved = r.counter(
            "mcim_plan_hbm_passes_saved_total",
            "Modelled whole-image HBM passes removed vs per-op execution, "
            "summed over built plans.",
        )
        # fused-pallas backend instrumentation (plan/pallas_exec.py):
        # decisions are made per traced shape at executable-build time,
        # so these advance once per (re)trace, not per dispatch
        self.pallas_stages = r.counter(
            "mcim_plan_pallas_stages_total",
            "Fused stages lowered as VMEM-resident megakernel launches "
            "(one pallas_call per stage; plan=fused-pallas).",
        )
        self.pallas_fallbacks = r.counter(
            "mcim_plan_pallas_fallbacks_total",
            "Fused-pallas stages rejected to the XLA stage walker, by "
            "closed reason (lut-op/no-f32-core/halo-too-large/"
            "image-too-small/vmem-budget/barrier).",
            labels=("reason",),
        )
        # per-op-within-stage MXU arm accounting (ops/mxu_kernels
        # stage_arm_for): resolved host-side per stage (re)trace, like
        # pallas_stages — the silent-ineligibility gap closed in round 8
        self.mxu_stage_ops = r.counter(
            "mcim_plan_mxu_in_stage_total",
            "Stencil ops lowered as MXU dot contractions inside a "
            "fused-pallas stage body, by arm (mxu/mxu-int8).",
            labels=("arm",),
        )
        self.mxu_stage_fallbacks = r.counter(
            "mcim_plan_mxu_in_stage_fallback_total",
            "MXU-capable stencil ops that landed on the VPU inside a "
            "fused-pallas stage, by closed reason (off/family/not-tpu/"
            "no-calibration; ops/mxu_kernels.STAGE_FALLBACK_REASONS).",
            labels=("reason",),
        )

    def on_build(self, plan) -> None:
        self.builds.inc(mode=plan.mode)
        for s in plan.stages:
            self.stages.inc(kind=s.kind)
        self.fused_ops.inc(plan.n_absorbed_ops)
        self.passes_saved.inc(plan.hbm_passes_saved)

    def snapshot(self) -> dict:
        return {
            "builds_fused": int(self.builds.value(mode="fused")),
            "builds_pointwise": int(self.builds.value(mode="pointwise")),
            "builds_off": int(self.builds.value(mode="off")),
            "builds_fused_pallas": int(
                self.builds.value(mode="fused-pallas")
            ),
            "builds_fused_pallas_mxu": int(
                self.builds.value(mode="fused-pallas-mxu")
            ),
            "stages_fused": int(self.stages.value(kind="fused")),
            "fused_ops": int(self.fused_ops.value()),
            "hbm_passes_saved": int(self.passes_saved.value()),
            "pallas_stages": int(self.pallas_stages.value()),
            "mxu_stage_ops": int(
                self.mxu_stage_ops.value(arm="mxu")
                + self.mxu_stage_ops.value(arm="mxu-int8")
            ),
        }


# the shared instance every build reports into (see module docstring)
plan_metrics = PlanMetrics()
