"""Durable tenant/spec registry — the federation state that must
survive a full restart.

A pod router holds tenant configs and pipeline specs only in memory: a
pod restart is recovered by the front door re-pushing stored state
before the first forward. But if the FRONT DOOR restarts, that stored
state must come from somewhere other than client re-registration — so
every accepted registration (tenant config, pipeline spec, session
binding) is appended here first, in the BatchJournal style
(resilience/journal.py): append-only JSONL, one record per line,
flush + fsync per append, a torn trailing line from a mid-write kill
terminated on the next append and skipped on load, later lines winning.

Record schema (one JSON object per line):

    {"kind": "tenant" | "pipeline" | "session",
     "key": "<tenant id>" | "<tenant>/<pipeline id>" | "<session id>",
     "payload": {...} | null,          (null = tombstone)
     "t_unix_s": <float>}

Re-appending an identical record is harmless (idempotent re-push is a
registration API guarantee, and load keeps only the last record per
(kind, key)), and a tombstone (payload null) deletes on replay.
"""

from __future__ import annotations

import json
import os
import threading
import time

KINDS = ("tenant", "pipeline", "session")

DEFAULT_NAME = ".mcim_fed_registry.jsonl"


class DurableRegistry:
    """The front door's fsync'd state journal + its in-memory view.

    `load()` replays the file into the in-memory maps; `put()`/`delete()`
    append THEN update memory, so an acknowledged registration is on
    disk before any client sees a 200 — a front-door crash between the
    two loses nothing a client was told succeeded."""

    def __init__(self, path: str | os.PathLike):
        self.path = str(path)
        self._lock = threading.Lock()
        # kind -> key -> payload (the replayed later-lines-win view)
        self._state: dict[str, dict[str, dict]] = {k: {} for k in KINDS}
        self.loaded_records = 0
        self.skipped_lines = 0  # torn/corrupt lines tolerated on load

    # -- load (replay) -----------------------------------------------------

    def load(self) -> "DurableRegistry":
        """Replay the journal into memory. Tolerates a missing file, torn
        trailing line, and corrupt interior lines (each skipped line is
        counted, never fatal — a registry that refuses to start over one
        bad line turns a crash into an outage)."""
        state: dict[str, dict[str, dict]] = {k: {} for k in KINDS}
        loaded = skipped = 0
        try:
            f = open(self.path, encoding="utf-8")
        except FileNotFoundError:
            f = None
        if f is not None:
            with f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        skipped += 1  # torn write from a mid-append kill
                        continue
                    if (
                        not isinstance(rec, dict)
                        or rec.get("kind") not in KINDS
                        or not isinstance(rec.get("key"), str)
                    ):
                        skipped += 1
                        continue
                    payload = rec.get("payload")
                    if payload is None:
                        state[rec["kind"]].pop(rec["key"], None)
                    else:
                        state[rec["kind"]][rec["key"]] = payload
                    loaded += 1
        with self._lock:
            self._state = state
            self.loaded_records = loaded
            self.skipped_lines = skipped
        return self

    # -- append ------------------------------------------------------------

    def _append(self, rec: dict) -> None:
        line = json.dumps(rec, sort_keys=True)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a+", encoding="utf-8") as f:
            # a torn line from a mid-write kill must only lose ITSELF:
            # terminate an unterminated final line so this record starts
            # fresh and stays parseable (resilience/journal.py idiom)
            f.seek(0, os.SEEK_END)
            if f.tell() > 0:
                f.seek(f.tell() - 1)
                if f.read(1) != "\n":
                    f.write("\n")
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())

    def put(self, kind: str, key: str, payload: dict) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown record kind {kind!r}")
        with self._lock:
            self._append(
                {
                    "kind": kind,
                    "key": key,
                    "payload": payload,
                    "t_unix_s": time.time(),
                }
            )
            self._state[kind][key] = payload

    def delete(self, kind: str, key: str) -> None:
        """Append a tombstone (payload null) and drop the key."""
        if kind not in KINDS:
            raise ValueError(f"unknown record kind {kind!r}")
        with self._lock:
            self._append(
                {
                    "kind": kind,
                    "key": key,
                    "payload": None,
                    "t_unix_s": time.time(),
                }
            )
            self._state[kind].pop(key, None)

    # -- views -------------------------------------------------------------

    def get(self, kind: str, key: str) -> dict | None:
        with self._lock:
            return self._state[kind].get(key)

    def items(self, kind: str) -> dict[str, dict]:
        with self._lock:
            return dict(self._state[kind])

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {k: len(v) for k, v in self._state.items()}
