"""Cross-pod quota leases — one global fixed-window budget, many pods.

PR 13's tenant quotas are enforced replica-side inside one pod, with
the router relaying a quota shed as FINAL so retries cannot multiply
the budget by replica count. Federation reopens the hole one level up:
if every pod pushes the tenant's FULL budget to its replicas, a tenant
driving P pods gets P x budget per window. The fix is the same shape as
the shed-is-final rule — make the budget a resource the upper tier
OWNS and the lower tier borrows:

    lease   an integral share of one tenant's per-window budget granted
            to one pod for the CURRENT fixed window. The pod overwrites
            the quota fields of its stored tenant config with the share
            and re-pushes to its replicas, which enforce it exactly as
            before (no replica-side changes at all).

Grant discipline (the invariant the tests pin):

  * shares are granted out of the window's REMAINING budget — the sum
    of granted shares can never exceed the budget, across any sequence
    of membership changes within a window;
  * a pod that already holds a lease for the current window gets THE
    SAME lease back (reconnect/heartbeat repeat is idempotent — an
    unexpired lease is honored, never re-split, because its tokens may
    already be spent);
  * a pod joining mid-window splits only what is still ungranted, in
    equal integral shares over the live pods that hold no lease yet;
  * a pod that dies mid-window keeps its grant on the books until the
    window rolls — conservative by construction (its unspent tokens are
    unavailable, never double-granted);
  * a new window forgets everything and re-splits over the pods live at
    grant time.

Windows are keyed by `int(now / window_s)` on the front door's clock.
Replica windows start at each tenant's first request, so the two tiers'
windows are not phase-aligned — the guarantee is "never more than one
global budget per FRONT-DOOR window", the same fixed-window semantics a
single pod already gives (graph/tenancy.py).
"""

from __future__ import annotations

import threading


class LeaseLedger:
    """Per-tenant, per-window grant book. Pure arithmetic over an
    injected clock — unit-testable with no pods anywhere."""

    def __init__(self, *, clock):
        self._clock = clock
        self._lock = threading.Lock()
        # (tenant, window_id) -> {pod_id: {"quota_requests": int|None,
        #                                  "quota_bytes": int|None}}
        self._grants: dict[tuple[str, int], dict[str, dict]] = {}
        self.grants_issued = 0

    @staticmethod
    def _split(remaining: int | None, ways: int) -> int | None:
        """One new pod's integral share of the ungranted remainder.
        Floor division is the conservative rounding: P pods can under-
        use up to P-1 tokens per window, never overrun."""
        if remaining is None:
            return None  # unlimited budget: leases are unlimited too
        return max(0, remaining) // max(1, ways)

    def lease(
        self,
        tenant: str,
        config: dict,
        pod_id: str,
        live_pods: list[str],
        now: float,
    ) -> dict:
        """The lease `pod_id` holds for tenant `tenant` in the current
        window. `config` is the tenant's registered payload (its
        quota_requests / quota_bytes / window_s fields are read here);
        `live_pods` is the current fresh-pod set (pod_id included)."""
        window_s = float(config.get("window_s") or 1.0)
        window_id = int(now / window_s)
        key = (tenant, window_id)
        with self._lock:
            # drop stale windows so the book stays bounded
            for k in [k for k in self._grants if k[0] == tenant and k[1] != window_id]:
                del self._grants[k]
            grants = self._grants.setdefault(key, {})
            held = grants.get(pod_id)
            if held is not None:
                return {**held, "window_id": window_id}
            budget_r = config.get("quota_requests")
            budget_b = config.get("quota_bytes")
            granted_r = sum(
                g["quota_requests"] or 0 for g in grants.values()
            )
            granted_b = sum(
                g["quota_bytes"] or 0 for g in grants.values()
            )
            ungranted = [
                p for p in set(live_pods) | {pod_id} if p not in grants
            ]
            share = {
                "quota_requests": self._split(
                    None if budget_r is None else int(budget_r) - granted_r,
                    len(ungranted),
                ),
                "quota_bytes": self._split(
                    None if budget_b is None else int(budget_b) - granted_b,
                    len(ungranted),
                ),
            }
            grants[pod_id] = share
            self.grants_issued += 1
            return {**share, "window_id": window_id}

    def leases_for_pod(
        self,
        pod_id: str,
        tenants: dict[str, dict],
        live_pods: list[str],
    ) -> dict[str, dict]:
        """Every quota-bearing tenant's current lease for one pod — the
        heartbeat-ack payload. Tenants with no quota at all are skipped
        (nothing to enforce, nothing to push)."""
        now = self._clock()
        out: dict[str, dict] = {}
        for tenant, config in tenants.items():
            if (
                config.get("quota_requests") is None
                and config.get("quota_bytes") is None
            ):
                continue
            out[tenant] = self.lease(
                tenant, config, pod_id, live_pods, now
            )
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "grants_issued": self.grants_issued,
                "windows": [
                    {
                        "tenant": t,
                        "window_id": w,
                        "pods": {
                            p: dict(g) for p, g in grants.items()
                        },
                    }
                    for (t, w), grants in self._grants.items()
                ],
            }
