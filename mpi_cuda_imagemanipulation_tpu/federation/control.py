"""Federation control plane — the pod -> front-door heartbeat protocol.

The fabric's replica->router protocol (fabric/control.py) applied one
tier up, with the pod as the unit of membership: each pod's ROUTER
pushes one JSON `PodHeartbeat` to the front door's
`/control/podheartbeat` every `MCIM_FED_HEARTBEAT_S` seconds:

    pod_id        stable identity across pod restarts (the operator
                  names it; routing affinity and metric labels key on it)
    incarnation   unique per router process start — the front door
                  detects a pod restart by the change, resets that pod's
                  breaker, and re-pushes tenant/spec state before the
                  cold pod receives its first forward
    addr/port     where the pod's /v1/* front door actually listens
    routable      how many replicas the pod can currently route to —
                  0 means the pod is alive but has no serving capacity,
                  and the front door routes around it
    queued/queue_depth   pod-aggregate admission-queue fill (summed over
                  routable replicas) — the front door's load signal
    warm_buckets  union of the routable replicas' warm "HxW" buckets
    pipelines     pipeline ids this pod can serve (specs registered
                  through its router plus replica heartbeat echoes) —
                  the front door re-pushes a stored spec before
                  forwarding to a pod whose beat lacks the id
    metrics       metrics-federation delta over the pod ROUTER's own
                  registry (obs/fleet.py DeltaSource payload) — the same
                  machinery that federates replica->router is applied a
                  second time router->frontdoor, keyed by pod id

The front door's ack body closes the control loops without a second
channel: `resync: true` asks for a full metrics snapshot next beat, and
`leases` carries the pod's current per-tenant quota-share leases
(federation/quota.py) — the pod applies them by overwriting the quota
fields of its stored tenant configs and re-pushing to its replicas, so
a tenant's GLOBAL fixed-window budget holds no matter how many pods it
drives (PR 13's admission_shed_is_final invariant, re-proven at pod
granularity).

Liveness is the absence of beats (`MCIM_FED_STALE_S`), exactly like the
replica protocol. The `pod.heartbeat` failpoint drops beats on the
sender so partition handling is testable without killing anything.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import urllib.request
from typing import Callable

from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_FED_HEARTBEAT_S = "MCIM_FED_HEARTBEAT_S"

POD_HEARTBEAT_PATH = "/control/podheartbeat"

# request header the front door stamps on every forward so the serving
# replica (serve/server.py) can echo which pod carried the request —
# the end-to-end federation identity thread for traces and smoke checks
HDR_FED_POD = "X-Fed-Pod"


@dataclasses.dataclass
class PodHeartbeat:
    """One pod's pushed aggregate state — the wire format is its JSON
    dict, with the same strictness as the replica heartbeat: front door
    and pod routers ship from one tree, so unknown or missing fields are
    version-skew bugs worth failing loudly on."""

    pod_id: str
    addr: str
    port: int
    pid: int
    incarnation: str
    routable: int
    queued: int
    queue_depth: int
    warm_buckets: list[str]
    pipelines: list[str]
    seq: int
    sent_unix_s: float
    # metrics-federation delta (obs/fleet.py DeltaSource payload) over
    # the pod router's registry, or None for a metrics-less beat
    metrics: dict | None = None

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "PodHeartbeat":
        raw = json.loads(data)
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - fields
        if unknown:
            raise ValueError(
                f"pod heartbeat has unknown fields {sorted(unknown)}"
            )
        required = {
            f.name
            for f in dataclasses.fields(cls)
            if f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        }
        missing = required - set(raw)
        if missing:
            raise ValueError(
                f"pod heartbeat missing fields {sorted(missing)}"
            )
        return cls(**raw)


def default_fed_heartbeat_s() -> float:
    return float(env_registry.get(ENV_FED_HEARTBEAT_S))


class PodHeartbeatSender:
    """The pod-side push loop: one daemon thread POSTing `collect()`'s
    PodHeartbeat to the front door until `stop()`. Same failure posture
    as the replica sender (fabric/control.HeartbeatSender): a dropped
    beat or an unreachable front door never raises — the pod's job is
    serving, and the front door's staleness window is the protocol's
    loss handling."""

    def __init__(
        self,
        frontdoor_url: str,
        collect: Callable[[int], PodHeartbeat],
        *,
        interval_s: float | None = None,
        on_ack: Callable[[PodHeartbeat, dict], None] | None = None,
    ):
        self.url = frontdoor_url.rstrip("/") + POD_HEARTBEAT_PATH
        self._collect = collect
        # on_ack(hb, ack_body): the front door acknowledged — the pod's
        # DeltaSource advances its baseline here and the ack's quota
        # leases are applied (fabric/router.Router._apply_leases)
        self._on_ack = on_ack
        self.interval_s = (
            default_fed_heartbeat_s() if interval_s is None else interval_s
        )
        self.sent = 0
        self.dropped = 0  # failpoint-dropped beats
        self.failed = 0  # front door unreachable / send error
        self._seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._log = get_logger()

    def start(self) -> "PodHeartbeatSender":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="mcim-fed-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _loop(self) -> None:
        # first beat immediately: the front door learns the pod's
        # address from it, so registration latency is one send
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.interval_s)

    def beat(self) -> bool:
        """One send attempt; True when the front door acknowledged."""
        self._seq += 1
        hb = self._collect(self._seq)
        try:
            # an armed pod.heartbeat failpoint models POD-LINK LOSS: the
            # beat is dropped before the socket, the pod serves on
            failpoints.maybe_fail(
                "pod.heartbeat", pod=hb.pod_id, seq=hb.seq
            )
        except failpoints.FailpointError:
            self.dropped += 1
            return False
        req = urllib.request.Request(
            self.url,
            data=hb.to_json(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=max(self.interval_s, 0.2)
            ) as resp:
                body = resp.read()
            self.sent += 1
            if self._on_ack is not None:
                try:
                    ack = json.loads(body) if body else {}
                except ValueError:
                    ack = {}
                self._on_ack(hb, ack)
            return True
        except Exception as e:  # front door down: serve on, log sparsely
            self.failed += 1
            if self.failed in (1, 10, 100):
                self._log.warning(
                    "pod heartbeat %s -> %s failed (%s; %d so far)",
                    hb.pod_id, self.url, type(e).__name__, self.failed,
                )
            return False
