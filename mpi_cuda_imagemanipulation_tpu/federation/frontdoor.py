"""The federation front door — a meta-router over whole pods.

The fabric router's design (fabric/router.py) applied one tier up, with
the pod as the unit of membership:

  * pods register by PUSHING `PodHeartbeat`s (federation/control.py) —
    the front door never polls; liveness is the absence of beats past
    `MCIM_FED_STALE_S`;
  * routing is rendezvous-sticky per affinity key (tenant|pipeline|
    bucket for graph traffic, the bucket for chains, "sess|sid" for
    video sessions) so pod death reroutes ONLY the dead pod's affinity
    slice — every other key keeps its pod and its warm executables;
  * per-pod breakers trip fast and reset fast, and a pod-level
    admission shed (`{"status": "shed"}` 503) relays as FINAL — exactly
    the replica-tier rule that stops retries from multiplying a
    tenant's budget, re-proven at pod granularity;
  * tenant configs and pipeline specs are DURABLE here
    (federation/registry.py): an accepted registration is fsync'd
    before the 200, rehydrated on restart, and re-pushed to any pod
    whose heartbeat lacks the state before that pod sees a forward —
    so neither a pod restart nor a front-door restart costs a client a
    re-registration;
  * tenant quota budgets are LEASED to pods (federation/quota.py) on
    the heartbeat ack, never copied — a tenant driving every pod at
    once still gets one global budget per window.

Every routed-away-from-affinity request is counted in
`mcim_fed_reroutes_total{reason=...}` with a reason from the CLOSED
vocabulary `REROUTE_REASONS` via the `count_reroute` choke point — the
same discipline as the systolic fallback ladder (graph/systolic.py),
enforced by mcim-check (analysis/rules_obs.py).

Session placement is locality-aware by construction: a session id binds
to one pod, frames forward there, and the journal-tail failover replay
happens WITHIN that pod (its router owns the tail). A cross-pod move —
only after the owning pod dies — starts the session fresh on the new
pod (counted `session_reset`): replaying a tail across pods would mean
shipping every session's frames through the federation tier, which is
exactly the locality the Casper placement argument says not to give up.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mpi_cuda_imagemanipulation_tpu.fabric import session as fabric_session
from mpi_cuda_imagemanipulation_tpu.fabric.router import (
    Router,
    _is_admission_shed,
    _json_response,
    _rendezvous_score,
    _ConnPool,
    _STATUS_LABEL,
)
from mpi_cuda_imagemanipulation_tpu.federation.control import (
    HDR_FED_POD,
    POD_HEARTBEAT_PATH,
    PodHeartbeat,
)
from mpi_cuda_imagemanipulation_tpu.federation.quota import LeaseLedger
from mpi_cuda_imagemanipulation_tpu.federation.registry import (
    DEFAULT_NAME,
    DurableRegistry,
)
from mpi_cuda_imagemanipulation_tpu.obs import fleet as obs_fleet
from mpi_cuda_imagemanipulation_tpu.obs import metrics as obs_metrics
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.resilience import deadline as deadline_mod
from mpi_cuda_imagemanipulation_tpu.resilience.breaker import BreakerBoard
from mpi_cuda_imagemanipulation_tpu.serve import bucketing
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_FED_STALE_S = "MCIM_FED_STALE_S"
ENV_FED_FORWARD_TIMEOUT_S = "MCIM_FED_FORWARD_TIMEOUT_S"
ENV_FED_FORWARD_ATTEMPTS = "MCIM_FED_FORWARD_ATTEMPTS"
ENV_FED_REGISTRY = "MCIM_FED_REGISTRY"

# The CLOSED vocabulary of reasons a request is served away from its
# rendezvous pod. Every reroute increments mcim_fed_reroutes_total with
# exactly one of these via count_reroute — mcim-check rejects unknown
# reasons, dynamic reason expressions, and vocabulary entries nothing
# uses (analysis/rules_obs.py, the systolic-fallback discipline).
#
#   pod_down        the affinity pod is stale/dead — its slice reroutes
#   breaker_open    the affinity pod's breaker refused the attempt
#   overloaded      the affinity pod is over the shed fraction
#   forward_failed  an attempt on the affinity pod failed; survivors took it
#   session_reset   a session's owning pod died; the session restarted
#                   fresh on a new pod (no cross-pod tail replay)
#   retry_budget    the token-bucket retry budget (resilience/deadline.py)
#                   refused the reroute: the request gave up with its best
#                   answer so far instead of amplifying a brownout
REROUTE_REASONS = (
    "pod_down",
    "breaker_open",
    "overloaded",
    "forward_failed",
    "session_reset",
    "retry_budget",
)


def count_reroute(counter, reason: str) -> None:
    """The single choke point for reroute accounting: an unknown reason
    is a bug in THIS tree, not a metric label."""
    if reason not in REROUTE_REASONS:
        raise ValueError(
            f"unknown reroute reason {reason!r} "
            f"(known: {REROUTE_REASONS})"
        )
    counter.inc(reason=reason)


class PodView:
    """One pod's last-observed heartbeat + bookkeeping."""

    __slots__ = ("hb", "last_seen", "beats")

    def __init__(self, hb: PodHeartbeat, now: float):
        self.hb = hb
        self.last_seen = now
        self.beats = 1

    @property
    def pod_id(self) -> str:
        return self.hb.pod_id

    def fresh(self, now: float, stale_s: float) -> bool:
        return (now - self.last_seen) <= stale_s

    def load_frac(self) -> float:
        return self.hb.queued / max(1, self.hb.queue_depth)


class PodTable:
    """The pod membership table (fabric/router.ReplicaTable one tier up)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pods: dict[str, PodView] = {}

    def observe(self, hb: PodHeartbeat, now: float) -> bool:
        """Fold one beat in; True when this is a NEW incarnation (first
        beat ever, or a pod restart behind the same id)."""
        with self._lock:
            view = self._pods.get(hb.pod_id)
            if view is None:
                self._pods[hb.pod_id] = PodView(hb, now)
                return True
            new_inc = view.hb.incarnation != hb.incarnation
            view.hb = hb
            view.last_seen = now
            view.beats += 1
            return new_inc

    def views(self) -> list[PodView]:
        with self._lock:
            return list(self._pods.values())

    def get(self, pod_id: str) -> PodView | None:
        with self._lock:
            return self._pods.get(pod_id)


@dataclasses.dataclass
class FrontDoorConfig:
    registry_path: str | None = None  # None: MCIM_FED_REGISTRY
    buckets: tuple[tuple[int, int], ...] = bucketing.DEFAULT_BUCKETS
    stale_s: float | None = None  # None: MCIM_FED_STALE_S
    forward_timeout_s: float | None = None
    forward_attempts: int | None = None
    # pod-level load shed point: a pod at/over this queue-fill fraction
    # loses sticky preference (counted `overloaded`)
    shed_frac: float = 0.9
    # per-pod breaker: same fast-trip/fast-reset posture as the
    # router's per-replica board — a dead pod costs one connect timeout
    # per probe, a restarted pod rejoins within a breaker window
    breaker_threshold: int = 2
    breaker_reset_s: float = 3.0
    # -- request lifecycle (resilience/deadline.py) ------------------------
    # edge deadline applied to requests that arrive WITHOUT their own
    # X-MCIM-Deadline-Ms budget; 0 disables. None: MCIM_FED_DEADLINE_MS
    default_deadline_ms: float | None = None
    # retry-budget token bucket: deposit `frac` per accepted request,
    # withdraw 1 per reroute; `reserve` covers cold-start failover.
    # None fields fall back to MCIM_RETRY_BUDGET_FRAC / _RESERVE
    retry_budget_frac: float | None = None
    retry_budget_reserve: float | None = None


class FrontDoor:
    """The federation front door. `start()` binds the HTTP listener;
    pods register by heartbeating `POST /control/podheartbeat`.

        POST /v1/process          proxied to a pod (graph lane sticky on
                                  tenant|pipeline|bucket, chain lane on
                                  the bucket; pod-level admission sheds
                                  relay FINAL)
        POST /v1/pipelines        validate + PERSIST + broadcast a spec
        POST /v1/tenants          tenant config, persisted + broadcast
                                  with each pod's LEASED quota share
        POST /v1/session/<sid>/frame
                                  sticky pod binding keyed by session id
        POST /control/podheartbeat  pod aggregate push; the ack carries
                                  resync + the pod's quota leases
        GET  /healthz             200 while >=1 fresh pod has capacity
        GET  /stats               pod table + federation state (JSON)
        GET  /metrics             mcim_fed_* + the federated pod
                                  families (obs/fleet.py, second hop)
    """

    def __init__(
        self,
        config: FrontDoorConfig | None = None,
        *,
        registry: Registry | None = None,
        clock=time.monotonic,
    ):
        self.config = config or FrontDoorConfig()
        self.stale_s = (
            float(env_registry.get(ENV_FED_STALE_S))
            if self.config.stale_s is None
            else self.config.stale_s
        )
        self.forward_timeout_s = (
            float(env_registry.get(ENV_FED_FORWARD_TIMEOUT_S))
            if self.config.forward_timeout_s is None
            else self.config.forward_timeout_s
        )
        self.forward_attempts = (
            int(env_registry.get(ENV_FED_FORWARD_ATTEMPTS))
            if self.config.forward_attempts is None
            else self.config.forward_attempts
        )
        self.buckets = tuple(self.config.buckets)
        self.shed_frac = self.config.shed_frac
        self.default_deadline_ms = (
            float(env_registry.get(deadline_mod.ENV_DEADLINE_MS))
            if self.config.default_deadline_ms is None
            else self.config.default_deadline_ms
        )
        self.retry_budget = deadline_mod.RetryBudget(
            frac=(
                float(env_registry.get(deadline_mod.ENV_BUDGET_FRAC))
                if self.config.retry_budget_frac is None
                else self.config.retry_budget_frac
            ),
            reserve=(
                float(env_registry.get(deadline_mod.ENV_BUDGET_RESERVE))
                if self.config.retry_budget_reserve is None
                else self.config.retry_budget_reserve
            ),
        )
        path = (
            self.config.registry_path
            or env_registry.get(ENV_FED_REGISTRY)
            or DEFAULT_NAME
        )
        # durable state FIRST: everything below serves what this replays
        self.durable = DurableRegistry(path).load()
        self._state_lock = threading.Lock()
        # tenant -> registered payload (global budgets, not leases)
        self.fed_tenants: dict[str, dict] = self.durable.items("tenant")
        # "tenant/pipeline" -> {"tenant": ..., "spec": ...}
        self.fed_specs: dict[str, dict] = self.durable.items("pipeline")
        # session id -> {"pod": ..., "ops": ...}
        self.session_pods: dict[str, dict] = self.durable.items("session")
        self.leases = LeaseLedger(clock=clock)
        self.table = PodTable()
        self.breakers = BreakerBoard(
            failure_threshold=self.config.breaker_threshold,
            reset_timeout_s=self.config.breaker_reset_s,
        )
        # (pod id, incarnation) -> tenants whose LEASED config that
        # exact pod process has received (the router's _tenant_pushed
        # discipline one tier up — a pod restart naturally re-pushes)
        self._pod_pushed: dict[tuple[str, str], set[str]] = {}
        self._pool = _ConnPool(self.forward_timeout_s)
        self._clock = clock
        self.registry = registry or Registry()
        # second federation hop (obs/fleet.py): pod-router registries
        # fold in via pod-heartbeat deltas, keyed by pod id
        self.fleet = obs_fleet.FleetAggregator(
            stale_s=self.stale_s, clock=clock
        )
        self._fleet_scraped_at: dict[str, float] = {}
        self._register_metrics()
        self.httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._closed = False
        self._log = get_logger()

    # -- metrics -----------------------------------------------------------

    def _register_metrics(self) -> None:
        r = self.registry
        self._m_requests = r.counter(
            "mcim_fed_requests_total",
            "Front-door requests by terminal status.",
            labels=("status",),
        )
        self._m_forwards = r.counter(
            "mcim_fed_forwards_total",
            "Proxy attempts per pod, by outcome (ok/shed/http_error/"
            "net_error).",
            labels=("pod", "outcome"),
        )
        self._m_retries = r.counter(
            "mcim_fed_forward_retries_total",
            "Requests re-forwarded to another pod after a failed "
            "attempt (attempt 2+ each counts once).",
        )
        self._m_reroutes = r.counter(
            "mcim_fed_reroutes_total",
            "Requests served away from their rendezvous pod, by closed-"
            "vocabulary reason (REROUTE_REASONS — count_reroute is the "
            "only increment site).",
            labels=("reason",),
        )
        self._m_heartbeats = r.counter(
            "mcim_fed_heartbeats_total",
            "Pod heartbeats accepted, per pod.",
            labels=("pod",),
        )
        self._m_forward_s = r.histogram(
            "mcim_fed_forward_seconds",
            "Front-door -> pod proxy time per successful attempt.",
        )
        # request-lifecycle accounting (resilience/deadline.py): expiry
        # answered locally at THIS tier, and reroutes the retry budget
        # refused (the latter also count a `retry_budget` reroute)
        self._m_deadline = deadline_mod.expired_counter(r)
        self._m_budget_denied = deadline_mod.budget_denied_counter(r)
        self._m_pushes = r.counter(
            "mcim_fed_pushes_total",
            "Tenant/spec state re-pushed to a pod whose heartbeat "
            "lacked it (cold-pod / restart reconvergence).",
        )
        self._m_lease_grants = r.counter(
            "mcim_fed_lease_grants_total",
            "Quota-share leases granted to pods (one per pod per "
            "tenant per window; reconnects return the held lease and "
            "do not count).",
        )
        self._m_session_frames = r.counter(
            "mcim_fed_session_frames_total",
            "Session frames through the front door, by outcome.",
            labels=("outcome",),
        )
        r.gauge(
            "mcim_fed_pods",
            "Fresh pods with routable capacity.",
            fn=lambda: float(len(self._live())),
        )
        r.gauge(
            "mcim_fed_tenants",
            "Tenant configs in the durable registry.",
            fn=lambda: float(len(self.fed_tenants)),
        )
        r.gauge(
            "mcim_fed_specs",
            "(tenant, pipeline) specs in the durable registry.",
            fn=lambda: float(len(self.fed_specs)),
        )
        r.gauge(
            "mcim_fed_sessions",
            "Session -> pod bindings held (durable).",
            fn=lambda: float(len(self.session_pods)),
        )

    # -- membership / routing ----------------------------------------------

    def _live(self) -> list[PodView]:
        now = self._clock()
        return [
            v
            for v in self.table.views()
            if v.fresh(now, self.stale_s) and v.hb.routable > 0
        ]

    def route_pod(
        self, affinity_key: str
    ) -> tuple[list[PodView], str | None, str | None]:
        """(ordered candidates, preferred pod id, demotion reason).

        The preferred pod is the rendezvous winner over ALL KNOWN pods —
        including stale ones — so a request served elsewhere because its
        pod died is counted `pod_down`, not silently re-homed. The
        candidate order starts at the sticky live winner unless it is
        over the shed fraction (`overloaded`)."""
        known = self.table.views()
        preferred = (
            max(
                known,
                key=lambda v: _rendezvous_score(affinity_key, v.pod_id),
            ).pod_id
            if known
            else None
        )
        live = self._live()
        if not live:
            return [], preferred, None
        sticky = max(
            live, key=lambda v: _rendezvous_score(affinity_key, v.pod_id)
        )
        rest = sorted(
            (v for v in live if v.pod_id != sticky.pod_id),
            key=lambda v: v.load_frac(),
        )
        if preferred is not None and sticky.pod_id != preferred:
            return [sticky] + rest, preferred, "pod_down"
        if sticky.load_frac() >= self.shed_frac:
            return rest + [sticky], preferred, "overloaded"
        return [sticky] + rest, preferred, None

    # -- forwarding --------------------------------------------------------

    def _forward_once(
        self,
        view: PodView,
        path: str,
        body: bytes,
        extra_headers,
        trace_id: str,
    ):
        addr = view.hb.addr or "127.0.0.1"
        port = view.hb.port
        conn = self._pool.take(addr, port)
        try:
            hdrs = {
                "Content-Type": "application/octet-stream",
                HDR_FED_POD: view.pod_id,
            }
            for k, v in extra_headers:
                hdrs[k] = v
            if trace_id:
                hdrs["X-Trace-Id"] = trace_id
            conn.request("POST", path, body=body, headers=hdrs)
            resp = conn.getresponse()
            out = resp.read()
            ctype = resp.getheader("Content-Type", "application/json")
            passthrough = [
                (h, resp.getheader(h))
                for h in (
                    "Retry-After",
                    "X-MCIM-Histogram",
                    "X-MCIM-Stats",
                    "X-Fabric-Replica",
                )
                if resp.getheader(h)
            ]
        except BaseException:
            conn.close()
            raise
        self._pool.give(addr, port, conn)
        return resp.status, ctype, out, passthrough

    def _forward_with_retries(
        self,
        root,
        path: str,
        body: bytes,
        candidates: list[PodView],
        preferred: str | None,
        base_reason: str | None,
        *,
        extra_headers=(),
        before_forward=None,
        admission_shed_is_final: bool = False,
        deadline: deadline_mod.Deadline | None = None,
    ):
        """Walk the pod candidates until one answers. The reroute
        accounting fires exactly once, when the request completes on a
        pod other than its rendezvous-preferred one — with the most
        specific reason observed (`base_reason` from routing, upgraded
        by what actually happened to the preferred pod in this loop).

        Deadline-honest and retry-bounded (resilience/deadline.py): the
        remaining budget is re-checked before every attempt (an expired
        request answers 504 HERE instead of burning a pod), each forward
        carries the remainder on the wire, and attempt 2+ must withdraw
        from the retry budget — a refused withdrawal gives up with the
        best answer so far, counted under the closed `retry_budget`
        reroute reason."""
        reason = base_reason
        last: tuple | None = None
        attempts = 0
        for view in candidates:
            if deadline is not None and deadline.expired():
                deadline_mod.count_expired(self._m_deadline, "door")
                return _json_response(
                    504, deadline_mod.expired_response_body()
                )
            pod = view.pod_id
            breaker = self.breakers.get(pod)
            if not breaker.allow():
                if pod == preferred and reason is None:
                    reason = "breaker_open"
                continue
            attempts += 1
            if attempts > 1:
                if not self.retry_budget.try_withdraw():
                    deadline_mod.count_budget_denied(
                        self._m_budget_denied, "door"
                    )
                    count_reroute(self._m_reroutes, "retry_budget")
                    break
                self._m_retries.inc()
            fwd_extra = tuple(extra_headers)
            if deadline is not None:
                fwd_extra = fwd_extra + (
                    (deadline_mod.HEADER, deadline.header_value()),
                )
            if before_forward is not None:
                try:
                    before_forward(view)
                except Exception as e:
                    breaker.on_failure()
                    self._m_forwards.inc(pod=pod, outcome="net_error")
                    if pod == preferred and reason is None:
                        reason = "forward_failed"
                    self._log.warning(
                        "fed: state push to pod %s failed (%s: %s)",
                        pod, type(e).__name__, str(e)[:120],
                    )
                    continue
            t0 = self._clock()
            try:
                with obs_trace.span(
                    "fed.forward", parent=root.context(), pod=pod
                ):
                    code, ctype, out, passthrough = self._forward_once(
                        view, path, body, fwd_extra, root.trace_id
                    )
            except Exception as e:
                breaker.on_failure()
                self._m_forwards.inc(pod=pod, outcome="net_error")
                if pod == preferred and reason is None:
                    reason = "forward_failed"
                self._log.warning(
                    "fed: forward to pod %s failed (%s: %s)",
                    pod, type(e).__name__, str(e)[:120],
                )
                continue
            if code == 504:
                # a downstream deadline verdict is FINAL by definition:
                # the budget is as gone on every sibling pod as it was
                # on this one, so a retry could only burn more replica
                # time on work the caller already abandoned. Not a pod
                # fault either — the pod answered honestly.
                breaker.on_success()
                self._m_forwards.inc(pod=pod, outcome="http_error")
                return (
                    code, ctype, out,
                    passthrough + [(HDR_FED_POD, pod)],
                )
            if (
                admission_shed_is_final
                and code == 503
                and _is_admission_shed(out)
            ):
                # a pod-level quota/QoS shed is FINAL: trying the next
                # pod would hand the tenant another pod's lease on top
                # of the one it just exhausted (the budget x pods bug)
                breaker.on_success()
                self._m_forwards.inc(pod=pod, outcome="shed")
                return (
                    code, ctype, out,
                    passthrough + [(HDR_FED_POD, pod)],
                )
            if code in (429, 503) or code >= 500:
                if code >= 500:
                    breaker.on_failure()
                self._m_forwards.inc(pod=pod, outcome="http_error")
                if pod == preferred and reason is None:
                    reason = "forward_failed"
                if not any(k == "Retry-After" for k, _ in passthrough):
                    passthrough = passthrough + [("Retry-After", "1")]
                last = (
                    code, ctype, out,
                    passthrough + [(HDR_FED_POD, pod)],
                )
                continue
            breaker.on_success()
            self._m_forwards.inc(pod=pod, outcome="ok")
            self._m_forward_s.observe(
                self._clock() - t0, exemplar=root.trace_id or None
            )
            if preferred is not None and pod != preferred:
                # literal per-reason sites: the closed REROUTE_REASONS
                # vocabulary stays machine-checkable (mcim-check walks
                # every count_reroute caller for a literal member)
                if reason == "pod_down":
                    count_reroute(self._m_reroutes, "pod_down")
                elif reason == "breaker_open":
                    count_reroute(self._m_reroutes, "breaker_open")
                elif reason == "overloaded":
                    count_reroute(self._m_reroutes, "overloaded")
                else:
                    count_reroute(self._m_reroutes, "forward_failed")
            return (
                code, ctype, out,
                passthrough
                + [(HDR_FED_POD, pod), ("X-Fed-Attempts", str(attempts))],
            )
        if last is not None:
            return last
        return _json_response(
            503,
            {"error": "no pod is serving", "status": "unavailable"},
            extra=[("Retry-After", "1")],
        )

    # -- request path ------------------------------------------------------

    def handle_process(
        self, body: bytes, headers, query: dict | None = None
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """One `/v1/process` through the federation tier. The graph lane
        stickies on (tenant, pipeline, bucket) and converges the target
        pod's tenant/spec state before the first forward; the chain lane
        stickies on the bucket so a pod's warm executables keep their
        traffic."""
        from mpi_cuda_imagemanipulation_tpu.graph.service import (
            HDR_PIPELINE,
            HDR_TENANT,
        )

        q = query or {}

        def _pick(hname: str, qname: str) -> str:
            v = headers.get(hname)
            if v:
                return v
            vals = q.get(qname)
            return vals[0] if vals else ""

        tenant = _pick(HDR_TENANT, "tenant") or "default"
        pipeline = _pick(HDR_PIPELINE, "pipeline")
        # the deadline chain starts HERE: adopt the client's remaining
        # budget, or mint the edge default for clients that sent none
        dl = deadline_mod.from_headers(headers, clock=self._clock)
        if dl is None and self.default_deadline_ms > 0:
            dl = deadline_mod.Deadline(
                self.default_deadline_ms, clock=self._clock
            )
        if dl is not None and dl.expired():
            deadline_mod.count_expired(self._m_deadline, "door")
            self._m_requests.inc(status="deadline_expired")
            return _json_response(
                504, deadline_mod.expired_response_body()
            )
        try:
            h, w = Router._sniff_dims(body)
        except Exception as e:
            self._m_requests.inc(status="rejected")
            return _json_response(
                400, {"error": f"undecodable image: {e}"}
            )
        picked = bucketing.pick_bucket(h, w, self.buckets)
        bucket = f"{picked[0]}x{picked[1]}" if picked else f"{h}x{w}"
        if pipeline:
            affinity = f"{tenant}|{pipeline}|{bucket}"
            extra = ((HDR_TENANT, tenant), (HDR_PIPELINE, pipeline))
            before = lambda v: self._ensure_pod_state(v, tenant, pipeline)  # noqa: E731
            shed_final = True
        else:
            affinity = bucket
            extra = ()
            before = None
            shed_final = False
        candidates, preferred, base_reason = self.route_pod(affinity)
        if not candidates:
            self._m_requests.inc(status="unavailable")
            return _json_response(
                503,
                {"error": "no pod is serving", "status": "unavailable"},
                extra=[("Retry-After", "1")],
            )
        root = obs_trace.start_trace(
            "fed.request", h=h, w=w, bucket=bucket,
            tenant=tenant, pipeline=pipeline or None,
        )
        # one accepted request = one retry-budget deposit (the bucket
        # the reroute withdrawals below draw down)
        self.retry_budget.deposit()
        code, ctype, out, hdrs_out = self._forward_with_retries(
            root, "/v1/process", body, candidates, preferred,
            base_reason, extra_headers=extra, before_forward=before,
            admission_shed_is_final=shed_final, deadline=dl,
        )
        self._m_requests.inc(
            status=_STATUS_LABEL.get(
                code, "error" if code >= 500 else "ok"
            )
        )
        root.set(status=code)
        root.end()
        if root.trace_id:
            hdrs_out = hdrs_out + [("X-Trace-Id", root.trace_id)]
        return code, ctype, out, hdrs_out

    # -- state convergence -------------------------------------------------

    def _push_json(self, view: PodView, path: str, payload: dict):
        addr = view.hb.addr or "127.0.0.1"
        port = view.hb.port
        conn = self._pool.take(addr, port)
        try:
            conn.request(
                "POST", path, body=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            out = resp.read()
        except BaseException:
            conn.close()
            raise
        self._pool.give(addr, port, conn)
        return resp.status, out

    def _leased_payload(self, payload: dict, pod_id: str) -> dict:
        """The tenant config AS THIS POD RECEIVES IT: quota fields
        replaced by the pod's current window lease. Quota-less tenants
        pass through untouched."""
        if (
            payload.get("quota_requests") is None
            and payload.get("quota_bytes") is None
        ):
            return payload
        before = self.leases.grants_issued
        lease = self.leases.lease(
            payload["tenant"], payload, pod_id,
            [v.pod_id for v in self._live()], self._clock(),
        )
        grew = self.leases.grants_issued - before
        if grew:
            self._m_lease_grants.inc(grew)
        return {
            **payload,
            "quota_requests": lease["quota_requests"],
            "quota_bytes": lease["quota_bytes"],
        }

    def _ensure_pod_state(
        self, view: PodView, tenant: str, pipeline: str
    ) -> None:
        """Converge one pod's federation state before a forward: push
        the stored spec when the pod's heartbeat lacks the pipeline id,
        and push the tenant's LEASED config when this exact pod
        incarnation has never received it. The router's
        `_ensure_graph_state` discipline one tier up — a restarted
        (cold) pod reconverges within one forward, not never."""
        inc_key = (view.pod_id, view.hb.incarnation)
        with self._state_lock:
            reg = self.fed_specs.get(f"{tenant}/{pipeline}")
            tcfg = self.fed_tenants.get(tenant)
            need_tenant = (
                tcfg is not None
                and tenant not in self._pod_pushed.get(inc_key, ())
            )
        need_spec = (
            reg is not None and pipeline not in (view.hb.pipelines or ())
        )
        if not need_tenant and not need_spec:
            return
        if need_tenant:
            leased = self._leased_payload(tcfg, view.pod_id)
            code, out = self._push_json(view, "/v1/tenants", leased)
            if code != 200:
                raise RuntimeError(
                    f"tenant push to pod {view.pod_id} answered {code}: "
                    f"{out[:120]!r}"
                )
            with self._state_lock:
                self._pod_pushed.setdefault(inc_key, set()).add(tenant)
        if need_spec:
            code, out = self._push_json(view, "/v1/pipelines", reg)
            if code != 200:
                raise RuntimeError(
                    f"spec push to pod {view.pod_id} answered {code}: "
                    f"{out[:120]!r}"
                )
        self._m_pushes.inc()
        self._log.info(
            "fed: re-pushed %s/%s to pod %s (tenant=%s spec=%s)",
            tenant, pipeline, view.pod_id, need_tenant, need_spec,
        )

    # -- registration ------------------------------------------------------

    def handle_graph_register(self, body: bytes) -> tuple[int, dict]:
        """`POST /v1/pipelines` at the federation tier: validate (the
        closed taxonomy), PERSIST (the fsync happens before any client
        sees the 200), broadcast to every live pod."""
        from mpi_cuda_imagemanipulation_tpu.graph.ir import dag_fingerprint
        from mpi_cuda_imagemanipulation_tpu.graph.spec import (
            SpecError,
            parse_spec,
        )

        try:
            try:
                payload = json.loads(body or b"null")
            except ValueError as e:
                raise SpecError(
                    "bad-json", f"body is not JSON: {e}"
                ) from None
            if not isinstance(payload, dict):
                raise SpecError(
                    "bad-root", "registration body must be an object"
                )
            spec = payload.get("spec", payload)
            tenant = payload.get("tenant") or "default"
            graph = parse_spec(spec)
        except SpecError as e:
            return (
                400 if e.code == "bad-json" else 422,
                {"status": "rejected", "code": e.code, "error": str(e)},
            )
        pid = dag_fingerprint(graph)
        reg = {"tenant": tenant, "spec": spec}
        self.durable.put("pipeline", f"{tenant}/{pid}", reg)
        with self._state_lock:
            self.fed_specs[f"{tenant}/{pid}"] = reg
        pushed: dict[str, object] = {}
        for v in self._live():
            try:
                code, _out = self._push_json(v, "/v1/pipelines", reg)
                pushed[v.pod_id] = code
            except Exception as e:
                pushed[v.pod_id] = f"error: {type(e).__name__}"
        return 200, {
            "pipeline": pid,
            "tenant": tenant,
            "name": graph.name,
            "nodes": len(graph.nodes),
            "outputs": sorted(graph.outputs),
            "persisted": True,
            "pods": pushed,
        }

    def handle_graph_tenant(self, body: bytes) -> tuple[int, dict]:
        """`POST /v1/tenants` at the federation tier: validate, persist
        the GLOBAL config, broadcast each pod its LEASED share."""
        from mpi_cuda_imagemanipulation_tpu.graph.spec import SpecError
        from mpi_cuda_imagemanipulation_tpu.graph.tenancy import (
            TenantConfig,
        )

        try:
            try:
                payload = json.loads(body or b"null")
            except ValueError as e:
                raise SpecError(
                    "bad-json", f"body is not JSON: {e}"
                ) from None
            if not isinstance(payload, dict):
                raise SpecError(
                    "bad-root", "tenant config must be an object"
                )
            TenantConfig(  # validation only; pods hold the live state
                tenant_id=payload.get("tenant", ""),
                qos=payload.get("qos", "standard"),
                quota_requests=payload.get("quota_requests"),
                quota_bytes=payload.get("quota_bytes"),
                window_s=payload.get("window_s"),
            )
        except SpecError as e:
            return (
                400 if e.code == "bad-json" else 422,
                {"status": "rejected", "code": e.code, "error": str(e)},
            )
        tenant = payload["tenant"]
        self.durable.put("tenant", tenant, payload)
        with self._state_lock:
            self.fed_tenants[tenant] = payload
        pushed: dict[str, object] = {}
        for v in self._live():
            try:
                leased = self._leased_payload(payload, v.pod_id)
                code, _out = self._push_json(v, "/v1/tenants", leased)
                pushed[v.pod_id] = code
                if code == 200:
                    with self._state_lock:
                        self._pod_pushed.setdefault(
                            (v.pod_id, v.hb.incarnation), set()
                        ).add(tenant)
            except Exception as e:
                pushed[v.pod_id] = f"error: {type(e).__name__}"
        return 200, {"tenant": tenant, "persisted": True, "pods": pushed}

    # -- sessions ----------------------------------------------------------

    def handle_session_frame(
        self, sid: str, body: bytes, headers
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """One session frame: sticky pod binding keyed by session id,
        persisted so a front-door restart keeps every session on its
        pod. Failover WITHIN a pod (replica death) is the pod router's
        journal-tail replay and is invisible here; a cross-pod move —
        only when the owning pod is gone — restarts the session fresh
        on the rendezvous survivor (counted `session_reset`)."""
        ops = headers.get(fabric_session.HDR_OPS) or ""
        if not ops:
            self._m_session_frames.inc(outcome="error")
            return _json_response(
                400,
                {"error": f"missing {fabric_session.HDR_OPS} header"},
            )
        live = self._live()
        if not live:
            self._m_session_frames.inc(outcome="unavailable")
            return _json_response(
                503,
                {"error": "no pod is serving", "status": "unavailable"},
                extra=[("Retry-After", "1")],
            )
        with self._state_lock:
            bound = self.session_pods.get(sid, {}).get("pod")
        view = next((v for v in live if v.pod_id == bound), None)
        moved = False
        if view is None:
            view = max(
                live,
                key=lambda v: _rendezvous_score("sess|" + sid, v.pod_id),
            )
            moved = bound is not None and bound != view.pod_id
        if bound != view.pod_id:
            self.durable.put(
                "session", sid, {"pod": view.pod_id, "ops": ops}
            )
            with self._state_lock:
                self.session_pods[sid] = {
                    "pod": view.pod_id, "ops": ops,
                }
        if moved:
            # the owning pod died: its tail died with it — the session
            # restarts fresh on the survivor rather than shipping every
            # frame through this tier to make cross-pod replay possible
            count_reroute(self._m_reroutes, "session_reset")
            self._log.info(
                "fed: session %s moved %s -> %s (fresh start, no "
                "cross-pod tail replay)", sid, bound, view.pod_id,
            )
        fwd_headers = [(fabric_session.HDR_OPS, ops)]
        raw_seq = headers.get(fabric_session.HDR_SEQ)
        if raw_seq is not None:
            fwd_headers.append((fabric_session.HDR_SEQ, raw_seq))
        root = obs_trace.start_trace("fed.session", sid=sid)
        try:
            code, ctype, out, passthrough = self._forward_once(
                view,
                f"{fabric_session.SESSION_PATH_PREFIX}{sid}/frame",
                body, fwd_headers, root.trace_id,
            )
        except Exception as e:
            self.breakers.get(view.pod_id).on_failure()
            self._m_session_frames.inc(outcome="error")
            root.set(status=502)
            root.end()
            return _json_response(
                502,
                {"error": (
                    f"session forward to pod {view.pod_id} failed "
                    f"({type(e).__name__}: {str(e)[:120]})"
                )},
            )
        self.breakers.get(view.pod_id).on_success()
        self._m_session_frames.inc(
            outcome="ok" if code == 200 else "error"
        )
        root.set(status=code)
        root.end()
        extra = passthrough + [(HDR_FED_POD, view.pod_id)]
        if root.trace_id:
            extra = extra + [("X-Trace-Id", root.trace_id)]
        return code, ctype, out, extra

    # -- control -----------------------------------------------------------

    def handle_pod_heartbeat(self, body: bytes) -> tuple[int, dict]:
        try:
            hb = PodHeartbeat.from_json(body)
        except (ValueError, TypeError) as e:
            return 400, {"error": f"bad pod heartbeat: {e}"}
        now = self._clock()
        new_inc = self.table.observe(hb, now)
        if new_inc:
            # a restarted pod must not inherit its predecessor's open
            # breaker, and must get every tenant/spec re-pushed before
            # its first forward (the _pod_pushed key rolls with the
            # incarnation, so that happens by construction)
            self.breakers.reset(hb.pod_id)
            self._log.info(
                "pod %s registered (incarnation %s, %s:%d, %d routable)",
                hb.pod_id, hb.incarnation, hb.addr or "127.0.0.1",
                hb.port, hb.routable,
            )
        self._m_heartbeats.inc(pod=hb.pod_id)
        ok = self.fleet.apply(hb.pod_id, hb.incarnation, hb.metrics, now)
        with self._state_lock:
            tenants = dict(self.fed_tenants)
        before = self.leases.grants_issued
        leases = self.leases.leases_for_pod(
            hb.pod_id, tenants, [v.pod_id for v in self._live()]
        )
        grew = self.leases.grants_issued - before
        if grew:
            self._m_lease_grants.inc(grew)
        return 200, {"ok": True, "resync": not ok, "leases": leases}

    def _fleet_refresh(self) -> None:
        """Full-scrape fallback, second hop: a pod whose metrics view is
        stale (beats lost or deltas refused) gets one pull of its
        router's `GET /fleet/snapshot` per staleness window."""
        now = self._clock()
        ages = self.fleet.ages(now)
        for v in self.table.views():
            pid = v.pod_id
            age = ages.get(pid)
            if age is not None and age <= self.stale_s:
                continue
            if now - self._fleet_scraped_at.get(pid, -1e18) < self.stale_s:
                continue
            self._fleet_scraped_at[pid] = now
            url = (
                f"http://{v.hb.addr or '127.0.0.1'}:{v.hb.port}"
                f"{obs_fleet.SNAPSHOT_PATH}"
            )
            try:
                with urllib.request.urlopen(url, timeout=2.0) as resp:
                    snap = json.loads(resp.read())
                self.fleet.full_sync(pid, v.hb.incarnation, snap, now)
            except Exception as e:
                self._log.debug(
                    "fed: full scrape of pod %s failed (%s)", pid,
                    type(e).__name__,
                )

    def render_metrics(self) -> str:
        self._fleet_refresh()
        return self.registry.render() + self.fleet.render()

    def healthz(self) -> tuple[int, dict]:
        live = self._live()
        code = 200 if live else 503
        return code, {
            "state": "serving" if live else "unavailable",
            "pods": sorted(v.pod_id for v in live),
            "known": len(self.table.views()),
        }

    def stats(self) -> dict:
        now = self._clock()
        with self._state_lock:
            tenants = sorted(self.fed_tenants)
            specs = sorted(self.fed_specs)
            sessions = {
                sid: dict(b) for sid, b in self.session_pods.items()
            }
        return {
            "stale_s": self.stale_s,
            "forward_attempts": self.forward_attempts,
            "default_deadline_ms": self.default_deadline_ms,
            "retry_budget": self.retry_budget.stats(),
            "registry": {
                "path": self.durable.path,
                "counts": self.durable.counts(),
                "loaded_records": self.durable.loaded_records,
                "skipped_lines": self.durable.skipped_lines,
            },
            "tenants": tenants,
            "specs": specs,
            "sessions": sessions,
            "leases": self.leases.stats(),
            "fleet": self.fleet.stats(now),
            "pods": {
                v.pod_id: {
                    "addr": v.hb.addr or "127.0.0.1",
                    "port": v.hb.port,
                    "pid": v.hb.pid,
                    "incarnation": v.hb.incarnation,
                    "routable": v.hb.routable,
                    "fresh": v.fresh(now, self.stale_s),
                    "age_s": now - v.last_seen,
                    "queued": v.hb.queued,
                    "queue_depth": v.hb.queue_depth,
                    "warm_buckets": v.hb.warm_buckets,
                    "pipelines": v.hb.pipelines,
                    "beats": v.beats,
                }
                for v in self.table.views()
            },
            "breakers": self.breakers.snapshot(),
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self, host: str = "", port: int = 0) -> "FrontDoor":
        try:
            self.httpd = _FrontDoorHTTPServer(
                (host, port), _make_handler(self)
            )
            self._http_thread = threading.Thread(
                target=self.httpd.serve_forever,
                name="mcim-fed-frontdoor",
                daemon=True,
            )
            self._http_thread.start()
        except BaseException:
            self.close()
            raise
        return self

    @property
    def address(self) -> tuple[str, int]:
        assert self.httpd is not None, "FrontDoor not started"
        host, port = self.httpd.server_address[:2]
        return (host, port)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.address[1]}"

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.httpd is not None:
            try:
                self.httpd.shutdown()
            except Exception:
                pass
            self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=10.0)
        self._pool.close_all()

    def __enter__(self) -> "FrontDoor":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class _FrontDoorHTTPServer(ThreadingHTTPServer):
    # the federation tier fronts every pod's client burst
    request_queue_size = 128


def _make_handler(door: FrontDoor):
    log = get_logger()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug("fed-http: " + fmt, *args)

        def _reply(self, code, ctype, body, extra=()):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code, payload, extra=()):
            c, t, b, e = _json_response(code, payload, list(extra))
            self._reply(c, t, b, e)

        def do_GET(self):  # noqa: N802 (stdlib casing)
            if self.path == "/healthz":
                code, payload = door.healthz()
                self._reply_json(code, payload)
            elif self.path == "/stats":
                self._reply_json(200, door.stats())
            elif self.path == "/metrics":
                body = door.render_metrics().encode()
                self._reply(200, obs_metrics.CONTENT_TYPE, body)
            else:
                self._reply_json(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802
            from urllib.parse import parse_qs, urlsplit

            n = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(n)
            split = urlsplit(self.path)
            path = split.path
            if self.path == POD_HEARTBEAT_PATH:
                code, payload = door.handle_pod_heartbeat(body)
                self._reply_json(code, payload)
            elif path == "/v1/process":
                code, ctype, out, extra = door.handle_process(
                    body, self.headers, query=parse_qs(split.query)
                )
                self._reply(code, ctype, out, extra)
            elif path == "/v1/pipelines":
                code, payload = door.handle_graph_register(body)
                self._reply_json(code, payload)
            elif path == "/v1/tenants":
                code, payload = door.handle_graph_tenant(body)
                self._reply_json(code, payload)
            elif (route := fabric_session.parse_session_path(self.path)):
                code, ctype, out, extra = door.handle_session_frame(
                    route[0], body, self.headers
                )
                self._reply(code, ctype, out, extra)
            else:
                self._reply_json(404, {"error": f"no route {self.path}"})

    return Handler
