"""Multi-pod federation — the tier above the fabric.

One fabric pod is a router over N replica processes (fabric/). The
federation tier is the same design one level up: a front door
(federation/frontdoor.py) routes `/v1/*` across registered PODS, each
pod's router pushing pod-level aggregate heartbeats
(federation/control.py) the way replicas push replica heartbeats to it.
Tenant configs and pipeline specs survive a full-pod (or front-door)
restart in a durable fsync'd JSONL registry (federation/registry.py),
and a tenant's fixed-window quota holds GLOBALLY because the front door
leases per-pod token shares (federation/quota.py) instead of letting
every pod enforce the full budget.
"""
