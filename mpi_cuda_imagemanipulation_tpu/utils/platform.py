"""Backend-claim helper: pick a JAX platform in a way that survives this
machine's boot hook (and any embedding app).

The threat model (observed on the tunnelled single-chip TPU this framework
is developed against): a sitecustomize-style hook force-registers an
accelerator plugin whenever ``PALLAS_AXON_POOL_IPS`` is set and overrides
the platform choice via ``jax.config.update("jax_platforms", "axon,cpu")``
at interpreter startup — which beats the ``JAX_PLATFORMS`` env var — and
that plugin's first backend init can block *forever* on a wedged tunnel.
A user (or test harness) asking for cpu must never touch it.

One canonical recipe, shared by cli._configure_platform,
__graft_entry__.dryrun_multichip and tests/conftest.py (review finding:
three drifting copies previously existed).
"""

from __future__ import annotations

import os


def claim_platform(device: str, n_host_devices: int | None = None) -> None:
    """Claim ``device`` ("cpu", "tpu", or a comma list) for this process.

    - device == "cpu": also pops the accelerator-plugin trigger env var so
      child processes (watchdog reruns, bench workers) never re-register
      the plugin. Comma lists keep the trigger — a secondary platform is
      explicitly wanted there.
    - n_host_devices: set the XLA fake-host-device count (the
      multi-chip-without-hardware test rig, SURVEY.md §4). Replaces any
      previous count flag; only meaningful with cpu.

    Safe to call before or after jax's first import; if backends were
    already initialized under someone else's platform choice, the cache is
    dropped so the next dispatch re-resolves under ours.
    """
    if device == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    if n_host_devices is not None:
        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_host_devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = device

    import jax

    # config beats the env var, so re-assert the choice there; then drop
    # any backend set cached under the previous choice (no-op when nothing
    # initialized yet).
    jax.config.update("jax_platforms", device)
    import jax.extend.backend

    jax.extend.backend.clear_backends()
