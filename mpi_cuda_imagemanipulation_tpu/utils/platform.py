"""Backend-claim helper: pick a JAX platform in a way that survives this
machine's boot hook (and any embedding app).

The threat model (observed on the tunnelled single-chip TPU this framework
is developed against): a sitecustomize-style hook force-registers an
accelerator plugin whenever ``PALLAS_AXON_POOL_IPS`` is set and overrides
the platform choice via ``jax.config.update("jax_platforms", "axon,cpu")``
at interpreter startup — which beats the ``JAX_PLATFORMS`` env var — and
that plugin's first backend init can block *forever* on a wedged tunnel.
A user (or test harness) asking for cpu must never touch it.

One canonical recipe, shared by cli._configure_platform,
__graft_entry__.dryrun_multichip and tests/conftest.py (review finding:
three drifting copies previously existed).
"""

from __future__ import annotations

import os
import sys


def is_tpu_backend() -> bool:
    """Whether the default JAX backend is real TPU silicon.

    The single definition of "real hardware" for every kernel entry
    point's ``interpret=None`` resolution and the bench/autotune guards
    (advisor round-4 finding: kernel entry points checked
    ``!= "tpu"`` while the tooling accepted ``("tpu", "axon")`` — if the
    tunnelled chip ever surfaces as platform "axon", the kernels would
    silently run the Pallas interpreter while the tooling recorded the
    numbers as hardware). Initializes the default backend on first call —
    callers that must not touch a wedged tunnel claim cpu first
    (claim_platform).
    """
    import jax

    return jax.default_backend() in ("tpu", "axon")


def _backends_initialized() -> bool:
    """Whether any JAX backend client already exists in this process.

    Probes ``xla_bridge.backends_are_initialized()`` (the closest thing to
    a supported API) and falls back to the private ``_backends`` dict. Both
    are jax internals; ``tests/test_platform_claim.py`` asserts they exist
    so a jax upgrade that removes them fails loudly instead of silently
    disabling the count-change guard below (advisor round-2 finding: the
    old fail-open probe would have turned the guard into a no-op exactly
    when it was needed)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized"):
            return bool(xla_bridge.backends_are_initialized())
        return bool(xla_bridge._backends)
    except Exception:
        return False


def claim_platform(
    device: str,
    n_host_devices: int | None = None,
    *,
    keep_existing_count: bool = False,
) -> None:
    """Claim ``device`` ("cpu", "tpu", or a comma list) for this process.

    - device == "cpu": also pops the accelerator-plugin trigger env var so
      child processes (watchdog reruns, bench workers) never re-register
      the plugin. Comma lists keep the trigger — a secondary platform is
      explicitly wanted there.
    - n_host_devices: set the XLA fake-host-device count (the
      multi-chip-without-hardware test rig, SURVEY.md §4). Replaces any
      previous count flag; only meaningful with cpu.
    - keep_existing_count: treat n_host_devices as a default — an explicit
      count already in XLA_FLAGS (e.g. a 16-device sweep run) wins. This
      policy lives here so call sites can't drift (review finding).

    Safe to call before or after jax's first import; if backends were
    already initialized under someone else's platform choice, the cache is
    dropped so the next dispatch re-resolves under ours. The one thing that
    cannot change after first device use is the host-device *count* (XLA
    parses XLA_FLAGS once per process) — requesting a count change then
    raises RuntimeError instead of silently no-opping.
    """
    if device == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    if n_host_devices is not None:
        existing = os.environ.get("XLA_FLAGS", "")
        count_flag = f"--xla_force_host_platform_device_count={n_host_devices}"
        if not (
            keep_existing_count
            and "--xla_force_host_platform_device_count" in existing
        ) and count_flag not in existing.split():
            # XLA parses XLA_FLAGS once per process: a count change after
            # any backend initialized would silently not take effect (and
            # make_mesh would later see too few devices), so fail loudly
            # here instead. clear_backends below cannot help — it drops
            # jax's backend cache, not XLA's parsed flags.
            if _backends_initialized():
                raise RuntimeError(
                    f"claim_platform(n_host_devices={n_host_devices}) called "
                    "after a JAX backend was already initialized; XLA_FLAGS "
                    "is parsed once per process, so the count cannot change "
                    "anymore. Claim the platform before first device use."
                )
            flags = [
                f
                for f in existing.split()
                if not f.startswith("--xla_force_host_platform_device_count")
            ]
            flags.append(count_flag)
            os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = device

    import jax

    # config beats the env var, so re-assert the choice there; then drop
    # any backend set cached under the previous choice (no-op when nothing
    # initialized yet).
    jax.config.update("jax_platforms", device)
    import jax.extend.backend

    jax.extend.backend.clear_backends()
