"""Benchmark timing — the framework's replacement for the reference's two
inconsistent std::chrono spans (SURVEY.md §2.5: kern.cpp:60,86-87 vs
kernel.cu:190,226-227, which time different windows).

Rules: compile excluded (explicit warmup), device-synchronised via
`jax.block_until_ready`, medians over repeats, and a first-class
megapixels/sec metric (the BASELINE.json unit).
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Callable, Sequence

import jax


@dataclasses.dataclass(frozen=True)
class BenchResult:
    name: str
    reps: int
    wall_s: tuple[float, ...]  # per-rep synchronised wall times
    megapixels: float  # image megapixels processed per rep
    compile_s: float  # first (warmup) call, includes compile

    @property
    def median_s(self) -> float:
        return statistics.median(self.wall_s)

    @property
    def min_s(self) -> float:
        return min(self.wall_s)

    @property
    def mp_per_s(self) -> float:
        return self.megapixels / self.median_s

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "reps": self.reps,
            "median_ms": self.median_s * 1e3,
            "min_ms": self.min_s * 1e3,
            "compile_s": self.compile_s,
            "megapixels": self.megapixels,
            "mp_per_s": self.mp_per_s,
        }


def benchmark(
    fn: Callable,
    args: Sequence,
    *,
    name: str = "bench",
    megapixels: float,
    warmup: int = 2,
    reps: int = 10,
) -> BenchResult:
    """Time `fn(*args)` with compile excluded and device sync included."""
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    for _ in range(max(0, warmup - 1)):
        jax.block_until_ready(fn(*args))
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        walls.append(time.perf_counter() - t0)
    return BenchResult(
        name=name,
        reps=reps,
        wall_s=tuple(walls),
        megapixels=megapixels,
        compile_s=compile_s,
    )
