"""Benchmark timing — the framework's replacement for the reference's two
inconsistent std::chrono spans (SURVEY.md §2.5: kern.cpp:60,86-87 vs
kernel.cu:190,226-227, which time different windows).

Rules: compile excluded (explicit warmup), device-synchronised via
`jax.block_until_ready`, medians over repeats, and a first-class
megapixels/sec metric (the BASELINE.json unit).
"""

from __future__ import annotations

import dataclasses
import math
import statistics
import time
from typing import Callable, Iterable, Sequence

import jax


def percentiles(
    samples: Iterable[float], qs: Sequence[float] = (50, 95, 99)
) -> dict[float, float]:
    """Percentiles of `samples` by sorted-rank linear interpolation (numpy's
    default 'linear' method), as a {q: value} dict.

    One definition shared by the serving metrics (serve/metrics.py p50/p95/
    p99 latency) and the bench suite's load-generator lane, so the two never
    report subtly different quantile conventions. Raises on an empty sample
    set — a caller with nothing measured should say so, not report NaNs.
    """
    xs = sorted(samples)
    if not xs:
        raise ValueError("percentiles() needs at least one sample")
    out: dict[float, float] = {}
    for q in qs:
        if not 0 <= q <= 100:
            raise ValueError(f"percentile out of range [0, 100]: {q}")
        rank = (len(xs) - 1) * (q / 100.0)
        lo = math.floor(rank)
        hi = math.ceil(rank)
        frac = rank - lo
        out[q] = xs[lo] + (xs[hi] - xs[lo]) * frac
    return out


@dataclasses.dataclass(frozen=True)
class BenchResult:
    name: str
    reps: int
    wall_s: tuple[float, ...]  # per-rep synchronised wall times
    megapixels: float  # image megapixels processed per rep
    compile_s: float  # first (warmup) call, includes compile

    @property
    def median_s(self) -> float:
        return statistics.median(self.wall_s)

    @property
    def min_s(self) -> float:
        return min(self.wall_s)

    @property
    def mp_per_s(self) -> float:
        return self.megapixels / self.median_s

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "reps": self.reps,
            "median_ms": self.median_s * 1e3,
            "min_ms": self.min_s * 1e3,
            "compile_s": self.compile_s,
            "megapixels": self.megapixels,
            "mp_per_s": self.mp_per_s,
        }


def _sync(out) -> None:
    """Force completion of everything enqueued before `out`.

    On this machine's tunneled TPU, jax.block_until_ready can return before
    device execution finishes (remote relay), so a scalar readback is the
    only reliable barrier: it cannot complete until the buffer exists.
    """
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(leaf.reshape(-1)[0])


def device_throughput(
    fn: Callable,
    args: Sequence,
    *,
    n_lo: int = 40,
    n_hi: int = 160,
    trials: int = 5,
    budget_s: float = 25.0,
) -> float:
    """Seconds per iteration of `fn(*args)` measured device-side.

    Every synchronized call through a remote-tunneled TPU pays a fixed
    network round-trip (~tens of ms) that dwarfs sub-ms kernels, so per-call
    wall timing measures the network. Instead: enqueue N iterations
    back-to-back (async dispatch), force one sync, and take the slope
    (wall(n_hi) - wall(n_lo)) / (n_hi - n_lo) — fixed costs cancel.

    The *median* over `trials` is reported. The minimum is biased low: one
    noise-inflated wall(n_lo) makes its trial's slope spuriously small
    (observed 7x-too-fast readings on the tunneled chip), and min() keeps
    exactly those. n_lo is large enough that the delta dwarfs single-RTT
    jitter; n_hi grows further if the delta is still under ~30 ms.

    `budget_s` caps total measured wall time: when the per-iteration cost is
    already far above the RTT noise floor (e.g. a CPU-fallback run of an 8K
    config at ~200 ms/iter), the full 5x(40+160) schedule would take many
    minutes; instead the iteration counts shrink so the whole measurement
    fits the budget while the slope delta still spans >= ~10x the noise.
    """

    def wall(n: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn(*args)
        _sync(out)
        return time.perf_counter() - t0

    _sync(fn(*args))  # compile + warm
    est = wall(4) / 4  # settle allocator/dispatch caches + rough per-iter cost
    if est * trials * (n_lo + n_hi) > budget_s:
        # slow path: the delta target (>= 0.3 s of compute) dwarfs RTT jitter
        # without needing large counts
        n_lo = max(2, int(0.05 / est) + 1)
        n_hi = n_lo + max(4, int(0.3 / est) + 1)
        trials = min(trials, 3)
        while est * trials * (n_lo + n_hi) > budget_s and trials > 1:
            trials -= 1
    # grow n_hi until the measured delta clears the noise floor (~30 ms),
    # so sub-0.1ms kernels don't produce a zero/negative slope
    while n_hi < 4096:
        lo = wall(n_lo)
        hi = wall(n_hi)
        if hi - lo > 0.03:
            break
        n_hi *= 2
    slopes = []
    for _ in range(trials):
        lo = wall(n_lo)
        hi = wall(n_hi)
        slopes.append((hi - lo) / (n_hi - n_lo))
    positive = [s for s in slopes if s > 0]
    if not positive:
        raise RuntimeError(
            f"could not measure a positive throughput slope (slopes={slopes}); "
            "host too noisy — rerun"
        )
    return statistics.median(positive)


def benchmark(
    fn: Callable,
    args: Sequence,
    *,
    name: str = "bench",
    megapixels: float,
    warmup: int = 2,
    reps: int = 10,
) -> BenchResult:
    """Time `fn(*args)` with compile excluded and device sync included."""
    t0 = time.perf_counter()
    _sync(fn(*args))
    compile_s = time.perf_counter() - t0
    for _ in range(max(0, warmup - 1)):
        _sync(fn(*args))
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(fn(*args))
        walls.append(time.perf_counter() - t0)
    return BenchResult(
        name=name,
        reps=reps,
        wall_s=tuple(walls),
        megapixels=megapixels,
        compile_s=compile_s,
    )
