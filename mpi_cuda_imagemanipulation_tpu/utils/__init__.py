from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics, get_logger
from mpi_cuda_imagemanipulation_tpu.utils.timing import BenchResult, benchmark

__all__ = ["emit_json_metrics", "get_logger", "BenchResult", "benchmark"]
