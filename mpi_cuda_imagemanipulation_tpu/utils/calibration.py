"""Measured per-device-kind calibration store (the `autotune` subcommand).

The streaming kernels' block-height heuristic (ops/pallas_kernels._pick_block_h)
and the VMEM budget behind it are calibrated on TPU v5e — the one generation
this framework has had silicon access to (see BASELINE.md's single-generation
caveat). On any other generation the heuristic still produces a *safe* block
height (the VMEM working-set model is conservative), but not necessarily the
*fastest* one: round-2 on-chip sweeps moved the headline ±8% across block
heights, and other gens have different VMEM sizes and DMA sweet spots.

This module closes that gap with measurement instead of more constants:

  ``mcim-tpu autotune`` sweeps block heights for a representative pipeline on
  whatever backend is live, and records the fastest one here, keyed by the
  device kind string (e.g. ``"TPU v5 lite"``). ``_pick_block_h`` then clamps
  its heuristic to the calibrated value: ``min(heuristic, calibrated)``. The
  min rule keeps the contract one-sided — a calibration can only *shrink* the
  block below the VMEM-safe heuristic, never push it past the working-set
  model into a Mosaic OOM, so a stale or cross-width calibration degrades
  performance at worst, not correctness.

The store is a single JSON file. Resolution order for its path:
``$MCIM_CALIB_FILE`` if set, else ``.mcim_calibration.json`` in the current
working directory (a cwd-local dotfile keeps the framework from writing
outside the project tree; a deployment that wants a shared store points the
env var somewhere durable). ``MCIM_NO_CALIB=1`` disables lookups entirely —
measurement tools (tools/roofline_probe.py sweeps block heights explicitly)
use it so a committed calibration can never contaminate an A/B.

The reference has no analogue: its BLOCK_SIZE is a compile-time constant
(kernel.cu:13) tuned by hand for one GPU.
"""

from __future__ import annotations

import json
import os
import tempfile
import time as _time

from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

_ENV_FILE = "MCIM_CALIB_FILE"
_ENV_DISABLE = "MCIM_NO_CALIB"
_DEFAULT_NAME = ".mcim_calibration.json"

# process-level cache: (path, mtime_ns) -> parsed dict. Lookup happens on
# every pallas_call build, so re-reading the file each time would put disk
# I/O on the trace path; the mtime key keeps a same-process autotune->run
# sequence coherent without an explicit invalidation hook.
_cache: dict = {"key": None, "data": None}


def calib_path() -> str:
    return env_registry.get(_ENV_FILE) or os.path.join(
        os.getcwd(), _DEFAULT_NAME
    )


def _load() -> dict:
    path = calib_path()
    try:
        st = os.stat(path)
        key = (path, st.st_mtime_ns)
    except OSError:
        return {}
    if _cache["key"] == key:
        return _cache["data"]
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        # a corrupt store must never break a run; autotune rewrites it whole
        data = {}
    _cache["key"] = key
    _cache["data"] = data
    return data


def entries() -> dict:
    """All calibration entries, `{device_kind: {impl: record}}` — the
    read-only view `mcim-tpu info` reports. Empty dict when no store."""
    e = _load().get("device_kinds")
    return e if isinstance(e, dict) else {}


def current_device_kind() -> str:
    """Device-kind key for the live backend (initializes it if needed).

    Callers sit on the run path (a dispatch is imminent), so touching the
    backend here is safe — unlike pipeline *parse*, which must stay host-pure
    (advisor round-2 finding on the contrast LUT).
    """
    import jax

    return jax.devices()[0].device_kind


def lookup_block_h(
    device_kind: str | None = None,
    impl: str = "pallas",
    width: int | None = None,
) -> int | None:
    """Calibrated preferred block height for (device kind, impl), if any.

    Keyed per impl because the u8 and wide-word streaming kernels have
    different per-block compute/VMEM profiles — a height tuned for one must
    not silently steer the other (review finding).

    When the caller supplies the run's image ``width`` and the entry
    recorded the width it was swept at, the calibration only applies within
    a factor of two of that width: block height trades off against row
    length, so an 8K-headline sweep must not clamp a narrow 1080p run whose
    heuristic wanted a much taller block (advisor round-3 finding — safe
    under the min rule, but a silent perf regression). Entries without a
    recorded width (legacy stores) apply unconditionally.
    """
    if env_registry.get(_ENV_DISABLE):
        return None
    if device_kind is None:
        try:
            device_kind = current_device_kind()
        except Exception:
            return None
    rec = entries().get(device_kind)
    if not isinstance(rec, dict):
        return None
    rec = rec.get(impl)
    if not isinstance(rec, dict):
        return None
    rec_w = rec.get("width")
    if (
        width is not None
        and isinstance(rec_w, (int, float))
        and rec_w > 0
        and not (rec_w / 2 <= width <= rec_w * 2)
    ):
        return None
    bh = rec.get("block_h")
    # lower bound 8, not 32: swar blocks are ext-row multiples of 8
    # (ops/swar_kernels._pick_swar_block_h); each impl's picker enforces
    # its own stricter minimum via the min rule
    if isinstance(bh, int) and 8 <= bh <= 4096:
        return bh
    return None


def record_block_h(
    device_kind: str, block_h: int, impl: str = "pallas", **extra
) -> str:
    """Write/replace the (device kind, impl) calibration entry; returns the
    store path.

    Atomic (tmp file + rename) so a concurrent reader never sees a torn
    JSON; other kinds' and impls' entries are preserved.
    """
    data, kind_rec = _kind_record(device_kind)
    kind_rec[impl] = {"block_h": int(block_h), **extra}
    return _write_store(data)


# --------------------------------------------------------------------------
# Backend-choice calibration (the VPU-vs-MXU autotune dimension)
#
# `mcim-tpu autotune --dimension backend` measures the VPU (Pallas
# streaming), MXU banded and hybrid formulations of each eligible stencil
# family on the live chip and records the winner here, keyed by device
# kind and op family (ops/mxu_kernels.mxu_family). `backend='auto'`
# routes a stencil group to the MXU ONLY behind such a measured win (or
# the MCIM_PREFER_MXU A/B switch) — and never off-TPU, so a platform
# without an MXU always takes the VPU/XLA paths. The same width window
# rule as block heights applies: a choice swept at 8K must not steer a
# 1080p run (block-vs-row-length tradeoffs differ; factor-of-two window).
# --------------------------------------------------------------------------

_BACKEND_KEY = "backend_choice"
BACKEND_CHOICES = ("vpu", "mxu", "hybrid")


def lookup_backend_choice(
    family: str | None,
    device_kind: str | None = None,
    width: int | None = None,
) -> str | None:
    """Calibrated backend for (op family, device kind), if any: 'vpu',
    'mxu' or 'hybrid'. None when no (valid, width-compatible) entry
    exists or MCIM_NO_CALIB is set — callers then keep their default
    (VPU/XLA) routing."""
    if family is None or env_registry.get(_ENV_DISABLE):
        return None
    if device_kind is None:
        try:
            device_kind = current_device_kind()
        except Exception:
            return None
    rec = entries().get(device_kind)
    if not isinstance(rec, dict):
        return None
    table = rec.get(_BACKEND_KEY)
    if not isinstance(table, dict):
        return None
    ent = table.get(family)
    if not isinstance(ent, dict):
        return None
    rec_w = ent.get("width")
    if (
        width is not None
        and isinstance(rec_w, (int, float))
        and rec_w > 0
        and not (rec_w / 2 <= width <= rec_w * 2)
    ):
        return None
    choice = ent.get("choice")
    return choice if choice in BACKEND_CHOICES else None


def record_backend_choice(
    device_kind: str, family: str, choice: str, **extra
) -> str:
    """Write/replace the (device kind, op family) backend choice; returns
    the store path. Same atomic-write contract as record_block_h."""
    if choice not in BACKEND_CHOICES:
        raise ValueError(
            f"unknown backend choice {choice!r}; known: {BACKEND_CHOICES}"
        )
    data, kind_rec = _kind_record(device_kind)
    table = kind_rec.setdefault(_BACKEND_KEY, {})
    if not isinstance(table, dict):  # legacy/corrupt entry: replace
        table = kind_rec[_BACKEND_KEY] = {}
    table[family] = {"choice": choice, **extra}
    return _write_store(data)


# --------------------------------------------------------------------------
# In-stage MXU arm calibration (the per-op-WITHIN-stage dimension)
#
# The fused-pallas megakernel resolves an execution arm per stencil op
# inside each stage (ops/mxu_kernels.stage_arm_for): 'vpu' (the golden
# shift-multiply walk), 'mxu' (bf16/f32 dot contraction) or 'mxu-int8'
# (int8/int32 dot). Keyed by MXU FAMILY (sepK/gradKxK/corrKxK — the
# same keys as backend_choice, the granularity the identity varies at),
# device kind and the factor-of-two width window. 'auto'
# (MCIM_MXU_STAGE unset) routes to an MXU arm only behind a record here,
# the same measured-win discipline as every other dimension.
# --------------------------------------------------------------------------

_STAGE_KEY = "stage_arm"
STAGE_ARM_CHOICES = ("vpu", "mxu", "mxu-int8")


def lookup_stage_arm(
    family: str | None,
    device_kind: str | None = None,
    width: int | None = None,
) -> str | None:
    """Calibrated in-stage arm for (MXU family, device kind), if any.
    None when no (valid, width-compatible) entry exists or MCIM_NO_CALIB
    is set — the megakernel then keeps its default (VPU) walk."""
    if family is None or env_registry.get(_ENV_DISABLE):
        return None
    if device_kind is None:
        try:
            device_kind = current_device_kind()
        except Exception:
            return None
    rec = entries().get(device_kind)
    if not isinstance(rec, dict):
        return None
    table = rec.get(_STAGE_KEY)
    if not isinstance(table, dict):
        return None
    ent = table.get(family)
    if not isinstance(ent, dict):
        return None
    rec_w = ent.get("width")
    if (
        width is not None
        and isinstance(rec_w, (int, float))
        and rec_w > 0
        and not (rec_w / 2 <= width <= rec_w * 2)
    ):
        return None
    choice = ent.get("choice")
    return choice if choice in STAGE_ARM_CHOICES else None


def record_stage_arm(
    device_kind: str, family: str, choice: str, **extra
) -> str:
    """Write/replace the (device kind, MXU family) in-stage arm; returns
    the store path. Same atomic-write contract as record_block_h."""
    if choice not in STAGE_ARM_CHOICES:
        raise ValueError(
            f"unknown stage arm {choice!r}; known: {STAGE_ARM_CHOICES}"
        )
    data, kind_rec = _kind_record(device_kind)
    table = kind_rec.setdefault(_STAGE_KEY, {})
    if not isinstance(table, dict):  # legacy/corrupt entry: replace
        table = kind_rec[_STAGE_KEY] = {}
    table[family] = {"choice": choice, **extra}
    return _write_store(data)


def stage_arm_entries(device_kind: str | None = None) -> dict:
    """The device kind's whole stage_arm table (family -> entry), for
    `mcim-tpu autotune info` — {} when absent."""
    if device_kind is None:
        try:
            device_kind = current_device_kind()
        except Exception:
            return {}
    rec = entries().get(device_kind)
    if not isinstance(rec, dict):
        return {}
    table = rec.get(_STAGE_KEY)
    return table if isinstance(table, dict) else {}


# --------------------------------------------------------------------------
# Plan-choice calibration (the fused-plan autotune dimension)
#
# `mcim-tpu autotune --dimension plan` measures the per-op ('off'),
# pointwise-absorption and fully fused execution plans of a pipeline on
# the live backend (all bit-identical — gated before timing) and records
# the fastest, keyed by device kind and PIPELINE FINGERPRINT
# (plan.ir.pipeline_fingerprint: op names + halos + families). The
# `plan='auto'` resolution (plan/planner.resolve_plan_mode) consults this
# table, so a recorded choice steers jit/batched/sharded/serving/stream
# alike; the serving compile cache keys executables by the RESOLVED
# plan's fingerprint, so flipping this entry can never serve a stale
# executable built for the previous structure. Same width window rule as
# the other dimensions.
# --------------------------------------------------------------------------

_PLAN_KEY = "plan_choice"
PLAN_CHOICES = ("off", "pointwise", "fused", "fused-pallas", "fused-pallas-mxu")


def lookup_plan_choice(
    pipeline_fp: str | None,
    device_kind: str | None = None,
    width: int | None = None,
) -> str | None:
    """Calibrated plan build mode for (pipeline fingerprint, device kind),
    if any. None when no (valid, width-compatible) entry exists or
    MCIM_NO_CALIB is set — callers then keep their default resolution."""
    if pipeline_fp is None or env_registry.get(_ENV_DISABLE):
        return None
    if device_kind is None:
        try:
            device_kind = current_device_kind()
        except Exception:
            return None
    rec = entries().get(device_kind)
    if not isinstance(rec, dict):
        return None
    table = rec.get(_PLAN_KEY)
    if not isinstance(table, dict):
        return None
    ent = table.get(pipeline_fp)
    if not isinstance(ent, dict):
        return None
    rec_w = ent.get("width")
    if (
        width is not None
        and isinstance(rec_w, (int, float))
        and rec_w > 0
        and not (rec_w / 2 <= width <= rec_w * 2)
    ):
        return None
    choice = ent.get("choice")
    return choice if choice in PLAN_CHOICES else None


def record_plan_choice(
    device_kind: str, pipeline_fp: str, choice: str, **extra
) -> str:
    """Write/replace the (device kind, pipeline fingerprint) plan choice;
    returns the store path. Same atomic-write contract as record_block_h.

    Stamps ``recorded_at`` (epoch seconds) unless the caller supplied one:
    the online tuner (tune/store.effective_plan_choice) resolves
    offline-vs-online disagreement by freshness, and an unstamped entry
    would silently lose every comparison. Legacy entries without the
    stamp sort as oldest."""
    if choice not in PLAN_CHOICES:
        raise ValueError(
            f"unknown plan choice {choice!r}; known: {PLAN_CHOICES}"
        )
    data, kind_rec = _kind_record(device_kind)
    table = kind_rec.setdefault(_PLAN_KEY, {})
    if not isinstance(table, dict):  # legacy/corrupt entry: replace
        table = kind_rec[_PLAN_KEY] = {}
    extra.setdefault("recorded_at", round(_time.time(), 3))
    table[pipeline_fp] = {"choice": choice, **extra}
    return _write_store(data)


def plan_entry(
    pipeline_fp: str | None,
    device_kind: str | None = None,
    width: int | None = None,
) -> dict | None:
    """The raw offline plan-choice entry for (fingerprint, device kind),
    width-window filtered — `{"choice", "width"?, "recorded_at"?, ...}`.

    Unlike lookup_plan_choice this exposes the entry's METADATA, which the
    online tuner needs for its newest-wins precedence rule. Same
    MCIM_NO_CALIB and factor-of-two width-window gating."""
    if pipeline_fp is None or env_registry.get(_ENV_DISABLE):
        return None
    if device_kind is None:
        try:
            device_kind = current_device_kind()
        except Exception:
            return None
    rec = entries().get(device_kind)
    if not isinstance(rec, dict):
        return None
    table = rec.get(_PLAN_KEY)
    if not isinstance(table, dict):
        return None
    ent = table.get(pipeline_fp)
    if not isinstance(ent, dict) or ent.get("choice") not in PLAN_CHOICES:
        return None
    rec_w = ent.get("width")
    if (
        width is not None
        and isinstance(rec_w, (int, float))
        and rec_w > 0
        and not (rec_w / 2 <= width <= rec_w * 2)
    ):
        return None
    return ent


def raw_store() -> dict:
    """A DEEP COPY of the parsed store (or {} when absent/corrupt).

    The online tuner (tune/store) keeps its records in a sibling
    top-level section of the same file; it mutates this copy and hands it
    to write_raw_store. A copy, not the cached dict: _load's cache is
    shared process-wide and callers must not alter it in place."""
    return json.loads(json.dumps(_load()))


def write_raw_store(data: dict) -> str:
    """Atomically replace the whole store file (tmp + rename, same
    contract as record_block_h). Callers merge into raw_store() output
    first — this is a whole-file swap, not a patch."""
    return _write_store(data)


def _kind_record(device_kind: str) -> tuple[dict, dict]:
    """(whole store, mutable per-device-kind record) — the caller mutates
    the record and hands the store back to _write_store."""
    data = _load()
    kinds = data.setdefault("device_kinds", {})
    kind_rec = kinds.setdefault(device_kind, {})
    if not isinstance(kind_rec, dict):  # legacy/corrupt entry: replace
        kind_rec = kinds[device_kind] = {}
    return data, kind_rec


def _write_store(data: dict) -> str:
    path = calib_path()
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".mcim_calib_")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _cache["key"] = None  # force re-read (mtime granularity is ns, but be sure)
    return path
