"""Central registry of every ``MCIM_*`` environment variable.

Before this module the env surface was scattered: each subsystem read
``os.environ`` directly, and nothing guaranteed a variable was documented
— or even spelled consistently — across readers, docs and the tpu_queue
scripts. Here every variable is declared ONCE with its default, consumer
module and a one-line doc, and the package reads env state only through
:func:`get`/:func:`get_bool`/... so a typo'd name fails loudly at the
read site instead of silently returning the fallback forever.

The declaration table is machine-checked, not aspirational: the
``env-unregistered`` / ``env-undocumented`` rules in
``mpi_cuda_imagemanipulation_tpu/analysis`` (run via
``tools/mcim_check.py``, blocking in CI) verify that

  * every ``MCIM_*`` literal read anywhere in the repo names a registered
    variable,
  * package modules go through this registry rather than ``os.environ``,
  * every registered variable appears in README.md or docs/ (the table in
    docs/design.md "Static analysis & invariants" is generated from
    :func:`doc_table`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class EnvVar:
    name: str
    default: str | None  # value get() returns when unset (None = unset)
    consumer: str  # the module that reads it
    doc: str  # one line; docs/design.md table row


_VARS = (
    # -- fault injection (resilience/failpoints.py) -------------------------
    EnvVar("MCIM_FAILPOINTS", None, "resilience/failpoints.py",
           "Arm deterministic fault injection: comma-separated site=mode "
           "pairs (e.g. serve.dispatch=0.1,io.decode=first:2)."),
    EnvVar("MCIM_FAILPOINT_SEED", "0", "resilience/failpoints.py",
           "Seed for probabilistic failpoint modes (deterministic "
           "fail/pass sequence per site)."),
    # -- observability (obs/, utils/log.py) ---------------------------------
    EnvVar("MCIM_TRACE_SAMPLE", None, "obs/trace.py",
           "Arm request-scoped tracing at this sample fraction "
           "(deterministic every-k-th; 1 = every trace)."),
    EnvVar("MCIM_TRACE_OUT", None, "bench_suite.py",
           "serve_loadgen lane: export the sweep's span timeline to this "
           "path (Chrome/Perfetto JSON)."),
    EnvVar("MCIM_TRACE_TAIL", "256", "obs/trace.py",
           "Deferred tail-keep buffer: sampled-OUT traces buffer up to "
           "this many concurrently-open traces and promote to kept when "
           "the root ends with an error/quarantine/deadline status or a "
           "p99-slow duration; 0 restores pure root sampling."),
    # -- cost attribution (obs/cost.py) ---------------------------------------
    EnvVar("MCIM_COST_ATTRIB", "1", "obs/cost.py",
           "=0 disables compiled-executable cost attribution (the "
           "cost_analysis/memory_analysis extraction at every compile-"
           "cache insertion site and its mcim_cost_* families)."),
    EnvVar("MCIM_COST_CAP", "64", "obs/cost.py",
           "Cost-ledger LRU capacity: attributions are keyed by "
           "(site, fingerprint, stage), which is unbounded in principle "
           "— metric label sets must not be."),
    EnvVar("MCIM_COST_DRIFT_MIN", "0.8", "obs/cost.py",
           "Lower edge of the acceptable plan-model drift band: a "
           "measured/modelled boundary-byte ratio below this trips "
           "mcim_cost_drift_alerts_total."),
    EnvVar("MCIM_COST_DRIFT_MAX", "1.25", "obs/cost.py",
           "Upper edge of the acceptable plan-model drift band."),
    EnvVar("MCIM_COST_PEAK_GBS", None, "obs/cost.py",
           "Override the measured-roofline denominator (GB/s); unset "
           "uses the datasheet table keyed by TPU generation "
           "(bench_suite.HBM_GB_S)."),
    # -- on-demand fleet profiling (obs/profile.py) ---------------------------
    EnvVar("MCIM_PROFILE_DIR", None, "obs/profile.py",
           "Directory on-demand profile captures write their device "
           "trace + merged artifact under (default artifacts/profile/)."),
    EnvVar("MCIM_PROFILE_MIN_INTERVAL_S", "30", "obs/profile.py",
           "Per-process rate limit between live profile captures: the "
           "control plane cannot stack captures on a serving replica."),
    EnvVar("MCIM_PROFILE_MAX_S", "10", "obs/profile.py",
           "Capture-window ceiling in seconds (must stay well under the "
           "router's forward timeout — the relay blocks for the "
           "capture)."),
    EnvVar("MCIM_PROFILE_DEFAULT_S", "2", "obs/profile.py",
           "Capture window when POST /control/profile names none."),
    EnvVar("MCIM_LOG_LEVEL", None, "utils/log.py",
           "Logger verbosity: level name or number (DEBUG..CRITICAL or "
           "10..50); default INFO."),
    # -- flight recorder (obs/recorder.py) -----------------------------------
    EnvVar("MCIM_RECORDER_DIR", None, "obs/recorder.py",
           "Directory post-mortem flight-recorder dumps are written to "
           "(default artifacts/recorder/)."),
    EnvVar("MCIM_RECORDER_CAP", "2048", "obs/recorder.py",
           "Flight-recorder ring capacity: the newest N entries (span/"
           "dispatch/failpoint/breaker/heartbeat/log facts) a dump can "
           "contain."),
    EnvVar("MCIM_RECORDER_MIN_INTERVAL_S", "30", "obs/recorder.py",
           "Per-trigger dump rate limit in seconds: a quarantine storm "
           "produces one artifact per window, not thousands."),
    # -- SLO burn-rate engine (obs/slo.py) -----------------------------------
    EnvVar("MCIM_SLO_SPECS", "avail:99.5,latency:1.0:99", "obs/slo.py",
           "Default SLO spec list for the fabric router's /slo engine: "
           "comma-separated avail:<pct> and latency:<le_seconds>:<pct> "
           "entries (docs/design.md \"Fleet observability\")."),
    EnvVar("MCIM_SLO_FAST_S", "300", "obs/slo.py",
           "Fast burn-rate window in seconds (the 5m page window; an "
           "alert fires only when fast AND slow burn exceed the "
           "threshold)."),
    EnvVar("MCIM_SLO_SLOW_S", "3600", "obs/slo.py",
           "Slow burn-rate window in seconds (the 1h confirmation "
           "window)."),
    EnvVar("MCIM_SLO_TICK_S", "5", "obs/slo.py",
           "SLO engine evaluation period in seconds (each tick samples "
           "the federated counters into the window ring)."),
    EnvVar("MCIM_SLO_BURN_THRESHOLD", "10", "obs/slo.py",
           "Burn-rate alert threshold: error-budget consumption rate "
           "(1 = exactly on budget) both windows must exceed to fire."),
    # -- concurrency checking (analysis/lockcheck.py) -----------------------
    EnvVar("MCIM_LOCK_CHECK", None, "analysis/lockcheck.py",
           "=1: instrument threading.Lock/RLock/Condition with the "
           "lock-order recorder for the whole test session; the observed "
           "acquisition graph is asserted cycle-free at exit."),
    # -- calibration store (utils/calibration.py) ---------------------------
    EnvVar("MCIM_CALIB_FILE", None, "utils/calibration.py",
           "Calibration store path (default ./.mcim_calibration.json)."),
    EnvVar("MCIM_NO_CALIB", None, "utils/calibration.py",
           "Any non-empty value disables calibration lookups (A/B tools "
           "must not be steered by a committed store)."),
    # -- backend routing switches (ops/) ------------------------------------
    EnvVar("MCIM_PREFER_SWAR", None, "ops/pallas_kernels.py",
           "=1: route eligible stencil groups through the SWAR "
           "quarter-strip backend on every auto path (A/B switch; "
           "measured 0.83x the u8 kernels, so off by default)."),
    EnvVar("MCIM_PREFER_MXU", None, "ops/mxu_kernels.py",
           "=1: route eligible stencil families onto the MXU banded path "
           "on auto paths without a calibration win (TPU-only A/B "
           "switch)."),
    EnvVar("MCIM_MXU_MODE", "banded", "ops/mxu_kernels.py",
           "MXU execution mode: banded (both separable passes on the "
           "MXU) or hybrid (VPU row pass + MXU column pass)."),
    EnvVar("MCIM_MXU_COL", "bf16split", "ops/mxu_kernels.py",
           "MXU column-pass arithmetic: bf16split (the proven 64a+b "
           "split) or f32 (direct einsum, A/B lane)."),
    EnvVar("MCIM_MXU_STAGE", "auto", "ops/mxu_kernels.py",
           "Per-op MXU arm INSIDE fused-pallas stages: auto (TPU + "
           "calibrated stage_arm win), off, on (force, int8 where "
           "proven — the interpret A/B switch), f32, int8."),
    # -- bench lanes (bench_suite.py) ----------------------------------------
    EnvVar("MCIM_HALO_AB", None, "bench_suite.py",
           "=1 forces the sharded serial-vs-overlap halo A/B on, =0 off; "
           "default: only on real TPU hardware."),
    EnvVar("MCIM_MXU_AB_OPS", None, "bench_suite.py",
           "mxu_ab lane: pipeline override (default gaussian:5)."),
    EnvVar("MCIM_MXU_AB_HEIGHT", None, "bench_suite.py",
           "mxu_ab lane: image height override."),
    EnvVar("MCIM_MXU_AB_WIDTH", None, "bench_suite.py",
           "mxu_ab lane: image width override."),
    EnvVar("MCIM_MXU_AB_JSON", None, "tests/test_mxu_backend.py",
           "CI: write the mxu_ab lane record to this path (uploaded as an "
           "artifact)."),
    EnvVar("MCIM_MXU_FUSED_AB_OPS", None, "bench_suite.py",
           "mxu_fused_ab lane: pipeline override (default "
           "gaussian:5,sharpen,box:5)."),
    EnvVar("MCIM_MXU_FUSED_AB_HEIGHT", None, "bench_suite.py",
           "mxu_fused_ab lane: image height override."),
    EnvVar("MCIM_MXU_FUSED_AB_WIDTH", None, "bench_suite.py",
           "mxu_fused_ab lane: image width override."),
    EnvVar("MCIM_MXU_FUSED_AB_JSON", None, "tests/test_mxu_backend.py",
           "CI: write the mxu_fused_ab lane record to this path (uploaded "
           "as an artifact)."),
    EnvVar("MCIM_ENGINE_AB_IMAGES", None, "bench_suite.py",
           "engine_ab lane: synthetic corpus size override."),
    EnvVar("MCIM_ENGINE_AB_DECODE_MS", None, "bench_suite.py",
           "engine_ab lane: per-image synthetic decode delay override."),
    EnvVar("MCIM_ENGINE_AB_ENCODE_MS", None, "bench_suite.py",
           "engine_ab lane: per-image synthetic encode delay override."),
    EnvVar("MCIM_ENGINE_AB_INFLIGHT", None, "bench_suite.py",
           "engine_ab lane: overlapped-lane dispatch depth override."),
    EnvVar("MCIM_ENGINE_AB_JSON", None, "tests/test_engine.py",
           "CI: write the engine_ab lane record to this path (uploaded "
           "as an artifact)."),
    EnvVar("MCIM_SERVE_RPS", None, "bench_suite.py",
           "serve_loadgen lane: offered-rate sweep override (comma "
           "list)."),
    EnvVar("MCIM_SERVE_DURATION_S", None, "bench_suite.py",
           "serve_loadgen lane: per-rate sweep duration override."),
    EnvVar("MCIM_SERVE_FAULT_RATE", None, "bench_suite.py",
           "serve_loadgen lane: injected transient dispatch-failure rate "
           "(availability columns)."),
    # -- pod-scale serving fabric (fabric/) ----------------------------------
    EnvVar("MCIM_FABRIC_HEARTBEAT_S", "0.5", "fabric/control.py",
           "Replica heartbeat period in seconds (replica -> router push "
           "over HTTP)."),
    EnvVar("MCIM_FABRIC_STALE_S", "2.0", "fabric/router.py",
           "Router freshness window: a replica whose last heartbeat is "
           "older than this is routed around until it beats again."),
    EnvVar("MCIM_FABRIC_FORWARD_TIMEOUT_S", "30", "fabric/router.py",
           "Per-attempt router -> replica proxy timeout (connect + full "
           "response read)."),
    EnvVar("MCIM_FABRIC_FORWARD_ATTEMPTS", "3", "fabric/router.py",
           "Forward attempts per request across DISTINCT replicas before "
           "the router answers 503 (attempt 2+ counts as retried)."),
    EnvVar("MCIM_FABRIC_SHED_FRAC", "0.8", "fabric/router.py",
           "Queue-fill fraction (queued/queue_depth from the heartbeat) "
           "past which the sticky target is skipped for the least-loaded "
           "healthy replica."),
    # -- elastic fabric (fabric/autoscaler.py, fabric/canary.py,
    # fabric/session.py) ----------------------------------------------------
    EnvVar("MCIM_FABRIC_MIN_REPLICAS", "1", "fabric/autoscaler.py",
           "Autoscaler floor: the control loop never drains the replica "
           "set below this count."),
    EnvVar("MCIM_FABRIC_MAX_REPLICAS", "8", "fabric/autoscaler.py",
           "Autoscaler ceiling: scale-up stops here regardless of "
           "pressure."),
    EnvVar("MCIM_FABRIC_SCALE_UP_FRAC", "0.75", "fabric/autoscaler.py",
           "Mean queue-fill fraction across routable replicas that, "
           "sustained for MCIM_FABRIC_SCALE_SUSTAIN_S, triggers a "
           "scale-up."),
    EnvVar("MCIM_FABRIC_SCALE_DOWN_FRAC", "0.15", "fabric/autoscaler.py",
           "Mean queue-fill fraction BELOW which (sustained, and with a "
           "majority of replicas idle) the autoscaler drains one "
           "replica."),
    EnvVar("MCIM_FABRIC_SCALE_SUSTAIN_S", "3", "fabric/autoscaler.py",
           "How long a pressure signal must persist before the "
           "autoscaler acts on it (the hysteresis window — a blip "
           "scales nothing)."),
    EnvVar("MCIM_FABRIC_SCALE_COOLDOWN_S", "5", "fabric/autoscaler.py",
           "Quiet period after any scale action before the next one "
           "(lets the new replica set settle before re-evaluating)."),
    EnvVar("MCIM_FABRIC_SCALE_TICK_S", "0.5", "fabric/autoscaler.py",
           "Autoscaler evaluation period in seconds."),
    EnvVar("MCIM_FABRIC_SCALE_P99_TARGET_S", None, "fabric/autoscaler.py",
           "Optional latency up-signal: a federated p99 above this "
           "(sustained) also triggers scale-up, independent of queue "
           "fill."),
    EnvVar("MCIM_FABRIC_SCALE_DRAIN_DEADLINE_S", "30",
           "fabric/autoscaler.py",
           "Drain-before-kill budget: a draining replica whose queue "
           "has not emptied by then is SIGTERMed anyway (the replica's "
           "own drain deadline still flushes in-flight work)."),
    EnvVar("MCIM_FABRIC_CANARY_FRAC", "0.05", "fabric/canary.py",
           "Fraction of front-door traffic routed to the canary replica "
           "while a config flip is under evaluation."),
    EnvVar("MCIM_FABRIC_CANARY_MIN_REQUESTS", "40", "fabric/canary.py",
           "Canary outcomes the rollback gate needs before it may "
           "decide (breach can fire earlier on shadow digest "
           "mismatches, which are individually damning)."),
    EnvVar("MCIM_FABRIC_CANARY_SHADOW_EVERY", "5", "fabric/canary.py",
           "Every k-th canary-routed request is ALSO forwarded to a "
           "stable replica and the response digests compared (the "
           "bit-exactness spot check; the client gets the stable "
           "answer)."),
    EnvVar("MCIM_FABRIC_CANARY_BAD_FRAC", "0.10", "fabric/canary.py",
           "Absolute canary bad-outcome fraction past which the gate "
           "rolls back."),
    EnvVar("MCIM_FABRIC_CANARY_BURN_RATIO", "3", "fabric/canary.py",
           "Relative breach: canary bad rate must stay under this "
           "multiple of the stable lanes' bad rate over the gate "
           "window (the canary-vs-stable burn-rate comparison)."),
    EnvVar("MCIM_FABRIC_CANARY_PROMOTE_REQUESTS", "400",
           "fabric/canary.py",
           "Canary outcomes without a breach after which the gate "
           "reports the flip promotable."),
    EnvVar("MCIM_FABRIC_SESSION_TAIL", "0", "fabric/session.py",
           "Frames of journal tail the router retains per live video "
           "session for failover replay; 0 = sized automatically from "
           "the session pipeline's temporal windows (sum of windows)."),
    EnvVar("MCIM_FABRIC_RPS", None, "bench_suite.py",
           "fabric_loadgen lane: offered-rate override (single float)."),
    EnvVar("MCIM_FABRIC_DURATION_S", None, "bench_suite.py",
           "fabric_loadgen lane: per-phase sweep duration override."),
    EnvVar("MCIM_FABRIC_REPLICAS", None, "bench_suite.py",
           "fabric_loadgen lane: scaled-lane replica count override "
           "(default 3; the baseline lane is always 1)."),
    EnvVar("MCIM_FABRIC_AB_JSON", None, "tests/test_fabric.py",
           "CI: write the fabric_loadgen lane record to this path "
           "(uploaded as an artifact)."),
    # -- streaming tile engine (stream/) -------------------------------------
    EnvVar("MCIM_STREAM_TILE_ROWS", "512", "cli.py",
           "Default row-band height for the `stream` subcommand "
           "(--tile-rows overrides); the constant-memory budget knob."),
    EnvVar("MCIM_STREAM_INFLIGHT", "2", "cli.py",
           "Default in-flight tile dispatches for the `stream` "
           "subcommand (--inflight overrides); >= 2 double-buffers the "
           "H2D prefetch of tile k+1 under tile k's compute."),
    EnvVar("MCIM_STREAM_AB_HEIGHT", None, "bench_suite.py",
           "stream_ab lane: image height override."),
    EnvVar("MCIM_STREAM_AB_WIDTH", None, "bench_suite.py",
           "stream_ab lane: image width override."),
    EnvVar("MCIM_STREAM_AB_TILE_ROWS", None, "bench_suite.py",
           "stream_ab lane: streamed-lane tile height override."),
    EnvVar("MCIM_STREAM_AB_JSON", None, "tests/test_stream.py",
           "CI: write the stream_ab lane record to this path (uploaded "
           "as an artifact)."),
    # -- fusion planner (plan/) ----------------------------------------------
    EnvVar("MCIM_PLAN", None, "plan/planner.py",
           "Global fusion-plan mode override consulted when an entry "
           "point is called with plan='auto': off / pointwise / fused "
           "('on' = fused). Unset: 'auto' resolves through the "
           "calibration store's plan-choice table, then the backend "
           "default (plan/planner.resolve_plan_mode)."),
    EnvVar("MCIM_PLAN_AB_OPS", None, "bench_suite.py",
           "plan_ab lane: pipeline override (default the pointwise-heavy "
           "grayscale,contrast,gaussian:5,quantize headline chain)."),
    EnvVar("MCIM_PLAN_AB_HEIGHT", None, "bench_suite.py",
           "plan_ab lane: image height override."),
    EnvVar("MCIM_PLAN_AB_WIDTH", None, "bench_suite.py",
           "plan_ab lane: image width override."),
    EnvVar("MCIM_PLAN_AB_JSON", None, "tests/test_plan.py",
           "CI: write the plan_ab lane record to this path (uploaded as "
           "an artifact)."),
    EnvVar("MCIM_PLAN_COMMUTE", "1", "plan/planner.py",
           "=0 disables geometric-commute fusion (hoisting rot180/flip "
           "pixel permutations out of pointwise runs before stage "
           "partitioning); on by default — bit-exact either way."),
    EnvVar("MCIM_MEGAKERNEL_AB_OPS", None, "bench_suite.py",
           "megakernel_ab lane: pipeline override (default the "
           "two-stencil grayscale,contrast,gaussian:5,sharpen,quantize "
           "chain — one temporally-blocked stage)."),
    EnvVar("MCIM_MEGAKERNEL_AB_HEIGHT", None, "bench_suite.py",
           "megakernel_ab lane: image height override."),
    EnvVar("MCIM_MEGAKERNEL_AB_WIDTH", None, "bench_suite.py",
           "megakernel_ab lane: image width override."),
    EnvVar("MCIM_MEGAKERNEL_AB_JSON", None, "tests/test_plan.py",
           "CI: write the megakernel_ab lane record to this path "
           "(uploaded as an artifact)."),
    # -- pipeline service (graph/) -------------------------------------------
    EnvVar("MCIM_GRAPH_MAX_NODES", "64", "graph/spec.py",
           "Node-count cap on POSTed pipeline specs (a hostile spec is "
           "refused with the closed `too-large` taxonomy code, never "
           "traced)."),
    EnvVar("MCIM_GRAPH_MAX_TENANTS", "64", "graph/tenancy.py",
           "Tenant-registry cap: tenant ids are metric labels, so the "
           "tenant set must be bounded (`tenant-limit` refusal past it)."),
    EnvVar("MCIM_GRAPH_CACHE_CAP", "8", "graph/tenancy.py",
           "Per-tenant compile-cache namespace cap (LRU entries): a "
           "tenant registering pipelines without bound recycles its own "
           "slots (the PR 8 bucket-cardinality-cap pattern)."),
    EnvVar("MCIM_GRAPH_QOS_SHED_FRAC", "0.5", "graph/tenancy.py",
           "Load fraction past which batch-class tenants shed (standard "
           "sheds halfway between this and 1; interactive rides to full "
           "capacity) — honored by both the graph service and the "
           "serving scheduler's qos= admission."),
    EnvVar("MCIM_GRAPH_QUOTA_WINDOW_S", "1.0", "graph/tenancy.py",
           "Default fixed quota window in seconds for per-tenant "
           "request/byte budgets (tenant config can override per "
           "tenant)."),
    EnvVar("MCIM_GRAPH_MAX_INFLIGHT", "8", "graph/service.py",
           "Concurrent graph dispatches per replica; past it even "
           "interactive traffic sheds with 503 + Retry-After."),
    EnvVar("MCIM_GRAPH_TENANTS", None, "bench_suite.py",
           "graph_loadgen lane: tenant-count override (--tenants flag "
           "works too)."),
    EnvVar("MCIM_GRAPH_AB_JSON", None, "tests/test_graph.py",
           "CI: write the graph_loadgen lane record to this path "
           "(uploaded as an artifact)."),
    # -- pod-level systolic execution (graph/systolic.py) --------------------
    EnvVar("MCIM_SYSTOLIC", "0", "fabric/replica.py",
           "Default for --systolic: accept stage-sharded graph "
           "dispatches (run a placed step range, forward the live env "
           "to the next stage owner) and advertise it in heartbeats."),
    EnvVar("MCIM_SYSTOLIC_MIN_STEPS", "4", "fabric/router.py",
           "Smallest program (compiled step count) the router will "
           "stage-shard; shorter programs stay on the pinned lane "
           "(counted as fallback reason 'ineligible')."),
    EnvVar("MCIM_SYSTOLIC_AB_OPS", None, "bench_suite.py",
           "systolic_ab lane: op-chain override for the >=8-stage DAG "
           "(must stay systolic-eligible: pointwise/stencil, "
           "channel-preserving)."),
    EnvVar("MCIM_SYSTOLIC_AB_REQUESTS", None, "bench_suite.py",
           "systolic_ab lane: requests per arm."),
    EnvVar("MCIM_SYSTOLIC_AB_HEIGHT", None, "bench_suite.py",
           "systolic_ab lane: image height override."),
    EnvVar("MCIM_SYSTOLIC_AB_JSON", None, "tools/systolic_smoke.py",
           "CI: write the systolic_ab lane record to this path "
           "(uploaded as an artifact)."),
    # -- multi-pod federation (federation/) ----------------------------------
    EnvVar("MCIM_FED_HEARTBEAT_S", "1.0", "federation/control.py",
           "Pod -> front-door heartbeat interval (the pod router pushes "
           "aggregate PodHeartbeats; liveness at the federation tier is "
           "the absence of beats)."),
    EnvVar("MCIM_FED_STALE_S", "4.0", "federation/frontdoor.py",
           "Beat absence past which the front door treats a pod as dead "
           "and reroutes only that pod's affinity slice."),
    EnvVar("MCIM_FED_REGISTRY", ".mcim_fed_registry.jsonl",
           "federation/frontdoor.py",
           "Path of the front door's durable tenant/spec/session "
           "registry (fsync'd JSONL; rehydrated on restart so clients "
           "never re-register)."),
    EnvVar("MCIM_FED_FORWARD_TIMEOUT_S", "30.0", "federation/frontdoor.py",
           "Per-attempt front-door -> pod proxy timeout."),
    EnvVar("MCIM_FED_FORWARD_ATTEMPTS", "3", "federation/frontdoor.py",
           "Pod candidates tried per request before 503 (pod-level "
           "admission sheds are FINAL and never retried — the "
           "lease-not-budget-times-pods invariant)."),
    EnvVar("MCIM_GRAPH_COALESCE", "1", "serve/server.py",
           "=0 disables graph micro-batch coalescing (per-request "
           "dispatch through the scheduler's (dag_fingerprint, bucket) "
           "queue; batched executables are vmapped and bit-exact)."),
    # -- request lifecycle (resilience/deadline.py) --------------------------
    EnvVar("MCIM_FED_DEADLINE_MS", "0", "federation/frontdoor.py",
           "Default end-to-end deadline budget (ms) the front door "
           "stamps on requests that arrive without X-MCIM-Deadline-Ms; "
           "0 = no default (only client-set budgets propagate)."),
    EnvVar("MCIM_RETRY_BUDGET_FRAC", "0.1", "resilience/deadline.py",
           "Retry-budget deposit per accepted request at the door and "
           "router: retries/reroutes/hedges each withdraw one token, "
           "bounding attempt amplification at 1+frac asymptotically."),
    EnvVar("MCIM_RETRY_BUDGET_RESERVE", "8", "resilience/deadline.py",
           "Retry-budget starting balance (tokens): cold-start failover "
           "headroom before any deposits have banked (the breaker board "
           "trips within ~2 failures, so this covers the first probes)."),
    EnvVar("MCIM_HEDGE_DELAY_FRAC", "0", "fabric/router.py",
           "Hedged requests: a chain forward still pending past this "
           "fraction of the router's federated p99 gets ONE secondary "
           "forward to a different replica, first response wins; 0 "
           "disables hedging."),
    EnvVar("MCIM_HEDGE_MAX_FRAC", "0.05", "fabric/router.py",
           "Cap on hedges as a fraction of accepted requests (on top of "
           "the retry-budget withdrawal each hedge makes)."),
    # -- continuous autotuning (tune/) ---------------------------------------
    EnvVar("MCIM_TUNE", "0", "tune/store.py",
           "=1 arms the online autotuning loop: serve-path observations "
           "persist to the calibration store and the router's tune "
           "controller proposes/promotes config flips through the canary "
           "gate (fabric --tune sets it on every replica)."),
    EnvVar("MCIM_TUNE_TICK_S", "1.0", "tune/controller.py",
           "Tune controller decision-tick period (seconds)."),
    EnvVar("MCIM_TUNE_MIN_SAMPLES", "8", "tune/controller.py",
           "Effective observations an arm needs before the controller "
           "will exploit against it (below this: insufficient_data / "
           "explore)."),
    EnvVar("MCIM_TUNE_EXPLORE_C", "0.35", "tune/controller.py",
           "UCB exploration coefficient — widens the optimistic lower "
           "confidence bound on under-sampled arms; 0 = pure greedy."),
    EnvVar("MCIM_TUNE_MIN_GAIN", "1.05", "tune/controller.py",
           "Measured speedup a candidate must hold over the current arm "
           "to be proposed/promoted (1.05 = 5% — flips below this are "
           "churn, not wins)."),
    EnvVar("MCIM_TUNE_FLIP_TIMEOUT_S", "300", "tune/controller.py",
           "A promoted-by-the-gate flip that has produced no canary "
           "measurements after this long is reverted (rollback decision)."),
    EnvVar("MCIM_TUNE_CANARY_FRAC", None, "tune/controller.py",
           "Traffic fraction routed to a tuner-proposed canary replica "
           "(overrides the pod's CanaryConfig.frac for tuner flips only)."),
    EnvVar("MCIM_TUNE_ARMS", None, "fabric/supervisor.py",
           "Comma-separated candidate arms the controller may propose "
           "(e.g. plan:off,plan:fused); default: every plan mode the "
           "pipeline supports."),
    EnvVar("MCIM_TUNE_STALE_S", "900", "tune/store.py",
           "Staleness half-life for online observations (seconds): a "
           "sample this old carries half the weight of a fresh one; "
           "samples older than 8 half-lives are dropped."),
    EnvVar("MCIM_TUNE_RESERVOIR", "64", "tune/store.py",
           "Max online samples kept per (device kind, fingerprint, "
           "width window, arm) — newest kept, oldest dropped."),
    EnvVar("MCIM_TUNE_FLUSH_S", "1.0", "tune/store.py",
           "Min seconds between online-record merges into the "
           "calibration file (observation ingestion is in-memory "
           "between flushes)."),
    EnvVar("MCIM_TUNE_CONV_OPS", None, "bench_suite.py",
           "tune_convergence lane: pipeline override (default the "
           "pointwise-heavy headline chain, where fused-vs-off is the "
           "measured spread the controller must find)."),
    EnvVar("MCIM_TUNE_CONV_HEIGHT", None, "bench_suite.py",
           "tune_convergence lane: bucket height override."),
    EnvVar("MCIM_TUNE_CONV_WIDTH", None, "bench_suite.py",
           "tune_convergence lane: bucket width override."),
    # -- chaos harness (resilience/chaos.py, tools/chaos_smoke.py) -----------
    EnvVar("MCIM_CHAOS_SEED", None, "tools/chaos_smoke.py",
           "Comma-separated ChaosSchedule seeds the chaos smoke runs "
           "(default: the two fixed CI seeds)."),
    EnvVar("MCIM_CHAOS_RPS", "30", "tools/chaos_smoke.py",
           "Open-loop offered load (req/s) per chaos run."),
    EnvVar("MCIM_CHAOS_DURATION_S", "8", "tools/chaos_smoke.py",
           "Duration of each chaos run's load + fault window."),
    # -- bench driver (bench.py, repo root) ----------------------------------
    EnvVar("MCIM_NO_HISTORY", None, "bench.py",
           "Any non-empty value: do not append promoted records to "
           "BENCH_HISTORY.jsonl (tests set this)."),
    EnvVar("MCIM_PROBE_SCHEDULE", None, "bench.py",
           "Comma-separated seconds between device-availability probe "
           "attempts (overrides the backend-sized default)."),
    EnvVar("MCIM_RETRY_PROBE_SCHEDULE", None, "bench.py",
           "Legacy alias for MCIM_PROBE_SCHEDULE (still honored)."),
    # -- test harness / archived tools ---------------------------------------
    EnvVar("MCIM_MP_BACKEND", None, "tests/_mp_worker.py",
           "Multi-process coordinator tests: backend the spawned worker "
           "claims."),
    EnvVar("MCIM_MP_MESH", None, "tests/_mp_worker.py",
           "Multi-process coordinator tests: RxC mesh the spawned worker "
           "builds."),
)

REGISTRY: dict[str, EnvVar] = {v.name: v for v in _VARS}


def spec(name: str) -> EnvVar:
    """The declaration for `name`; raises KeyError with the fix-it hint
    for unregistered names (the analyzer enforces this statically too)."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"env var {name!r} is not registered in "
            "mpi_cuda_imagemanipulation_tpu/utils/env.py — declare it "
            "there (name, default, consumer, doc) first"
        ) from None


def get(name: str, env=None) -> str | None:
    """The registered variable's value (or its declared default). `env`
    defaults to os.environ; tests pass a mapping."""
    v = spec(name)
    raw = (os.environ if env is None else env).get(name)
    return v.default if raw is None else raw


def get_bool(name: str, env=None) -> bool:
    """Switch semantics shared by every MCIM_* toggle: unset, empty and
    "0" are off, anything else is on."""
    return get(name, env=env) not in (None, "", "0")


def get_int(name: str, env=None) -> int | None:
    raw = get(name, env=env)
    return None if raw in (None, "") else int(raw)


def get_float(name: str, env=None) -> float | None:
    raw = get(name, env=env)
    return None if raw in (None, "") else float(raw)


def registry_rows() -> tuple[EnvVar, ...]:
    """Every declared variable, sorted by name (docs/tests)."""
    return tuple(sorted(_VARS, key=lambda v: v.name))


def doc_table() -> str:
    """The markdown table docs/design.md embeds — regenerate with
    ``python -c "from mpi_cuda_imagemanipulation_tpu.utils import env;
    print(env.doc_table())"`` after adding a variable."""
    lines = [
        "| Variable | Default | Consumer | Meaning |",
        "|---|---|---|---|",
    ]
    lines.extend(
        f"| `{v.name}` | {'`' + v.default + '`' if v.default else '—'} "
        f"| `{v.consumer}` | {v.doc} |"
        for v in registry_rows()
    )
    return "\n".join(lines)
