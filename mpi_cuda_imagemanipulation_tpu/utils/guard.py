"""Device-hang guard: run a pipeline in a watchdog subprocess.

The framework's failure-detection posture is fail-fast (SURVEY.md §5 — the
reference instead `return 1`s mid-collective and deadlocks its peers,
kernel.cu:150). One failure mode fail-fast cannot catch in-process is a
wedged accelerator backend: on a remote-attached TPU the first device call
can block forever inside the runtime, beyond the reach of Python signal
handlers. `run_guarded` executes the pipeline in a child process with a
wall-clock budget, so the parent always regains control and can report a
clean, actionable error (the same isolation strategy bench.py uses per
config). Exposed on the CLI as `run --device-timeout SECS`.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

import numpy as np


class DeviceTimeoutError(RuntimeError):
    """The device computation exceeded its wall-clock budget."""


_WORKER = """\
import json
import sys
import time

import numpy as np

from mpi_cuda_imagemanipulation_tpu.parallel.mesh import distributed_init

distributed_init()  # mpirun-analogue env (inherited) works guarded too

from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
from mpi_cuda_imagemanipulation_tpu.utils.timing import _sync

inp, outp, spec, impl, block, shards, halo_mode = sys.argv[1:8]
img = np.load(inp)
pipe = Pipeline.parse(spec)
from mpi_cuda_imagemanipulation_tpu.parallel.mesh import mesh_from_shards

_mesh = mesh_from_shards(shards)
if _mesh is not None:
    fn = pipe.sharded(_mesh, backend=impl, halo_mode=halo_mode)
else:
    fn = pipe.jit(backend=impl, block_h=int(block) or None)

# two device-synced windows so guarded mode can report steady-state
# latency like an unguarded run (VERDICT r2 weak #4: the one-shot child
# conflated compile and run, so watchdog mode and benchmarking could not
# combine — on a chronically wedged tunnel that is exactly the
# combination wanted)
t0 = time.perf_counter()
out = fn(img)
_sync(out)
compile_s = time.perf_counter() - t0
t0 = time.perf_counter()
out = fn(img)
_sync(out)
steady_s = time.perf_counter() - t0
np.save(outp, np.asarray(out))
with open(outp + ".timings.json", "w") as f:
    json.dump({"compile_and_run_s": compile_s, "steady_s": steady_s}, f)
"""


def run_guarded(
    spec: str,
    img: np.ndarray,
    timeout_s: float,
    *,
    impl: str = "auto",
    block_h: int | None = None,
    shards: int | str = 1,
    halo_mode: str = "serial",
    timings: dict | None = None,
) -> np.ndarray:
    """Run `spec` over `img` in a subprocess with a wall-clock budget.

    Raises DeviceTimeoutError when the budget is exceeded (wedged backend,
    runaway compile) and RuntimeError on any child failure. The child
    inherits the environment, so platform selection behaves exactly like an
    in-process run. If `timings` is given, it is filled with the child's
    device-synced windows: "compile_and_run_s" (first call) and "steady_s"
    (second, warm call) — so guarded mode reports steady-state latency
    like an unguarded run. The budget covers both calls plus interpreter
    startup.
    """
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be positive, got {timeout_s}")
    with tempfile.TemporaryDirectory(prefix="mcim_guard_") as td:
        inp = os.path.join(td, "in.npy")
        outp = os.path.join(td, "out.npy")
        np.save(inp, np.asarray(img))
        cmd = [
            sys.executable, "-c", _WORKER,
            inp, outp, spec, impl, str(block_h or 0), str(shards),
            halo_mode,
        ]
        try:
            proc = subprocess.run(
                cmd, timeout=timeout_s, capture_output=True, text=True
            )
        except subprocess.TimeoutExpired:
            raise DeviceTimeoutError(
                f"device computation exceeded {timeout_s:.0f}s — the "
                "accelerator backend may be wedged (remote tunnel) or the "
                "compile runaway; retry, raise --device-timeout, or run "
                "with --device cpu"
            ) from None
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout or "").strip()[-800:]
            raise RuntimeError(f"guarded run failed (rc={proc.returncode}): {tail}")
        if timings is not None:
            try:
                with open(outp + ".timings.json") as f:
                    timings.update(json.load(f))
            except (OSError, ValueError):
                pass  # result is still good; timings are best-effort
        return np.load(outp)
