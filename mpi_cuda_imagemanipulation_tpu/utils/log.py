"""Structured logging + JSON metrics output.

Replaces the reference's bare stdout prints (kernel.cu:186-188,231-232) with
a configurable logger and a machine-readable metrics record (SURVEY.md §5
"metrics/logging" entry).

Verbosity comes from the `MCIM_LOG_LEVEL` env var (name or number:
`DEBUG`, `INFO`, `WARNING`, `ERROR`, `CRITICAL`, or `10`..`50`; default
INFO), read at `get_logger()` time so `MCIM_LOG_LEVEL=DEBUG` on any entry
point just works.

`get_logger()` returns a `logging.LoggerAdapter` that prefixes each
message with the calling thread's active trace id (`[<trace_id>]`,
obs/trace.py) when one exists — log lines are joinable with `--trace-out`
spans and `X-Trace-Id` response headers by grep. The adapter resolves the
id per call, so one shared logger serves every thread correctly.
"""

from __future__ import annotations

import json
import logging
import sys

from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

_FORMAT = "%(asctime)s %(levelname)s %(name)s :: %(message)s"

ENV_LEVEL = "MCIM_LOG_LEVEL"


def _level_from_env(default: int = logging.INFO) -> int:
    raw = (env_registry.get(ENV_LEVEL) or "").strip()
    if not raw:
        return default
    if raw.isdigit():
        return int(raw)
    level = logging.getLevelName(raw.upper())
    return level if isinstance(level, int) else default


class _RecorderHandler(logging.Handler):
    """WARNING+ log lines feed the flight recorder's ring (obs/recorder):
    a post-mortem dump then carries the process's recent warnings next to
    its span/dispatch/breaker entries. Failures here must never break
    logging itself."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            from mpi_cuda_imagemanipulation_tpu.obs import recorder

            recorder.note(
                "log",
                level=record.levelname,
                msg=record.getMessage()[:300],
            )
        except Exception:  # a broken ring must never kill logging
            pass


class TraceAdapter(logging.LoggerAdapter):
    """Prefixes messages with the active obs trace id — the log/trace
    join key. No-allocation when untraced (the common case): the id
    lookup is one contextvar read."""

    def process(self, msg, kwargs):
        from mpi_cuda_imagemanipulation_tpu.obs.trace import current_trace_id

        tid = current_trace_id()
        if tid:
            msg = f"[{tid}] {msg}"
        return msg, kwargs


def get_logger(
    name: str = "mcim_tpu", level: int | None = None
) -> logging.LoggerAdapter:
    """The shared logger, trace-aware. `level` overrides MCIM_LOG_LEVEL;
    both override the INFO default. Idempotent handler setup."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        rec_handler = _RecorderHandler(level=logging.WARNING)
        logger.addHandler(rec_handler)
        logger.setLevel(level if level is not None else _level_from_env())
        logger.propagate = False
    elif level is not None:
        logger.setLevel(level)
    return TraceAdapter(logger, {})


def emit_json_metrics(record: dict, path: str | None = None) -> str:
    """Serialise a metrics record to one JSON line; write to `path` or stdout."""
    line = json.dumps(record, sort_keys=True)
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")
    else:
        print(line)
    return line
