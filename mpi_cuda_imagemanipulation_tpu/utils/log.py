"""Structured logging + JSON metrics output.

Replaces the reference's bare stdout prints (kernel.cu:186-188,231-232) with
a configurable logger and a machine-readable metrics record (SURVEY.md §5
"metrics/logging" entry).
"""

from __future__ import annotations

import json
import logging
import sys

_FORMAT = "%(asctime)s %(levelname)s %(name)s :: %(message)s"


def get_logger(name: str = "mcim_tpu", level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.setLevel(level)
        logger.propagate = False
    return logger


def emit_json_metrics(record: dict, path: str | None = None) -> str:
    """Serialise a metrics record to one JSON line; write to `path` or stdout."""
    line = json.dumps(record, sort_keys=True)
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")
    else:
        print(line)
    return line
