from mpi_cuda_imagemanipulation_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
