"""Native runtime: C++ image codec + batch loader (ctypes bindings).

The reference's runtime around the kernels is native C++ (OpenCV I/O, MPI,
CUDA memory management — SURVEY.md §1 L2-L4). The TPU equivalents of L2/L3
are XLA's allocator and collectives; the I/O layer keeps a native component:
`runtime/native/` builds `libmcim_runtime.so` (PPM/PGM codec + threaded batch
prefetcher), bound here via ctypes with a pure-Python fallback when unbuilt.
"""

from mpi_cuda_imagemanipulation_tpu.runtime import codec

__all__ = ["codec"]
