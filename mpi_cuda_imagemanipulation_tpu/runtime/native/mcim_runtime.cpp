// Native runtime for the TPU image framework: binary PPM/PGM codec and a
// multithreaded batch prefetch loader.
//
// The reference's runtime layer is native C++ throughout (OpenCV I/O at
// kern.cpp:33,92 / kernel.cu:110,236; MPI; CUDA memory management). The TPU
// equivalents of device memory + collectives are XLA's job, but the host I/O
// path stays native here: uncompressed PPM/PGM decode is a straight memcpy
// that Python/PIL overhead dominates, and the batch loader overlaps disk
// reads with device compute (double-buffering at the host level, the
// counterpart of the reference's cudaMemcpy staging at kernel.cu:163,202).
//
// Exposed C ABI (bound via ctypes in runtime/codec.py):
//   mcim_read_header(path, &h, &w, &c)            -> 0 on success
//   mcim_read_image(path, buf, buf_size)          -> 0 on success
//   mcim_write_image(path, buf, h, w, c)          -> 0 on success
//   mcim_loader_create(paths, n, n_threads)       -> handle (>=0) or -1
//   mcim_loader_next(handle, buf, cap, &idx,&h,&w,&c) -> 1 item, 0 done, <0 err
//   mcim_loader_destroy(handle)
//   mcim_version()                                -> int

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kVersion = 1;

struct Image {
  int h = 0, w = 0, c = 0;
  std::vector<uint8_t> data;
};

// ---- PPM/PGM (binary P5/P6, maxval <= 255) ----

bool read_pnm_header(FILE* f, int* h, int* w, int* c) {
  char magic[3] = {0};
  if (fscanf(f, "%2s", magic) != 1) return false;
  int channels;
  if (strcmp(magic, "P6") == 0) {
    channels = 3;
  } else if (strcmp(magic, "P5") == 0) {
    channels = 1;
  } else {
    return false;
  }
  // skip whitespace + comments between tokens
  auto next_int = [&](int* out) -> bool {
    int ch;
    while ((ch = fgetc(f)) != EOF) {
      if (ch == '#') {
        while ((ch = fgetc(f)) != EOF && ch != '\n') {
        }
      } else if (!isspace(ch)) {
        ungetc(ch, f);
        break;
      }
    }
    return fscanf(f, "%d", out) == 1;
  };
  int width, height, maxval;
  if (!next_int(&width) || !next_int(&height) || !next_int(&maxval)) return false;
  if (width <= 0 || height <= 0 || maxval <= 0 || maxval > 255) return false;
  int ch = fgetc(f);  // single whitespace before raster
  if (ch == EOF) return false;
  *h = height;
  *w = width;
  *c = channels;
  return true;
}

bool read_pnm(const char* path, Image* img) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  int h, w, c;
  if (!read_pnm_header(f, &h, &w, &c)) {
    fclose(f);
    return false;
  }
  size_t n = static_cast<size_t>(h) * w * c;
  img->h = h;
  img->w = w;
  img->c = c;
  img->data.resize(n);
  bool ok = fread(img->data.data(), 1, n, f) == n;
  fclose(f);
  return ok;
}

bool write_pnm(const char* path, const uint8_t* buf, int h, int w, int c) {
  if (c != 1 && c != 3) return false;
  FILE* f = fopen(path, "wb");
  if (!f) return false;
  fprintf(f, "%s\n%d %d\n255\n", c == 3 ? "P6" : "P5", w, h);
  size_t n = static_cast<size_t>(h) * w * c;
  bool ok = fwrite(buf, 1, n, f) == n;
  fclose(f);
  return ok;
}

// ---- batch prefetch loader ----

struct Loader {
  std::vector<std::string> paths;
  std::vector<std::thread> workers;
  std::atomic<size_t> next_job{0};
  std::map<size_t, Image> ready;  // decoded, awaiting delivery in order
  size_t next_deliver = 0;
  size_t max_ahead = 16;  // bound memory: decode at most this far ahead
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits for next_deliver
  std::condition_variable cv_window;  // workers wait for the window to move
  std::atomic<bool> stop{false};

  void worker() {
    for (;;) {
      if (stop.load()) return;
      size_t idx = next_job.fetch_add(1);
      if (idx >= paths.size()) return;
      {
        // stay within the prefetch window
        std::unique_lock<std::mutex> lock(mu);
        cv_window.wait(lock, [&] {
          return stop.load() || idx < next_deliver + max_ahead;
        });
        if (stop.load()) return;
      }
      Image img;
      bool ok = read_pnm(paths[idx].c_str(), &img);
      {
        std::lock_guard<std::mutex> lock(mu);
        if (!ok) img = Image{};  // deliver an empty record; python raises
        ready.emplace(idx, std::move(img));
      }
      cv_ready.notify_all();
    }
  }
};

std::mutex g_loaders_mu;
std::map<int64_t, std::unique_ptr<Loader>> g_loaders;
int64_t g_next_handle = 1;

}  // namespace

extern "C" {

int mcim_version() { return kVersion; }

int mcim_read_header(const char* path, int* h, int* w, int* c) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  bool ok = read_pnm_header(f, h, w, c);
  fclose(f);
  return ok ? 0 : -2;
}

int mcim_read_image(const char* path, uint8_t* buf, size_t buf_size) {
  Image img;
  if (!read_pnm(path, &img)) return -1;
  if (img.data.size() != buf_size) return -2;
  memcpy(buf, img.data.data(), buf_size);
  return 0;
}

int mcim_write_image(const char* path, const uint8_t* buf, int h, int w, int c) {
  return write_pnm(path, buf, h, w, c) ? 0 : -1;
}

int64_t mcim_loader_create(const char** paths, int n, int n_threads) {
  if (n < 0 || n_threads <= 0) return -1;
  auto loader = std::make_unique<Loader>();
  loader->paths.assign(paths, paths + n);
  int threads = std::min<int>(n_threads, std::max(1, n));
  for (int i = 0; i < threads; i++) {
    loader->workers.emplace_back(&Loader::worker, loader.get());
  }
  std::lock_guard<std::mutex> lock(g_loaders_mu);
  int64_t handle = g_next_handle++;
  g_loaders.emplace(handle, std::move(loader));
  return handle;
}

// Delivers images strictly in input order. Returns 1 with the image copied
// into buf (or, if cap is too small, returns -3 and only fills h/w/c so the
// caller can retry with a bigger buffer), 0 when the batch is exhausted,
// negative on error. A decode failure delivers h=w=c=0 for that index.
int mcim_loader_next(int64_t handle, uint8_t* buf, size_t cap, int* idx,
                     int* h, int* w, int* c) {
  Loader* loader;
  {
    std::lock_guard<std::mutex> lock(g_loaders_mu);
    auto it = g_loaders.find(handle);
    if (it == g_loaders.end()) return -1;
    loader = it->second.get();
  }
  std::unique_lock<std::mutex> lock(loader->mu);
  if (loader->next_deliver >= loader->paths.size()) return 0;
  size_t want = loader->next_deliver;
  loader->cv_ready.wait(lock, [&] { return loader->ready.count(want) > 0; });
  Image& img = loader->ready[want];
  *idx = static_cast<int>(want);
  *h = img.h;
  *w = img.w;
  *c = img.c;
  size_t n = img.data.size();
  if (n > cap) return -3;  // caller re-reads header and retries
  if (n > 0) memcpy(buf, img.data.data(), n);
  loader->ready.erase(want);
  loader->next_deliver++;
  loader->cv_window.notify_all();
  return 1;
}

void mcim_loader_destroy(int64_t handle) {
  std::unique_ptr<Loader> loader;
  {
    std::lock_guard<std::mutex> lock(g_loaders_mu);
    auto it = g_loaders.find(handle);
    if (it == g_loaders.end()) return;
    loader = std::move(it->second);
    g_loaders.erase(it);
  }
  loader->stop.store(true);
  loader->cv_window.notify_all();
  for (auto& t : loader->workers) t.join();
}

}  // extern "C"
