"""ctypes binding for the native C++ codec (libmcim_runtime.so).

Build with `python -m mpi_cuda_imagemanipulation_tpu.runtime.build` (uses the
Makefile in runtime/native/). Falls back gracefully: `available()` returns
False when the shared library hasn't been built, and callers use PIL.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB_NAME = "libmcim_runtime.so"
_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_lib: ctypes.CDLL | None = None
_load_failed = False


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    path = os.path.join(_NATIVE_DIR, _LIB_NAME)
    if not os.path.exists(path):
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.mcim_read_header.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int),  # height
            ctypes.POINTER(ctypes.c_int),  # width
            ctypes.POINTER(ctypes.c_int),  # channels
        ]
        lib.mcim_read_header.restype = ctypes.c_int
        lib.mcim_read_image.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.mcim_read_image.restype = ctypes.c_int
        lib.mcim_write_image.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.mcim_write_image.restype = ctypes.c_int
        lib.mcim_loader_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.mcim_loader_create.restype = ctypes.c_int64
        lib.mcim_loader_next.argtypes = [
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.mcim_loader_next.restype = ctypes.c_int
        lib.mcim_loader_destroy.argtypes = [ctypes.c_int64]
        lib.mcim_loader_destroy.restype = None
        lib.mcim_version.argtypes = []
        lib.mcim_version.restype = ctypes.c_int
        _lib = lib
    except OSError:
        _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def read_image(path: str) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native codec not built")
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    rc = lib.mcim_read_header(path.encode(), ctypes.byref(h), ctypes.byref(w), ctypes.byref(c))
    if rc != 0:
        raise IOError(f"native codec failed to read header of {path} (rc={rc})")
    shape = (h.value, w.value, c.value) if c.value > 1 else (h.value, w.value)
    out = np.empty(shape, dtype=np.uint8)
    rc = lib.mcim_read_image(
        path.encode(),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.size,
    )
    if rc != 0:
        raise IOError(f"native codec failed to read {path} (rc={rc})")
    return out


class BatchLoader:
    """Ordered, multithreaded prefetching reader over a list of PPM/PGM files.

    Worker threads decode up to 16 images ahead while the consumer (the
    device pipeline) runs — host-side I/O overlapped with TPU compute, the
    counterpart of the reference's host-device staging (kernel.cu:163,202).
    Iterate to get (index, (H, W[, C]) uint8 array) in input order.
    """

    def __init__(self, paths: list[str], n_threads: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native codec not built")
        self._lib = lib
        self._n = len(paths)
        self._paths = [str(p) for p in paths]
        arr = (ctypes.c_char_p * self._n)(*[p.encode() for p in self._paths])
        self._handle = lib.mcim_loader_create(arr, self._n, int(n_threads))
        if self._handle < 0:
            raise RuntimeError("mcim_loader_create failed")
        self._buf = np.empty(1 << 20, dtype=np.uint8)

    def __iter__(self):
        return self

    def __next__(self):
        idx = ctypes.c_int()
        h = ctypes.c_int()
        w = ctypes.c_int()
        c = ctypes.c_int()
        while True:
            rc = self._lib.mcim_loader_next(
                self._handle,
                self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                self._buf.size,
                ctypes.byref(idx),
                ctypes.byref(h),
                ctypes.byref(w),
                ctypes.byref(c),
            )
            if rc == 0:
                raise StopIteration
            if rc == -3:  # buffer too small: grow and retry
                self._buf = np.empty(
                    max(h.value * w.value * max(c.value, 1), 2 * self._buf.size),
                    dtype=np.uint8,
                )
                continue
            if rc < 0:
                raise IOError(f"loader_next failed (rc={rc})")
            break
        if h.value == 0:
            raise IOError(f"failed to decode {self._paths[idx.value]}")
        n = h.value * w.value * c.value
        shape = (h.value, w.value, c.value) if c.value > 1 else (h.value, w.value)
        return idx.value, self._buf[:n].reshape(shape).copy()

    def close(self) -> None:
        if getattr(self, "_handle", None) is not None:
            self._lib.mcim_loader_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def write_image(path: str, img: np.ndarray) -> None:
    lib = _load()
    if lib is None:
        raise RuntimeError("native codec not built")
    img = np.ascontiguousarray(img, dtype=np.uint8)
    h, w = img.shape[:2]
    c = img.shape[2] if img.ndim == 3 else 1
    rc = lib.mcim_write_image(
        path.encode(),
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        h,
        w,
        c,
    )
    if rc != 0:
        raise IOError(f"native codec failed to write {path} (rc={rc})")
