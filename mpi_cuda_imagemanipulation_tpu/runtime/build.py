"""Build the native runtime: `python -m mpi_cuda_imagemanipulation_tpu.runtime.build`.

Runs make in runtime/native/ (g++, no external deps). Idempotent; the
framework works without it (PIL fallback), just slower on the batch path.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")


def build(verbose: bool = True) -> bool:
    """Build libmcim_runtime.so; returns True on success."""
    if shutil.which("make") is None or shutil.which("g++") is None:
        if verbose:
            print("native build skipped: make/g++ not available", file=sys.stderr)
        return False
    proc = subprocess.run(
        ["make", "-C", NATIVE_DIR],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        if verbose:
            print(proc.stdout, file=sys.stderr)
            print(proc.stderr, file=sys.stderr)
        return False
    if verbose:
        print(f"built {os.path.join(NATIVE_DIR, 'libmcim_runtime.so')}")
    return True


if __name__ == "__main__":
    sys.exit(0 if build() else 1)
