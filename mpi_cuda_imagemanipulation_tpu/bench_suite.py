"""Benchmark suite — the five BASELINE.json configs, measured properly.

The reference's only "profiling subsystem" is two inconsistent wall-clock
spans printed to stdout (SURVEY.md §2.5/§6: kern.cpp:60,86-87 times compute
only; kernel.cu:190,226-227 times compute *plus* MPI_Gather). Here each
config reports device-side seconds/iteration via utils.timing.device_throughput
(compile excluded, N-scaling slope — robust to the tunnel RTT of remote
TPU attach) and a first-class megapixels/sec metric.

The headline metric (BASELINE.json): megapixels/sec/chip on 8K 5x5 Gaussian.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
from mpi_cuda_imagemanipulation_tpu.ops.spec import StencilOp
from mpi_cuda_imagemanipulation_tpu.parallel.halo import exchange_halo_strips
from mpi_cuda_imagemanipulation_tpu.parallel.mesh import (
    ROWS,
    make_mesh,
    shard_map_compat,
)
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics, get_logger
from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend
from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

# Estimated reference performance on its own headline config (BASELINE.md
# records the derivation: reference publishes no numbers, so this is a
# first-principles estimate of the CUDA+MPI pipeline on 4xV100 at 8K 5x5,
# timed the way kernel.cu times itself, i.e. including MPI_Gather).
REFERENCE_BASELINE_MP_S_PER_CHIP = 1850.0

HEADLINE = "gaussian5_8k"

# Peak HBM bandwidth per chip, GB/s — the roofline denominator for the
# streaming kernels (whose modeled traffic is one u8 read + one u8 write of
# the image per fused group; ops/pallas_kernels.py module comment).
HBM_GB_S = {"v4": 1228.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0}

# Measured u8 compute-kernel-class element rate, giga-elements/s — a
# same-chip reference denominator alongside the datasheet byte roofline
# above. History: the round-3 probe read it as a hardware element-rate
# ceiling; the round-5 round-robin probe FALSIFIED that (u8 copy kernels
# sustain ~550 GB/s — artifacts/roofline_rr_r05.out), so this figure is
# the best observed rate of the u8 compute-kernel class (the kernels are
# VPU-compute-bound, not load/store-capped; BASELINE.md round-5 section).
# Kept as the kernel-class reference point for elem_ceiling_frac. Only
# v5e has been measured; other gens get no elem_ceiling_frac until a
# probe runs there (single-generation calibration caveat,
# docs/measurement.md).
ELEM_G_S_MEASURED = {"v5e": 100.7}


@dataclasses.dataclass(frozen=True)
class BenchConfig:
    name: str
    pipeline: str
    height: int
    width: int
    channels: int
    sharded: bool = False  # row-shard over every visible device
    batch: int = 0  # >0: vmap-stack this many images per dispatch
    halo_mode: str = "serial"  # sharded halo execution (parallel.api.HALO_MODES)


# BASELINE.json "configs", in order, plus beyond-parity extras.
CONFIGS: dict[str, BenchConfig] = {
    c.name: c
    for c in [
        BenchConfig("grayscale_1080p", "grayscale", 1080, 1920, 3),
        BenchConfig("gaussian3_4k", "gaussian:3", 2160, 3840, 1),
        BenchConfig("sobel_4k", "sobel", 2160, 3840, 1),
        BenchConfig("gaussian5_8k", "gaussian:5", 4320, 7680, 1),
        BenchConfig("gaussian7_8k", "gaussian:7", 4320, 7680, 1),
        BenchConfig("reference_pipeline_4k", "grayscale,contrast:3.5,emboss:3", 2160, 3840, 3),
        BenchConfig("gaussian5_8k_sharded", "gaussian:5", 4320, 7680, 1, sharded=True),
        # overlap lane: same workload with the interior-first overlapped
        # halo execution (hide ICI ppermute latency behind interior
        # compute) — the serial-vs-overlap comparison also rides every
        # sharded record as `halo_ab` when enabled (see _halo_ab)
        BenchConfig(
            "gaussian5_8k_sharded_overlap", "gaussian:5", 4320, 7680, 1,
            sharded=True, halo_mode="overlap",
        ),
        BenchConfig(
            "reference_1080p_batch8",
            "grayscale,contrast:3.5,emboss:3",
            1080, 1920, 3,
            batch=8,  # dispatch amortisation via Pipeline.batched
        ),
        BenchConfig("median3_4k", "median:3", 2160, 3840, 1),
        BenchConfig("erode5_4k", "erode:5", 2160, 3840, 1),
        # batched headline: probes whether the ~92 GB/s effective cap is
        # per-dispatch (vmap amortises grid setup / exposes more DMA
        # parallelism) — see BASELINE.md round-2 analysis
        BenchConfig("gaussian5_8k_batch2", "gaussian:5", 4320, 7680, 1, batch=2),
    ]
}


def modeled_hbm_bytes(cfg: BenchConfig) -> int:
    """Minimum HBM traffic model for the config's Pallas execution: each
    fused [pointwise*, stencil?] group reads its input planes and writes its
    output planes from/to HBM exactly once, as u8 (the streaming-kernel
    contract, ops/pallas_kernels.py module comment). The same model is
    reported for XLA runs for comparability — XLA's fusion achieves the
    same per-group traffic for these pipelines."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _channels_after,
        group_ops,
    )

    pipe = Pipeline.parse(cfg.pipeline)
    n_ch = cfg.channels
    total = 0
    for pointwise, stencil in group_ops(pipe.ops):
        n_out = _channels_after(pointwise, n_ch)
        total += (n_ch + n_out) * cfg.height * cfg.width
        n_ch = n_out
    return total * max(1, cfg.batch)


def _tpu_gen() -> str:
    return os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")


def _halo_ab_enabled() -> bool:
    """Whether sharded configs run the serial-vs-overlap halo A/B and the
    per-group comms breakdown. MCIM_HALO_AB=1 forces it on, =0 off;
    default: only on real TPU hardware (the extra compiles are worth chip
    minutes, not CPU test minutes)."""
    v = env_registry.get("MCIM_HALO_AB") or ""
    if v == "1":
        return True
    if v == "0":
        return False
    return is_tpu_backend()


def _comms_only_fn(mesh, halo: int, ndim: int):
    """A jitted program that performs ONLY one stencil group's ghost-strip
    exchange (two ring ppermutes of (halo, W[, C]) strips) — the comms
    denominator for the per-group breakdown."""
    n = mesh.shape[ROWS]

    def tile_fn(tile):
        top, bottom = exchange_halo_strips(tile, halo, n)
        return top + bottom  # consume both so neither transfer is dropped

    spec = P(ROWS, *([None] * (ndim - 1)))
    return jax.jit(
        shard_map_compat(
            tile_fn, mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False,
        )
    )


def _halo_ab(cfg: BenchConfig, pipe: Pipeline, mesh, img, impl: str) -> dict | None:
    """Serial-vs-overlap A/B plus per-group comms/compute breakdown for a
    sharded config.

    Per stencil group: `comms_ms` times the group's ghost exchange alone;
    `serial_ms` times the group's sharded serial execution standalone
    (pointwise prologue included), so `compute_ms_est = serial_ms -
    comms_ms`. Pipeline-level: `comms_hidden_frac` = the fraction of total
    exchange time the overlap restructuring removed from the critical
    path, clipped to [0, 1] — the tools/tpu_queue A/B's headline alongside
    MP/s."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _channels_after,
        group_ops,
    )

    stencils = [
        op for op in pipe.ops if isinstance(op, StencilOp) and op.halo >= 1
    ]
    if not stencils:
        return None
    ab: dict = {}
    for mode in ("serial", "overlap"):
        fn = pipe.sharded(mesh, backend=impl, halo_mode=mode)
        ab[f"{mode}_ms"] = device_throughput(fn, [img]) * 1e3
    per_group = []
    comms_total = 0.0
    n_ch = cfg.channels
    for gidx, (pointwise, stencil) in enumerate(group_ops(pipe.ops)):
        in_ch = n_ch
        n_ch = _channels_after(pointwise, n_ch)
        if stencil is None or stencil.halo < 1:
            continue
        gimg = jnp.asarray(
            synthetic_image(cfg.height, cfg.width, channels=in_ch, seed=17)
        )
        comms_ms = (
            device_throughput(_comms_only_fn(mesh, stencil.halo, gimg.ndim), [gimg])
            * 1e3
        )
        comms_total += comms_ms
        entry = {
            "group": gidx,
            "ops": [op.name for op in pointwise] + [stencil.name],
            "halo": stencil.halo,
            "comms_ms": comms_ms,
        }
        if len(stencils) <= 3:  # bound the extra compiles per config
            gpipe = Pipeline(ops=tuple(pointwise) + (stencil,))
            gserial = (
                device_throughput(
                    gpipe.sharded(mesh, backend=impl, halo_mode="serial"),
                    [gimg],
                )
                * 1e3
            )
            entry["serial_ms"] = gserial
            entry["compute_ms_est"] = gserial - comms_ms
        per_group.append(entry)
    ab["per_group"] = per_group
    ab["comms_ms_total"] = comms_total
    ab["compute_ms_est"] = ab["serial_ms"] - comms_total
    if comms_total > 0:
        ab["comms_hidden_frac"] = max(
            0.0,
            min(1.0, (ab["serial_ms"] - ab["overlap_ms"]) / comms_total),
        )
    return ab


def run_config(cfg: BenchConfig, impl: str, *, n_shards: int | None = None) -> dict:
    if cfg.batch:
        import numpy as np

        img = jnp.asarray(
            np.stack(
                [
                    synthetic_image(
                        cfg.height, cfg.width, channels=cfg.channels, seed=99 + k
                    )
                    for k in range(cfg.batch)
                ]
            )
        )
    else:
        img = jnp.asarray(
            synthetic_image(cfg.height, cfg.width, channels=cfg.channels, seed=99)
        )
    pipe = Pipeline.parse(cfg.pipeline)
    n_chips = 1
    mesh = None
    if cfg.sharded:
        n_chips = n_shards or len(jax.devices())
        mesh = make_mesh(n_chips)
        fn = pipe.sharded(mesh, backend=impl, halo_mode=cfg.halo_mode)
    elif cfg.batch:
        fn = pipe.batched(backend=impl)
    else:
        fn = pipe.jit(backend=impl)
    sec = device_throughput(fn, [img])
    mp = cfg.height * cfg.width * max(1, cfg.batch) / 1e6
    platform = jax.default_backend()
    on_tpu = is_tpu_backend()
    hbm_bytes = modeled_hbm_bytes(cfg)
    gb_s = hbm_bytes / sec / n_chips / 1e9
    rec = {
        "config": cfg.name,
        "pipeline": cfg.pipeline,
        "impl": impl,
        "height": cfg.height,
        "width": cfg.width,
        "chips": n_chips,
        "platform": platform,
        "ms_per_iter": sec * 1e3,
        "mp_per_s": mp / sec,
        "mp_per_s_per_chip": mp / sec / n_chips,
        "hbm_bytes_model": hbm_bytes,
        "hbm_gb_s_model": gb_s,
    }
    # MEASURED traffic columns (obs/cost, the roofline_probe question
    # folded into the production path): what XLA's own cost model says
    # the compiled executable moves, next to the analytical u8 model —
    # the committed record carries both so the model stays checked, and
    # tools/bench_regress.py tracks the measured series too
    from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost

    if obs_cost.enabled():
        cost = obs_cost.extract(fn, [img])
        if cost is not None:
            rec["hbm_bytes_hlo"] = cost.hlo_bytes
            rec["hbm_gb_s_measured"] = obs_cost.measured_gb_s(
                cost.hlo_bytes, sec, n_chips
            )
            rec["hlo_flops"] = cost.flops
            rec["hlo_temp_bytes"] = cost.temp_bytes
    if cfg.sharded:
        rec["halo_mode"] = cfg.halo_mode
        if _halo_ab_enabled():
            ab = _halo_ab(cfg, pipe, mesh, img, impl)
            if ab:
                rec["halo_ab"] = ab
    if on_tpu:
        gen = _tpu_gen()
        rec["tpu_gen"] = gen
        rec["roofline_frac"] = gb_s / HBM_GB_S.get(gen, HBM_GB_S["v5e"])
        if "hbm_gb_s_measured" in rec:
            # the measured roofline fraction: compiled-executable bytes
            # over the datasheet bound — the number the analytical
            # roofline_frac claims to approximate
            rec["roofline_frac_measured"] = rec[
                "hbm_gb_s_measured"
            ] / HBM_GB_S.get(gen, HBM_GB_S["v5e"])
        # the traffic model counts u8 planes, so modeled bytes == modeled
        # elements and gb_s doubles as giga-elements/s against the measured
        # kernel-class element rate — but only for impls that stream u8
        # elements; the swar impl (and auto under MCIM_PREFER_SWAR) moves
        # the same bytes as u32 words (1/2 the elements), so the
        # equivalence breaks there and the field is omitted rather than
        # overstated. (The round-5 roofline RR probe measured u8 copy at
        # ~550 GB/s, so the "element ceiling" is a property of the compute
        # kernels, not the HBM path — the field is kept as the measured
        # same-kernel-class reference point.)
        from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
            prefer_swar,
        )

        # the mxu impl is excluded for the same class reason: it moves u8
        # bytes but contracts on the matrix unit, so the VPU-kernel-class
        # element-rate reference does not describe it
        streams_u8 = impl not in ("swar", "mxu") and not (
            impl == "auto" and prefer_swar()
        )
        if gen in ELEM_G_S_MEASURED and streams_u8:
            rec["elem_ceiling_frac"] = gb_s / ELEM_G_S_MEASURED[gen]
    return rec


SERVE_LOADGEN = "serve_loadgen"
ENGINE_AB = "engine_ab"
MXU_AB = "mxu_ab"
FABRIC_LOADGEN = "fabric_loadgen"
STREAM_AB = "stream_ab"
PLAN_AB = "plan_ab"
MEGAKERNEL_AB = "megakernel_ab"
MXU_FUSED_AB = "mxu_fused_ab"
GRAPH_LOADGEN = "graph_loadgen"
SYSTOLIC_AB = "systolic_ab"
FEDERATION_LOADGEN = "federation_loadgen"
TUNE_CONVERGENCE = "tune_convergence"


def fabric_loadgen_params() -> dict:
    """The pod-fabric lane knobs, sized to the backend. The offered rate
    deliberately EXCEEDS one replica's service capacity so the achieved
    column measures sustained pod throughput (capacity), not the arrival
    clock — that is what makes replicas=1 vs replicas=N a scaling claim.
    Env overrides: MCIM_FABRIC_RPS / MCIM_FABRIC_DURATION_S /
    MCIM_FABRIC_REPLICAS."""
    on_tpu = is_tpu_backend()
    params = {
        # several bucket keys spread sticky affinity over the replica set
        "ops": "grayscale,gaussian:5,contrast:3.5",
        "buckets": "512,768,1024,1536,2048" if on_tpu
        else "48,64,80,96,112,128",
        "max_batch": 8 if on_tpu else 4,
        "max_delay_ms": 4.0,
        "queue_depth": 256,
        "channels": "3",
        # saturation rate: must exceed ONE replica's service capacity so
        # `achieved` reads capacity (the scaling numerator/denominator)
        "offered_rps": 2048.0 if on_tpu else 600.0,
        # churn rate: moderate (below pod capacity) so the during-kill
        # phase measures rerouting, not saturation shedding
        "churn_rps": 512.0 if on_tpu else 120.0,
        "phase_s": 4.0 if on_tpu else 2.0,
        "replicas": 3,
        "n_images": 24,
        "heartbeat_s": 0.25,
        "max_workers": 256,
        # CPU only: per-dispatch synthetic DEVICE time via the sleep:MS
        # failpoint mode (resilience/failpoints.py). On a pod each
        # replica's dispatch waits on ITS OWN chip — that wait is what
        # parallelizes across replicas. A shared-core CI host has no
        # per-replica device, so without this floor every replica
        # contends for one CPU and replicas=N can never beat replicas=1
        # regardless of the fabric's correctness (the engine_ab lane's
        # synthetic decode/encode delays make the same modeling move).
        # On TPU the floor is OFF and the lane measures real chips.
        "device_floor_ms": None if on_tpu else 40.0,
    }
    raw = env_registry.get("MCIM_FABRIC_RPS")
    if raw:
        params["offered_rps"] = float(raw)
        params["churn_rps"] = float(raw) / 4.0
    raw = env_registry.get("MCIM_FABRIC_DURATION_S")
    if raw:
        params["phase_s"] = float(raw)
    raw = env_registry.get("MCIM_FABRIC_REPLICAS")
    if raw:
        params["replicas"] = int(raw)
    return params


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _FabricProc:
    """A whole pod (router + supervisor + replicas) as ONE subprocess via
    the `fabric` CLI. The loadgen client then owns this process's GIL
    alone — an in-process router would serialize against the 96 client
    threads and cap both lanes at the same number, which is exactly the
    measurement error a replicas=1 vs replicas=N claim cannot carry."""

    def __init__(
        self, p: dict, replicas: int, *, extra_args=(), extra_env=None
    ):
        import subprocess
        import sys

        self.port = _free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        self.replicas = replicas
        env = dict(os.environ)
        if p.get("device_floor_ms"):
            # replicas inherit this env from the fabric process: every
            # dispatch pays the synthetic device floor (sleep:MS mode)
            env["MCIM_FAILPOINTS"] = (
                f"serve.dispatch=sleep:{p['device_floor_ms']:g}"
            )
        # spill the sticky target early: under deliberate saturation the
        # lane wants queue pressure converted into cross-replica spread
        # (capacity additivity), not into one deep affinity queue
        env["MCIM_FABRIC_SHED_FRAC"] = "0.25"
        env.update(extra_env or {})
        self.proc = subprocess.Popen(
            env=env,
            args=[
                sys.executable, "-m", "mpi_cuda_imagemanipulation_tpu",
                "fabric",
                "--replicas", str(replicas),
                "--ops", p["ops"],
                "--buckets", p["buckets"],
                "--channels", p["channels"],
                "--max-batch", str(p["max_batch"]),
                "--max-delay-ms", str(p["max_delay_ms"]),
                "--queue-depth", str(p["queue_depth"]),
                "--host", "127.0.0.1",
                "--port", str(self.port),
                "--heartbeat-s", str(p["heartbeat_s"]),
                "--stale-s", str(4 * p["heartbeat_s"]),
                *extra_args,
            ],
        )

    def stats(self) -> dict:
        import json
        import urllib.request

        with urllib.request.urlopen(self.url + "/stats", timeout=10) as r:
            return json.loads(r.read())

    def routable(self) -> list[str]:
        try:
            st = self.stats()
        except Exception:
            return []
        return [
            rid
            for rid, rep in st["replicas"].items()
            if rep["fresh"] and rep["state"] in ("serving", "degraded")
        ]

    def wait_routable(self, n: int, timeout_s: float = 240.0) -> None:
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"fabric process exited rc={self.proc.returncode}"
                )
            if len(self.routable()) >= n:
                return
            _time.sleep(0.2)
        raise TimeoutError(
            f"{n} replicas not routable within {timeout_s:.0f}s "
            f"(routable: {self.routable()})"
        )

    def kill_replica(self, replica_id: str) -> int:
        """SIGKILL one replica by the pid its heartbeat reported; the
        fabric process's supervisor restarts it with backoff."""
        import signal as _signal

        pid = self.stats()["replicas"][replica_id]["pid"]
        os.kill(pid, _signal.SIGKILL)
        return pid

    def preempt_replica(self, replica_id: str) -> int:
        """SIGUSR1 = preemption notice: graceful drain + `preempt` dump
        + immediate no-backoff replacement by the supervisor."""
        import signal as _signal

        pid = self.stats()["replicas"][replica_id]["pid"]
        os.kill(pid, _signal.SIGUSR1)
        return pid

    def fresh_ids(self) -> list[str]:
        try:
            st = self.stats()
        except Exception:
            return []
        return [
            rid for rid, rep in st["replicas"].items() if rep["fresh"]
        ]

    def autoscaler_events(self) -> list[dict]:
        try:
            auto = self.stats().get("autoscaler")
        except Exception:
            return []
        return list(auto["events"]) if auto else []

    def close(self) -> None:
        import signal as _signal

        if self.proc.poll() is None:
            self.proc.send_signal(_signal.SIGTERM)
            try:
                self.proc.wait(timeout=60.0)
            except Exception:
                self.proc.kill()
                self.proc.wait(timeout=10.0)

    def __enter__(self) -> "_FabricProc":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def _phase_public(rec: dict) -> dict:
    """A phase record minus the raw per-request results (response bytes
    do not belong in a committed bench JSON)."""
    return {k: v for k, v in rec.items() if k != "results"}


def run_fabric_loadgen(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
    replicas: int | None = None,
) -> dict:
    """The pod-fabric bench lane: the SAME open-loop HTTP request mix
    against (a) one replica, (b) N replicas, (c) N replicas with a
    SIGKILL mid-sweep (serve/loadgen.churn_run), and (d) an AUTOSCALED
    pod that must grow 1->N under the saturating rate, absorb a SIGUSR1
    preemption mid-load, and drain back down once idle — throughput,
    p99, ok%/shed% columns per lane. The scaling headline is
    replicas=N achieved / replicas=1 achieved at equal mix; the churn
    headline is the during-phase ok%/retried% (rerouting, not luck);
    the elastic headline is scale-up/scale-down latency with the
    drain-before-kill reason asserted from the autoscaler's own event
    record. Successes are gated bit-exact against the golden
    per-request path before any timing (the proto discipline)."""
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.serve import loadgen
    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets
    from mpi_cuda_imagemanipulation_tpu.serve.padded import min_true_dim

    p = fabric_loadgen_params()
    if replicas is not None:
        p["replicas"] = replicas
    pipe = Pipeline.parse(p["ops"])
    images = loadgen.mixed_shapes(
        parse_buckets(p["buckets"]),
        p["n_images"],
        channels=3,
        seed=7,
        min_dim=min_true_dim(pipe),
    )
    # single-copy blobs: the encoder's own buffer posts as a memoryview
    blobs = [loadgen.encode_blob(im) for im in images]
    golden_fn = pipe.jit()
    golden = [np.asarray(golden_fn(im)) for im in images]

    def check_bit_exact(results) -> int:
        from mpi_cuda_imagemanipulation_tpu.io.image import (
            decode_image_bytes,
        )

        n = 0
        for k, r in results:
            if r["code"] != 200:
                continue
            got = decode_image_bytes(r["body"])
            if not np.array_equal(got, golden[k]):
                raise AssertionError(
                    f"fabric_loadgen: response for image {k} mismatches "
                    "the golden per-request output"
                )
            n += 1
        return n

    lanes: dict[str, dict] = {}
    n_rep = p["replicas"]
    # -- replicas=1 baseline ------------------------------------------------
    with _FabricProc(p, 1) as fab:
        fab.wait_routable(1)
        # bit-exact gate BEFORE any timing: one pass over the unique mix
        gate = loadgen.http_run_offered_load(
            fab.url, blobs, min(64.0, p["offered_rps"]),
            len(blobs) / min(64.0, p["offered_rps"]),
        )
        gate_checked = check_bit_exact(gate["results"])
        rec1 = loadgen.http_run_offered_load(
            fab.url, blobs, p["offered_rps"], p["phase_s"],
            max_workers=p["max_workers"],
        )
        check_bit_exact(rec1["results"])
        lanes["replicas_1"] = _phase_public(rec1)
    # -- replicas=N, same mix ----------------------------------------------
    with _FabricProc(p, n_rep) as fab:
        fab.wait_routable(n_rep)
        recn = loadgen.http_run_offered_load(
            fab.url, blobs, p["offered_rps"], p["phase_s"],
            max_workers=p["max_workers"],
        )
        check_bit_exact(recn["results"])
        lanes[f"replicas_{n_rep}"] = _phase_public(recn)
        # the router's federated view of the lane just measured: the
        # fleet p99 (bucket-merged across replicas) with its exemplar
        # trace id — the lane's outlier is pull-up-able by id
        try:
            import json as _json
            import urllib.request as _rq

            with _rq.urlopen(fab.url + "/slo", timeout=10.0) as resp:
                slo_view = _json.loads(resp.read())
            rec_slo = {
                "p99": slo_view.get("p99"),
                "slos": {
                    name: {
                        k: s[k]
                        for k in ("alert", "burn_fast", "burn_slow")
                    }
                    for name, s in slo_view.get("slos", {}).items()
                },
            }
        except Exception:
            rec_slo = None
        # -- churn: SIGKILL one replica mid-sweep, same fabric -------------
        # the victim is the replica serving the MOST traffic (sticky
        # affinity concentrates buckets): killing an idle sibling would
        # prove nothing about rerouting
        from collections import Counter

        by_replica = Counter(
            r["replica"] for _, r in recn["results"] if r["replica"]
        )
        victim = (
            by_replica.most_common(1)[0][0] if by_replica else "r0"
        )
        killed_pid: list[int] = []
        phases = loadgen.churn_run(
            fab.url,
            blobs,
            offered_rps=p["churn_rps"],
            phase_s=p["phase_s"],
            kill=lambda: killed_pid.append(fab.kill_replica(victim)),
            before_after=lambda: fab.wait_routable(n_rep),
        )
        for ph in phases.values():
            check_bit_exact(ph["results"])
        new_pid = fab.stats()["replicas"][victim]["pid"]
        lanes[f"replicas_{n_rep}_churn"] = {
            name: _phase_public(ph) for name, ph in phases.items()
        }
        lanes[f"replicas_{n_rep}_churn"].update(
            victim=victim,
            churn_rps=p["churn_rps"],
            killed_pid=killed_pid[0] if killed_pid else None,
            respawned=bool(killed_pid) and new_pid != killed_pid[0],
        )
    # -- elastic: autoscale 1->N under saturation, preempt, drain back ------
    # the same offered mix against an AUTOSCALED pod: starts at one
    # replica, must grow to n_rep under the saturating rate, absorb a
    # SIGUSR1 preemption mid-load (graceful drain + immediate no-backoff
    # replacement), and, once the load stops, shrink back by DRAINING
    # (the recorded scale-down reason must be "drained"). Shed (503 +
    # Retry-After) is the expected elastic response while capacity
    # catches up — counted in its own column, never as unavailability.
    import threading as _threading
    import time as _time

    scale_env = {
        "MCIM_FABRIC_SCALE_TICK_S": "0.25",
        "MCIM_FABRIC_SCALE_SUSTAIN_S": "1.0",
        "MCIM_FABRIC_SCALE_COOLDOWN_S": "3.0",
        "MCIM_FABRIC_SCALE_UP_FRAC": "0.5",
        "MCIM_FABRIC_SCALE_DOWN_FRAC": "0.15",
    }
    with _FabricProc(
        p, 1,
        extra_args=[
            "--autoscale", "--min-replicas", "1",
            "--max-replicas", str(n_rep),
        ],
        extra_env=scale_env,
    ) as fab:
        fab.wait_routable(1)
        stop_load = _threading.Event()
        elastic_recs: list[dict] = []

        def _elastic_load():
            while not stop_load.is_set():
                elastic_recs.append(
                    loadgen.http_run_offered_load(
                        fab.url, blobs, p["offered_rps"], 1.0,
                        max_workers=p["max_workers"],
                    )
                )

        loader = _threading.Thread(target=_elastic_load, daemon=True)
        t0 = _time.monotonic()
        loader.start()
        scale_up_s = None
        deadline = _time.monotonic() + 180.0
        while _time.monotonic() < deadline:
            if len(fab.routable()) >= n_rep:
                scale_up_s = _time.monotonic() - t0
                break
            _time.sleep(0.25)
        # preemption mid-load: evict one scaled-up replica gracefully
        preempted = False
        if scale_up_s is not None:
            victim = sorted(fab.routable())[-1]
            old_inc = fab.stats()["replicas"][victim]["incarnation"]
            fab.preempt_replica(victim)
            deadline = _time.monotonic() + 90.0
            while _time.monotonic() < deadline:
                rep = fab.stats()["replicas"].get(victim)
                if (
                    rep
                    and rep["incarnation"] != old_inc
                    and rep["state"] == "serving"
                ):
                    preempted = True
                    break
                _time.sleep(0.25)
        stop_load.set()
        loader.join(timeout=120.0)
        for rec_i in elastic_recs:
            check_bit_exact(rec_i["results"])
        # idle -> the loop must shrink back down by draining
        t1 = _time.monotonic()
        scale_down_s = None
        deadline = _time.monotonic() + 180.0
        while _time.monotonic() < deadline:
            if len(fab.fresh_ids()) <= 1:
                scale_down_s = _time.monotonic() - t1
                break
            _time.sleep(0.25)
        events = fab.autoscaler_events()
        n_el = sum(r["submitted"] for r in elastic_recs)
        ok_el = sum(r["ok"] for r in elastic_recs)
        shed_el = sum(r["shed"] for r in elastic_recs)
        accepted_el = sum(r["accepted"] for r in elastic_recs)
        lanes["elastic"] = {
            "offered_rps": p["offered_rps"],
            "submitted": n_el,
            "ok": ok_el,
            "ok_frac": ok_el / n_el if n_el else 0.0,
            "shed": shed_el,
            "shed_frac": shed_el / n_el if n_el else 0.0,
            "accepted": accepted_el,
            "ok_accepted_frac": (
                ok_el / accepted_el if accepted_el else 1.0
            ),
            "unavailable": sum(r["unavailable"] for r in elastic_recs),
            "retried_frac": (
                sum(r["retried"] for r in elastic_recs) / n_el
                if n_el else 0.0
            ),
            "achieved_rps": (
                sum(r["ok"] for r in elastic_recs)
                / sum(r["wall_s"] for r in elastic_recs)
                if elastic_recs else 0.0
            ),
            "scaled_up": scale_up_s is not None,
            "scale_up_s": scale_up_s,
            "preempted": preempted,
            "scaled_down": scale_down_s is not None,
            "scale_down_s": scale_down_s,
            "drained": any(
                e["direction"] == "down" and e["reason"] == "drained"
                for e in events
            ),
            "events": events,
        }
    scaling = (
        lanes[f"replicas_{n_rep}"]["achieved_rps"]
        / lanes["replicas_1"]["achieved_rps"]
        if lanes["replicas_1"]["achieved_rps"] > 0
        else None
    )
    rec = {
        "config": FABRIC_LOADGEN,
        "pipeline": p["ops"],
        "impl": "xla",
        "platform": jax.default_backend(),
        "buckets": p["buckets"],
        "replicas": n_rep,
        "offered_rps": p["offered_rps"],
        "phase_s": p["phase_s"],
        "bit_exact_gate": f"passed ({gate_checked} responses vs golden)",
        "lanes": lanes,
        "fleet_slo": rec_slo,
        "scaling_vs_1": scaling,
        "scaling_ok": scaling is not None and scaling >= 2.0,
    }
    printer(
        f"{'lane':22s} {'achieved':>9s} {'ok%':>6s} {'shed%':>6s} "
        f"{'retry%':>7s} {'p99 ms':>8s}"
    )

    def _row(name: str, r: dict) -> None:
        printer(
            f"{name:22s} {r['achieved_rps']:9.1f} "
            f"{r['ok_frac'] * 100:5.1f}% "
            f"{r.get('shed_frac', 0.0) * 100:5.1f}% "
            f"{r['retried_frac'] * 100:6.1f}% "
            f"{r.get('e2e_p99_ms', float('nan')):8.2f}"
        )

    _row("replicas_1", lanes["replicas_1"])
    _row(f"replicas_{n_rep}", lanes[f"replicas_{n_rep}"])
    for ph in ("before", "during", "after"):
        _row(f"churn/{ph}", lanes[f"replicas_{n_rep}_churn"][ph])
    el = lanes["elastic"]
    _row("elastic", el)
    printer(
        "elastic: scale-up "
        + (f"{el['scale_up_s']:.1f}s" if el["scaled_up"] else "NEVER")
        + ", preempt->replace "
        + ("ok" if el["preempted"] else "FAILED")
        + ", scale-down "
        + (f"{el['scale_down_s']:.1f}s" if el["scaled_down"] else "NEVER")
        + (" (drained)" if el["drained"] else " (NOT drained)")
    )
    printer(
        f"scaling replicas_{n_rep}/replicas_1 = "
        + (f"{scaling:.2f}x" if scaling else "n/a")
        + f" (>=2x: {rec['scaling_ok']})"
    )
    if rec_slo and rec_slo["p99"] and rec_slo["p99"].get("p99_s"):
        p99v = rec_slo["p99"]
        printer(
            f"federated p99 ~{p99v['p99_s'] * 1e3:.1f} ms"
            + (
                f"  p99~{p99v['exemplar_trace_id']}"
                if p99v.get("exemplar_trace_id")
                else ""
            )
        )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def federation_loadgen_params() -> dict:
    """The federation lane knobs: the fabric_loadgen posture one tier up
    — two whole pods (each `replicas` CPU replica processes behind its
    own router) joined to one front door. The fabric lane's env
    overrides (MCIM_FABRIC_RPS / _DURATION_S / _REPLICAS) apply here
    too; the pod count is fixed at 2 — the smallest topology where
    "reroute" and "failover" mean different pods."""
    p = fabric_loadgen_params()
    p["pods"] = 2
    return p


def run_federation_loadgen(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
) -> dict:
    """The multi-pod federation bench lane: the same open-loop HTTP mix
    through the federation front door (federation/frontdoor.py) over
    2 pods x N replicas, with a WHOLE POD SIGKILLed mid-sweep —
    supervisor and replicas together, no drain, no handover, and no
    restart (nothing supervises a pod; `after` measures the surviving
    single-pod steady state). The acceptance gate is the fabric churn
    rule one tier up: during the pod loss every ACCEPTED request
    completes 200 and bit-exact against the golden per-request path
    (unavailable == 0 — rerouting, not luck), and the front door books
    the loss in mcim_fed_reroutes_total under the closed
    REROUTE_REASONS vocabulary only. The front door runs in-process
    with the client threads (it proxies, the pods compute), so this
    lane's headline is availability under whole-pod loss — peak
    capacity is fabric_loadgen's claim."""
    import signal as _signal
    import tempfile as _tempfile
    import time

    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.federation.frontdoor import (
        REROUTE_REASONS,
        FrontDoor,
        FrontDoorConfig,
    )
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
    from mpi_cuda_imagemanipulation_tpu.serve import loadgen
    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets
    from mpi_cuda_imagemanipulation_tpu.serve.padded import min_true_dim

    p = federation_loadgen_params()
    pipe = Pipeline.parse(p["ops"])
    images = loadgen.mixed_shapes(
        parse_buckets(p["buckets"]),
        p["n_images"],
        channels=3,
        seed=7,
        min_dim=min_true_dim(pipe),
    )
    blobs = [loadgen.encode_blob(im) for im in images]
    golden_fn = pipe.jit()
    golden = [np.asarray(golden_fn(im)) for im in images]

    def check_bit_exact(results) -> int:
        from mpi_cuda_imagemanipulation_tpu.io.image import (
            decode_image_bytes,
        )

        n = 0
        for k, r in results:
            if r["code"] != 200:
                continue
            got = decode_image_bytes(r["body"])
            if not np.array_equal(got, golden[k]):
                raise AssertionError(
                    f"federation_loadgen: response for image {k} "
                    "mismatches the golden per-request output"
                )
            n += 1
        return n

    def _fed_forwards_ok(door) -> dict[str, float]:
        fams = parse_exposition(door.registry.render())
        out: dict[str, float] = {}
        fam = fams.get("mcim_fed_forwards_total")
        if fam:
            for (_n, labels), v in fam["samples"].items():
                if 'outcome="ok"' not in labels:
                    continue
                pod = labels.split('pod="', 1)[1].split('"', 1)[0]
                out[pod] = out.get(pod, 0.0) + v
        return out

    def _fed_reroutes(door) -> dict[str, float]:
        fams = parse_exposition(door.registry.render())
        out: dict[str, float] = {}
        fam = fams.get("mcim_fed_reroutes_total")
        if fam:
            for (_n, labels), v in fam["samples"].items():
                reason = labels.split('reason="', 1)[1].split('"', 1)[0]
                out[reason] = out.get(reason, 0.0) + v
        return out

    tmp = _tempfile.mkdtemp(prefix="federation_loadgen_")
    door = FrontDoor(
        FrontDoorConfig(
            registry_path=os.path.join(tmp, "fed_registry.jsonl"),
            buckets=tuple(parse_buckets(p["buckets"])),
            stale_s=4 * p["heartbeat_s"],
            forward_timeout_s=60.0,
            forward_attempts=3,
        )
    ).start(host="127.0.0.1", port=0)
    pods: dict[str, _FabricProc] = {}
    lanes: dict[str, dict] = {}
    try:
        for i in range(p["pods"]):
            pods[f"pod{i}"] = _FabricProc(
                p,
                p["replicas"],
                extra_args=["--federate", door.url, "--pod-id", f"pod{i}"],
                extra_env={
                    "MCIM_FED_HEARTBEAT_S": str(p["heartbeat_s"]),
                },
            )
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            for pid, fab in pods.items():
                if fab.proc.poll() is not None:
                    raise RuntimeError(
                        f"pod {pid} exited rc={fab.proc.returncode}"
                    )
            now = door._clock()
            ready = {
                v.pod_id
                for v in door.table.views()
                if v.fresh(now, door.stale_s)
                and v.hb.routable >= p["replicas"]
            }
            if ready >= set(pods):
                break
            time.sleep(0.25)
        else:
            raise TimeoutError(
                f"pods never joined the front door (ready: {ready})"
            )
        # bit-exact gate BEFORE any timing: one pass over the unique mix
        gate = loadgen.http_run_offered_load(
            door.url, blobs, min(64.0, p["offered_rps"]),
            len(blobs) / min(64.0, p["offered_rps"]),
        )
        gate_checked = check_bit_exact(gate["results"])
        # -- 2 pods, steady state -------------------------------------------
        rec2 = loadgen.http_run_offered_load(
            door.url, blobs, p["offered_rps"], p["phase_s"],
            max_workers=p["max_workers"],
        )
        check_bit_exact(rec2["results"])
        lanes["pods_2"] = _phase_public(rec2)
        # -- whole-pod churn ------------------------------------------------
        # the victim is the pod that carried the most successful forwards
        # (sticky affinity concentrates keys): killing the idle pod would
        # prove nothing about rerouting under loss
        by_pod = _fed_forwards_ok(door)
        victim = (
            max(by_pod, key=by_pod.get) if by_pod else next(iter(pods))
        )
        survivor = next(pid for pid in pods if pid != victim)
        killed_pids: list[int] = []

        def _kill_whole_pod():
            fab = pods[victim]
            try:
                killed_pids.extend(
                    rep["pid"] for rep in fab.stats()["replicas"].values()
                )
            except Exception:
                pass
            if fab.proc.poll() is None:
                killed_pids.append(fab.proc.pid)
                fab.proc.send_signal(_signal.SIGKILL)
            for pid in killed_pids:
                try:
                    os.kill(pid, _signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

        phases = loadgen.churn_run(
            door.url,
            blobs,
            offered_rps=p["churn_rps"],
            phase_s=p["phase_s"],
            kill=_kill_whole_pod,
        )
        for ph in phases.values():
            check_bit_exact(ph["results"])
        during = phases["during"]
        if during["unavailable"] or during["ok"] != during["accepted"]:
            raise AssertionError(
                f"federation_loadgen: requests lost during whole-pod "
                f"SIGKILL of {victim}: ok {during['ok']} / accepted "
                f"{during['accepted']} / unavailable "
                f"{during['unavailable']}"
            )
        reroutes = _fed_reroutes(door)
        unknown = set(reroutes) - set(REROUTE_REASONS)
        if unknown:
            raise AssertionError(
                f"federation_loadgen: reroute reasons outside the closed "
                f"vocabulary: {sorted(unknown)}"
            )
        if not reroutes:
            raise AssertionError(
                "federation_loadgen: whole-pod SIGKILL produced no "
                "counted reroute"
            )
        lanes["pod_churn"] = {
            name: _phase_public(ph) for name, ph in phases.items()
        }
        lanes["pod_churn"].update(
            victim=victim,
            survivor=survivor,
            churn_rps=p["churn_rps"],
            killed_pids=killed_pids,
            reroutes=reroutes,
        )
    finally:
        door.close()
        for fab in pods.values():
            fab.close()
    rec = {
        "config": FEDERATION_LOADGEN,
        "pipeline": p["ops"],
        "impl": "xla",
        "platform": jax.default_backend(),
        "buckets": p["buckets"],
        "pods": p["pods"],
        "replicas_per_pod": p["replicas"],
        "offered_rps": p["offered_rps"],
        "phase_s": p["phase_s"],
        "bit_exact_gate": f"passed ({gate_checked} responses vs golden)",
        "lanes": lanes,
        "reroutes": reroutes,
    }
    printer(
        f"{'lane':22s} {'achieved':>9s} {'ok%':>6s} {'shed%':>6s} "
        f"{'retry%':>7s} {'p99 ms':>8s}"
    )

    def _row(name: str, r: dict) -> None:
        printer(
            f"{name:22s} {r['achieved_rps']:9.1f} "
            f"{r['ok_frac'] * 100:5.1f}% "
            f"{r.get('shed_frac', 0.0) * 100:5.1f}% "
            f"{r['retried_frac'] * 100:6.1f}% "
            f"{r.get('e2e_p99_ms', float('nan')):8.2f}"
        )

    _row("pods_2", lanes["pods_2"])
    for ph in ("before", "during", "after"):
        _row(f"pod_churn/{ph}", lanes["pod_churn"][ph])
    printer(
        f"whole-pod SIGKILL of {victim}: during-phase "
        f"{during['ok']}/{during['accepted']} accepted requests ok "
        f"(bit-exact), reroutes {reroutes}"
    )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def mxu_ab_params() -> dict:
    """The MXU A/B lane knobs, sized to the backend: the headline 8K
    gaussian:5 on real hardware, a small shape on CPU (where the numbers
    prove structure, not speed). Env overrides for tools/tpu_queue and
    tests: MCIM_MXU_AB_OPS / _HEIGHT / _WIDTH."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "gaussian:5",
        "height": 4320 if on_tpu else 256,
        "width": 7680 if on_tpu else 512,
    }
    for env, key, cast in (
        ("MCIM_MXU_AB_OPS", "ops", str),
        ("MCIM_MXU_AB_HEIGHT", "height", int),
        ("MCIM_MXU_AB_WIDTH", "width", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    return params


def run_mxu_ab(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
) -> dict:
    """The VPU-vs-MXU bench lane (round-6 promotion of tools/mxu_proto.py
    / tools/hybrid_proto.py): the same workload three ways —

      * vpu    — the production u8 Pallas streaming kernels (the round-5
                 headline path, VPU-compute-bound at ~11% of roofline);
      * mxu    — the banded-matmul backend, both separable passes
                 contracting on the MXU (bf16 with the 64a+b column
                 split; ops/mxu_kernels.py);
      * hybrid — the split sub-mode: row pass on the VPU, column pass on
                 the MXU, one fused XLA launch.

    Each lane reports MP/s/chip and (on TPU) roofline_frac against the
    one-read-one-write u8 traffic model, so the queue artifact answers
    the round-5 judge's question directly: how much of the measured
    roofline headroom the MXU formulation cashes. All three lanes are
    gated bit-exact against the golden path on a small shape BEFORE any
    timing (the proto discipline)."""
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import pipeline_mxu
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        pipeline_pallas,
    )

    p = mxu_ab_params()
    pipe = Pipeline.parse(p["ops"])
    lanes: dict[str, Callable] = {
        "vpu": jax.jit(lambda x: pipeline_pallas(pipe.ops, x)),
        "mxu": jax.jit(lambda x: pipeline_mxu(pipe.ops, x, mode="banded")),
        "hybrid": jax.jit(
            lambda x: pipeline_mxu(pipe.ops, x, mode="hybrid")
        ),
    }

    # -- bit-exactness gate before any timing --
    for th, tw, seed in ((48, 64, 1), (37, 200, 2), (130, 384, 3)):
        timg = jnp.asarray(synthetic_image(th, tw, channels=1, seed=seed))
        golden = np.asarray(pipe(timg))
        for lane, fn in lanes.items():
            got = np.asarray(fn(timg))
            if not np.array_equal(got, golden):
                raise AssertionError(
                    f"mxu_ab gate: lane {lane!r} mismatches golden at "
                    f"{th}x{tw}"
                )

    img = jnp.asarray(
        synthetic_image(p["height"], p["width"], channels=1, seed=99)
    )
    mp = p["height"] * p["width"] / 1e6
    hbm_bytes = 2 * p["height"] * p["width"]  # one u8 read + one u8 write
    on_tpu = is_tpu_backend()
    gen = _tpu_gen() if on_tpu else None
    lane_recs: dict[str, dict] = {}
    for lane, fn in lanes.items():
        try:
            sec = device_throughput(fn, [img])
        except Exception as e:  # one lane failing must not kill the A/B
            lane_recs[lane] = {"error": str(e)[:200]}
            continue
        lr = {
            "ms_per_iter": sec * 1e3,
            "mp_per_s_per_chip": mp / sec,
            "hbm_gb_s_model": hbm_bytes / sec / 1e9,
        }
        if on_tpu:
            lr["roofline_frac"] = lr["hbm_gb_s_model"] / HBM_GB_S.get(
                gen, HBM_GB_S["v5e"]
            )
        lane_recs[lane] = lr
    ok = {k: v for k, v in lane_recs.items() if "error" not in v}
    best = max(ok, key=lambda k: ok[k]["mp_per_s_per_chip"]) if ok else None
    rec = {
        "config": MXU_AB,
        "pipeline": p["ops"],
        "impl": "mxu_ab",
        "platform": jax.default_backend(),
        "height": p["height"],
        "width": p["width"],
        "bit_exact_gate": "passed (3 shapes x 3 lanes vs golden)",
        "lanes": lane_recs,
        "best_lane": best,
    }
    if on_tpu:
        rec["tpu_gen"] = gen
    printer(
        f"{'lane':8s} {'ms/iter':>9s} {'MP/s/chip':>11s} {'roofline':>9s}"
    )
    for lane, lr in lane_recs.items():
        if "error" in lr:
            printer(f"{lane:8s} ERROR {lr['error'][:80]}")
            continue
        rl = (
            f"{lr['roofline_frac'] * 100:8.1f}%"
            if "roofline_frac" in lr
            else f"{'-':>9s}"
        )
        printer(
            f"{lane:8s} {lr['ms_per_iter']:9.3f} "
            f"{lr['mp_per_s_per_chip']:11.0f} {rl}"
        )
    if best:
        printer(f"best lane: {best}")
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def engine_ab_params() -> dict:
    """The engine A/B lane knobs, sized to the backend. The corpus is
    synthetic-slow-decode: real PNG bytes decoded per image plus a fixed
    host delay (models the long-tail codecs and filesystems a production
    batch actually pays), so the serial lane's device-idle fraction is
    substantial and the overlap win is measurable even where compute is
    fast. Env overrides for tools/tpu_queue and tests:
    MCIM_ENGINE_AB_IMAGES / _DECODE_MS / _ENCODE_MS / _INFLIGHT."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "grayscale,contrast:3.5,emboss:3",
        "n_images": 32 if on_tpu else 12,
        "height": 1080 if on_tpu else 96,
        "width": 1920 if on_tpu else 128,
        "channels": 3,
        "decode_ms": 8.0 if on_tpu else 20.0,
        "encode_ms": 4.0 if on_tpu else 10.0,
        "inflight": 2,
        "io_threads": 4,
        "decode_threads": 4,
    }
    for env, key, cast in (
        ("MCIM_ENGINE_AB_IMAGES", "n_images", int),
        ("MCIM_ENGINE_AB_DECODE_MS", "decode_ms", float),
        ("MCIM_ENGINE_AB_ENCODE_MS", "encode_ms", float),
        ("MCIM_ENGINE_AB_INFLIGHT", "inflight", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    return params


def run_engine_ab(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
    inflight: int | None = None,
) -> dict:
    """Serial-vs-overlapped end-to-end A/B over the async execution engine
    (engine/core.py), mirroring the `halo_ab` pattern: same inputs, same
    compiled pipeline, two execution structures.

      * serial lane:     decode → dispatch → force → encode, one image at
                         a time (the device idles through every host phase
                         — the reference's per-launch round-trip shape);
      * overlapped lane: decode prefetch pool → engine (`inflight`
                         dispatches outstanding, in-order completion,
                         encode worker pool).

    Reports e2e images/sec per lane, the measured speedup, and each lane's
    device-idle fraction — overlap is proven when the engine's idle
    fraction drops strictly below serial while outputs stay bit-identical."""
    import time as _time
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
    from mpi_cuda_imagemanipulation_tpu.io.image import (
        decode_image_bytes,
        encode_image_bytes,
    )

    p = engine_ab_params()
    if inflight is not None:
        p["inflight"] = inflight
    decode_s = p["decode_ms"] / 1e3
    encode_s = p["encode_ms"] / 1e3
    imgs = [
        synthetic_image(
            p["height"], p["width"], channels=p["channels"], seed=31 + k
        )
        for k in range(p["n_images"])
    ]
    blobs = [encode_image_bytes(im) for im in imgs]  # the on-"disk" corpus

    def decode(blob) -> np.ndarray:
        img = decode_image_bytes(blob)
        _time.sleep(decode_s)  # synthetic slow-decode tail
        return img

    def encode(out: np.ndarray) -> bytes:
        data = encode_image_bytes(out)
        _time.sleep(encode_s)  # synthetic slow-encode/write tail
        return data

    pipe = Pipeline.parse(p["ops"])
    fn = pipe.jit(backend="xla", donate=True)
    jax.block_until_ready(fn(imgs[0]))  # compile outside both timed lanes

    # -- serial lane -------------------------------------------------------
    serial_out: dict[int, np.ndarray] = {}
    busy = 0.0
    t0 = _time.perf_counter()
    for k, blob in enumerate(blobs):
        img = decode(blob)
        tb = _time.perf_counter()
        out = np.asarray(fn(img))  # forces completion inline
        busy += _time.perf_counter() - tb
        serial_out[k] = out
        encode(out)
    serial_wall = _time.perf_counter() - t0
    serial_idle = max(0.0, 1.0 - busy / serial_wall)

    # -- overlapped lane ---------------------------------------------------
    overlap_out: dict[int, np.ndarray] = {}
    errors: list = []

    def _on_done(k, out, info):
        arr = np.asarray(out)
        overlap_out[k] = arr
        encode(arr)

    metrics = EngineMetrics()
    engine = Engine(
        inflight=p["inflight"],
        io_threads=p["io_threads"],
        stage=jax.device_put,
        metrics=metrics,
        name="engine-ab",
    )
    t0 = _time.perf_counter()
    with ThreadPoolExecutor(p["decode_threads"]) as pool:
        pending: deque = deque()
        max_ahead = 2 * p["decode_threads"]
        it = iter(enumerate(blobs))
        exhausted = False
        while pending or not exhausted:
            while not exhausted and len(pending) < max_ahead:
                try:
                    k, blob = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append((k, pool.submit(decode, blob)))
            if not pending:
                break
            k, fut = pending.popleft()
            img = fut.result()
            engine.submit(
                k,
                lambda img=img: img,
                fn,
                on_done=_on_done,
                on_error=lambda k, e: errors.append((k, e)),
            )
        engine.close()
    overlap_wall = _time.perf_counter() - t0
    overlap_idle = metrics.device_idle_frac()

    if errors:
        raise RuntimeError(f"engine_ab overlapped lane failed: {errors[:3]}")
    bit_identical = len(overlap_out) == len(serial_out) and all(
        np.array_equal(serial_out[k], overlap_out[k]) for k in serial_out
    )
    n = p["n_images"]
    rec = {
        "config": ENGINE_AB,
        "pipeline": p["ops"],
        "impl": "xla",
        "platform": jax.default_backend(),
        "n_images": n,
        "height": p["height"],
        "width": p["width"],
        "decode_ms": p["decode_ms"],
        "encode_ms": p["encode_ms"],
        "inflight": p["inflight"],
        "io_threads": p["io_threads"],
        "decode_threads": p["decode_threads"],
        "serial": {
            "wall_s": serial_wall,
            "images_per_s": n / serial_wall,
            "device_idle_frac": serial_idle,
        },
        "overlap": {
            "wall_s": overlap_wall,
            "images_per_s": n / overlap_wall,
            "device_idle_frac": overlap_idle,
            "inflight_peak": metrics.snapshot()["inflight_peak"],
        },
        "speedup": serial_wall / overlap_wall if overlap_wall > 0 else None,
        # the overlap headline: how much of the serial lane's device-idle
        # time the engine removed from the critical path
        "overlap_won": (
            overlap_idle is not None and overlap_idle < serial_idle
        ),
        "bit_identical": bit_identical,
    }
    printer(
        f"{'lane':10s} {'wall s':>8s} {'img/s':>8s} {'dev idle':>9s}"
    )
    printer(
        f"{'serial':10s} {serial_wall:8.2f} {n / serial_wall:8.1f} "
        f"{serial_idle * 100:8.1f}%"
    )
    printer(
        f"{'overlap':10s} {overlap_wall:8.2f} {n / overlap_wall:8.1f} "
        + (
            f"{overlap_idle * 100:8.1f}%"
            if overlap_idle is not None
            else f"{'-':>9s}"
        )
    )
    printer(
        f"speedup {rec['speedup']:.2f}x, inflight {p['inflight']} "
        f"(peak {rec['overlap']['inflight_peak']}), "
        f"bit_identical={bit_identical}"
    )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def stream_ab_params() -> dict:
    """The stream A/B knobs, sized to the backend. The read stage carries
    a small synthetic per-band latency (models decode/disk — the same
    move as engine_ab's slow-decode corpus) so the serial lane's
    device-idle fraction is substantial and overlap is measurable on
    1-core CI. Env overrides: MCIM_STREAM_AB_HEIGHT/_WIDTH/_TILE_ROWS."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "grayscale,contrast:3.5,emboss:3",
        "n_images": 2 if on_tpu else 3,
        "height": 8192 if on_tpu else 1536,
        "width": 2048 if on_tpu else 256,
        "channels": 3,
        "tile_rows": 512 if on_tpu else 128,
        "inflight": 2,
        "read_ms_per_band": 0.0 if on_tpu else 4.0,
    }
    for env, key, cast in (
        ("MCIM_STREAM_AB_HEIGHT", "height", int),
        ("MCIM_STREAM_AB_WIDTH", "width", int),
        ("MCIM_STREAM_AB_TILE_ROWS", "tile_rows", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    return params


def run_stream_ab(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
    tile_rows: int | None = None,
) -> dict:
    """Serial-whole-image vs streamed-tiles A/B (stream/runner.py):

      * serial lane:   generate the full frame, ONE whole-image dispatch,
                       encode the full PNG — the pre-stream memory shape
                       (peak resident = the whole frame + its encoding);
      * streamed lane: the same rows through the tile engine — windowed
                       synthetic reader, seam-stitched fixed-shape tiles,
                       double-buffered dispatches, ordered incremental
                       PNG encode.

    Reports img/s, device-idle fraction and PEAK RESIDENT BYTES per lane
    — overlap is proven when the streamed lane's idle fraction drops
    below serial, and the constant-memory claim is the resident ratio.
    Outputs are gated bit-identical (decode both PNGs, compare) before
    any number is reported."""
    import io as _io
    import time as _time

    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
    from mpi_cuda_imagemanipulation_tpu.io.image import (
        decode_image_bytes,
        encode_image_bytes,
        synthetic_image,
    )
    from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
        PNGTileWriter,
        SyntheticTileReader,
    )
    from mpi_cuda_imagemanipulation_tpu.stream import (
        StreamMetrics,
        stream_pipeline,
    )
    from mpi_cuda_imagemanipulation_tpu.stream.tiles import out_channels

    p = stream_ab_params()
    if tile_rows is not None:
        p["tile_rows"] = tile_rows
    h, w, c = p["height"], p["width"], p["channels"]
    T = p["tile_rows"]
    n_bands = -(-h // T)
    read_s_band = p["read_ms_per_band"] / 1e3
    pipe = Pipeline.parse(p["ops"])
    out_c = out_channels(pipe.ops, c)

    fn = pipe.jit(backend="xla")
    # compile both lanes OUTSIDE the clocks (full serial shape + every
    # streamed tile variant) — the A/B compares execution structures,
    # not compile caches
    jax.block_until_ready(fn(synthetic_image(h, w, channels=c, seed=0)))

    # -- serial lane: whole image resident, one dispatch -------------------
    serial_png: dict[int, bytes] = {}
    serial_peak = 0
    busy = 0.0
    t0 = _time.perf_counter()
    for k in range(p["n_images"]):
        img = synthetic_image(h, w, channels=c, seed=100 + k)
        _time.sleep(read_s_band * n_bands)  # same modeled decode latency
        tb = _time.perf_counter()
        out = np.asarray(jax.block_until_ready(fn(img)))
        busy += _time.perf_counter() - tb
        png = encode_image_bytes(out)
        serial_peak = max(serial_peak, img.nbytes + out.nbytes + len(png))
        serial_png[k] = png
    serial_wall = _time.perf_counter() - t0
    serial_idle = max(0.0, 1.0 - busy / serial_wall)

    # -- streamed lane: fixed-shape tiles, constant footprint --------------
    class _SlowSynthetic(SyntheticTileReader):
        def _read(self, n):
            _time.sleep(read_s_band)  # modeled per-band decode latency
            return super()._read(n)

    smetrics = StreamMetrics()
    engine = Engine(
        inflight=p["inflight"],
        io_threads=2,
        stage=jax.device_put,
        metrics=EngineMetrics(registry=smetrics.registry),
        ordered_done=True,
        name="stream-ab",
    )
    from mpi_cuda_imagemanipulation_tpu.stream.tiles import TileFnCache

    fn_cache = TileFnCache(pipe.ops, global_h=h, global_w=w, impl="xla")
    # warm the streamed lane's compiles (one un-timed pass; the engine
    # metrics reset below so the timed window is clean)
    _warm = PNGTileWriter(_io.BytesIO(), h, w, out_c)
    with Engine(
        inflight=p["inflight"], io_threads=2, stage=jax.device_put,
        ordered_done=True, name="stream-ab-warm",
    ) as _weng:
        stream_pipeline(
            SyntheticTileReader(h, w, channels=c, seed=99), _warm,
            pipe.ops, tile_rows=T, impl="xla",
            metrics=StreamMetrics(), engine=_weng, fn_cache=fn_cache,
        )
    _warm.close()

    stream_png: dict[int, bytes] = {}
    t0 = _time.perf_counter()
    try:
        for k in range(p["n_images"]):
            sink = _io.BytesIO()
            writer = PNGTileWriter(sink, h, w, out_c)
            stream_pipeline(
                _SlowSynthetic(h, w, channels=c, seed=100 + k),
                writer,
                pipe.ops,
                tile_rows=T,
                impl="xla",
                metrics=smetrics,
                engine=engine,
                fn_cache=fn_cache,
            )
            writer.close()
            stream_png[k] = sink.getvalue()
    finally:
        engine.close()
    stream_wall = _time.perf_counter() - t0
    stream_idle = engine.metrics.device_idle_frac()
    stream_peak = smetrics.peak_resident_bytes

    bit_identical = all(
        np.array_equal(
            decode_image_bytes(serial_png[k]),
            decode_image_bytes(stream_png[k]),
        )
        for k in range(p["n_images"])
    )
    if not bit_identical:
        raise RuntimeError(
            "stream_ab gate: streamed output mismatches the whole-image "
            "golden — refusing to report performance for wrong results"
        )
    n = p["n_images"]
    rec = {
        "config": STREAM_AB,
        "pipeline": p["ops"],
        "impl": "xla",
        "platform": jax.default_backend(),
        "n_images": n,
        "height": h,
        "width": w,
        "channels": c,
        "tile_rows": T,
        "inflight": p["inflight"],
        "read_ms_per_band": p["read_ms_per_band"],
        "serial": {
            "wall_s": serial_wall,
            "images_per_s": n / serial_wall,
            "mp_per_s": n * h * w / 1e6 / serial_wall,
            "device_idle_frac": serial_idle,
            "peak_resident_bytes": serial_peak,
        },
        "stream": {
            "wall_s": stream_wall,
            "images_per_s": n / stream_wall,
            "mp_per_s": n * h * w / 1e6 / stream_wall,
            "device_idle_frac": stream_idle,
            "peak_resident_bytes": stream_peak,
            "inflight_peak": engine.metrics.snapshot()["inflight_peak"],
        },
        "speedup": serial_wall / stream_wall if stream_wall > 0 else None,
        "memory_ratio": serial_peak / stream_peak if stream_peak else None,
        "overlap_won": (
            stream_idle is not None and stream_idle < serial_idle
        ),
        "bit_identical": bit_identical,
    }
    printer(
        f"{'lane':10s} {'wall s':>8s} {'img/s':>8s} {'dev idle':>9s} "
        f"{'peak MiB':>9s}"
    )
    printer(
        f"{'serial':10s} {serial_wall:8.2f} {n / serial_wall:8.2f} "
        f"{serial_idle * 100:8.1f}% {serial_peak / 2**20:9.2f}"
    )
    printer(
        f"{'stream':10s} {stream_wall:8.2f} {n / stream_wall:8.2f} "
        + (
            f"{stream_idle * 100:8.1f}%"
            if stream_idle is not None
            else f"{'-':>9s}"
        )
        + f" {stream_peak / 2**20:9.2f}"
    )
    printer(
        f"speedup {rec['speedup']:.2f}x, memory {rec['memory_ratio']:.1f}x "
        f"smaller resident, tile_rows {T}, bit_identical={bit_identical}"
    )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def plan_ab_params() -> dict:
    """The fusion-planner A/B knobs, sized to the backend: the
    representative pointwise-heavy headline chain (two pointwise ops
    riding one stencil, plus a trailing pointwise) at 8K on real
    hardware, a CPU-sized shape otherwise. Env overrides for
    tools/tpu_queue and tests: MCIM_PLAN_AB_OPS/_HEIGHT/_WIDTH."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "grayscale,contrast:3.5,gaussian:5,quantize:6",
        "height": 4320 if on_tpu else 512,
        "width": 7680 if on_tpu else 512,
        "channels": 3,
    }
    for env, key, cast in (
        ("MCIM_PLAN_AB_OPS", "ops", str),
        ("MCIM_PLAN_AB_HEIGHT", "height", int),
        ("MCIM_PLAN_AB_WIDTH", "width", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    return params


def run_plan_ab(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
) -> dict:
    """Unfused-vs-fused execution-plan bench lane (plan/):

      * off       — the product's golden reference, `--plan off`: the
                    per-op chain in one jit — every op materialises u8
                    and pays its own whole-image pass;
      * per_op    — the op-at-a-time dispatch model: one INDEPENDENTLY
                    jitted callable per op, chained — the reference's
                    sequential kernel launches, each a full HBM round
                    trip plus its own dispatch;
      * pointwise — pointwise absorption only: each stencil carries its
                    adjacent pointwise run in one pass;
      * fused     — full temporal blocking: maximal pointwise/stencil
                    runs as single stages (`--plan fused`).

    Every lane is gated bit-identical to the golden per-op chain on
    three odd shapes BEFORE any timing (the mxu_ab discipline), then the
    same workload is timed e2e per lane, plus a per-stage breakdown of
    the fused plan — so the record shows WHERE the pass savings land,
    not just that they do. The modelled HBM-pass counts ride along: the
    speedup (fused vs `--plan off`, the two structures the plan knob
    actually switches between) is the measured side of
    `hbm_passes_saved`."""
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.plan import build_plan
    from mpi_cuda_imagemanipulation_tpu.plan.exec import (
        plan_callable,
        run_stage_full,
        run_unfused,
        unfused_callables,
    )

    p = plan_ab_params()
    pipe = Pipeline.parse(p["ops"])
    c = p["channels"]
    plans = {m: build_plan(pipe.ops, m) for m in ("pointwise", "fused")}
    per_op = unfused_callables(pipe.ops)
    lanes: dict[str, Callable] = {
        "off": pipe.jit(plan="off"),
        "per_op": lambda x: run_unfused(per_op, x),
        "pointwise": jax.jit(plan_callable(plans["pointwise"])),
        "fused": jax.jit(plan_callable(plans["fused"])),
    }

    # -- bit-exactness gate before any timing (vs the golden chain) --------
    for th, tw, seed in ((48, 64, 1), (37, 200, 2), (130, 384, 3)):
        timg = jnp.asarray(synthetic_image(th, tw, channels=c, seed=seed))
        golden = np.asarray(pipe(timg))
        for lane, fn in lanes.items():
            got = np.asarray(fn(timg))
            if not np.array_equal(got, golden):
                raise AssertionError(
                    f"plan_ab gate: lane {lane!r} mismatches golden at "
                    f"{th}x{tw}"
                )

    img = jnp.asarray(
        synthetic_image(p["height"], p["width"], channels=c, seed=99)
    )
    mp = p["height"] * p["width"] / 1e6
    lane_recs: dict[str, dict] = {}
    for lane, fn in lanes.items():
        try:
            sec = device_throughput(fn, [img])
        except Exception as e:  # one lane failing must not kill the A/B
            lane_recs[lane] = {"error": str(e)[:200]}
            continue
        plan = plans.get(lane)
        lane_recs[lane] = {
            "ms_per_iter": sec * 1e3,
            "mp_per_s_per_chip": mp / sec,
            "stages": len(plan.stages) if plan else len(pipe.ops),
            "hbm_passes_model": (
                plan.hbm_passes if plan else plans["fused"].hbm_passes_unfused
            ),
        }
    # -- per-stage breakdown of the fused plan (where the time went) -------
    stage_ms = []
    for stage in plans["fused"].stages:
        sfn = jax.jit(lambda x, s=stage: run_stage_full(s, x, "xla"))
        try:
            sec = device_throughput(sfn, [img], trials=3)
            stage_ms.append(
                {"ops": "+".join(stage.names), "halo": stage.halo,
                 "ms_per_iter": sec * 1e3}
            )
        except Exception as e:
            stage_ms.append(
                {"ops": "+".join(stage.names), "error": str(e)[:200]}
            )
    ok = {k: v for k, v in lane_recs.items() if "error" not in v}
    speedup = speedup_dispatch = None
    if "off" in ok and "fused" in ok:
        speedup = ok["off"]["ms_per_iter"] / ok["fused"]["ms_per_iter"]
    if "per_op" in ok and "fused" in ok:
        speedup_dispatch = (
            ok["per_op"]["ms_per_iter"] / ok["fused"]["ms_per_iter"]
        )
    rec = {
        "config": PLAN_AB,
        "pipeline": p["ops"],
        "impl": "plan_ab",
        "platform": jax.default_backend(),
        "height": p["height"],
        "width": p["width"],
        "channels": c,
        "bit_exact_gate": "passed (3 shapes x 3 lanes vs golden)",
        "lanes": lane_recs,
        "fused_stage_breakdown": stage_ms,
        "hbm_passes_saved_model": plans["fused"].hbm_passes_saved,
        "speedup_fused_vs_off": speedup,
        "speedup_fused_vs_per_op_dispatch": speedup_dispatch,
    }
    if is_tpu_backend():
        rec["tpu_gen"] = _tpu_gen()
    printer(
        f"{'lane':10s} {'ms/iter':>9s} {'MP/s/chip':>11s} "
        f"{'stages':>7s} {'hbm':>4s}"
    )
    for lane, lr in lane_recs.items():
        if "error" in lr:
            printer(f"{lane:10s} ERROR {lr['error'][:80]}")
            continue
        printer(
            f"{lane:10s} {lr['ms_per_iter']:9.3f} "
            f"{lr['mp_per_s_per_chip']:11.0f} {lr['stages']:7d} "
            f"{lr['hbm_passes_model']:4d}"
        )
    for s in stage_ms:
        printer(
            f"  stage {s['ops']}: "
            + (f"{s['ms_per_iter']:.3f} ms" if "ms_per_iter" in s
               else f"ERROR {s['error'][:60]}")
        )
    if speedup is not None:
        printer(
            f"fused speedup {speedup:.2f}x e2e vs --plan off "
            f"({plans['fused'].hbm_passes_saved} modelled HBM passes saved)"
        )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def megakernel_ab_params() -> dict:
    """The fused-XLA-vs-fused-pallas A/B knobs: a two-stencil chain so
    the headline stage is genuinely temporally blocked (gaussian:5 +
    sharpen fuse behind one halo-3 stage) at 8K on real hardware, a
    CPU-sized shape otherwise. Env overrides for tools/tpu_queue and
    tests: MCIM_MEGAKERNEL_AB_OPS/_HEIGHT/_WIDTH."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "grayscale,contrast:3.5,gaussian:5,sharpen,quantize:6",
        "height": 4320 if on_tpu else 384,
        "width": 7680 if on_tpu else 512,
        "channels": 3,
    }
    for env, key, cast in (
        ("MCIM_MEGAKERNEL_AB_OPS", "ops", str),
        ("MCIM_MEGAKERNEL_AB_HEIGHT", "height", int),
        ("MCIM_MEGAKERNEL_AB_WIDTH", "width", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    return params


def run_megakernel_ab(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
) -> dict:
    """Fused-XLA vs fused-pallas megakernel bench lane (plan/pallas_exec):

      * off          — `--plan off`, the per-op golden reference;
      * fused        — the PR-10 fused-XLA stage walker (the incumbent
                       this lane must beat on silicon);
      * fused_pallas — each eligible stage as ONE VMEM-resident
                       megakernel (`--plan fused-pallas`).

    Every lane is gated bit-identical to the golden per-op chain on
    three odd shapes BEFORE any timing (the plan_ab/mxu_ab discipline).
    Off-TPU the fused_pallas lane times the Pallas INTERPRETER — the
    committed CPU record is the gate + regression anchor, never a perf
    claim; tools/tpu_queue/29_megakernel_r07.sh carries the on-chip A/B.
    The record also reports the per-stage eligibility verdicts, so a
    silent everything-fell-back run is visible in the JSON."""
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.plan import build_plan
    from mpi_cuda_imagemanipulation_tpu.plan.exec import plan_callable
    from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
        plan_callable_pallas,
        stage_pallas_reject,
    )

    p = megakernel_ab_params()
    pipe = Pipeline.parse(p["ops"])
    c = p["channels"]
    plans = {
        "fused": build_plan(pipe.ops, "fused"),
        "fused_pallas": build_plan(pipe.ops, "fused-pallas"),
    }
    lanes: dict[str, Callable] = {
        "off": pipe.jit(plan="off"),
        "fused": jax.jit(plan_callable(plans["fused"])),
        "fused_pallas": jax.jit(plan_callable_pallas(plans["fused_pallas"])),
    }

    # -- bit-exactness gate before any timing (vs the golden chain) --------
    for th, tw, seed in ((48, 64, 1), (37, 200, 2), (130, 384, 3)):
        timg = jnp.asarray(synthetic_image(th, tw, channels=c, seed=seed))
        golden = np.asarray(pipe(timg))
        for lane, fn in lanes.items():
            got = np.asarray(fn(timg))
            if not np.array_equal(got, golden):
                raise AssertionError(
                    f"megakernel_ab gate: lane {lane!r} mismatches golden "
                    f"at {th}x{tw}"
                )

    img = jnp.asarray(
        synthetic_image(p["height"], p["width"], channels=c, seed=99)
    )
    mp = p["height"] * p["width"] / 1e6
    eligibility = [
        {
            "ops": "+".join(s.names),
            "halo": s.halo,
            "reject": stage_pallas_reject(s, p["height"], p["width"], c),
        }
        for s in plans["fused_pallas"].stages
    ]
    lane_recs: dict[str, dict] = {}
    for lane, fn in lanes.items():
        try:
            sec = device_throughput(fn, [img])
        except Exception as e:  # one lane failing must not kill the A/B
            lane_recs[lane] = {"error": str(e)[:200]}
            continue
        lane_recs[lane] = {
            "ms_per_iter": sec * 1e3,
            "mp_per_s_per_chip": mp / sec,
        }
    ok = {k: v for k, v in lane_recs.items() if "error" not in v}
    speedup = speedup_vs_off = None
    if "fused" in ok and "fused_pallas" in ok:
        speedup = ok["fused"]["ms_per_iter"] / ok["fused_pallas"]["ms_per_iter"]
    if "off" in ok and "fused_pallas" in ok:
        speedup_vs_off = (
            ok["off"]["ms_per_iter"] / ok["fused_pallas"]["ms_per_iter"]
        )
    rec = {
        "config": MEGAKERNEL_AB,
        "pipeline": p["ops"],
        "impl": "megakernel_ab",
        "platform": jax.default_backend(),
        "interpret_mode": not is_tpu_backend(),
        "height": p["height"],
        "width": p["width"],
        "channels": c,
        "bit_exact_gate": "passed (3 shapes x 3 lanes vs golden)",
        "lanes": lane_recs,
        "stage_eligibility": eligibility,
        "megakernel_stages": sum(
            1 for e in eligibility if e["reject"] is None
        ),
        "speedup_pallas_vs_fused": speedup,
        "speedup_pallas_vs_off": speedup_vs_off,
    }
    if is_tpu_backend():
        rec["tpu_gen"] = _tpu_gen()
    printer(f"{'lane':14s} {'ms/iter':>9s} {'MP/s/chip':>11s}")
    for lane, lr in lane_recs.items():
        if "error" in lr:
            printer(f"{lane:14s} ERROR {lr['error'][:80]}")
            continue
        printer(
            f"{lane:14s} {lr['ms_per_iter']:9.3f} "
            f"{lr['mp_per_s_per_chip']:11.0f}"
        )
    for e in eligibility:
        printer(
            f"  stage {e['ops']} halo={e['halo']}: "
            + ("megakernel" if e["reject"] is None
               else f"fallback ({e['reject']})")
        )
    if speedup is not None:
        printer(
            f"fused-pallas {speedup:.2f}x vs fused-XLA"
            + (" (INTERPRET mode — gate record, not a perf claim)"
               if rec["interpret_mode"] else "")
        )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def mxu_fused_ab_params() -> dict:
    """The in-stage-MXU A/B knobs: a three-stencil chain mixing a
    separable Gaussian, a dense 3x3 and a wide box — every op int8-
    provable, so the int8 arm covers the whole stage — at 8K on real
    hardware, a CPU-sized shape otherwise. Env overrides for
    tools/tpu_queue and tests: MCIM_MXU_FUSED_AB_OPS/_HEIGHT/_WIDTH."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "gaussian:5,sharpen,box:5",
        "height": 4320 if on_tpu else 256,
        "width": 7680 if on_tpu else 384,
    }
    for env, key, cast in (
        ("MCIM_MXU_FUSED_AB_OPS", "ops", str),
        ("MCIM_MXU_FUSED_AB_HEIGHT", "height", int),
        ("MCIM_MXU_FUSED_AB_WIDTH", "width", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    return params


def run_mxu_fused_ab(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
) -> dict:
    """The MXU-inside-the-megakernel bench lane (round 8): one fused
    stage, four executions of the same chain —

      * off            — `--plan off`, the per-op golden reference;
      * fused_vpu      — the megakernel with every in-stage op on the
                         VPU shift-multiply walk (MCIM_MXU_STAGE=off;
                         the incumbent the new arms must beat);
      * fused_mxu      — the megakernel with every eligible op as a
                         bf16 `lax.dot_general` contraction INSIDE the
                         pallas_call body (mxu_stage='f32');
      * fused_mxu_int8 — the int8-accumulation variant where
                         mxu_int8_ok proves exactness (mxu_stage='int8');
      * mxu_whole_op   — the PR-13 whole-op banded backend (one XLA
                         launch per op, HBM round trip between ops) —
                         the baseline that isolates what VMEM residency
                         adds ON TOP of MXU throughput.

    All lanes are gated bit-identical to the golden per-op chain on
    three odd shapes BEFORE any timing. Off-TPU the fused lanes time the
    Pallas INTERPRETER, where the banded dot's ~(B+2h)/kw arithmetic
    inflation is paid at VPU-equivalent FLOPs — the committed CPU record
    is the gate + regression anchor, never a perf claim (the dot only
    wins where a real MXU makes its FLOPs free);
    tools/tpu_queue/36_mxu_fused_r08.sh carries the on-chip A/B against
    the BASELINE.md pre-registered targets. The record reports the
    resolved per-op arms so a silently-ineligible run is visible."""
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
        mxu_int8_ok,
        pipeline_mxu,
        stage_arm_for,
    )
    from mpi_cuda_imagemanipulation_tpu.plan import build_plan
    from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
        plan_callable_pallas,
    )

    p = mxu_fused_ab_params()
    pipe = Pipeline.parse(p["ops"])
    plan_vpu = build_plan(pipe.ops, "fused-pallas")
    plan_mxu = build_plan(pipe.ops, "fused-pallas-mxu")
    lanes: dict[str, Callable] = {
        "off": pipe.jit(plan="off"),
        "fused_vpu": jax.jit(
            plan_callable_pallas(plan_vpu, mxu_stage="off")
        ),
        "fused_mxu": jax.jit(
            plan_callable_pallas(plan_mxu, mxu_stage="f32")
        ),
        "fused_mxu_int8": jax.jit(
            plan_callable_pallas(plan_mxu, mxu_stage="int8")
        ),
        "mxu_whole_op": jax.jit(lambda x: pipeline_mxu(pipe.ops, x)),
    }

    # -- bit-exactness gate before any timing (vs the golden chain) --------
    for th, tw, seed in ((48, 64, 1), (37, 200, 2), (130, 384, 3)):
        timg = jnp.asarray(synthetic_image(th, tw, channels=1, seed=seed))
        golden = np.asarray(pipe(timg))
        for lane, fn in lanes.items():
            got = np.asarray(fn(timg))
            if not np.array_equal(got, golden):
                raise AssertionError(
                    f"mxu_fused_ab gate: lane {lane!r} mismatches golden "
                    f"at {th}x{tw}"
                )

    img = jnp.asarray(
        synthetic_image(p["height"], p["width"], channels=1, seed=99)
    )
    mp = p["height"] * p["width"] / 1e6
    hbm_bytes = 2 * p["height"] * p["width"]  # one u8 read + one u8 write
    on_tpu = is_tpu_backend()
    gen = _tpu_gen() if on_tpu else None
    arms = {
        op.name: {
            "arm": stage_arm_for(op, width=p["width"], setting="on"),
            "int8_proven": mxu_int8_ok(op),
        }
        for op in pipe.ops
    }
    lane_recs: dict[str, dict] = {}
    for lane, fn in lanes.items():
        try:
            sec = device_throughput(fn, [img])
        except Exception as e:  # one lane failing must not kill the A/B
            lane_recs[lane] = {"error": str(e)[:200]}
            continue
        lr = {
            "ms_per_iter": sec * 1e3,
            "mp_per_s_per_chip": mp / sec,
            "hbm_gb_s_model": hbm_bytes / sec / 1e9,
        }
        if on_tpu:
            lr["roofline_frac"] = lr["hbm_gb_s_model"] / HBM_GB_S.get(
                gen, HBM_GB_S["v5e"]
            )
        lane_recs[lane] = lr
    ok = {k: v for k, v in lane_recs.items() if "error" not in v}

    def _speedup(a: str, b: str):  # lane a over lane b (>1: a faster)
        if a in ok and b in ok:
            return ok[b]["ms_per_iter"] / ok[a]["ms_per_iter"]
        return None

    mxu_lanes = [k for k in ("fused_mxu", "fused_mxu_int8") if k in ok]
    best_mxu = (
        min(mxu_lanes, key=lambda k: ok[k]["ms_per_iter"])
        if mxu_lanes else None
    )
    rec = {
        "config": MXU_FUSED_AB,
        "pipeline": p["ops"],
        "impl": "mxu_fused_ab",
        "platform": jax.default_backend(),
        "interpret_mode": not on_tpu,
        "height": p["height"],
        "width": p["width"],
        "bit_exact_gate": "passed (3 shapes x 5 lanes vs golden)",
        "lanes": lane_recs,
        "stage_arms": arms,
        "best_mxu_lane": best_mxu,
        "speedup_fused_mxu_vs_fused_vpu": (
            _speedup(best_mxu, "fused_vpu") if best_mxu else None
        ),
        "speedup_fused_mxu_f32_vs_fused_vpu": _speedup(
            "fused_mxu", "fused_vpu"
        ),
        "speedup_fused_mxu_int8_vs_f32": _speedup(
            "fused_mxu_int8", "fused_mxu"
        ),
        "speedup_fused_mxu_vs_whole_op": (
            _speedup(best_mxu, "mxu_whole_op") if best_mxu else None
        ),
    }
    if on_tpu:
        rec["tpu_gen"] = gen
    printer(
        f"{'lane':15s} {'ms/iter':>9s} {'MP/s/chip':>11s} {'roofline':>9s}"
    )
    for lane, lr in lane_recs.items():
        if "error" in lr:
            printer(f"{lane:15s} ERROR {lr['error'][:80]}")
            continue
        rl = (
            f"{lr['roofline_frac'] * 100:8.1f}%"
            if "roofline_frac" in lr
            else f"{'-':>9s}"
        )
        printer(
            f"{lane:15s} {lr['ms_per_iter']:9.3f} "
            f"{lr['mp_per_s_per_chip']:11.0f} {rl}"
        )
    for name, a in arms.items():
        printer(
            f"  op {name}: arm={a['arm']}"
            + (" (int8 proven)" if a["int8_proven"] else "")
        )
    sp = rec["speedup_fused_mxu_vs_fused_vpu"]
    if sp is not None:
        printer(
            f"fused-mxu ({best_mxu}) {sp:.2f}x vs fused-vpu"
            + (" (INTERPRET mode — gate record, not a perf claim)"
               if rec["interpret_mode"] else "")
        )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def tune_convergence_params() -> dict:
    """The autotune-convergence lane knobs: the pointwise-heavy headline
    chain (where fused-vs-off is a measured ~1.5x on CPU — the spread
    the controller must find), serving-bucket sized. Env overrides for
    tools/tpu_queue and tests: MCIM_TUNE_CONV_OPS/_HEIGHT/_WIDTH."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "grayscale,contrast:3.5,gaussian:5,quantize:6",
        "height": 2160 if on_tpu else 384,
        "width": 3840 if on_tpu else 384,
        "channels": 3,
        "batch": 4,
    }
    for env, key, cast in (
        ("MCIM_TUNE_CONV_OPS", "ops", str),
        ("MCIM_TUNE_CONV_HEIGHT", "height", int),
        ("MCIM_TUNE_CONV_WIDTH", "width", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    return params


def run_tune_convergence(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
) -> dict:
    """Online-autotuning convergence lane (tune/): the REAL control
    loop — TuneController + CanaryGate + OnlineStore — driven by real
    dispatch timings through the real serving executables, in one
    process with no sockets (the multi-process version is
    tools/tune_smoke.py; this lane measures the DYNAMICS):

      * converge_s / iters_to_converge — wall time and dispatches from
        "pinned to the slow plan, empty store" until the controller has
        explored `plan:fused` through the canary gate (real shadow
        comparisons against the incumbent's outputs) and promoted it;
      * tuned vs pinned — post-convergence device throughput on the
        promoted plan against the pinned `--plan off` baseline: the
        payoff the loop banked, in the same MP/s units as plan_ab.

    Bit-exactness is gated before any timing (fused output equals the
    off output on the bench batch), and the in-loop shadow spot-checks
    re-verify it the way the serving gate would."""
    import time as _time

    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.fabric.canary import (
        CANARY,
        CanaryConfig,
        CanaryGate,
    )
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
    from mpi_cuda_imagemanipulation_tpu.plan.ir import pipeline_fingerprint
    from mpi_cuda_imagemanipulation_tpu.serve.padded import make_serving_fn
    from mpi_cuda_imagemanipulation_tpu.tune.controller import (
        TuneConfig,
        TuneController,
    )
    from mpi_cuda_imagemanipulation_tpu.tune.store import OnlineStore

    p = tune_convergence_params()
    pipe = Pipeline.parse(p["ops"])
    B, H, W, C = p["batch"], p["height"], p["width"], p["channels"]
    fp = pipeline_fingerprint(pipe.ops)
    fns = {
        arm: make_serving_fn(pipe, H, W, C, B, plan=mode)
        for arm, mode in (("plan:off", "off"), ("plan:fused", "fused"))
    }
    imgs = np.stack(
        [synthetic_image(H, W, channels=C, seed=7 + i) for i in range(B)]
    )
    th = np.full((B,), H - 9, np.int32)
    tw = np.full((B,), W - 5, np.int32)

    # -- bit-exactness gate before any timing (and compile warmup) ---------
    outs = {}
    for arm, fn in fns.items():
        outs[arm] = np.asarray(jax.block_until_ready(fn(imgs, th, tw)))
    if not np.array_equal(outs["plan:off"], outs["plan:fused"]):
        raise AssertionError(
            "tune_convergence gate: fused output mismatches --plan off"
        )

    gate = CanaryGate(
        CanaryConfig(
            frac=0.25, min_requests=8, shadow_every=4, promote_requests=16
        )
    )
    canary_arm: dict = {"arm": None}

    def deploy(flip: dict) -> None:
        argv = flip["argv"]
        canary_arm["arm"] = "plan:" + argv[argv.index("--plan") + 1]
        gate.start("bench", flip)

    store = OnlineStore()  # in-memory unless MCIM_TUNE arms persistence
    ctl = TuneController(
        gate=gate,
        deploy=deploy,
        pipe_fp=fp,
        current_arm="plan:off",
        arms=("plan:off", "plan:fused"),
        registry=Registry(),
        store=store,
        config=TuneConfig(
            tick_s=0.05,
            min_samples=6,
            explore_c=0.35,
            min_gain=1.02,
            flip_timeout_s=600.0,
        ),
    )

    decisions: dict[str, int] = {}
    shadow_checks = 0
    max_iters = 3000
    iters = 0
    t0 = _time.perf_counter()
    while ctl.current_arm != "plan:fused" and iters < max_iters:
        iters += 1
        lane_arm, lane = ctl.current_arm, "stable"
        if gate.state == CANARY and gate.take_canary():
            lane_arm, lane = canary_arm["arm"], "canary"
        t1 = _time.perf_counter()
        out = jax.block_until_ready(fns[lane_arm](imgs, th, tw))
        dt = _time.perf_counter() - t1
        store.record_dispatch(fp, W, lane_arm, dt / B)
        if gate.state == CANARY:
            gate.record(lane, True)
            if lane == "canary" and gate.take_shadow():
                ref = np.asarray(
                    jax.block_until_ready(
                        fns[ctl.current_arm](imgs, th, tw)
                    )
                )
                shadow_checks += 1
                gate.record_shadow(np.array_equal(np.asarray(out), ref))
        d = ctl.tick()
        decisions[d] = decisions.get(d, 0) + 1
    converge_s = _time.perf_counter() - t0
    if ctl.current_arm != "plan:fused":
        raise AssertionError(
            f"tune_convergence: not converged after {iters} dispatches: "
            f"{ctl.status()}"
        )

    # -- the banked payoff: tuned throughput vs the pinned baseline --------
    mp = B * int(th[0]) * int(tw[0]) / 1e6
    tuned_sec = device_throughput(fns[ctl.current_arm], [imgs, th, tw])
    pinned_sec = device_throughput(fns["plan:off"], [imgs, th, tw])
    rec = {
        "config": TUNE_CONVERGENCE,
        "pipeline": p["ops"],
        "impl": "tune_convergence",
        "platform": jax.default_backend(),
        "height": H,
        "width": W,
        "channels": C,
        "batch": B,
        "bit_exact_gate": "passed (fused vs --plan off on the bench batch)",
        "converge_s": converge_s,
        "iters_to_converge": iters,
        "shadow_checks": shadow_checks,
        "decisions": decisions,
        "tuned_arm": ctl.current_arm,
        "tuned_ms_per_iter": tuned_sec * 1e3,
        "tuned_mp_per_s_per_chip": mp / tuned_sec,
        "pinned_off_ms_per_iter": pinned_sec * 1e3,
        "pinned_off_mp_per_s_per_chip": mp / pinned_sec,
        "speedup_tuned_vs_pinned_off": pinned_sec / tuned_sec,
    }
    if is_tpu_backend():
        rec["tpu_gen"] = _tpu_gen()
    printer(
        f"tune_convergence: converged to {ctl.current_arm} in "
        f"{converge_s:.1f}s / {iters} dispatches "
        f"({shadow_checks} shadow checks, decisions {decisions})"
    )
    printer(
        f"tuned {rec['tuned_ms_per_iter']:.3f} ms/iter vs pinned off "
        f"{rec['pinned_off_ms_per_iter']:.3f} ms/iter -> "
        f"{rec['speedup_tuned_vs_pinned_off']:.2f}x banked"
    )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def serve_loadgen_params() -> dict:
    """The serving-lane knobs, sized to the backend: CPU keeps the sweep
    small enough for tests/dev; real hardware gets serving-sized buckets
    and offered loads (override points for tools/tpu_queue via env:
    MCIM_SERVE_RPS as a comma list, MCIM_SERVE_DURATION_S)."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": "grayscale,contrast:3.5,emboss:3",
        "buckets": ((512, 512), (1024, 1024), (2048, 2048))
        if on_tpu
        else ((64, 64), (128, 128)),
        "max_batch": 8,
        "max_delay_ms": 4.0,
        "queue_depth": 256,
        # the sweep should cross saturation: the last rate must exceed the
        # single-dispatch service rate so queueing (and hence coalescing)
        # actually shows up in the occupancy column
        "offered_rps": (64.0, 256.0, 1024.0) if on_tpu else (50.0, 200.0, 800.0),
        "duration_s": 4.0 if on_tpu else 1.5,
        "n_images": 48,
        # availability lane: inject this transient dispatch-failure rate
        # (serve.dispatch failpoint) so the sweep reports success/retried/
        # shed fractions under faults; 0 = fault-free latency sweep
        "fault_rate": 0.0,
    }
    rps_env = env_registry.get("MCIM_SERVE_RPS")
    if rps_env:
        params["offered_rps"] = tuple(
            float(t) for t in rps_env.split(",") if t.strip()
        )
    dur_env = env_registry.get("MCIM_SERVE_DURATION_S")
    if dur_env:
        params["duration_s"] = float(dur_env)
    fault_env = env_registry.get("MCIM_SERVE_FAULT_RATE")
    if fault_env:
        params["fault_rate"] = float(fault_env)
    return params


def run_serve_loadgen(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
    fault_rate: float | None = None,
) -> dict:
    """The online-serving bench lane: stand up a ServeApp, sweep open-loop
    offered load, report throughput vs latency percentiles plus the
    batch-occupancy curve (serve/loadgen.py). With `fault_rate` (or
    MCIM_SERVE_FAULT_RATE) the sweep runs with that injected transient
    dispatch-failure rate and the table gains availability columns
    (success %, retried %). One record, `sweep` inside."""
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.serve import loadgen
    from mpi_cuda_imagemanipulation_tpu.serve.server import ServeApp, ServeConfig

    p = serve_loadgen_params()
    if fault_rate is not None:
        p["fault_rate"] = fault_rate
    # MCIM_TRACE_OUT: run the sweep traced (sample from MCIM_TRACE_SAMPLE,
    # default every request) and export the span timeline — per-rate
    # records then carry slowest_traces/failed_traces ids to pull p99
    # outliers up by id (serve/loadgen.py; the CI obs smoke lane uses this)
    trace_out = env_registry.get("MCIM_TRACE_OUT")
    if trace_out:
        obs_trace.configure(
            sample=float(env_registry.get(obs_trace.ENV_SAMPLE) or "1.0")
        )
    app = ServeApp(
        ServeConfig(
            ops=p["ops"],
            buckets=p["buckets"],
            max_batch=p["max_batch"],
            max_delay_ms=p["max_delay_ms"],
            queue_depth=p["queue_depth"],
            channels=(3,),
        )
    ).start()
    try:
        sweep = loadgen.sweep(
            app,
            offered_rps=p["offered_rps"],
            duration_s=p["duration_s"],
            n_images=p["n_images"],
            fault_rate=p["fault_rate"],
        )
    finally:
        app.stop(drain=True)
    rec = {
        "config": SERVE_LOADGEN,
        "pipeline": p["ops"],
        "impl": "xla",
        "platform": jax.default_backend(),
        "buckets": [f"{h}x{w}" for h, w in p["buckets"]],
        "max_batch": p["max_batch"],
        "max_delay_ms": p["max_delay_ms"],
        "queue_depth": p["queue_depth"],
        "fault_rate": p["fault_rate"],
        "cache": app.cache.stats(),
        "sweep": sweep,
    }
    if trace_out:
        rec["trace_out"] = trace_out
        rec["trace_events"] = obs_trace.export(trace_out)
    printer(
        f"{'offered rps':>11s} {'achieved':>9s} {'ok%':>6s} {'shed%':>6s} "
        f"{'retry%':>6s} {'occup':>6s} "
        f"{'p50 ms':>8s} {'p95 ms':>8s} {'p99 ms':>8s}"
    )
    for s in sweep:
        ex = s.get("p99_exemplar")
        printer(
            f"{s['offered_rps']:11.0f} {s['achieved_rps']:9.1f} "
            f"{s['ok_frac'] * 100:5.1f}% "
            f"{s['shed_frac'] * 100:5.1f}% "
            f"{s.get('retried_frac', 0.0) * 100:5.1f}% "
            f"{s.get('mean_batch_occupancy') or 0:6.2f} "
            f"{s.get('e2e_p50_ms', float('nan')):8.2f} "
            f"{s.get('e2e_p95_ms', float('nan')):8.2f} "
            f"{s.get('e2e_p99_ms', float('nan')):8.2f}"
            # the p99's exemplar trace id (obs/metrics.py histogram
            # exemplars): the outlier, pull-up-able by id in --trace-out
            + (f"  p99~{ex['trace_id']}" if ex else "")
        )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def graph_loadgen_params() -> dict:
    """The pipeline-service lane knobs, sized to the backend. The tenant
    count cycles the QoS classes (interactive/standard/batch), so the
    per-tenant columns show the admission ladder under one offered mix.
    Overrides: MCIM_GRAPH_TENANTS / the --tenants flag."""
    on_tpu = is_tpu_backend()
    params = {
        # a pointwise-heavy linear chain: the SAME workload runs as the
        # baked-in chain path and as a registered degenerate-DAG spec
        "ops": "grayscale,contrast:3.5,gaussian:5",
        "buckets": ((512, 512), (1024, 1024)) if on_tpu
        else ((64, 64), (96, 96)),
        "max_batch": 8 if on_tpu else 4,
        "max_delay_ms": 4.0,
        "queue_depth": 64,
        "offered_rps": 512.0 if on_tpu else 120.0,
        "duration_s": 3.0 if on_tpu else 1.5,
        "tenants": 3,
        "n_images": 8,
    }
    raw = env_registry.get("MCIM_GRAPH_TENANTS")
    if raw:
        params["tenants"] = int(raw)
    return params


def run_graph_loadgen(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
    tenants: int | None = None,
) -> dict:
    """The pipeline-service bench lane (graph/): ONE serving stack over
    real HTTP, the SAME linear chain driven down both doors —

      * ``chain`` — the baked-in `--ops` path (`POST /v1/process`);
      * ``dag``   — the identical chain registered as a degenerate-DAG
                    spec (`POST /v1/pipelines`) and served by pipeline id
                    (the graph lane: per-tenant admission + per-request
                    jitted graph executor, no micro-batching);

    gated BIT-IDENTICAL response bytes pre-timing (the acceptance
    contract: a linear DAG is indistinguishable from the chain), then
    measured under the same offered load — the dag column prices what
    "pipelines as data" costs over the baked-in path. A multi-tenant mix
    (``--tenants N``, QoS classes cycling interactive/standard/batch)
    rides the same stack and reports per-tenant ok% / shed% / p99 — the
    admission-ladder columns. Client and server share this process (and
    its GIL): both lanes pay identically, so the comparison is
    structure-vs-structure, not a throughput claim (the fabric lane's
    process split covers that)."""
    import json as _json
    import urllib.request

    from mpi_cuda_imagemanipulation_tpu.graph.spec import chain_as_spec
    from mpi_cuda_imagemanipulation_tpu.serve import loadgen
    from mpi_cuda_imagemanipulation_tpu.serve.server import (
        Server,
        ServeConfig,
    )

    p = graph_loadgen_params()
    if tenants is not None:
        p["tenants"] = tenants
    qos_cycle = ("interactive", "standard", "batch")
    with Server(
        ServeConfig(
            ops=p["ops"],
            buckets=p["buckets"],
            max_batch=p["max_batch"],
            max_delay_ms=p["max_delay_ms"],
            queue_depth=p["queue_depth"],
            channels=(3,),
        ),
        port=0,
    ) as srv:
        url = f"http://127.0.0.1:{srv.address[1]}"

        def post_json(path: str, payload: dict) -> dict:
            req = urllib.request.Request(
                url + path, data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                return _json.loads(resp.read())

        tenant_ids = [f"t{i}" for i in range(max(1, p["tenants"]))]
        for i, tid in enumerate(tenant_ids):
            post_json(
                "/v1/tenants",
                {"tenant": tid, "qos": qos_cycle[i % len(qos_cycle)]},
            )
            reg = post_json(
                "/v1/pipelines",
                {"tenant": tid, "spec": chain_as_spec(p["ops"])},
            )
        pid = reg["pipeline"]

        min_dim = max(op.halo for op in Pipeline.parse(p["ops"]).ops) + 1
        images = loadgen.mixed_shapes(
            p["buckets"], p["n_images"], channels=3, seed=11,
            min_dim=min_dim,
        )
        blobs = [bytes(loadgen.encode_blob(im)) for im in images]

        # -- bit-exactness gate BEFORE any timing --------------------------
        hdrs = {
            "X-MCIM-Tenant": tenant_ids[0], "X-MCIM-Pipeline": pid,
        }
        for k in range(min(3, len(blobs))):
            chain_r = loadgen.http_post_image(url, blobs[k])
            dag_r = loadgen.http_post_image(url, blobs[k], headers=hdrs)
            if chain_r["code"] != 200 or dag_r["code"] != 200:
                raise AssertionError(
                    f"graph_loadgen gate: image {k} answered "
                    f"{chain_r['code']}/{dag_r['code']}"
                )
            if chain_r["body"] != dag_r["body"]:
                raise AssertionError(
                    f"graph_loadgen gate: DAG response for image {k} is "
                    "not byte-identical to the chain path"
                )

        # -- the two lanes under the same offered load ---------------------
        chain_rec = loadgen.http_run_offered_load(
            url, blobs, p["offered_rps"], p["duration_s"]
        )
        chain_rec.pop("results", None)
        dag_rec = loadgen.multi_tenant_run(
            url,
            [{"tenant": tenant_ids[0], "blobs": blobs, "headers": hdrs}],
            p["offered_rps"],
            p["duration_s"],
        )[tenant_ids[0]]

        # -- the multi-tenant QoS mix --------------------------------------
        lanes = [
            {
                "tenant": tid,
                "blobs": blobs,
                "headers": {"X-MCIM-Tenant": tid, "X-MCIM-Pipeline": pid},
            }
            for tid in tenant_ids
        ]
        mix = loadgen.multi_tenant_run(
            url, lanes, p["offered_rps"], p["duration_s"]
        )
        graph_stats = srv.app.graph_service.stats()
    rec = {
        "config": GRAPH_LOADGEN,
        "pipeline": p["ops"],
        "impl": "graph_loadgen",
        "platform": jax.default_backend(),
        "buckets": [f"{h}x{w}" for h, w in p["buckets"]],
        "offered_rps": p["offered_rps"],
        "duration_s": p["duration_s"],
        "pipeline_id": pid,
        "bit_exact_gate": "passed (3 images, DAG bytes == chain bytes)",
        "lanes": {"chain": chain_rec, "dag": dag_rec},
        "tenants": {
            tid: {
                "qos": qos_cycle[i % len(qos_cycle)],
                **mix[tid],
            }
            for i, tid in enumerate(tenant_ids)
        },
        "cache_entries": sum(
            t["cache_entries"]
            for t in graph_stats["tenants"].values()
        ),
    }
    printer(
        f"{'lane':14s} {'ok%':>6s} {'shed%':>6s} {'achieved':>9s} "
        f"{'p50 ms':>8s} {'p99 ms':>8s}"
    )

    def _row(name: str, r: dict) -> str:
        return (
            f"{name:14s} {r['ok_frac'] * 100:5.1f}% "
            f"{r['shed_frac'] * 100:5.1f}% {r['achieved_rps']:9.1f} "
            f"{r.get('e2e_p50_ms', float('nan')):8.2f} "
            f"{r.get('e2e_p99_ms', float('nan')):8.2f}"
        )

    printer(_row("chain", chain_rec))
    printer(_row("dag", dag_rec))
    for i, tid in enumerate(tenant_ids):
        printer(
            _row(f"{tid}/{qos_cycle[i % len(qos_cycle)][:5]}", mix[tid])
        )
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def systolic_ab_params() -> dict:
    """The pod-level systolic A/B knobs: a chain LONG enough that
    stage-sharding it across two replicas is a real structural change
    (8 per-op steps, comfortably past the placement floor), every op
    streamable and channel-preserving so the program is
    systolic-eligible. Env overrides for tools/tpu_queue and tests:
    MCIM_SYSTOLIC_AB_OPS/_REQUESTS/_HEIGHT."""
    on_tpu = is_tpu_backend()
    params = {
        "ops": (
            "invert,gaussian:3,sharpen,box:3,quantize:6,"
            "gaussian:5,posterize:4,median"
        ),
        "height": 1024 if on_tpu else 72,
        "requests": 64 if on_tpu else 16,
        "channels": "3",
        "max_batch": 4,
        "max_delay_ms": 2.0,
        "queue_depth": 64,
        "heartbeat_s": 0.2,
    }
    for env, key, cast in (
        ("MCIM_SYSTOLIC_AB_OPS", "ops", str),
        ("MCIM_SYSTOLIC_AB_REQUESTS", "requests", int),
        ("MCIM_SYSTOLIC_AB_HEIGHT", "height", int),
    ):
        raw = env_registry.get(env)
        if raw:
            params[key] = cast(raw)
    params["width"] = params["height"]
    params["buckets"] = str(params["height"])
    return params


def run_systolic_ab(
    *,
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
) -> dict:
    """The pod-level systolic bench lane: the SAME >= 8-stage DAG
    pipeline driven through two pod shapes —

      * ``systolic`` — a real 2-replica pod with `--systolic` armed: the
        router stage-shards the registered program across both replicas
        and the live env streams replica-to-replica at every stage
        boundary (graph/systolic.py);
      * ``pinned``   — the identical 2-replica pod with the knob off:
        sticky affinity pins each request to ONE replica that walks all
        stages itself (the baseline every fallback degrades to);

    gated BIT-IDENTICAL pre-timing (both lanes' response bytes vs the
    in-process golden executor — the u8 exact-integer carry makes the
    cross-replica handoff lossless, so anything else is a bug, not a
    tolerance), then measured closed-loop over the same request count.
    After timing, the federated mcim_systolic_tiles_forwarded_total must
    read EXACTLY requests x stage boundaries — the transport mirror of
    the HLO collective-permute count, proving no request silently fell
    back to the pinned lane mid-measurement."""
    import json as _json
    import time as _time
    import urllib.request

    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.graph import (
        compile_graph,
        graph_callable,
        parse_spec,
    )
    from mpi_cuda_imagemanipulation_tpu.graph.spec import chain_as_spec
    from mpi_cuda_imagemanipulation_tpu.io.image import (
        decode_image_bytes,
        encode_image_bytes,
    )
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import parse_exposition
    from mpi_cuda_imagemanipulation_tpu.serve import loadgen

    p = systolic_ab_params()
    spec = chain_as_spec(p["ops"])
    n_steps = len(p["ops"].split(","))
    img = synthetic_image(p["height"], p["width"], channels=3, seed=23)
    blob = bytes(loadgen.encode_blob(np.asarray(img)))
    golden = np.asarray(
        graph_callable(compile_graph(parse_spec(spec)))(img)["image"]
    )

    def counter(fams: dict, name: str) -> float:
        fam = fams.get(name)
        if not fam:
            return 0.0
        return sum(fam["samples"].values())

    def run_lane(systolic: bool) -> tuple[dict, bytes, dict]:
        extra = ("--systolic",) if systolic else ()
        with _FabricProc(p, 2, extra_args=extra) as fab:
            fab.wait_routable(2)
            if systolic:
                # placement needs BOTH replicas advertising stage
                # ownership (heartbeats) before the first dispatch, or
                # early requests fall back and the one-forward-per-
                # boundary accounting below goes soft
                deadline = _time.monotonic() + 60.0
                while _time.monotonic() < deadline:
                    reps = fab.stats()["replicas"]
                    if sum(
                        1 for r in reps.values()
                        if r["fresh"] and r["systolic"]
                    ) >= 2:
                        break
                    _time.sleep(0.2)
            req = urllib.request.Request(
                fab.url + "/v1/pipelines",
                data=_json.dumps(
                    {"tenant": "acme", "spec": spec}
                ).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                pid = _json.loads(resp.read())["pipeline"]
            hdrs = {"X-MCIM-Tenant": "acme", "X-MCIM-Pipeline": pid}

            # -- bit-exactness gate BEFORE any timing (also the warmup:
            # the owners compile their stage ranges here) ---------------
            n_sent = 0
            deadline = _time.monotonic() + 120.0
            while True:
                gate = loadgen.http_post_image(fab.url, blob, headers=hdrs)
                n_sent += 1
                if gate["code"] == 200:
                    break
                if _time.monotonic() > deadline:
                    raise AssertionError(
                        f"systolic_ab gate: lane "
                        f"{'systolic' if systolic else 'pinned'} never "
                        f"answered 200 (last {gate['code']})"
                    )
                _time.sleep(0.2)
            np.testing.assert_array_equal(
                decode_image_bytes(gate["body"]), golden,
                err_msg="systolic_ab gate: response is not bit-exact "
                "against the in-process golden executor",
            )
            if systolic:
                pl = fab.stats()["systolic"]["placements"].get(pid)
                if not pl or len(pl["ranges"]) < 2:
                    raise AssertionError(
                        f"systolic_ab: program was never stage-sharded "
                        f"(placement {pl})"
                    )
                if len(set(pl["owners"])) < 2:
                    raise AssertionError(
                        f"systolic_ab: both ranges landed on one "
                        f"replica ({pl['owners']})"
                    )
            else:
                pl = None

            # -- the timed closed loop ----------------------------------
            results = []
            t0 = _time.monotonic()
            for _ in range(p["requests"]):
                r = loadgen.http_post_image(fab.url, blob, headers=hdrs)
                if r["code"] == 200 and r["body"] != gate["body"]:
                    raise AssertionError(
                        "systolic_ab: a response drifted mid-run"
                    )
                results.append((0, r))
                n_sent += 1
            wall = _time.monotonic() - t0
            rec = loadgen.summarize_http_results(
                results, wall, len(results) / wall if wall else 0.0
            )

            extras: dict = {}
            if systolic:
                # exactly one transport forward per stage boundary, for
                # EVERY request this lane sent (gate included) — counted
                # federated, so give the last heartbeat time to land
                boundaries = len(pl["ranges"]) - 1
                expect = n_sent * boundaries
                deadline = _time.monotonic() + 60.0
                while True:
                    with urllib.request.urlopen(
                        fab.url + "/metrics", timeout=10.0
                    ) as resp:
                        fams = parse_exposition(resp.read().decode())
                    forwards = counter(
                        fams, "mcim_systolic_tiles_forwarded_total"
                    )
                    if forwards >= expect:
                        break
                    if _time.monotonic() > deadline:
                        raise AssertionError(
                            f"systolic_ab: {forwards:.0f} transport "
                            f"forwards for {n_sent} requests x "
                            f"{boundaries} boundaries — some requests "
                            "fell back mid-measurement"
                        )
                    _time.sleep(0.2)
                if forwards != expect:
                    raise AssertionError(
                        f"systolic_ab: {forwards:.0f} forwards != "
                        f"{n_sent} requests x {boundaries} boundaries"
                    )
                extras = {
                    "placement": pl,
                    "requests_sent": n_sent,
                    "stage_boundaries": boundaries,
                    "forwards": forwards,
                    "forwards_per_request": forwards / n_sent,
                    "exchange_bytes_per_request": counter(
                        fams, "mcim_systolic_exchange_bytes_total"
                    ) / n_sent,
                }
            return rec, gate["body"], extras

    sys_rec, sys_body, sys_extras = run_lane(True)
    pin_rec, pin_body, _ = run_lane(False)
    if sys_body != pin_body:
        raise AssertionError(
            "systolic_ab: systolic and pinned response bytes differ — "
            "the cross-replica handoff is NOT lossless"
        )
    speedup = (
        sys_rec["achieved_rps"] / pin_rec["achieved_rps"]
        if pin_rec["achieved_rps"]
        else None
    )
    rec = {
        "config": SYSTOLIC_AB,
        "pipeline": p["ops"],
        "impl": "systolic_ab",
        "platform": jax.default_backend(),
        "height": p["height"],
        "width": p["width"],
        "requests": p["requests"],
        "stages": n_steps,
        "bit_exact_gate": (
            "passed (systolic bytes == pinned bytes == in-process golden)"
        ),
        "lanes": {"systolic": sys_rec, "pinned": pin_rec},
        **sys_extras,
        "speedup_systolic_vs_pinned": speedup,
    }
    printer(
        f"{'lane':10s} {'ok%':>6s} {'req/s':>8s} "
        f"{'p50 ms':>8s} {'p99 ms':>8s}"
    )
    for name, lr in (("systolic", sys_rec), ("pinned", pin_rec)):
        printer(
            f"{name:10s} {lr['ok_frac'] * 100:5.1f}% "
            f"{lr['achieved_rps']:8.1f} "
            f"{lr.get('e2e_p50_ms', float('nan')):8.2f} "
            f"{lr.get('e2e_p99_ms', float('nan')):8.2f}"
        )
    pl = sys_extras["placement"]
    printer(
        f"placed {pl['ranges']} on {pl['owners']} ({pl['source']}); "
        f"{sys_extras['forwards']:.0f} forwards / "
        f"{sys_extras['requests_sent']} requests == "
        f"{sys_extras['stage_boundaries']} per request, "
        f"{sys_extras['exchange_bytes_per_request']:.0f} exchange "
        "bytes/request"
    )
    if speedup is not None:
        printer(f"systolic vs pinned: {speedup:.2f}x achieved req/s")
    if json_path:
        emit_json_metrics(rec, None if json_path == "-" else json_path)
    return rec


def run_suite(
    names: Sequence[str] | None = None,
    *,
    impl: str = "both",
    json_path: str | None = None,
    printer: Callable[[str], None] = print,
    halo_mode: str | None = None,
) -> list[dict]:
    log = get_logger()
    impls = ("xla", "pallas") if impl == "both" else (impl,)
    records: list[dict] = []
    if names and SERVE_LOADGEN in names:
        # the serving lane is not a BenchConfig (it measures a queueing
        # system, not one executable) — run it on the side and keep going
        names = [n for n in names if n != SERVE_LOADGEN]
        records.append(
            run_serve_loadgen(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names and ENGINE_AB in names:
        # likewise the engine lane: it measures the e2e decode/dispatch/
        # encode pipeline, not one executable
        names = [n for n in names if n != ENGINE_AB]
        records.append(run_engine_ab(json_path=json_path, printer=printer))
        if not names:
            return records
    if names and MXU_AB in names:
        # the MXU lane compares three formulations of one workload, so it
        # owns its own impl axis rather than riding the suite's
        names = [n for n in names if n != MXU_AB]
        records.append(run_mxu_ab(json_path=json_path, printer=printer))
        if not names:
            return records
    if names and FABRIC_LOADGEN in names:
        # the fabric lane measures a multi-process pod (router + replica
        # workers + churn), not one executable
        names = [n for n in names if n != FABRIC_LOADGEN]
        records.append(
            run_fabric_loadgen(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names and STREAM_AB in names:
        # the stream lane compares two execution structures (whole-image
        # vs tiled stream) over one workload, like engine_ab
        names = [n for n in names if n != STREAM_AB]
        records.append(run_stream_ab(json_path=json_path, printer=printer))
        if not names:
            return records
    if names and PLAN_AB in names:
        # the plan lane compares execution STRUCTURES of one chain
        # (per-op vs pointwise-absorbed vs temporally blocked), so it
        # owns its own lane axis like mxu_ab
        names = [n for n in names if n != PLAN_AB]
        records.append(run_plan_ab(json_path=json_path, printer=printer))
        if not names:
            return records
    if names and MEGAKERNEL_AB in names:
        # the megakernel lane compares the fused-XLA stage walker against
        # the fused-pallas VMEM megakernel over one chain, like plan_ab
        names = [n for n in names if n != MEGAKERNEL_AB]
        records.append(
            run_megakernel_ab(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names and MXU_FUSED_AB in names:
        # the in-stage-MXU lane compares execution ARMS of one megakernel
        # stage (VPU walk vs f32/int8 dot contraction) plus the whole-op
        # MXU baseline, so it owns its own lane axis like megakernel_ab
        names = [n for n in names if n != MXU_FUSED_AB]
        records.append(
            run_mxu_fused_ab(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names and GRAPH_LOADGEN in names:
        # the pipeline-service lane measures the graph door vs the chain
        # door of one serving stack (plus the multi-tenant mix), not one
        # executable
        names = [n for n in names if n != GRAPH_LOADGEN]
        records.append(
            run_graph_loadgen(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names and SYSTOLIC_AB in names:
        # the systolic lane measures two whole-pod structures (stage-
        # sharded vs pinned) over one DAG, not one executable
        names = [n for n in names if n != SYSTOLIC_AB]
        records.append(
            run_systolic_ab(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names and FEDERATION_LOADGEN in names:
        # the federation lane measures a two-pod topology behind the
        # front door (whole-pod SIGKILL mid-sweep), not one executable
        names = [n for n in names if n != FEDERATION_LOADGEN]
        records.append(
            run_federation_loadgen(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names and TUNE_CONVERGENCE in names:
        # the tune lane measures the closed control loop (controller +
        # canary gate over real dispatch timings) converging onto the
        # measured-faster plan, not one executable
        names = [n for n in names if n != TUNE_CONVERGENCE]
        records.append(
            run_tune_convergence(json_path=json_path, printer=printer)
        )
        if not names:
            return records
    if names:
        unknown = [n for n in names if n not in CONFIGS]
        if unknown:
            raise ValueError(
                f"unknown bench config(s) {unknown}; known: "
                f"{sorted(CONFIGS) + [ENGINE_AB, FABRIC_LOADGEN, FEDERATION_LOADGEN, GRAPH_LOADGEN, MEGAKERNEL_AB, MXU_AB, MXU_FUSED_AB, PLAN_AB, SERVE_LOADGEN, STREAM_AB, SYSTOLIC_AB, TUNE_CONVERGENCE]}"
            )
        selected = [CONFIGS[n] for n in names]
    else:
        selected = list(CONFIGS.values())
    if halo_mode is not None:  # CLI override for A/B runs
        selected = [
            dataclasses.replace(c, halo_mode=halo_mode) if c.sharded else c
            for c in selected
        ]
    printer(
        f"{'config':26s} {'impl':7s} {'chips':>5s} {'ms/iter':>9s} "
        f"{'MP/s':>10s} {'MP/s/chip':>10s} {'roofline':>9s}"
    )
    for cfg in selected:
        for im in impls:
            try:
                rec = run_config(cfg, im)
            except Exception as e:  # keep the suite running past one failure
                log.warning("config %s impl %s failed: %s", cfg.name, im, e)
                continue
            records.append(rec)
            rl = (
                f"{rec['roofline_frac'] * 100:8.1f}%"
                if "roofline_frac" in rec
                else f"{'-':>9s}"
            )
            printer(
                f"{rec['config']:26s} {rec['impl']:7s} {rec['chips']:5d} "
                f"{rec['ms_per_iter']:9.3f} {rec['mp_per_s']:10.0f} "
                f"{rec['mp_per_s_per_chip']:10.0f} {rl}"
            )
            if json_path:
                emit_json_metrics(rec, None if json_path == "-" else json_path)
    return records


def headline_record(records: list[dict]) -> dict | None:
    """The BASELINE.json headline: best MP/s/chip on 8K 5x5 Gaussian.

    Both execution strategies for that workload qualify (single-chip and the
    row-sharded ppermute path — on a pod the sharded one is the relevant
    run); the record names which impl/chip-count won.
    """
    cands = [
        r for r in records if r["config"] in (HEADLINE, HEADLINE + "_sharded")
    ]
    if not cands:
        return None
    best = max(cands, key=lambda r: r["mp_per_s_per_chip"])
    rec = {
        "metric": "megapixels/sec/chip on 8K 5x5 Gaussian",
        "value": round(best["mp_per_s_per_chip"], 1),
        "unit": "MP/s/chip",
        "impl": best["impl"],
        "chips": best["chips"],
        "platform": best.get("platform"),
    }
    # measured-ceiling fraction leads (VERDICT r4 #7): it rests on a
    # measured same-chip reference rate, while vs_baseline divides by a
    # first-principles ESTIMATE of the reference's hardware (BASELINE.md)
    # — lead with the number that doesn't require trusting the estimate.
    # Round-5 re-basing: the roofline RR probe measured u8 COPY kernels at
    # ~550 GB/s, so this is NOT a hardware element-rate wall — it is the
    # best observed u8 compute-kernel-class rate (the kernels are
    # VPU-compute-bound; BASELINE.md round-5 section), kept as the
    # same-class measured reference point
    if "elem_ceiling_frac" in best:
        rec["ceiling_frac"] = round(best["elem_ceiling_frac"], 4)
        rec["ceiling_basis"] = (
            "measured u8 compute-kernel element rate (roofline probe; "
            "bench_suite.ELEM_G_S_MEASURED — a kernel-class reference, "
            "not a hardware wall: u8 copy measures ~550 GB/s)"
        )
    rec["vs_baseline"] = round(
        best["mp_per_s_per_chip"] / REFERENCE_BASELINE_MP_S_PER_CHIP, 2
    )
    if "roofline_frac" in best:
        rec["roofline_frac"] = round(best["roofline_frac"], 4)
        rec["tpu_gen"] = best.get("tpu_gen")
    if "elem_ceiling_frac" in best:
        rec["elem_ceiling_frac"] = round(best["elem_ceiling_frac"], 4)
    return rec


def main(argv: Sequence[str] | None = None) -> int:
    """Single-config worker: run ONE (config, impl) in this process and print
    exactly one JSON line. bench.py launches this in a subprocess per config
    so a Mosaic crash or a wedged TPU tunnel loses that config's record, not
    the whole suite (the round-1 failure mode, VERDICT.md)."""
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="bench_suite")
    ap.add_argument(
        "--config",
        required=True,
        choices=sorted(CONFIGS)
        + [ENGINE_AB, FABRIC_LOADGEN, GRAPH_LOADGEN, MEGAKERNEL_AB, MXU_AB,
           MXU_FUSED_AB, PLAN_AB, SERVE_LOADGEN, STREAM_AB, SYSTOLIC_AB,
           TUNE_CONVERGENCE],
    )
    ap.add_argument(
        "--impl",
        default="pallas",
        choices=("xla", "pallas", "swar", "mxu", "auto"),
    )
    ap.add_argument(
        "--halo-mode",
        default=None,
        choices=("serial", "overlap"),
        help="override the config's sharded halo execution mode",
    )
    ap.add_argument(
        "--shards",
        type=int,
        default=None,
        help="mesh size for sharded configs (default: every visible "
        "device) — the serial-vs-overlap A/B sweeps this",
    )
    ap.add_argument(
        "--fault-rate",
        type=float,
        default=None,
        help="serve_loadgen only: inject this transient dispatch-failure "
        "rate (serve.dispatch failpoint) so the sweep reports "
        "availability (success/retried/shed fractions) alongside the "
        "latency percentiles; env MCIM_SERVE_FAULT_RATE works too",
    )
    ap.add_argument(
        "--inflight",
        type=int,
        default=None,
        help="engine_ab only: overlapped-lane dispatch depth "
        "(env MCIM_ENGINE_AB_INFLIGHT works too)",
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=None,
        help="fabric_loadgen only: scaled-lane replica count "
        "(env MCIM_FABRIC_REPLICAS works too)",
    )
    ap.add_argument(
        "--tile-rows",
        type=int,
        default=None,
        help="stream_ab only: streamed-lane tile height "
        "(env MCIM_STREAM_AB_TILE_ROWS works too)",
    )
    ap.add_argument(
        "--tenants",
        type=int,
        default=None,
        help="graph_loadgen only: multi-tenant mix size, QoS classes "
        "cycling interactive/standard/batch "
        "(env MCIM_GRAPH_TENANTS works too)",
    )
    ap.add_argument(
        "--json-metrics",
        default=None,
        help="also write the record to this path ('-' = stdout); the "
        "one JSON line always goes to stdout regardless",
    )
    args = ap.parse_args(argv)
    if args.config == SERVE_LOADGEN:
        rec = run_serve_loadgen(
            printer=lambda s: None, fault_rate=args.fault_rate
        )
    elif args.config == FABRIC_LOADGEN:
        rec = run_fabric_loadgen(
            printer=lambda s: None, replicas=args.replicas
        )
    elif args.config == ENGINE_AB:
        rec = run_engine_ab(printer=lambda s: None, inflight=args.inflight)
    elif args.config == MXU_AB:
        rec = run_mxu_ab(printer=lambda s: None)
    elif args.config == STREAM_AB:
        rec = run_stream_ab(
            printer=lambda s: None, tile_rows=args.tile_rows
        )
    elif args.config == PLAN_AB:
        rec = run_plan_ab(printer=lambda s: None)
    elif args.config == MEGAKERNEL_AB:
        rec = run_megakernel_ab(printer=lambda s: None)
    elif args.config == MXU_FUSED_AB:
        rec = run_mxu_fused_ab(printer=lambda s: None)
    elif args.config == GRAPH_LOADGEN:
        rec = run_graph_loadgen(
            printer=lambda s: None, tenants=args.tenants
        )
    elif args.config == SYSTOLIC_AB:
        rec = run_systolic_ab(printer=lambda s: None)
    elif args.config == TUNE_CONVERGENCE:
        rec = run_tune_convergence(printer=lambda s: None)
    else:
        cfg = CONFIGS[args.config]
        if args.halo_mode is not None and cfg.sharded:
            cfg = dataclasses.replace(cfg, halo_mode=args.halo_mode)
        rec = run_config(cfg, args.impl, n_shards=args.shards)
    if args.json_metrics and args.json_metrics != "-":
        emit_json_metrics(rec, args.json_metrics)
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
