"""Metrics registry — counters/gauges/histograms, one naming scheme,
Prometheus text exposition.

Before this module the repo had three disconnected metric islands
(`serve/metrics.py`, `engine/metrics.py`, ad-hoc dicts in the bench
lanes), each with its own counters and reporting conventions. Here there
is ONE registry per process (or per ServeApp — the registry is an
instance, so tests compose freely): every subsystem registers its metrics
into it, `render()` emits Prometheus text exposition format 0.0.4 for
`GET /metrics` / `--metrics-out`, and `/stats` is a *view* over the same
objects — the two can never drift.

Naming scheme (docs/design.md "Observability"):

    mcim_<subsystem>_<what>[_total|_seconds]{label="value"}

  * prefix `mcim_`; subsystem in {serve, engine, cache, breaker, health,
    batch, fabric, stream};
  * counters end `_total` and only go up; durations are SECONDS with a
    `_seconds` suffix (never ms — the exposition consumer rescales);
  * statuses/stages/buckets are LABELS, not name suffixes, so one family
    aggregates across them.

Histograms keep both the Prometheus cumulative buckets AND a bounded
reservoir of recent samples — the buckets feed scraping, the reservoir
feeds the exact p50/p95/p99 the `/stats` payload and shutdown summaries
always reported (`utils.timing.percentiles`, the same quantile definition
the bench suite uses). A serving process must not grow memory with request
count: the reservoir is a `deque(maxlen=sample_cap)` and label
cardinality is bounded by the callers (buckets and statuses are finite
sets by construction).

`parse_exposition()` is the matching parser — tests and the CI smoke lane
use it to assert `/metrics` actually parses as exposition text.
"""

from __future__ import annotations

import threading
from collections import deque

from mpi_cuda_imagemanipulation_tpu.utils.timing import percentiles

# latency-in-seconds buckets: 1 ms .. 10 s, roughly log-spaced — covers
# both CPU-smoke and real-chip serving latencies
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

PERCENTILES = (50, 95, 99)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _label_str(names: tuple[str, ...], values: tuple[str, ...],
               extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [
        f'{n}="{_escape_label(str(v))}"' for n, v in zip(names, values)
    ] + [f'{n}="{_escape_label(str(v))}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    """Shared labeled-value storage: {label-values-tuple: float}."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}

    def _key(self, labels: dict) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def values(self) -> dict[tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            lines.append(
                f"{self.name}{_label_str(self.label_names, key)} "
                f"{_fmt_value(v)}"
            )
        return lines


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (inc {n})")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help, labels=(), fn=None):
        super().__init__(name, help, labels)
        # callback gauge: `fn()` -> value (unlabeled) or {labels: value};
        # evaluated at render/value time so the scrape always sees the
        # live state (breaker boards, health machine, cache stats)
        self._fn = fn

    def set(self, v: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(v)

    def inc(self, n: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def set_max(self, v: float, **labels) -> None:
        """Monotone high-water update (peak gauges), atomic under the
        metric lock."""
        key = self._key(labels)
        with self._lock:
            self._values[key] = max(self._values.get(key, 0.0), float(v))

    def _eval_fn(self) -> None:
        if self._fn is None:
            return
        got = self._fn()
        with self._lock:
            if isinstance(got, dict):
                self._values = {
                    (k,) if isinstance(k, str) else tuple(map(str, k)): float(v)
                    for k, v in got.items()
                }
            else:
                self._values = {(): float(got)}

    def value(self, **labels) -> float:
        self._eval_fn()
        return super().value(**labels)

    def values(self) -> dict[tuple[str, ...], float]:
        self._eval_fn()
        return super().values()

    def render(self) -> list[str]:
        self._eval_fn()
        return super().render()


class Histogram:
    """Prometheus histogram + bounded percentile reservoir.

    One instance carries every label combination (like Counter/Gauge);
    each combination owns cumulative bucket counts, sum, count, and a
    recent-sample deque for exact percentiles."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labels: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                 sample_cap: int = 65536):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self.buckets = tuple(sorted(buckets))
        self.sample_cap = sample_cap
        self._lock = threading.Lock()
        # key -> [bucket_counts list, sum, count, reservoir deque]
        self._series: dict[tuple[str, ...], list] = {}

    def _key(self, labels: dict) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def _cell(self, key):
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = [
                [0] * len(self.buckets), 0.0, 0,
                deque(maxlen=self.sample_cap),
            ]
        return s

    def observe(self, v: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts, _sum, _n, reservoir = self._cell(key)
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    counts[i] += 1
            s = self._series[key]
            s[1] = _sum + v
            s[2] = _n + 1
            reservoir.append(v)

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(self._key(labels))
            return s[2] if s else 0

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(self._key(labels))
            return s[1] if s else 0.0

    def samples(self, **labels) -> list[float]:
        with self._lock:
            s = self._series.get(self._key(labels))
            return list(s[3]) if s else []

    def percentiles_ms(self, qs=PERCENTILES, **labels) -> dict | None:
        """`{"p50_ms": ...}` over the recent reservoir — the exact
        percentile view /stats and the shutdown summaries report
        (same definition as the bench suite: utils.timing.percentiles)."""
        xs = self.samples(**labels)
        if not xs:
            return None
        got = percentiles(xs, qs)
        return {f"p{int(q)}_ms": got[q] * 1e3 for q in qs}

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            series = {
                k: (list(s[0]), s[1], s[2]) for k, s in self._series.items()
            }
        for key in sorted(series):
            counts, total, n = series[key]
            for i, ub in enumerate(self.buckets):
                ls = _label_str(
                    self.label_names, key, (("le", _fmt_value(ub)),)
                )
                lines.append(f"{self.name}_bucket{ls} {counts[i]}")
            inf_ls = _label_str(self.label_names, key, (("le", "+Inf"),))
            lines.append(f"{self.name}_bucket{inf_ls} {n}")
            plain = _label_str(self.label_names, key)
            lines.append(f"{self.name}_sum{plain} {repr(float(total))}")
            lines.append(f"{self.name}_count{plain} {n}")
        return lines


class Registry:
    """One process's (or one ServeApp's) metric namespace. Registering an
    existing name returns the existing metric — subsystems that share a
    registry share the family (that is the point)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _register(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type/labels"
                    )
                return m
            m = self._metrics[name] = cls(name, help, labels, **kw)
            return m

    def counter(self, name: str, help: str,
                labels: tuple[str, ...] = ()) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str, labels: tuple[str, ...] = (),
              fn=None) -> Gauge:
        return self._register(Gauge, name, help, labels, fn=fn)

    def histogram(self, name: str, help: str,
                  labels: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  sample_cap: int = 65536) -> Histogram:
        return self._register(
            Histogram, name, help, labels, buckets=buckets,
            sample_cap=sample_cap,
        )

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4 (the `GET /metrics`
        body / `--metrics-out` snapshot)."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse Prometheus text exposition into
    `{family: {"type": str, "help": str, "samples": {(name, labelstr): value}}}`.
    Raises ValueError on malformed lines — the CI smoke lane's
    "/metrics parses" assertion."""
    families: dict[str, dict] = {}

    def fam(name: str) -> dict:
        return families.setdefault(
            name, {"type": "untyped", "help": "", "samples": {}}
        )

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            fam(name)["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE {kind!r}")
            fam(name)["type"] = kind
            continue
        if line.startswith("#"):
            continue
        # sample line: name[{labels}] value
        if "{" in line:
            name, _, rest = line.partition("{")
            labels, sep, val_part = rest.rpartition("} ")
            if not sep:
                raise ValueError(f"line {lineno}: unterminated labels")
            labelstr = labels
            value_str = val_part.strip().split()[0]
        else:
            parts = line.split()
            if len(parts) < 2:
                raise ValueError(f"line {lineno}: expected 'name value'")
            name, value_str = parts[0], parts[1]
            labelstr = ""
        try:
            value = float(value_str)
        except ValueError:
            raise ValueError(
                f"line {lineno}: unparsable value {value_str!r}"
            ) from None
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in families:
                base = name[: -len(suffix)]
                break
        fam(base)["samples"][(name, labelstr)] = value
    return families
