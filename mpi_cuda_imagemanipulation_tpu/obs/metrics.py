"""Metrics registry — counters/gauges/histograms, one naming scheme,
Prometheus text exposition.

Before this module the repo had three disconnected metric islands
(`serve/metrics.py`, `engine/metrics.py`, ad-hoc dicts in the bench
lanes), each with its own counters and reporting conventions. Here there
is ONE registry per process (or per ServeApp — the registry is an
instance, so tests compose freely): every subsystem registers its metrics
into it, `render()` emits Prometheus text exposition format 0.0.4 for
`GET /metrics` / `--metrics-out`, and `/stats` is a *view* over the same
objects — the two can never drift.

Naming scheme (docs/design.md "Observability"):

    mcim_<subsystem>_<what>[_total|_seconds]{label="value"}

  * prefix `mcim_`; subsystem in {serve, engine, cache, breaker, health,
    batch, fabric, stream};
  * counters end `_total` and only go up; durations are SECONDS with a
    `_seconds` suffix (never ms — the exposition consumer rescales);
  * statuses/stages/buckets are LABELS, not name suffixes, so one family
    aggregates across them.

Histograms keep both the Prometheus cumulative buckets AND a bounded
reservoir of recent samples — the buckets feed scraping, the reservoir
feeds the exact p50/p95/p99 the `/stats` payload and shutdown summaries
always reported (`utils.timing.percentiles`, the same quantile definition
the bench suite uses). A serving process must not grow memory with request
count: the reservoir is a `deque(maxlen=sample_cap)` and label
cardinality is bounded by the callers (buckets and statuses are finite
sets by construction).

Histograms also carry **exemplars** (docs/design.md "Fleet
observability"): `observe(v, exemplar=trace_id)` remembers the most
recent (trace_id, value, ts) per bucket, rendered OpenMetrics-style after
the bucket line (`... # {trace_id="..."} value ts`). A p99 spike in the
exposition therefore links directly to a concrete trace in the Perfetto
export instead of being an anonymous count — `exemplar_for_quantile(99)`
is the programmatic version the loadgen/bench reports use.

`parse_exposition()` is the matching parser — tests and the CI smoke lane
use it to assert `/metrics` actually parses as exposition text. It
tokenizes label blocks with full escape handling (`\\`, `\"`, `\n` in
label values), so render→parse round-trips even adversarial values, and
captures exemplars per sample.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from mpi_cuda_imagemanipulation_tpu.utils.timing import percentiles

# latency-in-seconds buckets: 1 ms .. 10 s, roughly log-spaced — covers
# both CPU-smoke and real-chip serving latencies
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

PERCENTILES = (50, 95, 99)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    # HELP text escapes only backslash and newline (the exposition spec);
    # quotes are legal there
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _unescape(v: str) -> str:
    """Inverse of `_escape_label`/`_escape_help` (one pass, so '\\\\n'
    round-trips as backslash + n, not newline)."""
    out: list[str] = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _label_str(names: tuple[str, ...], values: tuple[str, ...],
               extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [
        f'{n}="{_escape_label(str(v))}"' for n, v in zip(names, values)
    ] + [f'{n}="{_escape_label(str(v))}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    """Shared labeled-value storage: {label-values-tuple: float}."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}

    def _key(self, labels: dict) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def values(self) -> dict[tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            lines.append(
                f"{self.name}{_label_str(self.label_names, key)} "
                f"{_fmt_value(v)}"
            )
        return lines


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (inc {n})")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help, labels=(), fn=None):
        super().__init__(name, help, labels)
        # callback gauge: `fn()` -> value (unlabeled) or {labels: value};
        # evaluated at render/value time so the scrape always sees the
        # live state (breaker boards, health machine, cache stats)
        self._fn = fn

    def set(self, v: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(v)

    def inc(self, n: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def set_max(self, v: float, **labels) -> None:
        """Monotone high-water update (peak gauges), atomic under the
        metric lock."""
        key = self._key(labels)
        with self._lock:
            self._values[key] = max(self._values.get(key, 0.0), float(v))

    def _eval_fn(self) -> None:
        if self._fn is None:
            return
        got = self._fn()
        with self._lock:
            if isinstance(got, dict):
                self._values = {
                    (k,) if isinstance(k, str) else tuple(map(str, k)): float(v)
                    for k, v in got.items()
                }
            else:
                self._values = {(): float(got)}

    def value(self, **labels) -> float:
        self._eval_fn()
        return super().value(**labels)

    def values(self) -> dict[tuple[str, ...], float]:
        self._eval_fn()
        return super().values()

    def render(self) -> list[str]:
        self._eval_fn()
        return super().render()


def _fmt_exemplar(ex: tuple[str, float, float] | None) -> str:
    """OpenMetrics exemplar suffix for a bucket line, or ''. Our
    `parse_exposition` reads these back; 0.0.4-only scrapers treat the
    trailing ` # ...` as the OpenMetrics spec defines (an exemplar), and
    plain-text consumers ignore everything after the value."""
    if ex is None:
        return ""
    trace_id, value, ts = ex
    return (
        f' # {{trace_id="{_escape_label(trace_id)}"}} '
        f"{_fmt_value(value)} {repr(float(ts))}"
    )


class Histogram:
    """Prometheus histogram + bounded percentile reservoir.

    One instance carries every label combination (like Counter/Gauge);
    each combination owns cumulative bucket counts, sum, count, and a
    recent-sample deque for exact percentiles."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labels: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                 sample_cap: int = 65536):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self.buckets = tuple(sorted(buckets))
        self.sample_cap = sample_cap
        self._lock = threading.Lock()
        # key -> [bucket_counts list, sum, count, reservoir deque]
        self._series: dict[tuple[str, ...], list] = {}

    def _key(self, labels: dict) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def _cell(self, key):
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = [
                [0] * len(self.buckets), 0.0, 0,
                deque(maxlen=self.sample_cap),
                # per-bucket exemplar slots (last = +Inf): the most recent
                # (trace_id, value, unix_ts) observed into that bucket
                [None] * (len(self.buckets) + 1),
            ]
        return s

    def _bucket_index(self, v: float) -> int:
        """Index of the FIRST bucket containing v (len(buckets) = +Inf)."""
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                return i
        return len(self.buckets)

    def observe(self, v: float, *, exemplar: str | None = None,
                **labels) -> None:
        """Record one observation. `exemplar` attaches a trace id to the
        observation's bucket — the exposition then links that bucket (and
        any percentile that lands in it) to a concrete trace."""
        key = self._key(labels)
        with self._lock:
            s = self._cell(key)
            counts, _sum, _n, reservoir, exemplars = s
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    counts[i] += 1
            s[1] = _sum + v
            s[2] = _n + 1
            reservoir.append(v)
            if exemplar:
                exemplars[self._bucket_index(v)] = (
                    str(exemplar), float(v), time.time()
                )

    def data(self) -> dict[tuple[str, ...], dict]:
        """Raw per-series state for federation snapshots (obs/fleet.py):
        cumulative bucket counts, sum, count and the exemplar slots."""
        with self._lock:
            return {
                k: {
                    "buckets": list(s[0]),
                    "sum": s[1],
                    "count": s[2],
                    "exemplars": [
                        [i, *ex]
                        for i, ex in enumerate(s[4])
                        if ex is not None
                    ],
                }
                for k, s in self._series.items()
            }

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(self._key(labels))
            return s[2] if s else 0

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(self._key(labels))
            return s[1] if s else 0.0

    def samples(self, **labels) -> list[float]:
        with self._lock:
            s = self._series.get(self._key(labels))
            return list(s[3]) if s else []

    def exemplars(self, **labels) -> dict[str, tuple[str, float, float]]:
        """`{le_string: (trace_id, value, unix_ts)}` for the buckets that
        hold one ("+Inf" for the overflow bucket)."""
        with self._lock:
            s = self._series.get(self._key(labels))
            if not s:
                return {}
            exs = list(s[4])
        out = {}
        for i, ex in enumerate(exs):
            if ex is not None:
                le = (
                    _fmt_value(self.buckets[i])
                    if i < len(self.buckets)
                    else "+Inf"
                )
                out[le] = ex
        return out

    def exemplar_for_quantile(
        self, q: float, **labels
    ) -> tuple[str, float, float] | None:
        """The exemplar nearest the q-th percentile: compute the
        percentile over the recent reservoir, then return the exemplar of
        the bucket it falls in (or the nearest populated bucket at or
        above it). The join from "p99 spiked" to "this trace shows why"."""
        xs = self.samples(**labels)
        if not xs:
            return None
        v = percentiles(xs, (q,))[q]
        with self._lock:
            s = self._series.get(self._key(labels))
            exs = list(s[4]) if s else []
        if not exs:
            return None
        start = self._bucket_index(v)
        # nearest populated bucket by index distance (ties go up — a
        # tail quantile should prefer the slower neighbour)
        for d in range(len(exs)):
            for i in (start + d, start - d):
                if 0 <= i < len(exs) and exs[i] is not None:
                    return exs[i]
        return None

    def percentiles_ms(self, qs=PERCENTILES, **labels) -> dict | None:
        """`{"p50_ms": ...}` over the recent reservoir — the exact
        percentile view /stats and the shutdown summaries report
        (same definition as the bench suite: utils.timing.percentiles)."""
        xs = self.samples(**labels)
        if not xs:
            return None
        got = percentiles(xs, qs)
        return {f"p{int(q)}_ms": got[q] * 1e3 for q in qs}

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            series = {
                k: (list(s[0]), s[1], s[2], list(s[4]))
                for k, s in self._series.items()
            }
        for key in sorted(series):
            counts, total, n, exemplars = series[key]
            for i, ub in enumerate(self.buckets):
                ls = _label_str(
                    self.label_names, key, (("le", _fmt_value(ub)),)
                )
                lines.append(
                    f"{self.name}_bucket{ls} {counts[i]}"
                    + _fmt_exemplar(exemplars[i])
                )
            inf_ls = _label_str(self.label_names, key, (("le", "+Inf"),))
            lines.append(
                f"{self.name}_bucket{inf_ls} {n}"
                + _fmt_exemplar(exemplars[len(self.buckets)])
            )
            plain = _label_str(self.label_names, key)
            lines.append(f"{self.name}_sum{plain} {repr(float(total))}")
            lines.append(f"{self.name}_count{plain} {n}")
        return lines


class Registry:
    """One process's (or one ServeApp's) metric namespace. Registering an
    existing name returns the existing metric — subsystems that share a
    registry share the family (that is the point)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _register(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type/labels"
                    )
                return m
            m = self._metrics[name] = cls(name, help, labels, **kw)
            return m

    def counter(self, name: str, help: str,
                labels: tuple[str, ...] = ()) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str, labels: tuple[str, ...] = (),
              fn=None) -> Gauge:
        return self._register(Gauge, name, help, labels, fn=fn)

    def histogram(self, name: str, help: str,
                  labels: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  sample_cap: int = 65536) -> Histogram:
        return self._register(
            Histogram, name, help, labels, buckets=buckets,
            sample_cap=sample_cap,
        )

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> list:
        """Every registered metric object, name-sorted (federation
        snapshots walk these; obs/fleet.py)."""
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4 (the `GET /metrics`
        body / `--metrics-out` snapshot)."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _parse_label_block(
    line: str, i: int, lineno: int
) -> tuple[dict[str, str], str, int]:
    """Tokenize `line[i:]` starting at '{': returns (labels dict with
    unescaped values, the raw inner text, index just past '}'). Escape-
    aware, so label values containing `\\`, `\"`, `}`, `,` or rendered
    newlines parse correctly — rpartition-style splitting does not."""
    assert line[i] == "{"
    j = i + 1
    labels: dict[str, str] = {}
    while True:
        if j >= len(line):
            raise ValueError(f"line {lineno}: unterminated label block")
        if line[j] == "}":
            return labels, line[i + 1 : j], j + 1
        k = j
        while j < len(line) and line[j] not in '="}':
            j += 1
        if j >= len(line) or line[j] != "=":
            raise ValueError(f"line {lineno}: expected label=\"value\"")
        name = line[k:j].strip(", \t")
        j += 1
        if j >= len(line) or line[j] != '"':
            raise ValueError(
                f"line {lineno}: label {name!r} value must be quoted"
            )
        j += 1
        buf: list[str] = []
        while True:
            if j >= len(line):
                raise ValueError(
                    f"line {lineno}: unterminated value for label {name!r}"
                )
            c = line[j]
            if c == "\\" and j + 1 < len(line):
                buf.append(
                    {"n": "\n", "\\": "\\", '"': '"'}.get(
                        line[j + 1], c + line[j + 1]
                    )
                )
                j += 2
                continue
            if c == '"':
                j += 1
                break
            buf.append(c)
            j += 1
        labels[name] = "".join(buf)
        if j < len(line) and line[j] == ",":
            j += 1


def parse_labels(labelstr: str) -> dict[str, str]:
    """Parse the inner text of a label block (the `labelstr` keys
    `parse_exposition` returns) into `{name: unescaped value}`."""
    if not labelstr:
        return {}
    labels, _raw, _end = _parse_label_block("{" + labelstr + "}", 0, 0)
    return labels


def _parse_sample_line(line: str, lineno: int):
    """One sample line -> (name, raw labelstr, value, exemplar | None).
    Exemplars are the OpenMetrics ` # {labels} value [ts]` suffix."""
    i = 0
    while i < len(line) and line[i] not in "{ \t":
        i += 1
    name = line[:i]
    raw = ""
    if i < len(line) and line[i] == "{":
        _labels, raw, i = _parse_label_block(line, i, lineno)
    rest = line[i:].strip()
    exemplar = None
    if " # " in rest:
        val_part, _, ex_part = rest.partition(" # ")
        ex_part = ex_part.strip()
        if not ex_part.startswith("{"):
            raise ValueError(f"line {lineno}: malformed exemplar")
        ex_labels, _exraw, k = _parse_label_block(ex_part, 0, lineno)
        ex_fields = ex_part[k:].split()
        if not ex_fields:
            raise ValueError(f"line {lineno}: exemplar missing value")
        exemplar = {
            "labels": ex_labels,
            "value": float(ex_fields[0]),
            "ts": float(ex_fields[1]) if len(ex_fields) > 1 else None,
        }
    else:
        val_part = rest
    fields = val_part.split()
    if not fields:
        raise ValueError(f"line {lineno}: expected 'name value'")
    try:
        value = float(fields[0])
    except ValueError:
        raise ValueError(
            f"line {lineno}: unparsable value {fields[0]!r}"
        ) from None
    return name, raw, value, exemplar


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse Prometheus text exposition into
    `{family: {"type": str, "help": str, "samples": {(name, labelstr):
    value}, "exemplars": {(name, labelstr): {...}}}}`.
    Raises ValueError on malformed lines — the CI smoke lane's
    "/metrics parses" assertion. Label values round-trip escapes
    (`parse_labels` on a labelstr recovers the original values), and
    histogram bucket exemplars are captured per sample."""
    families: dict[str, dict] = {}

    def fam(name: str) -> dict:
        return families.setdefault(
            name,
            {"type": "untyped", "help": "", "samples": {}, "exemplars": {}},
        )

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            fam(name)["help"] = _unescape(help_text)
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE {kind!r}")
            fam(name)["type"] = kind
            continue
        if line.startswith("#"):
            continue
        name, labelstr, value, exemplar = _parse_sample_line(line, lineno)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in families:
                base = name[: -len(suffix)]
                break
        fam(base)["samples"][(name, labelstr)] = value
        if exemplar is not None:
            fam(base)["exemplars"][(name, labelstr)] = exemplar
    return families
