"""Cost attribution — what XLA actually compiled, keyed by the plan/dag
fingerprints every compile cache already uses.

Every performance claim in the repo — the roofline_frac headline, the
planner's `hbm_passes_saved` accounting, the megakernel's one-u8-read +
one-u8-write-per-stage contract — was computed from an ANALYTICAL byte
model until this module: nothing ever read `compiled.cost_analysis()` or
`memory_analysis()`. Here every compile-cache insertion site (serve
bucket cache, stream TileFnCache, per-tenant graph cache, plan
callables) extracts the compiled executable's measured cost and records
it into one bounded ledger, so the model is CHECKED against what XLA
compiled, continuously, on every platform CI runs on.

Two distinct byte quantities, used for two distinct questions:

  * **boundary bytes** (`memory_analysis().argument_size_in_bytes +
    output_size_in_bytes - alias_size_in_bytes`) — what crosses the
    executable boundary. This is EXACTLY what the planner models: a
    fused stage's contract is "one u8 read + one u8 write of the image
    per stage, intermediates never materialize at the boundary". The
    **drift ratio** = boundary bytes / planner-modelled bytes
    (`mcim_cost_model_drift_ratio{site,stage}`) is therefore a
    structural check that holds on CPU CI too: per-op dispatch must sit
    at ~1.0 (each op's executable takes u8 in, returns u8 out), a fused
    or megakernel stage must sit at ~1.0 (absorbed ops add NOTHING at
    the boundary), and a mis-modelled stage — an executable that leaks
    its f32 carry, double-materializes, or grows hidden operands —
    lands outside [MCIM_COST_DRIFT_MIN, MCIM_COST_DRIFT_MAX] and trips
    `mcim_cost_drift_alerts_total` plus a flight-recorder note. The
    `cost.model` failpoint deliberately mis-models a stage so the alert
    path itself is CI-provable.
  * **HLO bytes accessed** (`cost_analysis()['bytes accessed']`) — the
    total traffic XLA's cost model charges the compiled program,
    intermediates included. Divided by the dispatch-time histograms
    (`mcim_serve_device_seconds` et al.) this yields the MEASURED
    `hbm_gb_s` / `roofline_frac` columns the bench suite now reports
    next to the analytical model (tools/roofline_probe.py's question,
    folded into the production path).

Extraction is AOT (`fn.lower(*args).compile()`), so the jit trace runs
ONCE and the same compiled executable that was costed serves the
traffic: `attribute_jit` returns a `CompiledOrJit` wrapper that
dispatches to the costed executable for matching shapes and falls back
to the original jit callable otherwise (and permanently on the first
compiled-call failure — cost attribution must never take serving down).
`MCIM_COST_ATTRIB=0` disables the whole layer; every failure path
degrades to the un-attributed callable and a counter.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

from mpi_cuda_imagemanipulation_tpu.obs import recorder
from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_ATTRIB = "MCIM_COST_ATTRIB"
ENV_CAP = "MCIM_COST_CAP"
ENV_DRIFT_MIN = "MCIM_COST_DRIFT_MIN"
ENV_DRIFT_MAX = "MCIM_COST_DRIFT_MAX"
ENV_PEAK_GBS = "MCIM_COST_PEAK_GBS"

# the bounded attribution-site label set (one per compile-cache kind)
SITES = ("serve", "plan", "graph", "stream", "bench")


def enabled() -> bool:
    return env_registry.get_bool(ENV_ATTRIB)


def drift_band() -> tuple[float, float]:
    return (
        float(env_registry.get(ENV_DRIFT_MIN)),
        float(env_registry.get(ENV_DRIFT_MAX)),
    )


@dataclasses.dataclass(frozen=True)
class CostRecord:
    """One compiled executable's measured cost, normalized across the
    list-vs-dict `cost_analysis()` return shapes."""

    flops: float
    hlo_bytes: float  # total 'bytes accessed' (intermediates included)
    arg_bytes: float
    out_bytes: float
    alias_bytes: float
    temp_bytes: float
    code_bytes: float

    @property
    def boundary_bytes(self) -> float:
        """Bytes crossing the executable boundary — donated/aliased
        buffers counted once (the planner's modelled quantity)."""
        return self.arg_bytes + self.out_bytes - self.alias_bytes

    @property
    def peak_bytes(self) -> float:
        """Peak device allocation the executable needs beyond code:
        arguments + outputs + temporaries."""
        return self.arg_bytes + self.out_bytes + self.temp_bytes

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["boundary_bytes"] = self.boundary_bytes
        d["peak_bytes"] = self.peak_bytes
        return d


def cost_from_compiled(compiled) -> CostRecord | None:
    """Extract a CostRecord from a `jax.stages.Compiled`; None when the
    backend exposes neither analysis (extraction never raises)."""
    flops = hlo_bytes = 0.0
    have_any = False
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            flops = float(ca.get("flops", 0.0) or 0.0)
            hlo_bytes = float(ca.get("bytes accessed", 0.0) or 0.0)
            have_any = True
    except Exception:
        pass
    arg = out = alias = temp = code = 0.0
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = float(ma.argument_size_in_bytes)
            out = float(ma.output_size_in_bytes)
            alias = float(ma.alias_size_in_bytes)
            temp = float(ma.temp_size_in_bytes)
            code = float(ma.generated_code_size_in_bytes)
            have_any = True
    except Exception:
        pass
    if not have_any:
        return None
    return CostRecord(
        flops=flops, hlo_bytes=hlo_bytes, arg_bytes=arg, out_bytes=out,
        alias_bytes=alias, temp_bytes=temp, code_bytes=code,
    )


class CostLedger:
    """The bounded attribution store + its `mcim_cost_*` families.

    Module-level instance (like plan/metrics.plan_metrics): executables
    are built from many entry points, and a per-call ledger would
    fragment the drift history across them. The store is an LRU capped
    at MCIM_COST_CAP entries keyed (site, key, stage) — fingerprints are
    unbounded in principle, metric label sets must not be."""

    def __init__(self, registry: Registry | None = None):
        self.registry = registry or Registry()
        self._lock = threading.Lock()
        self._store: OrderedDict[tuple[str, str, str], dict] = OrderedDict()
        r = self.registry
        self.executables = r.counter(
            "mcim_cost_executables_total",
            "Compiled executables cost-attributed, per compile site.",
            labels=("site",),
        )
        self.failures = r.counter(
            "mcim_cost_extract_failures_total",
            "Cost extractions that degraded to the un-attributed "
            "callable, per compile site.",
            labels=("site",),
        )
        self.drift_alerts = r.counter(
            "mcim_cost_drift_alerts_total",
            "Drift ratios outside [MCIM_COST_DRIFT_MIN, "
            "MCIM_COST_DRIFT_MAX] — the plan-model falsification gate.",
            labels=("site",),
        )
        self.drift_ratio = r.gauge(
            "mcim_cost_model_drift_ratio",
            "Measured executable-boundary bytes / planner-modelled bytes "
            "per attributed stage (~1.0 = the one-read-one-write model "
            "holds structurally).",
            labels=("site", "stage"),
            fn=self._drift_gauge,
        )
        self.hlo_bytes = r.gauge(
            "mcim_cost_hlo_bytes",
            "Total HLO bytes-accessed of the newest attribution per "
            "(site, key) — the measured-roofline numerator.",
            labels=("site", "key"),
            fn=lambda: self._field_gauge("hlo_bytes"),
        )
        self.flops = r.gauge(
            "mcim_cost_flops",
            "HLO flops of the newest attribution per (site, key).",
            labels=("site", "key"),
            fn=lambda: self._field_gauge("flops"),
        )
        self.temp_bytes = r.gauge(
            "mcim_cost_temp_bytes",
            "Compiled temp allocation per (site, key) — what the "
            "executable materializes beyond its boundary.",
            labels=("site", "key"),
            fn=lambda: self._field_gauge("temp_bytes"),
        )

    # -- gauges over the store ----------------------------------------------

    def _drift_gauge(self) -> dict:
        with self._lock:
            return {
                (site, stage): e["drift_ratio"]
                for (site, _key, stage), e in self._store.items()
                if e.get("drift_ratio") is not None
            }

    def _field_gauge(self, field: str) -> dict:
        out: dict = {}
        with self._lock:
            # one sample per (site, key): stages of one executable family
            # share the key, the whole-executable entry ("all") wins
            for (site, key, stage), e in self._store.items():
                if stage == "all" or (site, key) not in out:
                    out[(site, key)] = e["cost"][field]
        return out

    # -- recording -----------------------------------------------------------

    def record(
        self,
        site: str,
        key: str,
        cost: CostRecord,
        *,
        modeled_bytes: float | None = None,
        stage: str = "all",
    ) -> float | None:
        """Fold one attribution in; returns the drift ratio (measured
        boundary / modelled bytes) when a model was provided.

        The `cost.model` failpoint deliberately corrupts the model (4x)
        so the alert wiring is provable end to end: a tripped site is
        exactly what a real planner mis-model would look like."""
        if site not in SITES:
            raise ValueError(f"unknown cost site {site!r}; known: {SITES}")
        ratio = None
        if modeled_bytes is not None and modeled_bytes > 0:
            try:
                failpoints.maybe_fail("cost.model", cost_site=site, key=key)
            except failpoints.FailpointError:
                # the deliberate mis-model: the planner "claims" 4x the
                # real traffic, so measured/modelled lands at ~0.25
                modeled_bytes = modeled_bytes * 4.0
            ratio = cost.boundary_bytes / modeled_bytes
        entry = {
            "cost": cost.to_dict(),
            "modeled_bytes": modeled_bytes,
            "drift_ratio": ratio,
        }
        with self._lock:
            self._store[(site, key, stage)] = entry
            self._store.move_to_end((site, key, stage))
            while len(self._store) > int(env_registry.get(ENV_CAP)):
                self._store.popitem(last=False)
        self.executables.inc(site=site)
        if ratio is not None:
            lo, hi = drift_band()
            if not lo <= ratio <= hi:
                self.drift_alerts.inc(site=site)
                recorder.note(
                    "cost_drift", site=site, key=key, stage=stage,
                    ratio=round(ratio, 4),
                    measured=cost.boundary_bytes, modeled=modeled_bytes,
                )
                get_logger().warning(
                    "cost drift alert: %s/%s stage %s ratio %.3f outside "
                    "[%.2f, %.2f] (measured %d B vs modelled %d B)",
                    site, key, stage, ratio, lo, hi,
                    int(cost.boundary_bytes), int(modeled_bytes),
                )
            if site == "plan":
                # plan-site ratios feed the online autotuning store so
                # OTHER processes can correct the analytical byte model
                # (tune/store.persisted_io_scale); lazy import — obs/ must
                # not hard-depend on tune/ — and advisory: a store hiccup
                # never fails the attribution
                try:
                    from mpi_cuda_imagemanipulation_tpu.tune.store import (
                        online_store,
                    )

                    online_store.record_io_scale(key, stage, ratio)
                except Exception:
                    pass
        return ratio

    def on_extract_failure(self, site: str) -> None:
        self.failures.inc(site=site)

    def entries(self) -> dict[tuple[str, str, str], dict]:
        with self._lock:
            return dict(self._store)

    def drift(self, site: str, key: str, stage: str = "all") -> float | None:
        with self._lock:
            e = self._store.get((site, key, stage))
        return None if e is None else e.get("drift_ratio")

    def snapshot(self) -> dict:
        entries = self.entries()
        alerts = {
            s: int(self.drift_alerts.value(site=s)) for s in SITES
        }
        return {
            "entries": len(entries),
            "attributed": {
                s: int(self.executables.value(site=s)) for s in SITES
            },
            "drift_alerts": alerts,
            "ratios": {
                f"{site}/{key}/{stage}": e["drift_ratio"]
                for (site, key, stage), e in entries.items()
                if e.get("drift_ratio") is not None
            },
        }


# the shared ledger every compile site reports into (see class docstring)
cost_ledger = CostLedger()


# --------------------------------------------------------------------------
# AOT attribution wrappers
# --------------------------------------------------------------------------


class CompiledOrJit:
    """The costed AOT executable with the original jit callable behind
    it: matching-shape calls hit the compiled artifact (the very one the
    cost record describes), anything else — a novel shape, or the first
    compiled-call failure — falls back to the jit path permanently for
    that shape class. Never raises beyond what the jit callable would."""

    __slots__ = ("_compiled", "_jit", "_shapes", "_use_compiled")

    def __init__(self, compiled, jitted, args):
        self._compiled = compiled
        self._jit = jitted
        self._shapes = tuple(
            (getattr(a, "shape", None), str(getattr(a, "dtype", "")))
            for a in args
        )
        self._use_compiled = True

    def _matches(self, args) -> bool:
        if len(args) != len(self._shapes):
            return False
        return all(
            (getattr(a, "shape", None), str(getattr(a, "dtype", "")))
            == want
            for a, want in zip(args, self._shapes)
        )

    def __call__(self, *args):
        if self._use_compiled and self._matches(args):
            try:
                return self._compiled(*args)
            except Exception:
                # e.g. a sharding/placement mismatch the AOT path is
                # stricter about than jit dispatch: degrade once, serve on
                self._use_compiled = False
        return self._jit(*args)

    def lower(self, *args, **kwargs):
        """AOT passthrough — HLO-inspection callers keep working."""
        return self._jit.lower(*args, **kwargs)


def extract(jitted, args: tuple | list) -> CostRecord | None:
    """AOT-lower `jitted` for `args` and read its cost; None on any
    failure. Pays one compile — the bench-suite measured-column path
    (attribute_jit is the serving path, which reuses the compile)."""
    try:
        return cost_from_compiled(jitted.lower(*args).compile())
    except Exception:
        return None


def attribute_jit(
    site: str,
    key: str,
    jitted,
    args: tuple | list,
    *,
    modeled_bytes: float | None = None,
    stage: str = "all",
    ledger: CostLedger | None = None,
):
    """Compile `jitted` AOT for `args`, record the attribution, and
    return `(callable, CostRecord | None)`. The callable is the costed
    executable (wrapped with the jit fallback) when extraction worked,
    the original `jitted` otherwise — callers always get something
    serviceable, and the jit trace ran exactly once either way."""
    led = ledger or cost_ledger
    if not enabled():
        return jitted, None
    try:
        compiled = jitted.lower(*args).compile()
        cost = cost_from_compiled(compiled)
    except Exception as e:
        led.on_extract_failure(site)
        get_logger().debug(
            "cost attribution for %s/%s failed (%s): serving the "
            "un-attributed callable", site, key, type(e).__name__,
        )
        return jitted, None
    if cost is None:
        led.on_extract_failure(site)
        return jitted, None
    led.record(site, key, cost, modeled_bytes=modeled_bytes, stage=stage)
    return CompiledOrJit(compiled, jitted, args), cost


class LazyAttributedFn:
    """Deferred attribution for caches that compile before the call
    shapes exist (stream TileFnCache, per-tenant graph caches): the
    FIRST call AOT-compiles with the live arguments (one compile — the
    jit path would have compiled here anyway), records the attribution,
    and keeps the costed executable for that shape; later novel shapes
    ride the jit callable exactly as before."""

    __slots__ = ("_jit", "_site", "_key", "_modeled_fn", "_stage", "_inner")

    def __init__(self, site: str, key: str, jitted, *, modeled_fn=None,
                 stage: str = "all"):
        self._jit = jitted
        self._site = site
        self._key = key
        # modeled_fn(args) -> planner-modelled boundary bytes for this
        # call signature (None = record cost without a drift check)
        self._modeled_fn = modeled_fn
        self._stage = stage
        self._inner = None

    def __call__(self, *args):
        if self._inner is None:
            modeled = None
            if self._modeled_fn is not None:
                try:
                    modeled = self._modeled_fn(args)
                except Exception:
                    modeled = None
            self._inner, _cost = attribute_jit(
                self._site, self._key, self._jit, args,
                modeled_bytes=modeled, stage=self._stage,
            )
        return self._inner(*args)

    def lower(self, *args, **kwargs):
        """AOT passthrough — HLO-inspection callers keep working."""
        return self._jit.lower(*args, **kwargs)


def wrap_cache_fn(site: str, key: str, jitted, *, modeled_fn=None):
    """The compile-cache insertion hook: lazy attribution when the layer
    is enabled, the bare callable when not (mcim-check's
    obs-cost-attribution rule verifies every insertion site calls
    this or attribute_jit)."""
    if not enabled():
        return jitted
    return LazyAttributedFn(site, key, jitted, modeled_fn=modeled_fn)


# --------------------------------------------------------------------------
# per-stage plan attribution (the megakernel one-read-one-write gate)
# --------------------------------------------------------------------------


def _shape_bytes(aval) -> int:
    import numpy as np

    return int(np.prod(aval.shape, dtype=np.int64)) * aval.dtype.itemsize


def attribute_plan(
    plan,
    shape: tuple,
    *,
    impl: str = "xla",
    pallas: bool = False,
    interpret: bool | None = None,
    ledger: CostLedger | None = None,
) -> list[dict]:
    """Attribute every stage of a built plan at `shape` — one AOT
    compile per stage, drift ratio per stage label `s<i>/<kind>`, keyed
    by the plan's fingerprint. This is the structural megakernel gate:
    stage executables whose boundary is anything but one u8 read + one
    u8 write (+ the halo'd context the model includes) trip the alert.

    Returns `[{stage, names, modeled_bytes, cost, drift_ratio}, ...]`
    (stages that fail extraction carry cost=None)."""
    import jax
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.plan.exec import run_stage_full

    led = ledger or cost_ledger
    key = plan.fingerprint
    out: list[dict] = []
    aval = jax.ShapeDtypeStruct(tuple(shape), np.uint8)
    for i, st in enumerate(plan.stages):
        if st.kind in ("geometric", "global"):
            fn = jax.jit(lambda x, o=st.ops[0]: o(x))
        elif pallas:
            from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
                run_stage_pallas,
                stage_pallas_reject,
            )

            h, w = aval.shape[0], aval.shape[1]
            ch = aval.shape[2] if len(aval.shape) == 3 else 1
            if stage_pallas_reject(st, h, w, ch) is None:
                fn = jax.jit(
                    lambda x, s=st: run_stage_pallas(
                        s, x, interpret=interpret
                    )
                )
            else:
                fn = jax.jit(lambda x, s=st: run_stage_full(s, x, impl))
        else:
            fn = jax.jit(lambda x, s=st: run_stage_full(s, x, impl))
        out_aval = jax.eval_shape(fn, aval)
        # the planner's model: the stage reads its u8 input once and
        # writes its u8 output once — absorbed member ops contribute
        # NOTHING at the executable boundary
        modeled = float(_shape_bytes(aval) + _shape_bytes(out_aval))
        stage_label = f"s{i}/{st.kind}"
        arg = np.zeros(aval.shape, np.uint8)
        cost = extract(fn, [arg])
        entry = {
            "stage": stage_label,
            "names": list(st.names),
            "modeled_bytes": modeled,
            "cost": None if cost is None else cost.to_dict(),
            "drift_ratio": None,
        }
        if cost is None:
            led.on_extract_failure("plan")
        else:
            entry["drift_ratio"] = led.record(
                "plan", key, cost, modeled_bytes=modeled, stage=stage_label
            )
        out.append(entry)
        aval = out_aval
    return out


# --------------------------------------------------------------------------
# measured roofline helpers
# --------------------------------------------------------------------------


def peak_gb_s(tpu_gen: str | None = None) -> float:
    """The roofline denominator: MCIM_COST_PEAK_GBS when set, else the
    datasheet table keyed by TPU generation (bench_suite.HBM_GB_S)."""
    override = env_registry.get(ENV_PEAK_GBS)
    if override:
        return float(override)
    from mpi_cuda_imagemanipulation_tpu.bench_suite import HBM_GB_S

    return HBM_GB_S.get(tpu_gen or "v5e", HBM_GB_S["v5e"])


def measured_gb_s(nbytes: float, seconds: float, chips: int = 1) -> float:
    return nbytes / max(seconds, 1e-12) / max(chips, 1) / 1e9


def measured_roofline_frac(
    nbytes: float, seconds: float, *, chips: int = 1,
    tpu_gen: str | None = None,
) -> float:
    return measured_gb_s(nbytes, seconds, chips) / peak_gb_s(tpu_gen)
