"""Device-memory observability — live/peak HBM gauges from the runtime.

A traffic-serving pod cannot run blind on HBM: a compile-cache growing
past its budget, a leaked donation, or a tenant's oversized graph shows
up FIRST as shrinking allocator headroom, and only later (fatally) as an
OOM mid-dispatch. This module turns `device.memory_stats()` — the
allocator's own live counters on TPU/GPU backends — into callback gauges
on the serving registry, so every scrape (and every heartbeat's
federation delta, obs/fleet.py) carries the current picture per replica:

    mcim_devmem_bytes_in_use{device}       live allocator bytes
    mcim_devmem_peak_bytes_in_use{device}  high-water mark
    mcim_devmem_bytes_limit{device}        allocator pool limit
    mcim_devmem_headroom_frac{device}      (limit - in_use) / limit

At the router the federated gauges gain a `{replica=...}` label (gauges
are never summed — a pod-mean headroom is a lie), and the SLO engine can
alert on the WORST replica's headroom via the `headroom:<min_frac>:<pct>`
spec kind (obs/slo.py): "99% of evaluation ticks must see >= 10%
headroom on every device of every replica" is a declarative objective,
not a dashboard eyeball.

CPU backends report no `memory_stats()` (the gauges render empty — the
fleet view simply has no devmem series), so tests and the CPU smoke
inject a `stats_fn` returning the same mapping shape the TPU runtime
produces. Keys follow jax's PJRT stats: `bytes_in_use`,
`peak_bytes_in_use`, `bytes_limit` (absent keys read 0)."""

from __future__ import annotations

from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry


def device_memory_stats() -> dict[str, dict]:
    """`{device_label: stats}` for every local device that reports
    allocator stats; {} on backends (CPU) that return None."""
    import jax

    out: dict[str, dict] = {}
    try:
        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[f"{d.platform}:{d.id}"] = dict(stats)
    return out


class DevMemGauges:
    """The gauge family over one stats source. Construct once per app
    registry (ServeApp does); `stats_fn` defaults to the live runtime
    and is injectable for CPU tests."""

    def __init__(self, registry: Registry, stats_fn=None):
        self.registry = registry
        self._stats_fn = stats_fn or device_memory_stats

        def field(name: str):
            def read() -> dict:
                return {
                    (dev,): float(stats.get(name, 0) or 0)
                    for dev, stats in self._stats_fn().items()
                }

            return read

        self.in_use = registry.gauge(
            "mcim_devmem_bytes_in_use",
            "Live allocator bytes per device (device.memory_stats).",
            labels=("device",),
            fn=field("bytes_in_use"),
        )
        self.peak = registry.gauge(
            "mcim_devmem_peak_bytes_in_use",
            "Peak allocator bytes per device since process start.",
            labels=("device",),
            fn=field("peak_bytes_in_use"),
        )
        self.limit = registry.gauge(
            "mcim_devmem_bytes_limit",
            "Allocator pool limit per device.",
            labels=("device",),
            fn=field("bytes_limit"),
        )
        self.headroom = registry.gauge(
            "mcim_devmem_headroom_frac",
            "Fraction of the allocator pool still free per device — the "
            "SLO-able OOM-distance signal (headroom:<frac>:<pct> specs).",
            labels=("device",),
            fn=self._headroom,
        )
        self.devices = registry.gauge(
            "mcim_devmem_devices",
            "Devices reporting allocator stats (0 on CPU backends).",
            fn=lambda: float(len(self._stats_fn())),
        )

    def _headroom(self) -> dict:
        out = {}
        for dev, stats in self._stats_fn().items():
            limit = float(stats.get("bytes_limit", 0) or 0)
            if limit <= 0:
                continue
            in_use = float(stats.get("bytes_in_use", 0) or 0)
            out[(dev,)] = max(0.0, (limit - in_use) / limit)
        return out

    def snapshot(self) -> dict:
        """The /stats section: raw per-device numbers plus headroom."""
        stats = self._stats_fn()
        return {
            dev: {
                "bytes_in_use": s.get("bytes_in_use", 0),
                "peak_bytes_in_use": s.get("peak_bytes_in_use", 0),
                "bytes_limit": s.get("bytes_limit", 0),
                "headroom_frac": (
                    (s["bytes_limit"] - s.get("bytes_in_use", 0))
                    / s["bytes_limit"]
                    if s.get("bytes_limit")
                    else None
                ),
            }
            for dev, s in stats.items()
        }
