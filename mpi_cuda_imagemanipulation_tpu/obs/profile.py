"""Perfetto/Chrome trace parsing + host-span/device-trace merging.

Graduation of `tools/profile_capture.py`'s offline parser (the tool stays
as a thin capture shim): parse a `jax.profiler` Perfetto trace, summarize
per-track time with a DMA-vs-compute split, and — the piece the roofline
program needs online — merge an `obs.trace` host-span file onto the SAME
timeline, so host stalls, DMA waits and device compute are one picture.

The two traces have different time bases (`jax.profiler` stamps its own
epoch; obs spans are relative to the tracer's start), so `merge_traces`
re-bases both to zero and keeps them on distinct pids — alignment is
structural (both cover the same run window), which is exactly what the
per-stage overlap question needs: "was the device idle while the host
coalesced/encoded" is a within-track question on each side, answered side
by side. Event-level cross-clock sync is out of scope and not required
for it.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import threading
import time
from collections import defaultdict

# event names that are DMA/copy-shaped on XLA device tracks — the split's
# classifier (inherited from tools/profile_capture.py round 3)
DMA_MARKERS = ("dma", "copy", "memcpy", "transfer", "infeed", "outfeed")

HOST_PID = 1_000_001  # merged-trace pid for the obs host spans


def load_device_trace(path: str) -> list[dict]:
    """Trace events from a jax.profiler output directory (newest
    `*.json.gz` Perfetto file under it) or from a plain `.json`/`.json.gz`
    trace file. Returns [] when nothing is found."""
    if os.path.isdir(path):
        paths = sorted(
            glob.glob(os.path.join(path, "**", "*.json.gz"), recursive=True),
            key=os.path.getmtime,
        )
        if not paths:
            return []
        path = paths[-1]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", data) if isinstance(data, dict) else data


def load_host_trace(path: str) -> list[dict]:
    """Trace events from an `obs.trace` export (`--trace-out` JSON)."""
    with open(path) as f:
        data = json.load(f)
    return data.get("traceEvents", data) if isinstance(data, dict) else data


def _ts_base(events: list[dict]) -> float:
    stamps = [float(e["ts"]) for e in events if "ts" in e and e.get("ph") != "M"]
    return min(stamps) if stamps else 0.0


def merge_traces(host_events: list[dict],
                 device_events: list[dict]) -> list[dict]:
    """One Chrome trace-event list with the obs host spans and the device
    trace side by side: both re-based to ts=0, host events forced onto
    the reserved `HOST_PID` process (named "mcim-host") so the tracks
    never collide with the profiler's pids."""
    out: list[dict] = []
    hbase = _ts_base(host_events)
    for e in host_events:
        e = dict(e)
        e["pid"] = HOST_PID
        if "ts" in e and e.get("ph") != "M":
            e["ts"] = float(e["ts"]) - hbase
        out.append(e)
    if not any(
        e.get("ph") == "M" and e.get("name") == "process_name"
        and e.get("pid") == HOST_PID
        for e in out
    ):
        out.insert(0, {
            "ph": "M", "name": "process_name", "pid": HOST_PID, "tid": 0,
            "args": {"name": "mcim-host"},
        })
    dbase = _ts_base(device_events)
    for e in device_events:
        e = dict(e)
        if "ts" in e and e.get("ph") != "M":
            e["ts"] = float(e["ts"]) - dbase
        out.append(e)
    return out


def summarize(events: list[dict], *, top_n: int = 40) -> dict:
    """Per-process top events by total duration + the device-side
    DMA-vs-compute split (the roofline corroboration table)."""
    pid_name: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e.get("pid")] = e.get("args", {}).get("name", "")
    agg: dict = defaultdict(lambda: [0.0, 0])  # (proc, name) -> [us, count]
    proc_total: dict = defaultdict(float)
    for e in events:
        if e.get("ph") != "X":
            continue
        dur = float(e.get("dur", 0.0))
        proc = pid_name.get(e.get("pid"), str(e.get("pid")))
        key = (proc, e.get("name", "?"))
        agg[key][0] += dur
        agg[key][1] += 1
        proc_total[proc] += dur
    top = sorted(agg.items(), key=lambda kv: -kv[1][0])[:top_n]
    # device-side DMA vs compute split: XLA device tracks are the
    # processes that are neither the python host thread nor our own
    # merged-in host-span track
    device_procs = {
        p for p in proc_total
        if not p.lower().startswith(("python", "/host", "mcim-host"))
    }
    dma_us = comp_us = 0.0
    for (proc, name), (us, _n) in agg.items():
        if proc not in device_procs:
            continue
        if any(m in name.lower() for m in DMA_MARKERS):
            dma_us += us
        else:
            comp_us += us
    return {
        "processes": {p: round(v, 1) for p, v in sorted(proc_total.items())},
        "device_dma_us": round(dma_us, 1),
        "device_compute_us": round(comp_us, 1),
        "top_events": [
            {
                "process": proc,
                "name": name,
                "total_us": round(us, 1),
                "count": n,
            }
            for (proc, name), (us, n) in top
        ],
    }


def summary_table(summary: dict) -> list[str]:
    """The markdown top-events table for a summary dict (shared by the
    capture tool and the merged-trace report)."""
    lines = [
        "| process | event | total us | count |",
        "|---|---|---|---|",
    ]
    for t in summary.get("top_events", []):
        lines.append(
            f"| {t['process']} | {t['name'][:60]} | "
            f"{t['total_us']} | {t['count']} |"
        )
    return lines


# --------------------------------------------------------------------------
# on-demand live capture (the fleet `POST /control/profile` unit)
# --------------------------------------------------------------------------

ENV_PROFILE_DIR = "MCIM_PROFILE_DIR"
ENV_PROFILE_MIN_INTERVAL_S = "MCIM_PROFILE_MIN_INTERVAL_S"
ENV_PROFILE_MAX_S = "MCIM_PROFILE_MAX_S"
ENV_PROFILE_DEFAULT_S = "MCIM_PROFILE_DEFAULT_S"


class ProfileUnavailable(RuntimeError):
    """A capture cannot run NOW: one is already in flight, or the
    per-process rate limit has not elapsed. Maps to HTTP 429 — live
    profiling is deliberately expensive and a fleet control plane must
    not be able to stack captures on a serving replica."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = max(retry_after_s, 1.0)


_capture_lock = threading.Lock()  # one capture per process, ever
_last_capture_ts = 0.0
_capture_seq = 0


def capture_live(
    seconds: float | None = None,
    *,
    out_dir: str | None = None,
    sleep=time.sleep,
) -> dict:
    """One rate-limited `jax.profiler` capture UNDER LIVE TRAFFIC: start
    the device profiler, keep serving for `seconds` (capped at
    MCIM_PROFILE_MAX_S — the capture window must stay well under the
    router's forward timeout), stop, merge the process's obs host spans
    onto the device timeline, write the merged Perfetto artifact, and
    file a `profile_capture` flight-recorder dump naming it.

    Returns {artifact, device_trace_dir, seconds, host_events,
    device_events, summary}. Raises ProfileUnavailable (HTTP 429) when a
    capture is in flight or the MCIM_PROFILE_MIN_INTERVAL_S limit has
    not elapsed — never leaves the profiler running."""
    from mpi_cuda_imagemanipulation_tpu.obs import recorder
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

    global _last_capture_ts, _capture_seq
    max_s = float(env_registry.get(ENV_PROFILE_MAX_S))
    default_s = float(env_registry.get(ENV_PROFILE_DEFAULT_S))
    min_interval = float(env_registry.get(ENV_PROFILE_MIN_INTERVAL_S))
    seconds = min(max(float(seconds or default_s), 0.1), max_s)
    if not _capture_lock.acquire(blocking=False):
        raise ProfileUnavailable("capture already in flight", seconds)
    try:
        now = time.time()
        since = now - _last_capture_ts
        if _last_capture_ts and since < min_interval:
            raise ProfileUnavailable(
                f"rate limited ({since:.1f}s since last capture, min "
                f"{min_interval:.0f}s)",
                min_interval - since,
            )
        _last_capture_ts = now
        _capture_seq += 1
        seq = _capture_seq
        base = out_dir or env_registry.get(ENV_PROFILE_DIR) or os.path.join(
            "artifacts", "profile"
        )
        run_dir = os.path.join(base, f"capture_{os.getpid()}_{seq}")
        os.makedirs(run_dir, exist_ok=True)
        import jax

        jax.profiler.start_trace(run_dir)
        try:
            # the capture window: traffic keeps flowing on the serving
            # threads while the profiler records them
            sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        tracer = obs_trace.get_tracer()
        host_events = tracer.chrome_events() if tracer is not None else []
        device_events = load_device_trace(run_dir)
        merged = merge_traces(host_events, device_events)
        artifact = os.path.join(run_dir, "merged_trace.json")
        with open(artifact, "w") as f:
            json.dump(
                {"traceEvents": merged, "displayTimeUnit": "ms"}, f
            )
        summary = summarize(merged)
        result = {
            "artifact": artifact,
            "device_trace_dir": run_dir,
            "seconds": seconds,
            "host_events": sum(
                1 for e in host_events if e.get("ph") != "M"
            ),
            "device_events": sum(
                1 for e in device_events if e.get("ph") != "M"
            ),
            "summary": summary,
        }
        recorder.dump(
            "profile_capture",
            extra={
                "artifact": artifact,
                "seconds": seconds,
                "device_events": result["device_events"],
            },
        )
        return result
    finally:
        _capture_lock.release()


def merge_and_summarize(host_path: str, device_path: str,
                        merged_out: str | None = None) -> dict:
    """The `--merge-host-trace` unit: load both traces, merge onto one
    timeline (optionally writing the combined Perfetto JSON), and return
    one summary whose table interleaves host spans with device tracks."""
    host = load_host_trace(host_path)
    device = load_device_trace(device_path)
    merged = merge_traces(host, device)
    if merged_out:
        with open(merged_out, "w") as f:
            json.dump(
                {"traceEvents": merged, "displayTimeUnit": "ms"}, f
            )
    summary = summarize(merged)
    summary["host_events"] = sum(1 for e in host if e.get("ph") != "M")
    summary["device_events"] = sum(1 for e in device if e.get("ph") != "M")
    if merged_out:
        summary["merged_trace"] = merged_out
    return summary
