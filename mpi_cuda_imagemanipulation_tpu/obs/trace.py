"""Request-scoped trace spans — one timeline from admission to encode.

The reference's only telemetry was bare stdout prints (kernel.cu:186-188);
the serving/engine/resilience stack needs to answer "where did request X
spend its 40 ms" across scheduler → coalesce → dispatch → retry/bisect →
D2H → encode. A span is a named wall-clock interval on one thread; spans
form a tree per *trace* (one trace per serving request / batch dispatch /
CLI run), and every retry attempt or breaker transition is an instant
event on the owning trace.

Design constraints, in order:

  * **Disarmed cost ≈ zero.** `span()`/`event()` check one module flag and
    return a shared no-op singleton — no allocation, no lock, no clock
    read. Sampled-out traces behave identically: the root decision is made
    once per trace, and every descendant call sees `sampled=False` and
    gets the same singleton back. Tracing is safe to leave compiled in on
    the dispatch hot path.
  * **Thread-safe, cross-thread parentage.** The serving pipeline hops
    threads (caller → scheduler → engine completion → encode pool), so
    parentage is carried explicitly: a `SpanContext` is a value (trace_id,
    span_id, sampled) that travels with the work item, and `span(name,
    parent=ctx)` re-anchors on any thread. Same-thread nesting rides a
    `contextvars.ContextVar` so `with span(...)` blocks compose without
    plumbing. Completed spans append to one bounded deque under a lock.
  * **Traces start only on purpose.** `span()` with no resolvable parent
    is a no-op, never an implicit new trace — only `start_trace()` (the
    per-request/per-run root) makes the sampling decision. A missing
    parent therefore degrades to "not traced", not to trace spam.

Export is Chrome/Perfetto trace-event JSON (`ph:"X"` duration events,
`ph:"i"` instants, metadata names), loadable in `ui.perfetto.dev` directly
and mergeable onto a `jax.profiler` device trace via obs/profile.py so
host stalls, DMA and compute land on one picture.

Timestamps use `time.perf_counter()` relative to the tracer's start, in
microseconds — the Chrome trace unit. Sampling is deterministic (every
k-th trace at rate 1/k), so a traced A/B re-run selects the same requests.

**Deferred tail keep** (`MCIM_TRACE_TAIL`, the armed-tracer default):
root-decided sampling has a blind spot — at sample 0.01 the error you
need to debug and the p99 outlier you need to explain are, with 99%
probability, exactly the traces the root decision threw away. With a
tail buffer armed, a sampled-OUT root still records: its spans go to a
BOUNDED side buffer (`tail` concurrently-open traces; the oldest evicts
when full), and when the root span ends the trace is either PROMOTED
into the real event buffer — the root recorded an error/quarantine/
deadline-class status, or its duration sits at/above the p99 of recent
roots — or dropped wholesale. Exemplars for slow traces therefore
resolve in the export even under aggressive sampling, and
`trace_kept(trace_id)` tells reporting layers (serve/loadgen's
slow-trace column) which ids actually resolve. Sampled-IN behavior,
and the disarmed zero-cost contract, are unchanged; sampled-out
requests now cost a bounded buffer instead of nothing — set
MCIM_TRACE_TAIL=0 for the old behavior.
"""

from __future__ import annotations

import contextvars
import json
import math
import os
import threading
import time
from collections import OrderedDict, deque
from typing import NamedTuple

from mpi_cuda_imagemanipulation_tpu.obs import recorder


class SpanContext(NamedTuple):
    """The value that carries parentage across threads: put it on the work
    item at submit, pass it as `parent=` where the work resumes."""

    trace_id: str
    span_id: int
    sampled: bool


NOT_SAMPLED = SpanContext("", 0, False)

_current: contextvars.ContextVar[SpanContext | None] = contextvars.ContextVar(
    "mcim_obs_span", default=None
)


class _NoopSpan:
    """The shared do-nothing span: every disarmed/sampled-out call returns
    THIS object (tests assert identity — that is the no-allocation
    guarantee on the hot path)."""

    __slots__ = ()
    trace_id = ""
    span_id = 0

    def context(self) -> SpanContext:
        return NOT_SAMPLED

    def set(self, **args) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    """One live span. `end()` (or context-manager exit) records it; `set()`
    attaches attributes; `context()` is the handle children parent to.
    A Span may be ended from a different thread than the one that opened
    it (the retroactive queue-wait pattern: open at submit, end at pop)."""

    __slots__ = (
        "_tracer", "name", "trace_id", "span_id", "parent_id",
        "t0", "tid", "args", "_token", "_ended",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: int, args: dict):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.args = args
        self.tid = threading.get_ident()
        self._token = None
        self._ended = False
        self.t0 = time.perf_counter()

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, True)

    def set(self, **args) -> None:
        self.args.update(args)

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self._tracer._record(self, time.perf_counter())

    def __enter__(self) -> "Span":
        self._token = _current.set(self.context())
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self.end()
        return False


# root statuses that must NOT promote a buffered tail trace: intentional
# outcomes (ok, explicit sheds, client garbage) — a shed storm promoting
# every trace would defeat sampling exactly when it matters most
_TAIL_BENIGN_STATUSES = {
    "ok", "overloaded", "shed", "rejected",
    "200", "204", "400", "429", "503",
}
# minimum recent-root sample before the slow-promotion threshold engages
_TAIL_MIN_DURS = 32


class Tracer:
    """Span collector: bounded event buffer behind one lock, deterministic
    trace-level sampling, deferred tail keep, Chrome trace-event export."""

    def __init__(self, *, sample: float = 1.0, max_events: int = 200_000,
                 tail: int = 0):
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.sample = sample
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max_events)
        self._thread_names: dict[int, str] = {}
        self._next_span = 0
        self._n_traces = 0
        self._n_sampled = 0
        # deferred tail keep (module docstring): sampled-out traces buffer
        # here until their root decides; bounded at `tail` open traces
        self.tail_cap = max(0, int(tail))
        self._tail: OrderedDict[str, list] = OrderedDict()
        # recently dropped provisional ids (bounded): trace_kept() answers
        # "will this id resolve in the export" for reporting layers
        self._tail_dropped: OrderedDict[str, None] = OrderedDict()
        self._root_durs: deque = deque(maxlen=512)
        self.tail_counts = {
            "buffered": 0, "kept_error": 0, "kept_slow": 0,
            "dropped": 0, "evicted": 0,
        }
        self.t0 = time.perf_counter()
        # run-unique trace-id prefix so merged multi-process traces never
        # collide (pid + coarse start time)
        self._prefix = f"{os.getpid():x}{int(time.time()) & 0xffffff:x}"

    # -- span creation -----------------------------------------------------

    def _new_span(self, name: str, trace_id: str, parent_id: int,
                  args: dict) -> Span:
        with self._lock:
            self._next_span += 1
            sid = self._next_span
            tid = threading.get_ident()
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
        return Span(self, name, trace_id, sid, parent_id, args)

    def start_trace(self, name: str, *, trace_id: str | None = None,
                    **args) -> Span:
        """Root span of a NEW trace — the only call that makes a sampling
        decision. Deterministic: at rate f, trace n is kept iff
        floor(n*f) > floor((n-1)*f), i.e. evenly every 1/f traces.

        `trace_id` ADOPTS an upstream id instead of minting one (the
        fabric router → replica hop: the router made the sampling
        decision and propagated the id via X-Trace-Id, so the replica's
        root span joins the same distributed trace rather than rolling
        its own dice — exports from both processes merge on the id)."""
        with self._lock:
            self._n_traces += 1
            n = self._n_traces
            take = trace_id is not None or math.floor(
                n * self.sample
            ) > math.floor((n - 1) * self.sample)
            if take:
                self._n_sampled += 1
        if not take:
            if self.tail_cap <= 0:
                return NOOP_SPAN
            # deferred tail keep: record this trace provisionally; the
            # root's end decides promote-or-drop (module docstring)
            trace_id = f"{self._prefix}-{n:x}"
            with self._lock:
                self._tail[trace_id] = []
                self.tail_counts["buffered"] += 1
                while len(self._tail) > self.tail_cap:
                    old_tid, _evs = self._tail.popitem(last=False)
                    self._mark_dropped_locked(old_tid)
                    self.tail_counts["evicted"] += 1
        trace_id = trace_id or f"{self._prefix}-{n:x}"
        span = self._new_span(name, trace_id, 0, args)
        span.args.setdefault("trace_id", trace_id)
        return span

    def span(self, name: str, parent: SpanContext | None = None, **args):
        """Child span. `parent=None` uses the calling thread's current
        span; no resolvable sampled parent → the shared no-op (a span
        never implicitly starts a trace)."""
        if parent is None:
            parent = _current.get()
        if parent is None or not parent.sampled:
            return NOOP_SPAN
        return self._new_span(name, parent.trace_id, parent.span_id, args)

    def event(self, name: str, parent: SpanContext | None = None,
              **args) -> None:
        """Instant event on the parent's trace (retry attempts, breaker
        transitions). Same no-op rule as `span`."""
        if parent is None:
            parent = _current.get()
        if parent is None or not parent.sampled:
            return
        ts = (time.perf_counter() - self.t0) * 1e6
        tid = threading.get_ident()
        args.setdefault("trace_id", parent.trace_id)
        args.setdefault("parent_id", parent.span_id)
        ev = {
            "ph": "i", "s": "t", "name": name, "ts": ts,
            "tid": tid, "args": args,
        }
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            buf = self._tail.get(parent.trace_id)
            if buf is not None:
                buf.append(ev)  # provisional: the root's end decides
            elif parent.trace_id not in self._tail_dropped:
                self._events.append(ev)

    def _record(self, span: Span, t1: float) -> None:
        ts = (span.t0 - self.t0) * 1e6
        args = span.args
        args.setdefault("trace_id", span.trace_id)
        args["span_id"] = span.span_id
        if span.parent_id:
            args.setdefault("parent_id", span.parent_id)
        dur_us = max((t1 - span.t0) * 1e6, 0.0)
        ev = {
            "ph": "X", "name": span.name, "ts": ts,
            "dur": dur_us,
            "tid": span.tid, "args": args,
        }
        is_root = span.parent_id == 0
        with self._lock:
            buf = self._tail.get(span.trace_id)
            if buf is not None:
                buf.append(ev)
                if is_root:
                    # the provisional trace is complete: promote or drop
                    self._decide_tail_locked(span.trace_id, args, dur_us)
            elif span.trace_id not in self._tail_dropped:
                self._events.append(ev)
            if is_root:
                # every root (sampled-in included) feeds the slow
                # threshold, so "p99-slow" means p99 of ALL roots
                self._root_durs.append(dur_us)
        # flight-recorder summary (obs/recorder.py): the always-on ring
        # keeps recent span names/durations even after this buffer wraps,
        # so a post-mortem dump shows what the process was doing
        recorder.note(
            "span", name=span.name, dur_ms=dur_us / 1e3,
            trace_id=span.trace_id,
        )

    # -- deferred tail keep (all called under self._lock) --------------------

    def _mark_dropped_locked(self, trace_id: str) -> None:
        self._tail_dropped[trace_id] = None
        while len(self._tail_dropped) > 4096:
            self._tail_dropped.popitem(last=False)

    def _tail_reason_locked(self, args: dict, dur_us: float) -> str | None:
        if "error" in args:
            return "error"
        status = args.get("status")
        if (
            status is not None
            and str(status) not in _TAIL_BENIGN_STATUSES
        ):
            # quarantined / deadline_expired / 422 / 5xx / anything the
            # caller flagged beyond the intentional outcomes
            return "error"
        if len(self._root_durs) >= _TAIL_MIN_DURS:
            durs = sorted(self._root_durs)
            p99 = durs[min(len(durs) - 1, int(0.99 * len(durs)))]
            if dur_us >= p99:
                return "slow"
        return None

    def _decide_tail_locked(
        self, trace_id: str, root_args: dict, dur_us: float
    ) -> None:
        buf = self._tail.pop(trace_id, None)
        if buf is None:
            return
        reason = self._tail_reason_locked(root_args, dur_us)
        if reason is None:
            self._mark_dropped_locked(trace_id)
            self.tail_counts["dropped"] += 1
            return
        root_args.setdefault("tail_kept", reason)
        self._events.extend(buf)
        self.tail_counts[f"kept_{reason}"] += 1

    def trace_kept(self, trace_id: str) -> bool:
        """Whether `trace_id` will resolve in this tracer's export:
        False only for a provisional trace that was dropped/evicted
        (in-flight and sampled-in ids report True)."""
        with self._lock:
            return trace_id not in self._tail_dropped

    # -- reporting ---------------------------------------------------------

    def counts(self) -> dict:
        with self._lock:
            return {
                "traces": self._n_traces,
                "sampled": self._n_sampled,
                "events": len(self._events),
                "sample": self.sample,
                "tail": dict(self.tail_counts),
                "tail_open": len(self._tail),
            }

    def drain(self) -> list[dict]:
        """Pop every buffered raw event (tests / incremental export)."""
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    def chrome_events(self, *, pid: int | None = None,
                      process_name: str = "mcim-host") -> list[dict]:
        """The buffered spans as Chrome trace-event dicts (non-draining),
        with process/thread metadata prepended."""
        pid = os.getpid() if pid is None else pid
        with self._lock:
            events = [dict(e) for e in self._events]
            names = dict(self._thread_names)
        meta: list[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        }]
        for tid, tname in sorted(names.items()):
            meta.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        for e in events:
            e["pid"] = pid
        return meta + events

    def export(self, path: str) -> int:
        """Write the Chrome trace JSON (`{"traceEvents": [...]}`); returns
        the number of events written. Load in ui.perfetto.dev, or merge
        with a jax.profiler device trace via obs/profile.py."""
        events = self.chrome_events()
        with open(path, "w") as f:
            json.dump(
                {"traceEvents": events, "displayTimeUnit": "ms"}, f
            )
        return len(events)


# -- module-level default tracer (the CLI/server wiring surface) -----------

ENV_SAMPLE = "MCIM_TRACE_SAMPLE"
ENV_TAIL = "MCIM_TRACE_TAIL"


def _tail_from_env(env=None) -> int:
    from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

    raw = env_registry.get(ENV_TAIL, env=env)
    return int(raw) if raw else 0


_tracer: Tracer | None = None
_enabled = False  # lock-free fast-path flag, flipped only by (de)configure


def configure(*, sample: float = 1.0, max_events: int = 200_000,
              tail: int | None = None) -> Tracer:
    """Arm the process-wide tracer (idempotent per call: a fresh buffer).
    `--trace-sample` < 1 keeps tracing cheap enough to leave on; the
    deferred tail-keep buffer (`tail`, default MCIM_TRACE_TAIL) then
    guarantees error/quarantine/p99-slow traces still export."""
    global _tracer, _enabled
    if tail is None:
        tail = _tail_from_env()
    _tracer = Tracer(sample=sample, max_events=max_events, tail=tail)
    _enabled = True
    return _tracer


def configure_from_env(env=None) -> Tracer | None:
    """Arm iff MCIM_TRACE_SAMPLE is set (a fraction; 1 = every trace)."""
    from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

    raw = env_registry.get(ENV_SAMPLE, env=env)
    if raw:
        return configure(
            sample=float(raw), tail=_tail_from_env(env)
        )
    return None


def disable() -> None:
    global _tracer, _enabled
    _enabled = False
    _tracer = None


def enabled() -> bool:
    return _enabled


def get_tracer() -> Tracer | None:
    return _tracer


def start_trace(name: str, *, trace_id: str | None = None, **args):
    if not _enabled:
        return NOOP_SPAN
    return _tracer.start_trace(name, trace_id=trace_id, **args)


def span(name: str, parent: SpanContext | None = None, **args):
    if not _enabled:
        return NOOP_SPAN
    return _tracer.span(name, parent=parent, **args)


def event(name: str, parent: SpanContext | None = None, **args) -> None:
    if not _enabled:
        return
    _tracer.event(name, parent=parent, **args)


def current_context() -> SpanContext | None:
    """The calling thread's active span context (None outside any span).
    Capture at submit time, hand to the thread that resumes the work."""
    return _current.get()


def current_trace_id() -> str:
    """The active trace id or "" — the log-line join key (utils/log.py)."""
    ctx = _current.get()
    return ctx.trace_id if ctx is not None and ctx.sampled else ""


def export(path: str) -> int:
    """Export the default tracer's buffer; 0 when tracing is disarmed."""
    if _tracer is None:
        return 0
    return _tracer.export(path)


def trace_kept(trace_id: str) -> bool:
    """Whether `trace_id` resolves in the default tracer's export: False
    only for a tail-dropped provisional trace. Reporting layers use this
    to prefer ids a reader can actually pull up (serve/loadgen's
    slow-trace column)."""
    if not _enabled or _tracer is None or not trace_id:
        return True
    return _tracer.trace_kept(trace_id)
