"""Observability fabric — tracing + metrics, one substrate for every
subsystem (docs/design.md "Observability").

  * `obs/trace.py`   — request-scoped spans with cross-thread context
                       propagation, deterministic sampling, Chrome/
                       Perfetto trace-event export (`--trace-out`).
  * `obs/metrics.py` — counters/gauges/histograms in one named registry
                       with Prometheus text exposition (`GET /metrics`,
                       `--metrics-out`); `/stats` is a view over the same
                       objects, so the two cannot drift.
  * `obs/profile.py` — Perfetto/Chrome trace parsing and the host-span /
                       jax.profiler device-trace merge (one timeline for
                       host stalls vs DMA vs compute; the capture tool
                       `tools/profile_capture.py` is a shim over this).
  * `obs/fleet.py`   — metrics federation: heartbeat delta snapshots,
                       restart-safe counter folding, bucket-merged fleet
                       histograms, the router's one-pod view.
  * `obs/slo.py`     — declarative SLOs evaluated as multi-window burn
                       rates over the federated view (`GET /slo`).
  * `obs/recorder.py`— the always-on flight recorder: bounded ring of
                       recent facts, dumped to JSON post-mortems on
                       breaker-open/quarantine/drain/replica-death.

The serving scheduler, the async engine, the resilience retry/bisect
path, the sharded halo dispatch and the batch CLI all report through
here — it is the substrate later fabric/streaming work reports through.
"""

from mpi_cuda_imagemanipulation_tpu.obs import fleet  # noqa: F401
from mpi_cuda_imagemanipulation_tpu.obs import recorder  # noqa: F401
from mpi_cuda_imagemanipulation_tpu.obs import slo  # noqa: F401
from mpi_cuda_imagemanipulation_tpu.obs import trace  # noqa: F401
from mpi_cuda_imagemanipulation_tpu.obs.metrics import (  # noqa: F401
    CONTENT_TYPE,
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    parse_exposition,
)
from mpi_cuda_imagemanipulation_tpu.obs.trace import (  # noqa: F401
    NOOP_SPAN,
    SpanContext,
    Tracer,
    current_context,
    current_trace_id,
    event,
    span,
    start_trace,
)

__all__ = [
    "CONTENT_TYPE",
    "DEFAULT_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "NOOP_SPAN",
    "Registry",
    "SpanContext",
    "Tracer",
    "current_context",
    "current_trace_id",
    "event",
    "fleet",
    "parse_exposition",
    "recorder",
    "slo",
    "span",
    "start_trace",
    "trace",
]
