"""Metrics federation — one fleet view over every replica's registry.

The router cannot answer "what is the pod's p99 right now" from its own
registry: latency lives in each replica's `mcim_serve_*` histograms. This
module moves those registries to the router WITHOUT a scrape round-trip:

  * **Replica side** — `DeltaSource` snapshots the replica's registries
    (`snapshot_registries`) and emits compact DELTAS on each heartbeat:
    only the series whose values changed since the last *acknowledged*
    snapshot ride the wire (values are ABSOLUTE, so a lost beat only
    delays freshness — it can never corrupt the merge). The router's
    heartbeat ack carries a `resync` flag when its baseline does not
    match (router restart, missed epoch): the replica then pushes one
    FULL snapshot on the next beat. `GET /fleet/snapshot` on the replica
    serves the same full snapshot for the router's active full-scrape
    fallback (heartbeat-gap recovery) and for CI equality checks.

  * **Router side** — `FleetAggregator` folds per-replica snapshots into
    one view, with the merge semantics the fleet exposition needs:

      counters     summed across replicas. Restart-safe: when a replica's
                   INCARNATION changes, the dying incarnation's last
                   values fold into a per-replica base so the new
                   process's counters (restarting from 0) add on top —
                   the fleet total never double-counts and never jumps
                   backward across a restart.
      histograms   bucket-merged (cumulative bucket counts, sum, count
                   all sum — identical bounds are required and checked).
                   The merged percentiles therefore equal the
                   percentiles of the POOLED observations at bucket
                   resolution (the property tests/test_fleet.py proves).
                   Exemplars: most recent timestamp wins per bucket, so
                   the federated p99 still links to a real trace id.
      gauges       never summed — each series gains a `replica` label
                   (a queue depth averaged across replicas is a lie).

    Stale replicas age OUT of the view: a replica whose snapshot has not
    been refreshed within `stale_s` stops contributing (same liveness
    definition as routing; its folded counter base leaves with it, which
    is exactly how a Prometheus federation behaves when a target
    disappears).

`quantile_from_buckets` is the Prometheus `histogram_quantile` rule
(linear interpolation inside the owning bucket) used by the SLO engine
and the fleet p99 readouts; `merged_exemplar_for_quantile` joins a
quantile to the nearest retained exemplar trace id.
"""

from __future__ import annotations

import threading
import time

from mpi_cuda_imagemanipulation_tpu.obs.metrics import (
    Registry,
    _escape_help,
    _fmt_exemplar,
    _fmt_value,
    _label_str,
)

SNAPSHOT_PATH = "/fleet/snapshot"


# --------------------------------------------------------------------------
# snapshots (replica side)
# --------------------------------------------------------------------------


def _capture(registries: list[Registry]) -> dict[str, dict]:
    """`{name: {kind, help, labels, [bounds,] series: {key: data}}}` over
    every metric in `registries` (later registries win name clashes —
    they shouldn't clash; the Registry dedups within one)."""
    out: dict[str, dict] = {}
    for reg in registries:
        for m in reg.metrics():
            entry: dict = {
                "kind": m.kind,
                "help": m.help,
                "labels": list(m.label_names),
            }
            if m.kind == "histogram":
                entry["bounds"] = list(m.buckets)
                entry["series"] = dict(m.data())
            else:
                entry["series"] = dict(m.values())
            out[m.name] = entry
    return out


def snapshot_registries(registries, *, seq: int = 0) -> dict:
    """A full, JSON-safe snapshot payload (series keys become lists)."""
    return {
        "seq": seq,
        "baseline_seq": 0,
        "full": True,
        "metrics": _to_wire(_capture(list(registries))),
    }


def _to_wire(metrics: dict[str, dict]) -> dict:
    wire = {}
    for name, entry in metrics.items():
        wire[name] = {
            **{k: v for k, v in entry.items() if k != "series"},
            "series": [
                [list(key), data] for key, data in entry["series"].items()
            ],
        }
    return wire


def _from_wire(metrics: dict) -> dict[str, dict]:
    out = {}
    for name, entry in metrics.items():
        out[name] = {
            **{k: v for k, v in entry.items() if k != "series"},
            "series": {
                tuple(key): data for key, data in entry["series"]
            },
        }
    return out


class DeltaSource:
    """The replica-side producer: `delta()` per heartbeat, `ack(seq)` on
    router acknowledgement, `force_full()` when the router asks for a
    resync. Values are absolute; a delta only narrows WHICH series ride
    the wire."""

    def __init__(self, registries):
        self._registries = list(registries)
        self._lock = threading.Lock()
        self._seq = 0
        self._acked: dict | None = None  # last router-applied capture
        self._acked_seq = 0
        self._pending: dict[int, dict] = {}  # seq -> capture

    def delta(self) -> dict:
        """The next heartbeat's metrics payload. Full until the first
        ack; afterwards only changed/new series (vs the acked capture)."""
        cur = _capture(self._registries)
        with self._lock:
            self._seq += 1
            seq = self._seq
            base = self._acked
            base_seq = self._acked_seq
            self._pending[seq] = cur
            # bound the pending window: unacked beats older than the
            # last few are useless (the router will resync anyway)
            for old in [s for s in self._pending if s < seq - 8]:
                del self._pending[old]
        if base is None:
            return {
                "seq": seq, "baseline_seq": 0, "full": True,
                "metrics": _to_wire(cur),
            }
        changed: dict[str, dict] = {}
        for name, entry in cur.items():
            old = base.get(name)
            if old is None:
                changed[name] = entry
                continue
            diff = {
                key: data
                for key, data in entry["series"].items()
                if old["series"].get(key) != data
            }
            if diff:
                changed[name] = {**entry, "series": diff}
        return {
            "seq": seq, "baseline_seq": base_seq, "full": False,
            "metrics": _to_wire(changed),
        }

    def ack(self, seq: int) -> None:
        with self._lock:
            cap = self._pending.pop(seq, None)
            if cap is not None and seq > self._acked_seq:
                self._acked = cap
                self._acked_seq = seq

    def force_full(self) -> None:
        with self._lock:
            self._acked = None
            self._acked_seq = 0
            self._pending.clear()


# --------------------------------------------------------------------------
# aggregation (router side)
# --------------------------------------------------------------------------


class _ReplicaMetrics:
    def __init__(self, incarnation: str):
        self.incarnation = incarnation
        self.seq = 0
        self.metrics: dict[str, dict] = {}
        self.last_update = 0.0


def _add_series(dst_entry: dict, key, data, kind: str) -> None:
    """Fold one series into an accumulating entry (counters add floats,
    histograms add buckets/sum/count and keep the freshest exemplars)."""
    series = dst_entry["series"]
    if kind != "histogram":
        series[key] = series.get(key, 0.0) + data
        return
    cur = series.get(key)
    if cur is None:
        series[key] = {
            "buckets": list(data["buckets"]),
            "sum": data["sum"],
            "count": data["count"],
            "exemplars": list(data.get("exemplars", ())),
        }
        return
    cur["buckets"] = [
        a + b for a, b in zip(cur["buckets"], data["buckets"])
    ]
    cur["sum"] += data["sum"]
    cur["count"] += data["count"]
    by_idx = {e[0]: e for e in cur["exemplars"]}
    for e in data.get("exemplars", ()):
        have = by_idx.get(e[0])
        if have is None or (e[3] or 0) >= (have[3] or 0):
            by_idx[e[0]] = e
    cur["exemplars"] = [by_idx[i] for i in sorted(by_idx)]


class FleetAggregator:
    """The router's fleet view. `apply()` folds heartbeat deltas in;
    `merged()`/`render()` produce the federated families; `stats()` the
    /stats section. One lock, short critical sections, no I/O under it."""

    def __init__(self, *, stale_s: float, clock=time.monotonic):
        self.stale_s = stale_s
        self._clock = clock
        self._lock = threading.Lock()
        self._replicas: dict[str, _ReplicaMetrics] = {}
        # rid -> {name: {kind, bounds?, series: {key: folded data}}} from
        # DEAD incarnations (counters/histograms only — restart survival)
        self._base: dict[str, dict[str, dict]] = {}
        self.applied_deltas = 0
        self.full_syncs = 0
        self.resyncs = 0
        self.merge_errors = 0

    # -- ingest -------------------------------------------------------------

    def _fold_into_base(self, rid: str, metrics: dict[str, dict]) -> None:
        """A replica incarnation died: bank its cumulative families so the
        successor's counters (restarting at 0) stack on top."""
        base = self._base.setdefault(rid, {})
        for name, entry in metrics.items():
            if entry["kind"] == "gauge":
                continue
            dst = base.get(name)
            if dst is None:
                dst = base[name] = {
                    **{k: v for k, v in entry.items() if k != "series"},
                    "series": {},
                }
            for key, data in entry["series"].items():
                _add_series(dst, key, data, entry["kind"])

    def apply(
        self, rid: str, incarnation: str, payload: dict | None,
        now: float | None = None,
    ) -> bool:
        """Fold one heartbeat's metrics payload in. Returns False when
        the replica must RESYNC (send a full snapshot next beat): unknown
        baseline, incarnation change mid-delta, or no payload history."""
        if payload is None:
            return True  # metrics-less heartbeat: nothing to do
        now = self._clock() if now is None else now
        metrics = _from_wire(payload.get("metrics", {}))
        with self._lock:
            st = self._replicas.get(rid)
            if st is None or st.incarnation != incarnation:
                if st is not None:
                    self._fold_into_base(rid, st.metrics)
                st = self._replicas[rid] = _ReplicaMetrics(incarnation)
                if not payload.get("full"):
                    self.resyncs += 1
                    return False
            if payload.get("full"):
                st.metrics = metrics
                st.seq = payload["seq"]
                st.last_update = now
                self.full_syncs += 1
                return True
            if payload.get("baseline_seq") != st.seq:
                self.resyncs += 1
                return False
            for name, entry in metrics.items():
                have = st.metrics.get(name)
                if have is None:
                    st.metrics[name] = entry
                else:
                    have["series"].update(entry["series"])
            st.seq = payload["seq"]
            st.last_update = now
            self.applied_deltas += 1
            return True

    def full_sync(
        self, rid: str, incarnation: str, snapshot: dict,
        now: float | None = None,
    ) -> None:
        """Replace a replica's state from an out-of-band full snapshot
        (the router's active `GET /fleet/snapshot` fallback). The stored
        seq stays 0 so the next heartbeat delta resyncs cleanly."""
        now = self._clock() if now is None else now
        with self._lock:
            st = self._replicas.get(rid)
            if st is not None and st.incarnation != incarnation:
                self._fold_into_base(rid, st.metrics)
                st = None
            if st is None:
                st = self._replicas[rid] = _ReplicaMetrics(incarnation)
            st.metrics = _from_wire(snapshot.get("metrics", {}))
            st.seq = 0
            st.last_update = now
            self.full_syncs += 1

    def forget(self, rid: str) -> None:
        with self._lock:
            self._replicas.pop(rid, None)
            self._base.pop(rid, None)

    # -- views --------------------------------------------------------------

    def ages(self, now: float | None = None) -> dict[str, float]:
        now = self._clock() if now is None else now
        with self._lock:
            return {
                rid: now - st.last_update
                for rid, st in self._replicas.items()
            }

    def fresh_ids(self, now: float | None = None) -> list[str]:
        ages = self.ages(now)
        return sorted(r for r, age in ages.items() if age <= self.stale_s)

    def merged(self, now: float | None = None) -> dict[str, dict]:
        """The federated families over FRESH replicas:
        `{name: {kind, help, labels, [bounds,] series: {key: value|hist
        data}}}` — counters/histograms summed (incl. each fresh replica's
        banked base), gauges re-labeled with `replica`."""
        now = self._clock() if now is None else now
        with self._lock:
            fresh = {
                rid: st
                for rid, st in self._replicas.items()
                if now - st.last_update <= self.stale_s
            }
            contributions = [
                (rid, src)
                for rid, st in fresh.items()
                for src in (st.metrics, self._base.get(rid, {}))
            ]
            out: dict[str, dict] = {}
            for rid, src in contributions:
                for name, entry in src.items():
                    kind = entry["kind"]
                    dst = out.get(name)
                    if dst is None:
                        labels = list(entry["labels"])
                        if kind == "gauge":
                            labels = labels + ["replica"]
                        dst = out[name] = {
                            **{
                                k: v
                                for k, v in entry.items()
                                if k != "series"
                            },
                            "labels": labels,
                            "series": {},
                        }
                    elif (
                        kind == "histogram"
                        and dst.get("bounds") != entry.get("bounds")
                    ):
                        self.merge_errors += 1
                        continue
                    for key, data in entry["series"].items():
                        if kind == "gauge":
                            dst["series"][key + (rid,)] = data
                        else:
                            _add_series(dst, key, data, kind)
            return out

    def render(self, now: float | None = None) -> str:
        """The federated exposition block appended to the router's own
        `GET /metrics` body."""
        lines: list[str] = []
        for name in sorted(merged := self.merged(now)):
            entry = merged[name]
            kind = entry["kind"]
            label_names = tuple(entry["labels"])
            lines.append(f"# HELP {name} {_escape_help(entry['help'])}")
            lines.append(f"# TYPE {name} {kind}")
            if kind != "histogram":
                for key in sorted(entry["series"]):
                    lines.append(
                        f"{name}{_label_str(label_names, key)} "
                        f"{_fmt_value(entry['series'][key])}"
                    )
                continue
            bounds = entry["bounds"]
            for key in sorted(entry["series"]):
                data = entry["series"][key]
                exemplars = {e[0]: tuple(e[1:]) for e in data["exemplars"]}
                for i, ub in enumerate(bounds):
                    ls = _label_str(
                        label_names, key, (("le", _fmt_value(ub)),)
                    )
                    lines.append(
                        f"{name}_bucket{ls} {data['buckets'][i]}"
                        + _fmt_exemplar(exemplars.get(i))
                    )
                inf_ls = _label_str(label_names, key, (("le", "+Inf"),))
                lines.append(
                    f"{name}_bucket{inf_ls} {data['count']}"
                    + _fmt_exemplar(exemplars.get(len(bounds)))
                )
                plain = _label_str(label_names, key)
                lines.append(f"{name}_sum{plain} {repr(float(data['sum']))}")
                lines.append(f"{name}_count{plain} {data['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def stats(self, now: float | None = None) -> dict:
        ages = self.ages(now)
        return {
            "replicas": sorted(ages),
            "fresh": self.fresh_ids(now),
            "ages_s": ages,
            "applied_deltas": self.applied_deltas,
            "full_syncs": self.full_syncs,
            "resyncs": self.resyncs,
            "merge_errors": self.merge_errors,
        }


# --------------------------------------------------------------------------
# quantiles + exemplars over merged histograms
# --------------------------------------------------------------------------


def quantile_from_buckets(
    bounds, cum_counts, total: float, q: float
) -> float | None:
    """Prometheus `histogram_quantile`: the q-th percentile estimated
    from CUMULATIVE bucket counts by linear interpolation inside the
    owning bucket. Observations past the last bound clamp to it."""
    if total <= 0:
        return None
    rank = (q / 100.0) * total
    prev_cum = 0.0
    prev_bound = 0.0
    for bound, cum in zip(bounds, cum_counts):
        if cum >= rank:
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (bound - prev_bound)
        prev_cum, prev_bound = cum, bound
    return float(bounds[-1]) if bounds else None


def merged_exemplar_for_quantile(
    entry: dict, q: float, key: tuple = ()
) -> tuple[str, float, float] | None:
    """The (trace_id, value, ts) exemplar nearest the q-th percentile of
    one merged histogram series — the federated p99's link back to a
    concrete trace."""
    data = entry["series"].get(key)
    if not data:
        return None
    bounds = entry["bounds"]
    v = quantile_from_buckets(bounds, data["buckets"], data["count"], q)
    if v is None:
        return None
    idx = len(bounds)
    for i, ub in enumerate(bounds):
        if v <= ub:
            idx = i
            break
    by_idx = {e[0]: tuple(e[1:]) for e in data.get("exemplars", ())}
    # nearest populated bucket by index distance (ties go up)
    for d in range(len(bounds) + 1):
        for i in (idx + d, idx - d):
            if i in by_idx:
                return by_idx[i]
    return None
