"""Always-on flight recorder — a bounded ring of recent facts, dumped on
failure.

Traces answer "where did request X go" *when sampling kept it*; the
recorder answers "what was this process doing just before it broke" —
ALWAYS. Every process keeps a bounded, lock-free ring (a
`deque(maxlen=cap)`; CPython's deque append is a single atomic bytecode
under the GIL — no lock, no allocation beyond the tuple) of recent

  * span summaries (name/duration/trace id, fed by obs/trace on record),
  * dispatch summaries (bucket, batch size, device ms — the "which bucket
    was hot" evidence the churn post-mortem needs),
  * failpoint hits (site + call number, resilience/failpoints.py),
  * breaker transitions (key + new state, resilience/breaker.py),
  * heartbeat observations (the router notes replica state changes), and
  * WARNING+ log lines (utils/log.py attaches a handler).

`dump(trigger)` freezes the ring into one JSON artifact. The trigger
vocabulary is CLOSED — `KNOWN_TRIGGERS`, machine-checked by mcim-check's
`obs-recorder-trigger-*` rules exactly like failpoint sites — and the
production wiring fires it on:

    breaker_open    a dispatch/forward breaker tripped (serve/scheduler,
                    fabric/router)
    quarantine      a poison request failed solo (serve/scheduler)
    sigterm_drain   the SIGTERM graceful-drain path (fabric/replica,
                    cli serve)
    replica_death   the supervisor observed a replica process exit
                    (fabric/supervisor — the dump is the SUPERVISOR's
                    ring, which holds the dead replica's last heartbeats
                    incl. its warm buckets)
    autoscale       the elastic control loop changed the replica set
                    (fabric/autoscaler — the dump records the signals
                    that drove the decision next to the heartbeats)
    preempt         a replica received a preemption/maintenance notice
                    (fabric/replica — the dump is the PREEMPTED process's
                    own ring, written after the graceful drain)
    canary_rollback the canary rollback gate auto-reverted a config flip
                    (fabric/router — the dump carries the canary-vs-
                    stable outcome counts and shadow mismatches)
    profile_capture an on-demand fleet profile capture completed
                    (obs/profile.capture_live — the dump names the
                    merged-trace artifact so the post-mortem and the
                    profile join on the same window)
    manual          operator/test-initiated (`dump("manual")`)

Dumps are rate-limited per trigger (`MCIM_RECORDER_MIN_INTERVAL_S`) so a
quarantine storm produces one artifact, not thousands; `force=True`
bypasses the limit for tests. Artifacts land in `MCIM_RECORDER_DIR`
(default `artifacts/recorder/`) as
`recorder_<trigger>_<pid>_<seq>.json` with a summary header (entry
counts by kind, hot buckets by dispatch count, last heartbeat per
replica) so the interesting facts are readable before the raw ring.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

ENV_DIR = "MCIM_RECORDER_DIR"
ENV_CAP = "MCIM_RECORDER_CAP"
ENV_MIN_INTERVAL_S = "MCIM_RECORDER_MIN_INTERVAL_S"

# the closed trigger vocabulary — every dump() literal must name one of
# these, and every entry must have a dump() caller (mcim-check
# obs-recorder-trigger-unknown / obs-recorder-trigger-unused)
KNOWN_TRIGGERS = (
    "breaker_open",
    "quarantine",
    "sigterm_drain",
    "replica_death",
    "autoscale",
    "preempt",
    "canary_rollback",
    "systolic_fallback",  # stage-sharded dispatch fell back pinned
    #                       (owner death / broken hop — fabric/router.py)
    "profile_capture",
    "manual",
)


class FlightRecorder:
    """One process's ring. The hot path is `note()` — one tuple build and
    one deque append, no lock (the deque's maxlen discipline IS the
    bound). Only `dump()` takes a lock, for the per-trigger rate limit."""

    def __init__(self, cap: int | None = None):
        if cap is None:
            cap = int(env_registry.get(ENV_CAP) or 2048)
        self.cap = cap
        self._ring: deque = deque(maxlen=cap)
        self._dump_lock = threading.Lock()
        self._last_dump: dict[str, float] = {}  # trigger -> unix ts
        self._dump_seq = 0
        self.noted = 0  # approximate (racy by design; the ring is exact)

    # -- recording (hot path, lock-free) ------------------------------------

    def note(self, kind: str, **fields) -> None:
        self._ring.append((time.time(), kind, fields))
        self.noted += 1

    def entries(self) -> list[tuple[float, str, dict]]:
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    # -- dumping -------------------------------------------------------------

    def summary(self, entries: list | None = None) -> dict:
        """The readable header of a dump: counts by kind, dispatch-count
        per bucket ("which bucket was hot"), breaker transitions, and the
        last heartbeat seen per replica (the router/supervisor process's
        ring holds these — a dead replica's warm buckets survive here)."""
        if entries is None:
            entries = self.entries()
        by_kind: dict[str, int] = {}
        hot_buckets: dict[str, int] = {}
        breaker_transitions: list[dict] = []
        last_heartbeat: dict[str, dict] = {}
        for ts, kind, fields in entries:
            by_kind[kind] = by_kind.get(kind, 0) + 1
            if kind == "dispatch" and "bucket" in fields:
                b = str(fields["bucket"])
                hot_buckets[b] = hot_buckets.get(b, 0) + int(
                    fields.get("n", 1)
                )
            elif kind == "breaker":
                breaker_transitions.append({"ts": ts, **fields})
            elif kind == "heartbeat" and "replica" in fields:
                last_heartbeat[str(fields["replica"])] = {"ts": ts, **fields}
        return {
            "entries": len(entries),
            "by_kind": by_kind,
            "hot_buckets": dict(
                sorted(hot_buckets.items(), key=lambda kv: -kv[1])
            ),
            "breaker_transitions": breaker_transitions[-20:],
            "last_heartbeat": last_heartbeat,
        }

    def dump(
        self,
        trigger: str,
        *,
        path: str | None = None,
        extra: dict | None = None,
        force: bool = False,
    ) -> str | None:
        """Freeze the ring into a JSON post-mortem artifact; returns the
        path, or None when rate-limited/unwritable (a dump must never
        take its process down — it runs on failure paths)."""
        if trigger not in KNOWN_TRIGGERS:
            raise ValueError(
                f"unknown recorder trigger {trigger!r}; known: "
                f"{KNOWN_TRIGGERS}"
            )
        now = time.time()
        min_interval = float(
            env_registry.get(ENV_MIN_INTERVAL_S) or 30.0
        )
        with self._dump_lock:
            last = self._last_dump.get(trigger)
            if not force and last is not None and now - last < min_interval:
                return None
            self._last_dump[trigger] = now
            self._dump_seq += 1
            seq = self._dump_seq
        entries = self.entries()
        payload = {
            "trigger": trigger,
            "ts": now,
            "pid": os.getpid(),
            "extra": extra or {},
            "summary": self.summary(entries),
            "entries": [
                {"ts": ts, "kind": kind, **fields}
                for ts, kind, fields in entries
            ],
        }
        if path is None:
            out_dir = env_registry.get(ENV_DIR) or os.path.join(
                "artifacts", "recorder"
            )
            path = os.path.join(
                out_dir, f"recorder_{trigger}_{os.getpid()}_{seq}.json"
            )
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, default=str)
        except OSError:
            return None
        return path


# -- module-level default recorder (the process-wide ring) -------------------

_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _recorder


def configure(cap: int) -> FlightRecorder:
    """Replace the process ring (tests / cap changes); the old entries
    are dropped."""
    global _recorder
    _recorder = FlightRecorder(cap)
    return _recorder


def note(kind: str, **fields) -> None:
    _recorder.note(kind, **fields)


def dump(
    trigger: str,
    *,
    path: str | None = None,
    extra: dict | None = None,
    force: bool = False,
) -> str | None:
    return _recorder.dump(trigger, path=path, extra=extra, force=force)
