"""SLO burn-rate engine — declarative objectives over the federated view.

An SLO is a target fraction of GOOD events; the error budget is
`1 - target`. The **burn rate** is how fast that budget is being spent:
an error rate of exactly `1 - target` burns at 1.0 (the budget lasts the
whole period); burn 10 means the month's budget is gone in three days.
Alerting on burn rates over TWO windows — a fast window that reacts and a
slow window that confirms — is the standard multi-window construction: a
blip trips neither, a real outage trips both quickly, and a slow leak
still trips the slow window. The alert FIRES when both windows exceed the
threshold and CLEARS when either drops back under it.

Spec grammar (`MCIM_SLO_SPECS` / `--slo`, comma-separated):

    avail:99.5            availability: 99.5% of resolved requests ok
                          (good = status "ok"; total excludes "rejected"
                          — a client sending garbage is not our outage)
    latency:0.25:99       latency: 99% of requests complete within 0.25 s
                          (the bound must be a histogram bucket edge;
                          good = cumulative count at that bucket)
    headroom:0.1:99       device memory: 99% of evaluation ticks must
                          see >= 10% allocator headroom on EVERY device
                          of EVERY fresh replica (the federated
                          mcim_devmem_headroom_frac gauges, obs/devmem
                          — each tick is one good/bad event, so the
                          same burn-rate machinery applies)

All kinds read the FEDERATED families (obs/fleet.py) —
`mcim_serve_requests_total`, `mcim_serve_e2e_latency_seconds`,
`mcim_devmem_headroom_frac` — so the burn rates are fleet-wide — a
single replica melting down moves them in proportion to its traffic
share, which is what an error budget means.

The engine samples those cumulative counters into a bounded ring each
tick and differences ring endpoints to get windowed rates — no
per-request cost, and restarts of individual replicas are already
incarnation-folded by the aggregator, so windows never see counters move
backward. Alert transitions are recorded three ways: an instant event on
a dedicated mini-trace (`slo.alert` — it lands in the Perfetto export
next to the requests that burned the budget), a flight-recorder note
(post-mortem dumps show the alert history), and the
`mcim_slo_transitions_total` counter. Current state is exposed as
`mcim_slo_*` gauges on the router registry and as JSON at `GET /slo`.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from mpi_cuda_imagemanipulation_tpu.obs import recorder
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_SPECS = "MCIM_SLO_SPECS"
ENV_FAST_S = "MCIM_SLO_FAST_S"
ENV_SLOW_S = "MCIM_SLO_SLOW_S"
ENV_TICK_S = "MCIM_SLO_TICK_S"
ENV_BURN_THRESHOLD = "MCIM_SLO_BURN_THRESHOLD"

# availability: client-side rejections are not availability failures
_AVAIL_EXCLUDED_STATUSES = ("rejected",)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    name: str
    kind: str  # "availability" | "latency" | "headroom"
    target: float  # good fraction in (0, 1)
    # latency: bound in seconds (bucket edge); headroom: the minimum
    # free-fraction every device must keep
    le: float | None = None

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_slo_specs(spec: str) -> tuple[SLOSpec, ...]:
    """Parse the `avail:<pct>,latency:<le>:<pct>` grammar; raises
    ValueError with the offending token on anything else."""
    out: list[SLOSpec] = []
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        parts = tok.split(":")
        try:
            if parts[0] in ("avail", "availability") and len(parts) == 2:
                pct = float(parts[1])
                if not 0.0 < pct < 100.0:
                    raise ValueError
                out.append(
                    SLOSpec(
                        name=f"availability_{parts[1]}",
                        kind="availability",
                        target=pct / 100.0,
                    )
                )
                continue
            if parts[0] == "latency" and len(parts) == 3:
                le = float(parts[1])
                pct = float(parts[2])
                if le <= 0.0 or not 0.0 < pct < 100.0:
                    raise ValueError
                out.append(
                    SLOSpec(
                        name=f"latency_le{parts[1]}_{parts[2]}",
                        kind="latency",
                        target=pct / 100.0,
                        le=le,
                    )
                )
                continue
            if parts[0] == "headroom" and len(parts) == 3:
                frac = float(parts[1])
                pct = float(parts[2])
                if not 0.0 < frac < 1.0 or not 0.0 < pct < 100.0:
                    raise ValueError
                out.append(
                    SLOSpec(
                        name=f"headroom_{parts[1]}_{parts[2]}",
                        kind="headroom",
                        target=pct / 100.0,
                        le=frac,
                    )
                )
                continue
            raise ValueError
        except ValueError:
            raise ValueError(
                f"bad SLO spec token {tok!r} (want avail:<pct>, "
                "latency:<le_seconds>:<pct> or headroom:<min_frac>:<pct>)"
            ) from None
    return tuple(out)


def fleet_slo_source(merged_fn):
    """A `source()` over the fleet view: `{spec-kind key: (good, total)}`
    cumulative counts. `merged_fn()` is `FleetAggregator.merged` (or any
    callable returning the same shape, which is what the tests inject)."""

    # headroom specs turn each evaluation tick into one good/bad event
    # (gauges have no cumulative counter to difference); the accumulators
    # live here so the ring-endpoint machinery sees monotone counts
    headroom_cum: dict[str, list[float]] = {}

    def source(specs: tuple[SLOSpec, ...]) -> dict[str, tuple[float, float]]:
        merged = merged_fn()
        out: dict[str, tuple[float, float]] = {}
        req = merged.get("mcim_serve_requests_total")
        lat = merged.get("mcim_serve_e2e_latency_seconds")
        hr = merged.get("mcim_devmem_headroom_frac")
        for s in specs:
            good = total = 0.0
            if s.kind == "headroom":
                cum = headroom_cum.setdefault(s.name, [0.0, 0.0])
                series = (hr or {}).get("series", {})
                if series:
                    # the WORST device of the WORST fresh replica decides
                    worst = min(series.values())
                    cum[1] += 1.0
                    if worst >= (s.le or 0.0):
                        cum[0] += 1.0
                out[s.name] = (cum[0], cum[1])
                continue
            if s.kind == "availability" and req is not None:
                for key, v in req["series"].items():
                    status = key[0] if key else ""
                    if status in _AVAIL_EXCLUDED_STATUSES:
                        continue
                    total += v
                    if status == "ok":
                        good += v
            elif s.kind == "latency" and lat is not None:
                data = lat["series"].get(())
                if data:
                    bounds = lat["bounds"]
                    # the greatest bucket edge <= le holds the good count
                    idx = None
                    for i, ub in enumerate(bounds):
                        if ub <= s.le + 1e-12:
                            idx = i
                    if idx is not None:
                        good = float(data["buckets"][idx])
                    total = float(data["count"])
            out[s.name] = (good, total)
        return out

    return source


class _AlertState:
    def __init__(self):
        self.firing = False
        self.since: float | None = None
        self.transitions = 0


class SLOEngine:
    """Ticks `source` into a bounded ring, computes fast/slow burn rates
    by differencing ring endpoints, and drives the per-SLO alert machine.
    `start()` runs the ticker thread; tests call `tick(now)` directly
    with a fake clock."""

    def __init__(
        self,
        specs: tuple[SLOSpec, ...],
        source,
        *,
        fast_s: float | None = None,
        slow_s: float | None = None,
        tick_s: float | None = None,
        burn_threshold: float | None = None,
        registry: Registry | None = None,
        clock=time.monotonic,
    ):
        self.specs = tuple(specs)
        self._source = source
        self.fast_s = (
            float(env_registry.get(ENV_FAST_S)) if fast_s is None else fast_s
        )
        self.slow_s = (
            float(env_registry.get(ENV_SLOW_S)) if slow_s is None else slow_s
        )
        self.tick_s = (
            float(env_registry.get(ENV_TICK_S)) if tick_s is None else tick_s
        )
        self.burn_threshold = (
            float(env_registry.get(ENV_BURN_THRESHOLD))
            if burn_threshold is None
            else burn_threshold
        )
        self._clock = clock
        self._lock = threading.Lock()
        # ring of (t, {name: (good, total)}); sized to cover the slow
        # window at tick resolution with slack
        cap = max(int(self.slow_s / max(self.tick_s, 1e-3)) + 8, 16)
        self._ring: deque = deque(maxlen=cap)
        self._alerts = {s.name: _AlertState() for s in self.specs}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._log = get_logger()
        if registry is not None:
            self._register_gauges(registry)

    def _register_gauges(self, r: Registry) -> None:
        r.gauge(
            "mcim_slo_burn_rate",
            "Error-budget burn rate per SLO and window (1 = on budget).",
            labels=("slo", "window"),
            fn=self._burn_gauge,
        )
        r.gauge(
            "mcim_slo_alert_firing",
            "1 while the SLO's multi-window burn alert is firing.",
            labels=("slo",),
            fn=lambda: {
                (name,): 1.0 if st.firing else 0.0
                for name, st in self._alerts.items()
            },
        )
        r.gauge(
            "mcim_slo_target",
            "Configured good-fraction target per SLO.",
            labels=("slo",),
            fn=lambda: {(s.name,): s.target for s in self.specs},
        )
        self._m_transitions = r.counter(
            "mcim_slo_transitions_total",
            "Alert state transitions per SLO and new state.",
            labels=("slo", "to"),
        )

    def _burn_gauge(self) -> dict:
        out = {}
        for s in self.specs:
            burns = self.burn_rates(s.name)
            out[(s.name, "fast")] = burns.get("fast") or 0.0
            out[(s.name, "slow")] = burns.get("slow") or 0.0
        return out

    # -- sampling + windows --------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        """One evaluation: sample the source, update every alert."""
        now = self._clock() if now is None else now
        counts = self._source(self.specs)
        with self._lock:
            self._ring.append((now, counts))
        for s in self.specs:
            self._evaluate(s, now)

    def _window_rate(
        self, name: str, window_s: float, now: float
    ) -> float | None:
        """Error rate over the trailing window: difference the newest
        ring sample against the oldest one inside the window (or the
        first ever sample while the ring is still shorter than the
        window). None until two samples exist or when no events moved."""
        with self._lock:
            ring = list(self._ring)
        if len(ring) < 2:
            return None
        newest_t, newest = ring[-1]
        base_t, base = ring[0]
        for t, counts in ring:
            if t >= now - window_s:
                base_t, base = t, counts
                break
        if base_t >= newest_t:
            return None
        g1, t1 = newest.get(name, (0.0, 0.0))
        g0, t0 = base.get(name, (0.0, 0.0))
        d_total = t1 - t0
        if d_total <= 0:
            return None
        d_bad = (t1 - g1) - (t0 - g0)
        return max(min(d_bad / d_total, 1.0), 0.0)

    def burn_rates(self, name: str, now: float | None = None) -> dict:
        now = self._clock() if now is None else now
        spec = next(s for s in self.specs if s.name == name)
        out = {}
        for window, window_s in (("fast", self.fast_s), ("slow", self.slow_s)):
            rate = self._window_rate(name, window_s, now)
            out[window] = (
                None if rate is None else rate / max(spec.budget, 1e-9)
            )
        return out

    # -- alerting ------------------------------------------------------------

    def _evaluate(self, spec: SLOSpec, now: float) -> None:
        burns = self.burn_rates(spec.name, now)
        fast, slow = burns["fast"], burns["slow"]
        firing = (
            fast is not None
            and slow is not None
            and fast > self.burn_threshold
            and slow > self.burn_threshold
        )
        st = self._alerts[spec.name]
        if firing == st.firing:
            return
        st.firing = firing
        st.since = now
        st.transitions += 1
        state = "firing" if firing else "ok"
        if hasattr(self, "_m_transitions"):
            self._m_transitions.inc(slo=spec.name, to=state)
        recorder.note(
            "slo", slo=spec.name, state=state,
            burn_fast=fast, burn_slow=slow,
        )
        self._log.warning(
            "slo %s -> %s (burn fast %.2f / slow %.2f, threshold %.2f)",
            spec.name, state, fast or 0.0, slow or 0.0, self.burn_threshold,
        )
        # the transition lands on the trace timeline as its own
        # mini-trace: an instant event next to the requests that burned
        # the budget (merged exports line them up by wall clock)
        with obs_trace.start_trace(
            "slo.alert", slo=spec.name, state=state
        ) as root:
            obs_trace.event(
                "slo.transition", parent=root.context(),
                slo=spec.name, state=state,
                burn_fast=fast, burn_slow=slow,
            )

    # -- lifecycle + reporting ----------------------------------------------

    def start(self) -> "SLOEngine":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="mcim-slo-engine", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                self._log.exception("slo tick failed")
            self._stop.wait(self.tick_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def status(self, now: float | None = None) -> dict:
        """The `GET /slo` payload."""
        now = self._clock() if now is None else now
        slos = {}
        with self._lock:
            newest = self._ring[-1][1] if self._ring else {}
        for s in self.specs:
            burns = self.burn_rates(s.name, now)
            st = self._alerts[s.name]
            good, total = newest.get(s.name, (0.0, 0.0))
            slos[s.name] = {
                **s.to_dict(),
                "burn_fast": burns["fast"],
                "burn_slow": burns["slow"],
                "alert": "firing" if st.firing else "ok",
                "alert_since_s": (
                    None if st.since is None else now - st.since
                ),
                "transitions": st.transitions,
                "good": good,
                "total": total,
            }
        return {
            "windows": {
                "fast_s": self.fast_s,
                "slow_s": self.slow_s,
                "tick_s": self.tick_s,
            },
            "burn_threshold": self.burn_threshold,
            "slos": slos,
        }
